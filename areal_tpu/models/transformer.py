"""The trainer transformer — pure-pytree, scan-over-layers, GSPMD-ready.

Replaces the reference's ReaLModel (``realhf/impl/model/nn/real_llm_api.py:100``
+ ``real_llm_base.py``: VocabPositionEmbedding, ReaLModelBlock×L, OutputHead)
with an idiomatic-JAX design:

 - Parameters are a plain pytree with **layers stacked on a leading axis**, so
   the forward pass is one ``lax.scan`` over layers — constant compile time in
   depth, and pipeline parallelism can partition the stacked axis.
 - Batches are fixed-shape ``[B, L]`` document-packed with segment ids
   (0 = pad) instead of 1-D ragged varlen — static shapes for XLA.
 - No module classes: ``init_params(cfg, key)`` + ``forward(params, cfg, ...)``
   are pure functions; sharding is applied externally as a PartitionSpec tree
   of the same structure (areal_tpu/parallel/sharding.py).

Supports GQA, RoPE (HF llama-style rotate-half), RMSNorm, gated-SiLU MLP,
optional qk-norm (qwen3), optional attention biases (qwen2), tied embeddings,
critic (scalar) head, and a KV-cache decode mode.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.models.config import TransformerConfig
from areal_tpu.ops.attention import decode_attention, packed_attention
from areal_tpu.parallel.sharding import constrain, current_mesh

Params = Dict[str, Any]


# ---------------- init ----------------

def init_params(cfg: TransformerConfig, key: jax.Array) -> Params:
    if cfg.moe is not None:
        raise NotImplementedError(
            "MoE layers are built by areal_tpu.models.moe (pending); dense only"
        )
    dtype = jnp.dtype(cfg.dtype)
    n, d, dh = cfg.n_layers, cfg.hidden_dim, cfg.head_dim
    qd, kvd, f = cfg.q_dim, cfg.kv_dim, cfg.intermediate_dim
    keys = jax.random.split(key, 16)

    def nrm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape) * scale).astype(dtype)

    layers: Dict[str, jnp.ndarray] = {
        "ln1": jnp.ones((n, d), dtype),
        "ln2": jnp.ones((n, d), dtype),
        "wq": nrm(keys[0], (n, d, qd)),
        "wk": nrm(keys[1], (n, d, kvd)),
        "wv": nrm(keys[2], (n, d, kvd)),
        "wo": nrm(keys[3], (n, qd, d)),
        "w_gate": nrm(keys[4], (n, d, f)),
        "w_up": nrm(keys[5], (n, d, f)),
        "w_down": nrm(keys[6], (n, f, d)),
    }
    if cfg.use_attention_bias:
        layers["bq"] = jnp.zeros((n, qd), dtype)
        layers["bk"] = jnp.zeros((n, kvd), dtype)
        layers["bv"] = jnp.zeros((n, kvd), dtype)
    if cfg.use_attn_output_bias:
        layers["bo"] = jnp.zeros((n, d), dtype)
    if cfg.use_qk_norm:
        layers["q_norm"] = jnp.ones((n, dh), dtype)
        layers["k_norm"] = jnp.ones((n, dh), dtype)

    params: Params = {
        "embedding": nrm(keys[7], (cfg.vocab_size, d)),
        "layers": layers,
        "final_ln": jnp.ones((d,), dtype),
    }
    if cfg.is_critic:
        params["value_head"] = nrm(keys[8], (d, 1))
    elif not cfg.tie_word_embeddings:
        params["lm_head"] = nrm(keys[8], (d, cfg.vocab_size))
    return params


# ---------------- primitives ----------------

def rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (w * (x32 * jax.lax.rsqrt(var + eps)).astype(dt)).astype(dt)


def rope_tables(
    positions: jnp.ndarray, head_dim: int, base: float
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin [..., head_dim] for HF-style rotate-half RoPE."""
    inv_freq = 1.0 / (
        base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., dh/2]
    emb = jnp.concatenate([angles, angles], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [B, T, H, Dh]; cos/sin: [B, T, Dh]."""
    c = cos[:, :, None, :].astype(x.dtype)
    s = sin[:, :, None, :].astype(x.dtype)
    half = x.shape[-1] // 2
    rot = jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)
    return x * c + rot * s


# ---------------- one block ----------------

def _block(
    cfg: TransformerConfig,
    h: jnp.ndarray,  # [B, T, D]
    lp: Dict[str, jnp.ndarray],  # this layer's params (leading axis sliced away)
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    segment_ids: Optional[jnp.ndarray],
    positions: Optional[jnp.ndarray],
    cache_kv: Optional[Tuple[jnp.ndarray, jnp.ndarray]],  # ([B,S,Hkv,Dh], ...)
    cache_write_index: Optional[jnp.ndarray],
    kv_valid: Optional[jnp.ndarray],
    attn_impl: str,
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    B, T, D = h.shape
    dh = cfg.head_dim

    x = rms_norm(h, lp["ln1"], cfg.rms_norm_eps)
    q = x @ lp["wq"]
    k = x @ lp["wk"]
    v = x @ lp["wv"]
    if "bq" in lp:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(B, T, cfg.n_q_heads, dh)
    k = k.reshape(B, T, cfg.n_kv_heads, dh)
    v = v.reshape(B, T, cfg.n_kv_heads, dh)
    if cfg.use_qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if cache_kv is None:
        mesh = current_mesh()
        # Ring attention needs shard_map-divisible shapes; shapes that don't
        # divide (e.g. generate()'s unbucketed batch dim) keep the tolerant
        # GSPMD path.
        use_ring = (
            mesh is not None
            and mesh.shape.get("sp", 1) > 1
            and cfg.sliding_window is None
            and B % (mesh.shape["dp"] * mesh.shape["fsdp"]) == 0
            and T % mesh.shape["sp"] == 0
            and cfg.n_q_heads % mesh.shape["tp"] == 0
            and cfg.n_kv_heads % mesh.shape["tp"] == 0
        )
        if use_ring:
            # Sequence dim sharded → context-parallel ring attention.
            from areal_tpu.parallel.ring import ring_attention

            attn = ring_attention(q, k, v, segment_ids, mesh)
        else:
            attn = packed_attention(
                q, k, v, segment_ids, segment_ids,
                q_positions=positions, kv_positions=positions,
                causal=True, sliding_window=cfg.sliding_window, impl=attn_impl,
            )
        new_kv = (k, v)
    else:
        k_cache, v_cache = cache_kv
        if getattr(cache_write_index, "ndim", 0) == 1:
            # Per-row write slots (continuous batching: rows of the batch
            # sit at different sequence lengths). T must be 1.
            rows = jnp.arange(B)
            k_cache = k_cache.at[rows, cache_write_index].set(k[:, 0])
            v_cache = v_cache.at[rows, cache_write_index].set(v[:, 0])
        else:
            k_cache = jax.lax.dynamic_update_slice_in_dim(
                k_cache, k, cache_write_index, axis=1
            )
            v_cache = jax.lax.dynamic_update_slice_in_dim(
                v_cache, v, cache_write_index, axis=1
            )
        attn = decode_attention(q, k_cache, v_cache, kv_valid)
        new_kv = (k_cache, v_cache)

    hid = "hidden" if cache_kv is None else "hidden_decode"
    attn = attn.reshape(B, T, cfg.q_dim) @ lp["wo"]
    if "bo" in lp:
        attn = attn + lp["bo"]
    h = constrain(h + attn, hid)

    x = rms_norm(h, lp["ln2"], cfg.rms_norm_eps)
    mlp = (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
    return constrain(h + mlp, hid), new_kv


# ---------------- forward ----------------

def forward(
    params: Params,
    cfg: TransformerConfig,
    tokens: jnp.ndarray,  # [B, T] int32
    positions: jnp.ndarray,  # [B, T] int32 — per-sequence positions (for RoPE)
    segment_ids: Optional[jnp.ndarray] = None,  # [B, T], 0 = pad (packed mode)
    kv_cache: Optional[Dict[str, jnp.ndarray]] = None,  # decode mode
    cache_write_index: Optional[jnp.ndarray] = None,
    kv_valid: Optional[jnp.ndarray] = None,
    attn_impl: str = "auto",
    remat: bool = False,  # rematerialize each layer in the backward pass
    return_kv: bool = True,  # False in training: don't stack per-layer K/V
) -> Tuple[jnp.ndarray, Optional[Dict[str, jnp.ndarray]]]:
    """Returns (output, kv) where output is logits [B, T, V] (or values [B, T]
    for critics) and kv stacks per-layer keys/values [n_layers, B, S, Hkv, Dh]
    (S = T in packed mode, the cache length in decode mode).

    Packed mode: ``segment_ids`` given, no cache — block-causal attention.
    Decode mode: ``kv_cache`` given — T is the new-token count (typically 1),
    cache slots are written at ``cache_write_index`` and attention runs over
    ``kv_valid`` cache slots.
    """
    decode = kv_cache is not None
    h = constrain(params["embedding"][tokens], "hidden" if not decode else "hidden_decode")
    cos, sin = rope_tables(positions, cfg.head_dim, cfg.rotary_base)
    layer_params = params["layers"]

    def body(h, xs):
        if decode:
            lp, (kc, vc) = xs
            h2, (kc2, vc2) = _block(
                cfg, h, lp, cos, sin, None, None, (kc, vc),
                cache_write_index, kv_valid, attn_impl,
            )
            return h2, (kc2, vc2)
        lp = xs
        h2, kv = _block(
            cfg, h, lp, cos, sin, segment_ids, positions,
            None, None, None, attn_impl,
        )
        return h2, (kv if return_kv else None)

    if remat and not decode:
        # HBM-for-FLOPs trade (the reference relies on Megatron activation
        # checkpointing; here it is one jax.checkpoint over the scan body).
        body = jax.checkpoint(body)
    if decode:
        h, (ks, vs) = jax.lax.scan(
            body, h, (layer_params, (kv_cache["k"], kv_cache["v"]))
        )
    elif return_kv:
        h, (ks, vs) = jax.lax.scan(body, h, layer_params)
    else:
        h, _ = jax.lax.scan(body, h, layer_params)
        ks = vs = None

    h = rms_norm(h, params["final_ln"], cfg.rms_norm_eps)
    lg = "logits" if not decode else "logits_decode"
    if cfg.is_critic:
        out = (h @ params["value_head"])[..., 0]
    elif cfg.tie_word_embeddings:
        out = constrain(h @ params["embedding"].T, lg)
    else:
        out = constrain(h @ params["lm_head"], lg)
    return out, ({"k": ks, "v": vs} if ks is not None else None)


def init_kv_cache(
    cfg: TransformerConfig, batch: int, length: int, dtype=jnp.float32
) -> Dict[str, jnp.ndarray]:
    shape = (cfg.n_layers, batch, length, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def param_count(cfg: TransformerConfig) -> int:
    n, d, f, v = cfg.n_layers, cfg.hidden_dim, cfg.intermediate_dim, cfg.vocab_size
    per_layer = d * (cfg.q_dim + 2 * cfg.kv_dim) + cfg.q_dim * d + 3 * d * f + 2 * d
    head = d * v if not (cfg.tie_word_embeddings or cfg.is_critic) else 0
    return v * d + n * per_layer + d + head + (d if cfg.is_critic else 0)
