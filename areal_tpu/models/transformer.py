"""The trainer transformer — pure-pytree, scan-over-layers, GSPMD-ready.

Replaces the reference's ReaLModel (``realhf/impl/model/nn/real_llm_api.py:100``
+ ``real_llm_base.py``: VocabPositionEmbedding, ReaLModelBlock×L, OutputHead)
with an idiomatic-JAX design:

 - Parameters are a plain pytree with **layers stacked on a leading axis**, so
   the forward pass is one ``lax.scan`` over layers — constant compile time in
   depth, and pipeline parallelism can partition the stacked axis.
 - Batches are fixed-shape ``[B, L]`` document-packed with segment ids
   (0 = pad) instead of 1-D ragged varlen — static shapes for XLA.
 - No module classes: ``init_params(cfg, key)`` + ``forward(params, cfg, ...)``
   are pure functions; sharding is applied externally as a PartitionSpec tree
   of the same structure (areal_tpu/parallel/sharding.py).

Supports GQA, RoPE (HF llama-style rotate-half), RMSNorm, gated-SiLU MLP,
optional qk-norm (qwen3), optional attention biases (qwen2), tied embeddings,
critic (scalar) head, and a KV-cache decode mode.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.models.config import TransformerConfig
from areal_tpu.ops.attention import decode_attention, packed_attention
from areal_tpu.parallel.sharding import constrain, current_mesh

Params = Dict[str, Any]


# ---------------- init ----------------

def init_params(cfg: TransformerConfig, key: jax.Array) -> Params:
    dtype = jnp.dtype(cfg.dtype)
    n, d, dh = cfg.n_layers, cfg.hidden_dim, cfg.head_dim
    qd, kvd, f = cfg.q_dim, cfg.kv_dim, cfg.intermediate_dim
    keys = jax.random.split(key, 16)

    def nrm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape) * scale).astype(dtype)

    layers: Dict[str, jnp.ndarray] = {
        "ln1": jnp.ones((n, d), dtype),
        "ln2": jnp.ones((n, d), dtype),
        "wq": nrm(keys[0], (n, d, qd)),
        "wk": nrm(keys[1], (n, d, kvd)),
        "wv": nrm(keys[2], (n, d, kvd)),
        "wo": nrm(keys[3], (n, qd, d)),
    }
    if cfg.moe is not None:
        from areal_tpu.models import moe as moemod

        layers.update(moemod.init_moe_params(cfg, keys[4], dtype))
    elif cfg.mlp_type == "plain":
        layers.update({
            "w_up": nrm(keys[5], (n, d, f)),
            "w_down": nrm(keys[6], (n, f, d)),
            "b_up": jnp.zeros((n, f), dtype),
            "b_down": jnp.zeros((n, d), dtype),
        })
    else:
        layers.update({
            "w_gate": nrm(keys[4], (n, d, f)),
            "w_up": nrm(keys[5], (n, d, f)),
            "w_down": nrm(keys[6], (n, f, d)),
        })
    if cfg.use_attention_bias:
        layers["bq"] = jnp.zeros((n, qd), dtype)
        layers["bk"] = jnp.zeros((n, kvd), dtype)
        layers["bv"] = jnp.zeros((n, kvd), dtype)
    if cfg.use_attn_output_bias:
        layers["bo"] = jnp.zeros((n, d), dtype)
    if cfg.use_qk_norm:
        layers["q_norm"] = jnp.ones((n, dh), dtype)
        layers["k_norm"] = jnp.ones((n, dh), dtype)
    if cfg.norm_type == "layer":
        layers["ln1_b"] = jnp.zeros((n, d), dtype)
        layers["ln2_b"] = jnp.zeros((n, d), dtype)

    params: Params = {
        "embedding": nrm(keys[7], (cfg.vocab_size, d)),
        "layers": layers,
        "final_ln": jnp.ones((d,), dtype),
    }
    if cfg.norm_type == "layer":
        params["final_ln_b"] = jnp.zeros((d,), dtype)
    if cfg.pos_embedding == "learned":
        assert cfg.max_position_embeddings, (
            "learned position embeddings need max_position_embeddings"
        )
        params["pos_embedding"] = nrm(
            keys[9], (cfg.max_position_embeddings, d)
        )
    if cfg.is_critic:
        params["value_head"] = nrm(keys[8], (d, 1))
    elif not cfg.tie_word_embeddings:
        params["lm_head"] = nrm(keys[8], (d, cfg.vocab_size))
    return params


# ---------------- primitives ----------------

def rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (w * (x32 * jax.lax.rsqrt(var + eps)).astype(dt)).astype(dt)


def layer_norm(
    x: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray, eps: float
) -> jnp.ndarray:
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return (w * ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(dt) + b).astype(dt)


def _norm(cfg: TransformerConfig, x, lp, key: str) -> jnp.ndarray:
    if cfg.norm_type == "layer":
        return layer_norm(x, lp[key], lp[key + "_b"], cfg.rms_norm_eps)
    return rms_norm(x, lp[key], cfg.rms_norm_eps)


_ACTIVATIONS = {
    "silu": jax.nn.silu,
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
}


def rope_tables(
    positions: jnp.ndarray, head_dim: int, base: float
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin [..., head_dim] for HF-style rotate-half RoPE."""
    inv_freq = 1.0 / (
        base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., dh/2]
    emb = jnp.concatenate([angles, angles], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [B, T, H, Dh]; cos/sin: [B, T, Dh]."""
    c = cos[:, :, None, :].astype(x.dtype)
    s = sin[:, :, None, :].astype(x.dtype)
    half = x.shape[-1] // 2
    rot = jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)
    return x * c + rot * s


# ---------------- one block ----------------

def _block(
    cfg: TransformerConfig,
    h: jnp.ndarray,  # [B, T, D]
    lp: Dict[str, jnp.ndarray],  # this layer's params (leading axis sliced away)
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    segment_ids: Optional[jnp.ndarray],
    positions: Optional[jnp.ndarray],
    cache_kv: Optional[Tuple[jnp.ndarray, jnp.ndarray]],  # ([B,S,Hkv,Dh], ...)
    cache_write_index: Optional[jnp.ndarray],
    kv_valid: Optional[jnp.ndarray],
    attn_impl: str,
    allow_ring: bool = True,
    ring_ctx=None,  # ring.RingCtx — already inside a manual sp region (PP∘SP)
    rng: Optional[jnp.ndarray] = None,  # per-layer key for MoE router jitter
    allow_ep: bool = True,  # False inside manual regions (pipeline stages)
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray], Optional[Dict[str, jnp.ndarray]]]:
    B, T, D = h.shape
    dh = cfg.head_dim

    x = _norm(cfg, h, lp, "ln1")
    q = x @ lp["wq"]
    k = x @ lp["wk"]
    v = x @ lp["wv"]
    if "bq" in lp:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(B, T, cfg.n_q_heads, dh)
    k = k.reshape(B, T, cfg.n_kv_heads, dh)
    v = v.reshape(B, T, cfg.n_kv_heads, dh)
    if cfg.use_qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    if cfg.pos_embedding == "rope":
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

    if cache_kv is None:
        from areal_tpu.parallel import ring as ring_mod

        mesh = current_mesh()
        # Ring attention needs shard_map-divisible shapes; shapes that
        # don't divide (e.g. generate()'s unbucketed batch dim) keep the
        # tolerant GSPMD path.
        use_ring = (
            allow_ring
            and segment_ids is not None
            and ring_mod.ring_eligible(mesh, cfg, B, T)
        )
        if allow_ring and ring_ctx is not None:
            # Already inside a manual region over the ring axis (the PP∘SP
            # pipeline stages): run the local ring body directly — a
            # nested shard_map would be rejected there.
            attn = ring_mod.ring_attention_inline(q, k, v, segment_ids,
                                                  ring_ctx)
        elif use_ring:
            # Sequence dim sharded → context-parallel ring attention.
            attn = ring_mod.ring_attention(q, k, v, segment_ids, mesh)
        else:
            attn = packed_attention(
                q, k, v, segment_ids, segment_ids,
                q_positions=positions, kv_positions=positions,
                causal=True, sliding_window=cfg.sliding_window, impl=attn_impl,
            )
        new_kv = (k, v)
    else:
        k_cache, v_cache = cache_kv
        if getattr(cache_write_index, "ndim", 0) == 1:
            # Per-row write slots (continuous batching: rows of the batch
            # sit at different sequence lengths).
            rows = jnp.arange(B)
            if T == 1:
                k_cache = k_cache.at[rows, cache_write_index].set(k[:, 0])
                v_cache = v_cache.at[rows, cache_write_index].set(v[:, 0])
            else:
                # Multi-token extension (prefix seeding): row b's T new
                # tokens land in slots cache_write_index[b] .. +T.
                idx = cache_write_index[:, None] + jnp.arange(T)[None, :]
                k_cache = k_cache.at[rows[:, None], idx].set(k)
                v_cache = v_cache.at[rows[:, None], idx].set(v)
        else:
            k_cache = jax.lax.dynamic_update_slice_in_dim(
                k_cache, k, cache_write_index, axis=1
            )
            v_cache = jax.lax.dynamic_update_slice_in_dim(
                v_cache, v, cache_write_index, axis=1
            )
        attn = decode_attention(q, k_cache, v_cache, kv_valid)
        new_kv = (k_cache, v_cache)

    hid = "hidden" if cache_kv is None else "hidden_decode"
    attn = attn.reshape(B, T, cfg.q_dim) @ lp["wo"]
    if "bo" in lp:
        attn = attn + lp["bo"]
    h = constrain(h + attn, hid)

    x = _norm(cfg, h, lp, "ln2")
    aux = None
    act = _ACTIVATIONS[cfg.hidden_act]
    if cfg.moe is not None:
        from areal_tpu.models import moe as moemod

        # Expert-parallel all-to-all path: only from GSPMD-auto regions
        # (a pipeline stage is already manual — nested shard_map is
        # rejected there; GSPMD still handles its ep-sharded weights) and
        # only for shard_map-divisible shapes; decode keeps the tolerant
        # single-shard paths (generation never expert-parallels,
        # api/cli_args.validate_config rejects it).
        ep_mesh = current_mesh() if (
            allow_ep and ring_ctx is None and cache_kv is None
        ) else None
        if ep_mesh is not None and not moemod.ep_eligible(
                ep_mesh, cfg.moe, B, T):
            ep_mesh = None
        mlp, aux = moemod.moe_mlp(
            x, lp, cfg.moe, rng=rng,
            mask=(segment_ids > 0) if segment_ids is not None else None,
            mesh=ep_mesh,
        )
    elif cfg.mlp_type == "plain":
        mlp = act(x @ lp["w_up"] + lp["b_up"]) @ lp["w_down"] + lp["b_down"]
    else:
        mlp = (act(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
    return constrain(h + mlp, hid), new_kv, aux


# ---------------- layer-stack application ----------------

def apply_layer_stack(
    cfg: TransformerConfig,
    h: jnp.ndarray,  # [B, T, D]
    layer_params: Dict[str, jnp.ndarray],  # stacked [L, ...] (any L)
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    segment_ids: Optional[jnp.ndarray],
    positions: Optional[jnp.ndarray],
    attn_impl: str = "auto",
    remat=False,
    allow_ring: bool = True,
    ring_ctx=None,  # ring.RingCtx when inside a manual sp region (PP∘SP)
    rng: Optional[jnp.ndarray] = None,
    allow_ep: bool = True,  # False inside manual regions (pipeline stages)
    unroll: bool = False,  # python loop over layers instead of lax.scan
):
    """Run a stacked layer dict over ``h`` via lax.scan (packed mode, no KV
    out). Returns (h, aux) where aux stacks per-layer MoE scalars ({} for
    dense). Shared by the GSPMD scan path and the pipeline-parallel stages
    (parallel/pipeline.py, which passes each stage's LOCAL slice — plus a
    ``ring_ctx`` under PP∘SP so attention rings inside the stage).

    ``remat``: False | True/"full" (recompute the whole layer in backward)
    | "dots" (save matmul outputs, recompute elementwise/norm/cast —
    near-free recompute, releases the non-GEMM residuals).

    ``rng``: base key for MoE router input jitter — split per layer and
    scanned alongside the params so each layer perturbs independently.
    ``rng=None`` keeps the original scan body (bit-identical off path).

    ``unroll``: replace the layer scan with a python loop. The 1F1B
    pipeline stages set this for grouped-dispatch MoE: on jax 0.4.x the
    transpose of this scan, nested inside the 1F1B backward's step scan
    in a shard_map manual region, silently produces wrong cotangents for
    the sort/gather ops of the grouped path (einsum dispatch and the
    GSPMD non-pipelined path are unaffected; parallel/pipeline.py
    _make_stage_fn has the full story). Stages hold n_layers/pp layers,
    so the jaxpr growth is bounded and small."""

    if unroll:
        n_layers = jax.tree_util.tree_leaves(layer_params)[0].shape[0]
        layer_keys = (jax.random.split(rng, n_layers)
                      if rng is not None else None)

        def body_i(h, lp, key):
            h2, _, aux = _block(
                cfg, h, lp, cos, sin, segment_ids, positions,
                None, None, None, attn_impl, allow_ring=allow_ring,
                ring_ctx=ring_ctx, rng=key, allow_ep=allow_ep,
            )
            return h2, aux

        body_i = _maybe_checkpoint(body_i, remat)
        auxes = []
        for i in range(n_layers):
            lp_i = jax.tree_util.tree_map(lambda a: a[i], layer_params)
            h, aux = body_i(
                h, lp_i, layer_keys[i] if layer_keys is not None else None
            )
            auxes.append(aux)
        if auxes and auxes[0] is not None:
            aux = jax.tree_util.tree_map(lambda *a: jnp.stack(a), *auxes)
        else:
            aux = {}
        return h, aux

    if rng is not None:
        n_layers = jax.tree_util.tree_leaves(layer_params)[0].shape[0]
        layer_keys = jax.random.split(rng, n_layers)

        def body(h, xs):
            lp, key = xs
            h2, _, aux = _block(
                cfg, h, lp, cos, sin, segment_ids, positions,
                None, None, None, attn_impl, allow_ring=allow_ring,
                ring_ctx=ring_ctx, rng=key, allow_ep=allow_ep,
            )
            return h2, aux

        body = _maybe_checkpoint(body, remat)
        h, aux = jax.lax.scan(body, h, (layer_params, layer_keys))
        return h, (aux if aux is not None else {})

    def body(h, lp):
        h2, _, aux = _block(
            cfg, h, lp, cos, sin, segment_ids, positions,
            None, None, None, attn_impl, allow_ring=allow_ring,
            ring_ctx=ring_ctx, allow_ep=allow_ep,
        )
        return h2, aux

    body = _maybe_checkpoint(body, remat)
    h, aux = jax.lax.scan(body, h, layer_params)
    return h, (aux if aux is not None else {})


def _maybe_checkpoint(body, remat):
    if not remat:
        return body
    if remat == "dots":
        return jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    return jax.checkpoint(body)


# ---------------- forward ----------------

def forward(
    params: Params,
    cfg: TransformerConfig,
    tokens: jnp.ndarray,  # [B, T] int32
    positions: jnp.ndarray,  # [B, T] int32 — per-sequence positions (for RoPE)
    segment_ids: Optional[jnp.ndarray] = None,  # [B, T], 0 = pad (packed mode)
    kv_cache: Optional[Dict[str, jnp.ndarray]] = None,  # decode mode
    cache_write_index: Optional[jnp.ndarray] = None,
    kv_valid: Optional[jnp.ndarray] = None,
    attn_impl: str = "auto",
    remat: bool = False,  # rematerialize each layer in the backward pass
    return_kv: bool = True,  # False in training: don't stack per-layer K/V
    return_aux: bool = False,  # also return MoE aux losses (layer means)
    pp_microbatches: Optional[int] = None,  # pipeline depth (None = auto)
    return_hidden: bool = False,  # skip the head; return final hidden
    rng: Optional[jnp.ndarray] = None,  # MoE router-jitter key (train only)
):
    """Returns (output, kv) — or (output, kv, aux) when ``return_aux`` —
    where output is logits [B, T, V] (or values [B, T] for critics) and kv
    stacks per-layer keys/values [n_layers, B, S, Hkv, Dh] (S = T in packed
    mode, the cache length in decode mode). ``aux`` is a dict of MoE
    balancing scalars averaged over layers ({} for dense models).

    Packed mode: ``segment_ids`` given, no cache — block-causal attention.
    Decode mode: ``kv_cache`` given — T is the new-token count (typically 1),
    cache slots are written at ``cache_write_index`` and attention runs over
    ``kv_valid`` cache slots.
    """
    decode = kv_cache is not None
    h = params["embedding"][tokens]
    if cfg.scale_embeddings:  # gemma normalizer
        h = h * jnp.asarray(cfg.hidden_dim ** 0.5, h.dtype)
    if cfg.pos_embedding == "learned":
        h = h + params["pos_embedding"][positions]
    h = constrain(h, "hidden" if not decode else "hidden_decode")
    cos, sin = rope_tables(positions, cfg.head_dim, cfg.rotary_base)
    layer_params = params["layers"]

    if decode:
        def body(h, xs):
            lp, (kc, vc) = xs
            h2, (kc2, vc2), aux = _block(
                cfg, h, lp, cos, sin, None, None, (kc, vc),
                cache_write_index, kv_valid, attn_impl,
            )
            return h2, ((kc2, vc2), aux)

        h, ((ks, vs), aux) = jax.lax.scan(
            body, h, (layer_params, (kv_cache["k"], kv_cache["v"]))
        )
    elif return_kv:
        def body(h, lp):
            h2, kv, aux = _block(
                cfg, h, lp, cos, sin, segment_ids, positions,
                None, None, None, attn_impl,
            )
            return h2, (kv, aux)

        body = _maybe_checkpoint(body, remat)
        h, ((ks, vs), aux) = jax.lax.scan(body, h, layer_params)
    else:
        ks = vs = None
        from areal_tpu.parallel import pipeline as pp_mod

        mesh = current_mesh()
        n_micro = pp_mod.pick_pp_microbatches(
            mesh, cfg, h.shape[0], pp_microbatches, seq_len=h.shape[1]
        )
        if n_micro is not None:
            # Real pipeline parallelism: micro-batches stream through the
            # pp stages via collective permute (parallel/pipeline.py).
            h, aux = pp_mod.pipeline_apply_layers(
                cfg, layer_params, h, cos, sin, segment_ids, positions,
                mesh, n_micro, attn_impl=attn_impl, remat=remat,
            )
        else:
            # remat note: HBM-for-FLOPs trade (the reference relies on
            # Megatron activation checkpointing; here one jax.checkpoint
            # over the scan body).
            # Router jitter rides only this (training) path: decode and
            # KV-returning forwards are inference, where jitter is off by
            # construction; the pipeline path drops it rather than thread
            # keys through collective permutes.
            h, aux = apply_layer_stack(
                cfg, h, layer_params, cos, sin, segment_ids, positions,
                attn_impl=attn_impl, remat=remat, rng=rng,
            )
    # aux ys are stacked per-layer on a leading [n_layers] axis (already
    # reduced in the pipeline path). The optimized total SUMS over layers
    # (the reference's aux tracker accumulates every MoE layer's loss);
    # the diagnostic stats are reported as layer means — vector stats
    # (the [E] expert_load histogram) mean over the layer axis only.
    aux = (
        {
            k: (jnp.sum(v) if k == "aux_total"
                else jnp.mean(v, axis=0) if v.ndim > 1
                else jnp.mean(v))
            for k, v in aux.items()
        }
        if aux is not None
        else {}
    )

    if cfg.norm_type == "layer":
        h = layer_norm(
            h, params["final_ln"], params["final_ln_b"], cfg.rms_norm_eps
        )
    else:
        h = rms_norm(h, params["final_ln"], cfg.rms_norm_eps)
    if return_hidden:
        out = h  # caller applies the head (e.g. chunked-logprob loss)
    else:
        out = apply_head(
            params, cfg, h, "logits" if not decode else "logits_decode"
        )
    kv_out = {"k": ks, "v": vs} if ks is not None else None
    if return_aux:
        return out, kv_out, aux
    return out, kv_out


def apply_head(params: Params, cfg: TransformerConfig, h, lg="logits"):
    """Final-hidden → logits (or values). Shared by forward and the
    engine's chunked-logprob path (backend/jax_train.py) so the head math
    has exactly one definition."""
    if cfg.is_critic:
        return (h @ params["value_head"])[..., 0]
    if cfg.tie_word_embeddings:
        return constrain(h @ params["embedding"].T, lg)
    return constrain(h @ params["lm_head"], lg)


def init_kv_cache(
    cfg: TransformerConfig, batch: int, length: int, dtype=jnp.float32
) -> Dict[str, jnp.ndarray]:
    shape = (cfg.n_layers, batch, length, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def param_count(cfg: TransformerConfig) -> int:
    n, d, f, v = cfg.n_layers, cfg.hidden_dim, cfg.intermediate_dim, cfg.vocab_size
    attn = d * (cfg.q_dim + 2 * cfg.kv_dim) + cfg.q_dim * d
    if cfg.moe is not None:
        fr = cfg.moe.routed_intermediate_dim or f
        mlp = cfg.moe.num_experts * 3 * d * fr + d * cfg.moe.num_experts
        if cfg.moe.shared_intermediate_dim:
            mlp += 3 * d * cfg.moe.shared_intermediate_dim
    elif cfg.mlp_type == "plain":
        mlp = 2 * d * f
    else:
        mlp = 3 * d * f
    per_layer = attn + mlp + 2 * d
    head = d * v if not (cfg.tie_word_embeddings or cfg.is_critic) else 0
    pos = (
        cfg.max_position_embeddings * d
        if cfg.pos_embedding == "learned"
        else 0
    )
    return v * d + n * per_layer + d + head + pos + (d if cfg.is_critic else 0)


def activated_param_count(cfg: TransformerConfig) -> int:
    """Parameters a token actually touches in one forward: for MoE, only
    ``top_k`` of the ``num_experts`` routed FFNs (plus router and shared
    expert) — the honest N for 6NT-style FLOPs/MFU accounting
    (base/monitor.py); equals :func:`param_count` for dense models."""
    if cfg.moe is None:
        return param_count(cfg)
    n, d, f = cfg.n_layers, cfg.hidden_dim, cfg.intermediate_dim
    fr = cfg.moe.routed_intermediate_dim or f
    total_mlp = cfg.moe.num_experts * 3 * d * fr
    active_mlp = cfg.moe.top_k * 3 * d * fr
    return param_count(cfg) - n * (total_mlp - active_mlp)
