"""Mixture-of-Experts layer — TPU-first (GShard-style dense dispatch).

Parity target: ``realhf/impl/model/modules/moe/`` — ``TopKRouter``
(router.py:24; aux-loss load balancing :78, z-loss :146, input jitter
:170), token dispatcher (token_dispatcher.py: permute + capacity drop) and
``GroupedMLP`` (experts.py:99, grouped_gemm). TPU-first differences:

 - no permute/unpermute or grouped-GEMM library: tokens are dispatched to
   fixed-capacity expert buffers with one-hot einsums (GShard/Switch
   layout) so every op is a static-shape batched matmul on the MXU;
 - expert parallelism = sharding the expert axis of the stacked weights
   over the "fsdp" mesh axis (parallel/sharding.py) — GSPMD inserts the
   all-to-alls the reference's dispatcher would hand-code (the reference
   itself ships with ep_size=1 only);
 - sinkhorn routing is not implemented (the reference defaults to aux-loss
   balancing for its shipped configs).

Weights per layer (stacked on the leading layer axis by the transformer):
``router [D, E]``, ``e_gate/e_up [E, D, F]``, ``e_down [E, F, D]``, and an
optional always-on shared expert ``s_gate/s_up [D, Fs]``, ``s_down [Fs, D]``.
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.models.config import MoEConfig


def capacity(n_tokens: int, moe: MoEConfig) -> int:
    c = math.ceil(moe.top_k * n_tokens * moe.capacity_factor / moe.num_experts)
    return max(int(c), 1)


def moe_mlp(
    x: jnp.ndarray,  # [B, T, D]
    lp: Dict[str, jnp.ndarray],  # this layer's params
    moe: MoEConfig,
    rng: jnp.ndarray = None,  # jitter noise (training only); None = off
    mask: jnp.ndarray = None,  # [B, T] bool/int — True for real tokens
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Returns (output [B, T, D], aux dict with load_balance_loss / z_loss /
    aux_total / dropped_frac).

    ``mask`` excludes grid-padding tokens from routing entirely: they take
    no expert-capacity slots and do not enter the balancing/z statistics
    (the reference runs on unpadded packed tokens, so padding never exists
    there; with [B, T] grids it must be masked out explicitly)."""
    B, T, D = x.shape
    E, k = moe.num_experts, moe.top_k
    N = B * T
    xf = x.reshape(N, D)
    valid = (
        jnp.ones((N,), jnp.float32) if mask is None
        else mask.reshape(N).astype(jnp.float32)
    )
    n_valid = jnp.maximum(jnp.sum(valid), 1.0)

    router_in = xf
    if moe.input_jitter_eps > 0 and rng is not None:
        # Router input jitter (reference router.py:170): train steps thread
        # a per-micro-batch key down through transformer.forward(rng=...);
        # inference passes rng=None and routes on the clean input — jitter
        # is a training-only regulariser, never a serving behaviour.
        eps = moe.input_jitter_eps
        router_in = xf * jax.random.uniform(
            rng, xf.shape, minval=1 - eps, maxval=1 + eps, dtype=xf.dtype
        )
    logits = (router_in @ lp["router"]).astype(jnp.float32)  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, k)  # [N, k]
    if moe.norm_topk_prob:
        top_p = top_p / jnp.maximum(
            jnp.sum(top_p, axis=-1, keepdims=True), 1e-9
        )

    # ---- balancing losses (reference router.py:78,146) ----
    # f_e: fraction of (real) tokens routed to expert e; P_e: mean prob.
    onehot = jax.nn.one_hot(top_i, E, dtype=jnp.float32)  # [N, k, E]
    onehot = onehot * valid[:, None, None]  # padding routes nowhere
    routed = jnp.sum(onehot, axis=1)  # [N, E] 0/1 counts
    f = jnp.sum(routed, axis=0) / n_valid * E / k
    P = jnp.sum(probs * valid[:, None], axis=0) / n_valid
    load_balance = jnp.sum(f * P)
    z = jnp.sum((jax.nn.logsumexp(logits, axis=-1) ** 2) * valid) / n_valid
    aux_total = moe.aux_loss_coeff * load_balance + moe.z_loss_coeff * z

    # ---- capacity dispatch ----
    C = capacity(N, moe)
    # position of each (token, choice) within its expert buffer: priority is
    # token order then choice order (same as the reference's dispatcher);
    # padding tokens have zeroed onehot and consume no slots.
    flat_oh = onehot.reshape(N * k, E)
    pos = (jnp.cumsum(flat_oh, axis=0) - flat_oh).reshape(N, k, E)
    pos = jnp.sum(pos * onehot, axis=-1)  # [N, k] slot per choice
    keep = (pos < C) & (jnp.sum(onehot, axis=-1) > 0)
    gate = top_p * keep  # dropped tokens contribute nothing
    dropped_frac = 1.0 - jnp.sum(keep) / jnp.maximum(n_valid * k, 1.0)

    # combine [N, E, C] — sparse; also serves (as booleans) for dispatch.
    slot_oh = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=jnp.float32)
    combine = jnp.einsum("nke,nkc,nk->nec", onehot, slot_oh, gate)
    dispatch = (combine > 0).astype(x.dtype)

    xe = jnp.einsum("nec,nd->ecd", dispatch, xf)  # [E, C, D]
    h = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", xe, lp["e_gate"])
    ) * jnp.einsum("ecd,edf->ecf", xe, lp["e_up"])
    ye = jnp.einsum("ecf,efd->ecd", h, lp["e_down"])  # [E, C, D]
    y = jnp.einsum("nec,ecd->nd", combine.astype(ye.dtype), ye)

    if "s_gate" in lp:  # always-on shared expert (qwen-moe)
        y = y + (jax.nn.silu(xf @ lp["s_gate"]) * (xf @ lp["s_up"])) @ lp["s_down"]

    aux = {
        "aux_total": aux_total,
        "load_balance_loss": load_balance,
        "z_loss": z,
        "dropped_frac": dropped_frac,
    }
    return y.reshape(B, T, D).astype(x.dtype), aux


def init_moe_params(cfg, key: jnp.ndarray, dtype) -> Dict[str, jnp.ndarray]:
    """Per-layer-stacked MoE weights ([n_layers, ...])."""
    moe = cfg.moe
    n, d = cfg.n_layers, cfg.hidden_dim
    f = moe.routed_intermediate_dim or cfg.intermediate_dim
    E = moe.num_experts
    ks = jax.random.split(key, 8)

    def nrm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape) * scale).astype(dtype)

    out = {
        "router": nrm(ks[0], (n, d, E)),
        "e_gate": nrm(ks[1], (n, E, d, f)),
        "e_up": nrm(ks[2], (n, E, d, f)),
        "e_down": nrm(ks[3], (n, E, f, d)),
    }
    if moe.shared_intermediate_dim:
        fs = moe.shared_intermediate_dim
        out["s_gate"] = nrm(ks[4], (n, d, fs))
        out["s_up"] = nrm(ks[5], (n, d, fs))
        out["s_down"] = nrm(ks[6], (n, fs, d))
    return out
