"""Mixture-of-Experts layer — TPU-first, sort-based grouped expert compute.

Parity target: ``realhf/impl/model/modules/moe/`` — ``TopKRouter``
(router.py:24; aux-loss load balancing :78, z-loss :146, input jitter
:170), token dispatcher (token_dispatcher.py: permute + capacity drop) and
``GroupedMLP`` (experts.py:99, grouped_gemm). TPU-first differences:

 - the production dispatch is **grouped** (MegaBlocks/dropless-MoE style):
   flatten (token, choice) entries, stable-argsort by expert id, and run
   the expert MLPs as grouped GEMMs over contiguous per-expert segments
   via ``jax.lax.ragged_dot`` (sorted-segment fallback on jax versions
   without it). Expert FLOPs/HBM scale with the tokens actually routed —
   no dense ``[E, C]`` capacity buffers on the compute path;
 - the original GShard one-hot-einsum dispatch is kept VERBATIM as the
   parity ORACLE behind ``AREAL_MOE_DISPATCH=einsum`` (same contract as
   ``AREAL_RING_SCHEDULE`` / ``AREAL_PP_SCHEDULE``). Both paths share the
   router/aux code and implement the identical Switch-style capacity/drop
   policy (priority = token order then choice order), so outputs and
   grads agree including dropped tokens and padding masks;
 - expert parallelism is a REAL mesh axis ("ep", parallel/mesh.py):
   expert weights shard over it (parallel/sharding.py) and
   :func:`moe_mlp` given a mesh with ep > 1 runs an all-to-all path —
   tokens dispatch into per-source capacity buffers, all-to-all to the
   shard owning their expert, batched expert GEMMs, and all-to-all back
   (GShard §3.2). Capacity/drop applies at the SHARD boundary (per-source
   ``capacity(N/ep)``), so the a2a payload is static-shape; the reference
   itself ships with ep_size=1 only;
 - sinkhorn routing is not implemented (the reference defaults to aux-loss
   balancing for its shipped configs).

Weights per layer (stacked on the leading layer axis by the transformer):
``router [D, E]``, ``e_gate/e_up [E, D, F]``, ``e_down [E, F, D]``, and an
optional always-on shared expert ``s_gate/s_up [D, Fs]``, ``s_down [Fs, D]``.

Routing-health aux (exported as ``train/moe_*`` telemetry by
backend/jax_train.py; docs/observability.md): ``dropped_frac``,
``expert_load`` ([E] fraction of routed assignments per expert, pre-drop)
and ``expert_load_ratio`` (max/mean of that — 1.0 is perfectly balanced,
→ E is total collapse; the sentinel ``expert_collapse`` rule baselines it).
"""

from __future__ import annotations

import math
import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from areal_tpu.models.config import MoEConfig

DISPATCH_METHODS = ("grouped", "einsum")


def resolve_dispatch(method: Optional[str] = None) -> str:
    """The dispatch actually run: explicit arg > ``AREAL_MOE_DISPATCH`` >
    "grouped". "einsum" is the GShard one-hot oracle kept for parity."""
    if method is None:
        method = os.environ.get("AREAL_MOE_DISPATCH", "").strip() or "grouped"
    if method not in DISPATCH_METHODS:
        raise ValueError(
            f"unknown MoE dispatch {method!r} (one of {DISPATCH_METHODS})"
        )
    return method


def capacity(n_tokens: int, moe: MoEConfig) -> int:
    c = math.ceil(moe.top_k * n_tokens * moe.capacity_factor / moe.num_experts)
    return max(int(c), 1)


def ep_eligible(mesh: Optional[Mesh], moe: Optional[MoEConfig],
                batch: int, seq_len: int = 1) -> bool:
    """Whether the all-to-all expert-parallel path can run: a real "ep"
    mesh axis, experts dividing over it, and batch/seq dims that divide
    their mesh axes (the full-manual shard_map needs exact blocks — e.g.
    generate()'s unbucketed batch dim does not divide, mirroring
    ring_eligible)."""
    if mesh is None or moe is None:
        return False
    ep = dict(mesh.shape).get("ep", 1)
    if ep <= 1 or moe.num_experts % ep:
        return False
    return (
        batch % (mesh.shape["dp"] * mesh.shape["fsdp"] * ep) == 0
        and seq_len % mesh.shape["sp"] == 0
    )


# ---------------- router + balancing stats (shared by all paths) ----------------

def _routing(
    xf: jnp.ndarray,  # [N, D]
    lp: Dict[str, jnp.ndarray],
    moe: MoEConfig,
    rng: Optional[jnp.ndarray],
    valid: jnp.ndarray,  # [N] float 0/1
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Returns (top_p [N, k] post-norm gates, top_i [N, k], onehot
    [N, k, E] with padding rows zeroed, aux dict sans dropped_frac)."""
    E, k = moe.num_experts, moe.top_k
    n_valid = jnp.maximum(jnp.sum(valid), 1.0)

    router_in = xf
    if moe.input_jitter_eps > 0 and rng is not None:
        # Router input jitter (reference router.py:170): train steps thread
        # a per-micro-batch key down through transformer.forward(rng=...);
        # inference passes rng=None and routes on the clean input — jitter
        # is a training-only regulariser, never a serving behaviour.
        eps = moe.input_jitter_eps
        router_in = xf * jax.random.uniform(
            rng, xf.shape, minval=1 - eps, maxval=1 + eps, dtype=xf.dtype
        )
    logits = (router_in @ lp["router"]).astype(jnp.float32)  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, k)  # [N, k]
    if moe.norm_topk_prob:
        top_p = top_p / jnp.maximum(
            jnp.sum(top_p, axis=-1, keepdims=True), 1e-9
        )

    # ---- balancing losses (reference router.py:78,146) ----
    # f_e: fraction of (real) tokens routed to expert e; P_e: mean prob.
    onehot = jax.nn.one_hot(top_i, E, dtype=jnp.float32)  # [N, k, E]
    onehot = onehot * valid[:, None, None]  # padding routes nowhere
    routed = jnp.sum(onehot, axis=1)  # [N, E] 0/1 counts
    counts_e = jnp.sum(routed, axis=0)  # [E] routed assignments per expert
    f = counts_e / n_valid * E / k
    Pm = jnp.sum(probs * valid[:, None], axis=0) / n_valid
    load_balance = jnp.sum(f * Pm)
    z = jnp.sum((jax.nn.logsumexp(logits, axis=-1) ** 2) * valid) / n_valid
    aux_total = moe.aux_loss_coeff * load_balance + moe.z_loss_coeff * z

    # Routing-health stats (pre-drop): per-expert share of assignments,
    # and its max/mean ratio (1 = balanced, E = collapse onto one expert).
    expert_load = counts_e / jnp.maximum(n_valid * k, 1.0)  # [E], sums to 1
    load_ratio = jnp.max(expert_load) / jnp.maximum(
        jnp.mean(expert_load), 1e-9
    )
    aux = {
        "aux_total": aux_total,
        "load_balance_loss": load_balance,
        "z_loss": z,
        "expert_load": expert_load,
        "expert_load_ratio": load_ratio,
    }
    return top_p, top_i, onehot, aux


def _capacity_keep(onehot: jnp.ndarray, C: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Switch-style slot assignment: position of each (token, choice)
    within its expert's capacity buffer — priority is token order then
    choice order (same as the reference's dispatcher); padding tokens have
    zeroed onehot and consume no slots. Returns (pos [N, k], keep [N, k])."""
    N, k, E = onehot.shape
    flat_oh = onehot.reshape(N * k, E)
    pos = (jnp.cumsum(flat_oh, axis=0) - flat_oh).reshape(N, k, E)
    pos = jnp.sum(pos * onehot, axis=-1)  # [N, k] slot per choice
    keep = (pos < C) & (jnp.sum(onehot, axis=-1) > 0)
    return pos, keep


def _expert_ffn(xe, gate_w, up_w, down_w):
    """Batched silu-gated expert MLP over [E, rows, D] capacity buffers."""
    h = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", xe, gate_w)
    ) * jnp.einsum("ecd,edf->ecf", xe, up_w)
    return jnp.einsum("ecf,efd->ecd", h, down_w)  # [E, rows, D]


# ---------------- einsum dispatch (GShard oracle) ----------------

def _dispatch_einsum(
    xf: jnp.ndarray,  # [N, D]
    top_p: jnp.ndarray,  # [N, k]
    onehot: jnp.ndarray,  # [N, k, E]
    lp: Dict[str, jnp.ndarray],
    moe: MoEConfig,
    n_valid: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The original one-hot capacity-buffer dispatch — every op a
    static-shape batched matmul, FLOPs/HBM scale with E × capacity.
    Kept as the parity oracle (``AREAL_MOE_DISPATCH=einsum``)."""
    N, D = xf.shape
    k = moe.top_k
    C = capacity(N, moe)
    pos, keep = _capacity_keep(onehot, C)
    gate = top_p * keep  # dropped tokens contribute nothing
    dropped_frac = 1.0 - jnp.sum(keep) / jnp.maximum(n_valid * k, 1.0)

    # combine [N, E, C] — sparse; also serves (as booleans) for dispatch.
    slot_oh = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=jnp.float32)
    combine = jnp.einsum("nke,nkc,nk->nec", onehot, slot_oh, gate)
    dispatch = (combine > 0).astype(xf.dtype)

    xe = jnp.einsum("nec,nd->ecd", dispatch, xf)  # [E, C, D]
    ye = _expert_ffn(xe, lp["e_gate"], lp["e_up"], lp["e_down"])
    y = jnp.einsum("nec,ecd->nd", combine.astype(ye.dtype), ye)
    return y, dropped_frac


# ---------------- grouped dispatch (sorted segments, the default) ----------------

def _grouped_matmul(xs: jnp.ndarray,  # [M, K] rows sorted by group
                    w: jnp.ndarray,  # [G, K, F]
                    group_sizes: jnp.ndarray,  # [G] int32
                    ) -> jnp.ndarray:
    """Grouped GEMM over contiguous row segments: row m multiplies
    ``w[g]`` where m falls in group g's segment. Rows beyond
    ``sum(group_sizes)`` yield zeros (ragged_dot guarantees this; the
    fallback masks them out) — sentinel-sorted padding entries land there.
    """
    if hasattr(jax.lax, "ragged_dot"):
        return jax.lax.ragged_dot(xs, w, group_sizes)
    # Sorted-segment fallback (pre-ragged_dot jax): static unroll over
    # groups with masked dense matmuls — correct, not fast.
    starts = jnp.cumsum(group_sizes) - group_sizes
    ends = starts + group_sizes
    idx = jnp.arange(xs.shape[0])
    out = jnp.zeros((xs.shape[0], w.shape[-1]), dtype=xs.dtype)
    for g in range(w.shape[0]):
        m = ((idx >= starts[g]) & (idx < ends[g])).astype(xs.dtype)
        out = out + (xs * m[:, None]) @ w[g]
    return out


def _dispatch_grouped(
    xf: jnp.ndarray,  # [N, D]
    top_p: jnp.ndarray,  # [N, k]
    top_i: jnp.ndarray,  # [N, k]
    valid: jnp.ndarray,  # [N]
    lp: Dict[str, jnp.ndarray],
    moe: MoEConfig,
    n_valid: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sort-based grouped expert compute: one stable argsort of the
    ``M = N·top_k`` (token, choice) entries by expert id makes each
    expert's rows contiguous, so the expert MLP is three grouped GEMMs
    over ``[M, D]`` instead of one-hot einsums over ``[E, C, D]`` buffers.

    Drop parity with the oracle is structural: a stable sort preserves
    flat (token-major, then choice) order within each expert, so an
    entry's position inside its segment IS the oracle's capacity-slot
    ``pos`` — ``pos >= C`` entries keep their gate zeroed (their GEMM rows
    are computed but contribute nothing, exactly like the oracle's
    unslotted tokens). Padding entries get sentinel id E, sort to the
    tail beyond ``sum(group_sizes)``, and come back as zeros."""
    N, D = xf.shape
    E, k = moe.num_experts, moe.top_k
    M = N * k
    C = capacity(N, moe)

    valid_b = valid.reshape(N, 1) > 0
    eid = jnp.where(valid_b, top_i, E).reshape(M)  # sentinel E = padding
    order = jnp.argsort(eid)  # jnp argsort is stable
    sorted_eid = jnp.take(eid, order)
    counts = jnp.bincount(eid, length=E + 1)  # [E+1], sentinel bin last
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(M) - jnp.take(starts, sorted_eid)  # slot within segment
    keep = (pos < C) & (sorted_eid < E)
    dropped_frac = 1.0 - jnp.sum(keep) / jnp.maximum(n_valid * k, 1.0)
    gate = jnp.take(top_p.reshape(M), order) * keep

    xs = jnp.take(xf, order // k, axis=0)  # [M, D] sorted expert inputs
    group_sizes = counts[:E].astype(jnp.int32)
    h = jax.nn.silu(
        _grouped_matmul(xs, lp["e_gate"], group_sizes)
    ) * _grouped_matmul(xs, lp["e_up"], group_sizes)
    ys = _grouped_matmul(h, lp["e_down"], group_sizes)  # [M, D]
    ys = ys * gate.astype(ys.dtype)[:, None]
    inv = jnp.argsort(order)  # inverse permutation
    y = jnp.sum(jnp.take(ys, inv, axis=0).reshape(N, k, D), axis=1)
    return y, dropped_frac


# ---------------- expert-parallel dispatch (all-to-all over "ep") ----------------

def _dispatch_ep(
    x: jnp.ndarray,  # [B, T, D] global
    top_p: jnp.ndarray,  # [N, k]
    top_i: jnp.ndarray,  # [N, k]
    valid: jnp.ndarray,  # [N]
    lp: Dict[str, jnp.ndarray],
    moe: MoEConfig,
    mesh: Mesh,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """GShard §3.2 expert parallelism over the mesh's "ep" axis: each ep
    shard dispatches its LOCAL tokens into per-destination capacity
    buffers (``capacity(N/ep)`` per source — the drop/pad happens at the
    shard boundary, so the exchange is static-shape), all-to-alls rows to
    the shard owning the expert, runs the batched expert GEMMs on its
    ``E/ep`` local experts × ``ep·C`` rows, and all-to-alls back for the
    local gate-weighted combine.

    Full-manual shard_map (the ring_attention pattern — 0.4.x's partial-
    manual partitioner miscompiles auto axes sharing a dim with manual
    ones): tokens split over DATA_AXES × sp, expert weights over ep with
    their ffn dim over tp (Megatron column→row: the ``e_down`` partial
    sums psum over "tp"); the ZeRO-3 fsdp shard of the weights
    all-gathers at the region boundary, exactly what GSPMD does for the
    dense paths. Numerics match the replicated paths exactly in the
    no-drop regime; under drops the priority is per-source-shard rather
    than global (tested/documented — docs/parallelism.md §Expert
    parallelism)."""
    from areal_tpu.parallel.compat import shard_map
    from areal_tpu.parallel.mesh import DATA_AXES

    B, T, D = x.shape
    E, k = moe.num_experts, moe.top_k
    tok_axes = DATA_AXES + ("sp",)

    def body(xl, gl, il, vl, gate_w, up_w, down_w):
        # Local shapes: xl [B/(dp·fsdp·ep), T/sp, D], gl/il [..., Tl, k],
        # vl [..., Tl]; weights [E/ep, D, F/tp] / [E/ep, F/tp, D].
        Bl, Tl = xl.shape[0], xl.shape[1]
        Nl = Bl * Tl
        xf = xl.reshape(Nl, D)
        vf = vl.reshape(Nl).astype(jnp.float32)
        onehot = jax.nn.one_hot(il.reshape(Nl, k), E, dtype=jnp.float32)
        onehot = onehot * vf[:, None, None]
        C = capacity(Nl, moe)  # per-SOURCE-shard capacity
        pos, keep = _capacity_keep(onehot, C)
        gate = gl.reshape(Nl, k) * keep
        slot_oh = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=jnp.float32)
        combine = jnp.einsum("nke,nkc,nk->nec", onehot, slot_oh, gate)
        dispatch = (combine > 0).astype(xl.dtype)

        xe = jnp.einsum("nec,nd->ecd", dispatch, xf)  # [E, C, D]
        # Ship each destination its experts' rows: [E, C, D] → split the
        # expert axis into ep blocks, concat received by source along the
        # row axis → [E/ep, ep·C, D] (rows grouped by source shard).
        xin = jax.lax.all_to_all(xe, "ep", split_axis=0, concat_axis=1,
                                 tiled=True)
        ye = _expert_ffn(xin, gate_w, up_w, down_w)  # [E/ep, ep·C, D]
        ye = jax.lax.psum(ye, "tp")  # row-parallel e_down partial sums
        # Inverse exchange: row-block s back to source s, concat received
        # by owner along the expert axis → [E, C, D] in global expert order.
        ye = jax.lax.all_to_all(ye, "ep", split_axis=1, concat_axis=0,
                                tiled=True)
        y = jnp.einsum("nec,ecd->nd", combine.astype(ye.dtype), ye)

        kept = jax.lax.psum(jnp.sum(keep.astype(jnp.float32)), tok_axes)
        nv = jax.lax.psum(jnp.sum(vf), tok_axes)
        dropped = 1.0 - kept / jnp.maximum(nv * k, 1.0)
        return y.reshape(Bl, Tl, D), dropped

    tok_spec = P(DATA_AXES, "sp")
    y, dropped_frac = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(DATA_AXES, "sp", None), tok_spec, tok_spec, tok_spec,
                  P("ep", None, "tp"), P("ep", None, "tp"),
                  P("ep", "tp", None)),
        out_specs=(P(DATA_AXES, "sp", None), P()),
    )(
        x,
        top_p.reshape(B, T, k),
        top_i.reshape(B, T, k),
        valid.reshape(B, T),
        lp["e_gate"], lp["e_up"], lp["e_down"],
    )
    return y.reshape(B * T, D), dropped_frac


# ---------------- the layer ----------------

def moe_mlp(
    x: jnp.ndarray,  # [B, T, D]
    lp: Dict[str, jnp.ndarray],  # this layer's params
    moe: MoEConfig,
    rng: jnp.ndarray = None,  # jitter noise (training only); None = off
    mask: jnp.ndarray = None,  # [B, T] bool/int — True for real tokens
    dispatch: Optional[str] = None,  # None → AREAL_MOE_DISPATCH → "grouped"
    mesh: Optional[Mesh] = None,  # a mesh with ep > 1 → all-to-all EP path
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Returns (output [B, T, D], aux dict with load_balance_loss / z_loss /
    aux_total / dropped_frac / expert_load / expert_load_ratio).

    ``mask`` excludes grid-padding tokens from routing entirely: they take
    no expert-capacity slots and do not enter the balancing/z statistics
    (the reference runs on unpadded packed tokens, so padding never exists
    there; with [B, T] grids it must be masked out explicitly).

    ``mesh``: pass the active mesh to take the expert-parallel all-to-all
    path; callers must gate on :func:`ep_eligible` (and must NOT pass a
    mesh from inside an already-manual shard_map region — the pipeline
    stages fall back to the single-shard paths with GSPMD handling the
    ep-sharded weights)."""
    B, T, D = x.shape
    N = B * T
    xf = x.reshape(N, D)
    valid = (
        jnp.ones((N,), jnp.float32) if mask is None
        else mask.reshape(N).astype(jnp.float32)
    )
    n_valid = jnp.maximum(jnp.sum(valid), 1.0)

    top_p, top_i, onehot, aux = _routing(xf, lp, moe, rng, valid)

    if mesh is not None and ep_eligible(mesh, moe, B, T):
        y, dropped_frac = _dispatch_ep(x, top_p, top_i, valid, lp, moe, mesh)
    elif resolve_dispatch(dispatch) == "einsum":
        y, dropped_frac = _dispatch_einsum(xf, top_p, onehot, lp, moe, n_valid)
    else:
        y, dropped_frac = _dispatch_grouped(
            xf, top_p, top_i, valid, lp, moe, n_valid
        )

    if "s_gate" in lp:  # always-on shared expert (qwen-moe)
        y = y + (jax.nn.silu(xf @ lp["s_gate"]) * (xf @ lp["s_up"])) @ lp["s_down"]

    aux = dict(aux)
    aux["dropped_frac"] = dropped_frac
    return y.reshape(B, T, D).astype(x.dtype), aux


def init_moe_params(cfg, key: jnp.ndarray, dtype) -> Dict[str, jnp.ndarray]:
    """Per-layer-stacked MoE weights ([n_layers, ...])."""
    moe = cfg.moe
    n, d = cfg.n_layers, cfg.hidden_dim
    f = moe.routed_intermediate_dim or cfg.intermediate_dim
    E = moe.num_experts
    # One key per weight actually initialized — adding a weight grows the
    # split instead of silently reusing a neighbour's key.
    names = ["router", "e_gate", "e_up", "e_down"]
    if moe.shared_intermediate_dim:
        names += ["s_gate", "s_up", "s_down"]
    ks = dict(zip(names, jax.random.split(key, len(names))))

    def nrm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape) * scale).astype(dtype)

    out = {
        "router": nrm(ks["router"], (n, d, E)),
        "e_gate": nrm(ks["e_gate"], (n, E, d, f)),
        "e_up": nrm(ks["e_up"], (n, E, d, f)),
        "e_down": nrm(ks["e_down"], (n, E, f, d)),
    }
    if moe.shared_intermediate_dim:
        fs = moe.shared_intermediate_dim
        out["s_gate"] = nrm(ks["s_gate"], (n, d, fs))
        out["s_up"] = nrm(ks["s_up"], (n, d, fs))
        out["s_down"] = nrm(ks["s_down"], (n, fs, d))
    return out
