"""Transformer configuration.

Parity target: ``ReaLModelConfig`` (reference realhf/api/core/model_api.py:340)
and the per-family HF conversion registry (realhf/api/from_hf/*.py). Families
are expressed as pure config differences (bias flags, qk-norm, tying), not
separate model classes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Mirrors ReaLMoEConfig (reference model_api.py:294)."""

    num_experts: int = 8
    top_k: int = 2
    # Expert-buffer size multiplier: capacity per expert is
    # ceil(top_k * n_tokens * capacity_factor / num_experts); overflow
    # tokens are dropped (contribute nothing), mirroring the reference's
    # token_dispatcher capacity drop.
    capacity_factor: float = 2.0
    routed_intermediate_dim: Optional[int] = None
    # qwen-moe style always-on shared expert; None = no shared expert
    shared_intermediate_dim: Optional[int] = None
    aux_loss_coeff: float = 1e-3
    z_loss_coeff: float = 0.0
    input_jitter_eps: float = 0.0
    norm_topk_prob: bool = True


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    n_layers: int
    hidden_dim: int
    n_q_heads: int
    n_kv_heads: int
    head_dim: int
    intermediate_dim: int
    vocab_size: int
    rotary_base: float = 10000.0
    rms_norm_eps: float = 1e-6
    use_attention_bias: bool = False  # qwen2: True on qkv
    use_attn_output_bias: bool = False
    use_qk_norm: bool = False  # qwen3
    tie_word_embeddings: bool = False
    is_critic: bool = False  # scalar head instead of lm head
    moe: Optional[MoEConfig] = None
    # sliding window attention (mistral/gemma2); None = full attention
    sliding_window: Optional[int] = None
    # MLP activation: "silu" (llama family), "gelu_tanh" (gemma/gpt2),
    # "gelu" (exact)
    hidden_act: str = "silu"
    # "gated" = SwiGLU/GeGLU (w_gate/w_up/w_down); "plain" = act(x@w_up)@w_down
    # with biases (gpt2)
    mlp_type: str = "gated"
    norm_type: str = "rms"  # "rms" | "layer" (gpt2 LayerNorm with bias)
    # "rope" | "learned" (gpt2 absolute position table)
    pos_embedding: str = "rope"
    max_position_embeddings: Optional[int] = None  # learned-pos table size
    scale_embeddings: bool = False  # gemma: hidden *= sqrt(hidden_dim)
    # HF family tag driving weight-name mapping + config.json emission
    # (models/hf.py); None for fabricated test configs.
    hf_family: Optional[str] = None
    dtype: str = "float32"  # param dtype; compute dtype chosen at call site

    @property
    def q_dim(self) -> int:
        return self.n_q_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    @property
    def group_size(self) -> int:
        assert self.n_q_heads % self.n_kv_heads == 0
        return self.n_q_heads // self.n_kv_heads


def tiny_config(
    vocab_size: int = 128,
    n_layers: int = 2,
    hidden_dim: int = 32,
    n_q_heads: int = 4,
    n_kv_heads: int = 2,
    is_critic: bool = False,
    **kw,
) -> TransformerConfig:
    """Small fabricated config for tests (reference testing.py:37-43).

    A ``moe`` kwarg may be a plain dict (the YAML/CLI ``actor.tiny.moe``
    form) — it is coerced to :class:`MoEConfig` here so every downstream
    consumer sees the dataclass.
    """
    if isinstance(kw.get("moe"), dict):
        kw["moe"] = MoEConfig(**kw["moe"])
    return TransformerConfig(
        n_layers=n_layers,
        hidden_dim=hidden_dim,
        n_q_heads=n_q_heads,
        n_kv_heads=n_kv_heads,
        head_dim=hidden_dim // n_q_heads,
        intermediate_dim=hidden_dim * 2,
        vocab_size=vocab_size,
        is_critic=is_critic,
        **kw,
    )
