"""In-process generation engine: prefill + KV-cache decode under one jit.

Parity target: the reference's in-house generation
(``realhf/impl/model/nn/real_llm_generate.py:30,256`` — genstep + generate
with KV cache). TPU-first differences:
 - the whole decode loop is a single ``lax.scan`` with static shapes (no
   CUDA-graph capture needed — XLA compiles the step once);
 - prompts are right-padded to a bucket length, responses capped at
   ``max_new_tokens``; finished rows keep emitting ``pad_token`` with zero
   logprob so shapes stay static.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import forward, init_kv_cache
from areal_tpu.ops.sampling import sample_token


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "gconfig", "max_new_tokens", "eos_token_id", "pad_token_id", "attn_impl",
    ),
)
def generate_batch(
    params,
    cfg: TransformerConfig,
    prompts: jnp.ndarray,  # [B, P] right-padded with pad_token
    prompt_lens: jnp.ndarray,  # [B]
    key: jax.Array,
    gconfig: GenerationHyperparameters,
    max_new_tokens: int,
    eos_token_id: int,
    pad_token_id: int,
    attn_impl: str = "auto",
) -> Dict[str, jnp.ndarray]:
    """Returns {"output_ids": [B, N], "output_logprobs": [B, N],
    "output_lens": [B], "prompt_logprobs": [B, P]}.

    output_lens counts generated tokens incl. the EOS; slots beyond it hold
    pad_token / 0.0 logprob.
    """
    B, P = prompts.shape
    N = max_new_tokens
    S = P + N

    positions = jnp.broadcast_to(jnp.arange(P)[None, :], (B, P))
    seg = (positions < prompt_lens[:, None]).astype(jnp.int32)
    logits, kv = forward(
        params, cfg, prompts, positions, segment_ids=seg, attn_impl=attn_impl
    )
    # Log-probs of prompt tokens (teacher-forced), for optional prompt
    # scoring — gather + fused logsumexp, no [B, P, V] f32 copy (ops/xent).
    from areal_tpu.ops.xent import gather_logprobs

    nxt = jnp.concatenate([prompts[:, 1:], prompts[:, :1]], axis=1)
    prompt_logprobs = gather_logprobs(logits, nxt)

    # Pad per-layer KV to the full decode length.
    kv_cache = init_kv_cache(cfg, B, S, dtype=kv["k"].dtype)
    kv_cache = {
        "k": jax.lax.dynamic_update_slice_in_dim(kv_cache["k"], kv["k"], 0, axis=2),
        "v": jax.lax.dynamic_update_slice_in_dim(kv_cache["v"], kv["v"], 0, axis=2),
    }

    last_idx = jnp.maximum(prompt_lens - 1, 0)
    last_logits = jnp.take_along_axis(logits, last_idx[:, None, None], axis=1)[:, 0]

    slot_ids = jnp.arange(S)

    def step(carry, n):
        kv_cache, last_logits, finished, key = carry
        key, sub = jax.random.split(key)
        if gconfig.min_new_tokens > 0:
            # Forbid EOS until min_new_tokens have been emitted (reference
            # suppresses EOS in its logits warper the same way).
            eos_block = (n < gconfig.min_new_tokens) & (
                jnp.arange(last_logits.shape[-1]) == eos_token_id
            )
            last_logits = jnp.where(eos_block[None, :], -1e30, last_logits)
        token, logprob = sample_token(last_logits, sub, gconfig)
        token = jnp.where(finished, pad_token_id, token)
        logprob = jnp.where(finished, 0.0, logprob)
        emit_token, emit_logprob = token, logprob

        pos = prompt_lens + n  # [B]
        valid = (slot_ids[None, :] < prompt_lens[:, None]) | (
            (slot_ids[None, :] >= P) & (slot_ids[None, :] <= P + n)
        )
        if cfg.sliding_window is not None:
            # Cache slot j holds position j (prompt) or plen + (j - P) (decode).
            slot_pos = jnp.where(
                slot_ids[None, :] < P,
                slot_ids[None, :],
                prompt_lens[:, None] + (slot_ids[None, :] - P),
            )
            valid = valid & ((pos[:, None] - slot_pos) < cfg.sliding_window)
        logits_step, kv_cache = forward(
            params,
            cfg,
            token[:, None],
            pos[:, None],
            kv_cache=kv_cache,
            cache_write_index=P + n,
            kv_valid=valid,
        )
        now_finished = finished | (token == eos_token_id)
        return (kv_cache, logits_step[:, 0], now_finished, key), (
            emit_token,
            emit_logprob,
            finished,
        )

    finished0 = jnp.zeros((B,), bool)
    (_, _, _, _), (toks, lps, was_finished) = jax.lax.scan(
        step, (kv_cache, last_logits, finished0, key), jnp.arange(N)
    )
    output_ids = toks.T  # [B, N]
    output_logprobs = lps.T
    gen_mask = ~was_finished.T  # True where the token was actually generated
    output_lens = gen_mask.sum(axis=1).astype(jnp.int32)
    return {
        "output_ids": output_ids,
        "output_logprobs": output_logprobs.astype(jnp.float32),
        "output_lens": output_lens,
        "gen_mask": gen_mask,
        "prompt_logprobs": prompt_logprobs.astype(jnp.float32),
    }


def pad_prompts(
    prompt_list, pad_token_id: int, bucket: int = 64
) -> Tuple[np.ndarray, np.ndarray]:
    """Right-pad a list of int lists/arrays to a bucketed max length (static
    shapes → no recompilation churn; SURVEY §7 hard-part 6)."""
    lens = np.array([len(p) for p in prompt_list], dtype=np.int32)
    P = max(int(np.max(lens)), 1)
    P = ((P + bucket - 1) // bucket) * bucket
    out = np.full((len(prompt_list), P), pad_token_id, dtype=np.int32)
    for i, p in enumerate(prompt_list):
        out[i, : len(p)] = np.asarray(p, dtype=np.int32)
    return out, lens
