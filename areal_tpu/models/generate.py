"""In-process generation engine: prefill + KV-cache decode under one jit.

Parity target: the reference's in-house generation
(``realhf/impl/model/nn/real_llm_generate.py:30,256`` — genstep + generate
with KV cache). TPU-first differences:
 - the whole decode loop is a single ``lax.scan`` with static shapes (no
   CUDA-graph capture needed — XLA compiles the step once);
 - prompts are right-padded to a bucket length, responses capped at
   ``max_new_tokens``; finished rows keep emitting ``pad_token`` with zero
   logprob so shapes stay static.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import forward, init_kv_cache
from areal_tpu.ops.sampling import (
    sample_token,
    sample_token_rows,
    sampling_from_gconfigs,
)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "gconfig", "max_new_tokens", "eos_token_id", "pad_token_id", "attn_impl",
    ),
)
def generate_batch(
    params,
    cfg: TransformerConfig,
    prompts: jnp.ndarray,  # [B, P] right-padded with pad_token
    prompt_lens: jnp.ndarray,  # [B]
    key: jax.Array,
    gconfig: GenerationHyperparameters,
    max_new_tokens: int,
    eos_token_id: int,
    pad_token_id: int,
    attn_impl: str = "auto",
) -> Dict[str, jnp.ndarray]:
    """Returns {"output_ids": [B, N], "output_logprobs": [B, N],
    "output_lens": [B], "prompt_logprobs": [B, P]}.

    output_lens counts generated tokens incl. the EOS; slots beyond it hold
    pad_token / 0.0 logprob.
    """
    B, P = prompts.shape
    N = max_new_tokens
    S = P + N

    positions = jnp.broadcast_to(jnp.arange(P)[None, :], (B, P))
    seg = (positions < prompt_lens[:, None]).astype(jnp.int32)
    logits, kv = forward(
        params, cfg, prompts, positions, segment_ids=seg, attn_impl=attn_impl
    )
    # Log-probs of prompt tokens (teacher-forced), for optional prompt
    # scoring — gather + fused logsumexp, no [B, P, V] f32 copy (ops/xent).
    from areal_tpu.ops.xent import gather_logprobs

    nxt = jnp.concatenate([prompts[:, 1:], prompts[:, :1]], axis=1)
    prompt_logprobs = gather_logprobs(logits, nxt)

    # Pad per-layer KV to the full decode length.
    kv_cache = init_kv_cache(cfg, B, S, dtype=kv["k"].dtype)
    kv_cache = {
        "k": jax.lax.dynamic_update_slice_in_dim(kv_cache["k"], kv["k"], 0, axis=2),
        "v": jax.lax.dynamic_update_slice_in_dim(kv_cache["v"], kv["v"], 0, axis=2),
    }

    last_idx = jnp.maximum(prompt_lens - 1, 0)
    last_logits = jnp.take_along_axis(logits, last_idx[:, None, None], axis=1)[:, 0]

    slot_ids = jnp.arange(S)

    def step(carry, n):
        kv_cache, last_logits, finished, key = carry
        key, sub = jax.random.split(key)
        if gconfig.min_new_tokens > 0:
            # Forbid EOS until min_new_tokens have been emitted (reference
            # suppresses EOS in its logits warper the same way).
            eos_block = (n < gconfig.min_new_tokens) & (
                jnp.arange(last_logits.shape[-1]) == eos_token_id
            )
            last_logits = jnp.where(eos_block[None, :], -1e30, last_logits)
        token, logprob = sample_token(last_logits, sub, gconfig)
        token = jnp.where(finished, pad_token_id, token)
        logprob = jnp.where(finished, 0.0, logprob)
        emit_token, emit_logprob = token, logprob

        pos = prompt_lens + n  # [B]
        valid = (slot_ids[None, :] < prompt_lens[:, None]) | (
            (slot_ids[None, :] >= P) & (slot_ids[None, :] <= P + n)
        )
        if cfg.sliding_window is not None:
            # Cache slot j holds position j (prompt) or plen + (j - P) (decode).
            slot_pos = jnp.where(
                slot_ids[None, :] < P,
                slot_ids[None, :],
                prompt_lens[:, None] + (slot_ids[None, :] - P),
            )
            valid = valid & ((pos[:, None] - slot_pos) < cfg.sliding_window)
        logits_step, kv_cache = forward(
            params,
            cfg,
            token[:, None],
            pos[:, None],
            kv_cache=kv_cache,
            cache_write_index=P + n,
            kv_valid=valid,
        )
        now_finished = finished | (token == eos_token_id)
        return (kv_cache, logits_step[:, 0], now_finished, key), (
            emit_token,
            emit_logprob,
            finished,
        )

    finished0 = jnp.zeros((B,), bool)
    (_, _, _, _), (toks, lps, was_finished) = jax.lax.scan(
        step, (kv_cache, last_logits, finished0, key), jnp.arange(N)
    )
    output_ids = toks.T  # [B, N]
    output_logprobs = lps.T
    gen_mask = ~was_finished.T  # True where the token was actually generated
    output_lens = gen_mask.sum(axis=1).astype(jnp.int32)
    return {
        "output_ids": output_ids,
        "output_logprobs": output_logprobs.astype(jnp.float32),
        "output_lens": output_lens,
        "gen_mask": gen_mask,
        "prompt_logprobs": prompt_logprobs.astype(jnp.float32),
    }


# ---------------------------------------------------------------------------
# Persistent decode state (chunked generation without re-prefill)
# ---------------------------------------------------------------------------
#
# The chunked-generation client re-submits prompt+accumulated tokens each
# chunk; re-prefilling that prefix every time is O(L²) over a generation
# (VERDICT r1 weakness #3 / the reference's SGLang radix-cache role,
# patch/sglang/v0.4.6.post4.patch). Instead the server keeps per-request
# decode state: a KV cache laid out COMPACTLY (slot j of row b is valid iff
# j < cur_len[b]; decode token n of a row writes slot cur_len, so the pad
# slots left by the bucketed prompt prefill are progressively overwritten)
# plus the last-step logits. A chunk continuation is then pure decode steps.
# Weight updates invalidate the state (KV computed under old weights is
# stale), which re-prefills once per version change — the same bound the
# reference gets by aborting requests on update_weights_from_disk.


@partial(jax.jit, static_argnames=("cfg", "S", "attn_impl"))
def prefill_state(
    params,
    cfg: TransformerConfig,
    prompts: jnp.ndarray,  # [B, P] right-padded
    prompt_lens: jnp.ndarray,  # [B]
    S: int,  # KV capacity (≥ P + first chunk length)
    attn_impl: str = "auto",
) -> Dict[str, jnp.ndarray]:
    """Prefill → decode state {kv_k, kv_v [L,B,S,Hkv,Dh], last_logits [B,V],
    cur_len [B]}."""
    B, P = prompts.shape
    positions = jnp.broadcast_to(jnp.arange(P)[None, :], (B, P))
    seg = (positions < prompt_lens[:, None]).astype(jnp.int32)
    logits, kv = forward(
        params, cfg, prompts, positions, segment_ids=seg, attn_impl=attn_impl
    )
    kv_cache = init_kv_cache(cfg, B, S, dtype=kv["k"].dtype)
    kv_cache = {
        "k": jax.lax.dynamic_update_slice_in_dim(kv_cache["k"], kv["k"], 0, axis=2),
        "v": jax.lax.dynamic_update_slice_in_dim(kv_cache["v"], kv["v"], 0, axis=2),
    }
    last_idx = jnp.maximum(prompt_lens - 1, 0)
    last_logits = jnp.take_along_axis(
        logits, last_idx[:, None, None], axis=1
    )[:, 0]
    return {
        "kv_k": kv_cache["k"],
        "kv_v": kv_cache["v"],
        "last_logits": last_logits.astype(jnp.float32),
        "cur_len": prompt_lens.astype(jnp.int32),
    }


@partial(
    jax.jit,
    static_argnames=("cfg", "n_tokens", "eos_token_id", "pad_token_id"),
    donate_argnames=("state",),
)
def decode_chunk_rows(
    params,
    cfg: TransformerConfig,
    state: Dict[str, jnp.ndarray],
    tokens_done: jnp.ndarray,  # [B] tokens generated in previous chunks
    key: jax.Array,
    sampling: Dict[str, jnp.ndarray],  # per-row arrays (ops.sampling)
    n_tokens: int,
    eos_token_id: int,
    pad_token_id: int,
    row_budget: Optional[jnp.ndarray] = None,  # [B] max tokens THIS chunk
) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """Continue decoding ``n_tokens`` from a decode state.

    Per-row sampling params (temperature/top_k/top_p/greedy/min_new_tokens)
    are DYNAMIC [B] arrays: one compiled kernel serves arbitrary gconfig
    mixes, so the server batches purely by computation shape. ``row_budget``
    finishes a row after its own token allowance even when the (static)
    chunk length is longer — mixed-budget batches stop sampling for
    exhausted rows instead of generating tokens the caller would discard.

    Returns (new_state, out) with out like generate_batch's (output_ids /
    output_logprobs / output_lens / gen_mask). Equivalent to the tail of
    ``generate_batch``'s scan — chunking N into pieces with this function
    yields identical greedy tokens (tested in test_kv_reuse.py).
    """
    S = state["kv_k"].shape[2]
    V = state["last_logits"].shape[-1]
    slot_ids = jnp.arange(S)

    def step(carry, n):
        kv_k, kv_v, last_logits, cur_len, done, finished, key = carry
        if row_budget is not None:
            finished = finished | (n >= row_budget)
        key, sub = jax.random.split(key)
        logits = last_logits
        # Forbid EOS while a row is under its min_new_tokens budget.
        eos_block = (done < sampling["min_new_tokens"])[:, None] & (
            jnp.arange(V) == eos_token_id
        )[None, :]
        logits = jnp.where(eos_block, -1e30, logits)
        token, logprob = sample_token_rows(logits, sub, sampling)
        token = jnp.where(finished, pad_token_id, token)
        logprob = jnp.where(finished, 0.0, logprob)

        pos = cur_len  # [B] slot & RoPE position of the new token
        valid = slot_ids[None, :] <= pos[:, None]
        if cfg.sliding_window is not None:
            valid = valid & (
                (pos[:, None] - slot_ids[None, :]) < cfg.sliding_window
            )
        logits_step, kv = forward(
            params, cfg, token[:, None], pos[:, None],
            kv_cache={"k": kv_k, "v": kv_v},
            cache_write_index=pos, kv_valid=valid,
        )
        now_finished = finished | (token == eos_token_id)
        cur_len = jnp.where(finished, cur_len, cur_len + 1)
        done = done + (~finished).astype(jnp.int32)
        # Freeze last_logits once a row is finished: later steps feed pad
        # tokens, and a retained state (serving-mode row_budget truncation)
        # must carry the logits after its last REAL token — a continuation
        # or a full-match prefix clone samples its next token from them.
        last_logits = jnp.where(
            finished[:, None], last_logits,
            logits_step[:, 0].astype(jnp.float32),
        )
        return (
            kv["k"], kv["v"], last_logits,
            cur_len, done, now_finished, key,
        ), (token, logprob, finished)

    finished0 = jnp.zeros(state["cur_len"].shape, bool)
    carry0 = (
        state["kv_k"], state["kv_v"], state["last_logits"],
        state["cur_len"], tokens_done.astype(jnp.int32), finished0, key,
    )
    (kv_k, kv_v, last_logits, cur_len, _, _, _), (toks, lps, was_fin) = (
        jax.lax.scan(step, carry0, jnp.arange(n_tokens))
    )
    gen_mask = ~was_fin.T
    new_state = {
        "kv_k": kv_k, "kv_v": kv_v,
        "last_logits": last_logits, "cur_len": cur_len,
    }
    out = {
        "output_ids": toks.T,
        "output_logprobs": lps.T.astype(jnp.float32),
        "output_lens": gen_mask.sum(axis=1).astype(jnp.int32),
        "gen_mask": gen_mask,
    }
    return new_state, out


def decode_chunk(
    params,
    cfg: TransformerConfig,
    state: Dict[str, jnp.ndarray],
    tokens_done: jnp.ndarray,
    key: jax.Array,
    gconfig: GenerationHyperparameters,
    n_tokens: int,
    eos_token_id: int,
    pad_token_id: int,
) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """Uniform-gconfig convenience wrapper over decode_chunk_rows."""
    B = int(state["cur_len"].shape[0])
    sampling = sampling_from_gconfigs([gconfig] * B)
    return decode_chunk_rows(
        params, cfg, state, tokens_done, key, sampling,
        n_tokens=n_tokens, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id,
    )


def clone_prefix(state: Dict[str, jnp.ndarray], L) -> Dict[str, jnp.ndarray]:
    """A decode state truncated to its first ``L`` tokens.

    The compact KV layout (slot j holds token j) makes this free: the KV
    arrays are shared as-is (jax arrays are immutable; slots ≥ L are
    masked out by every downstream ``kv_valid``), only ``cur_len`` drops
    to L. The cross-request prefix-seeding primitive: clone a donor's
    retained state at the shared-prefix length, then
    :func:`extend_state` the unshared suffix. ``last_logits`` is the
    donor's (stale for L < donor length) — callers must extend with ≥ 1
    token unless L equals the donor's full length.
    """
    return {
        "kv_k": state["kv_k"],
        "kv_v": state["kv_v"],
        "last_logits": state["last_logits"],
        "cur_len": jnp.full_like(state["cur_len"], L),
    }


@partial(jax.jit, static_argnames=("cfg", "attn_impl"))
def extend_state(
    params,
    cfg: TransformerConfig,
    state: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,  # [B, T] suffix, right-padded with pad tokens
    token_lens: jnp.ndarray,  # [B] real suffix lengths (≥ 1)
    attn_impl: str = "auto",
) -> Dict[str, jnp.ndarray]:
    """Teacher-force ``tokens`` through the model on top of an existing
    decode state — the suffix prefill of cross-request prefix seeding: a
    request whose prompt extends a retained state's tokens only pays
    forward passes for the unshared suffix, not the whole prompt.

    KV capacity must satisfy ``S ≥ max(cur_len + T)``. Slots written by
    the padding tail hold garbage K/V but sit at positions ≥ the new
    ``cur_len``: every later attention masks them (``slot ≤ pos``) until
    decode overwrites them one step at a time.
    """
    B, T = tokens.shape
    S = state["kv_k"].shape[2]
    cur = state["cur_len"].astype(jnp.int32)
    positions = cur[:, None] + jnp.arange(T)[None, :]  # [B, T]
    slot_ids = jnp.arange(S)
    # Causal over the compact layout: suffix token t of row b attends
    # slots j ≤ cur[b] + t (its own slot included — written above before
    # attention — but never its padded/future siblings).
    kv_valid = slot_ids[None, None, :] <= positions[:, :, None]  # [B, T, S]
    if cfg.sliding_window is not None:
        kv_valid = kv_valid & (
            (positions[:, :, None] - slot_ids[None, None, :])
            < cfg.sliding_window
        )
    logits, kv = forward(
        params, cfg, tokens, positions,
        kv_cache={"k": state["kv_k"], "v": state["kv_v"]},
        cache_write_index=cur, kv_valid=kv_valid, attn_impl=attn_impl,
    )
    last_idx = jnp.maximum(token_lens - 1, 0)
    last_logits = jnp.take_along_axis(
        logits, last_idx[:, None, None], axis=1
    )[:, 0]
    return {
        "kv_k": kv["k"],
        "kv_v": kv["v"],
        "last_logits": last_logits.astype(jnp.float32),
        "cur_len": cur + token_lens.astype(jnp.int32),
    }


def grow_state(state: Dict[str, jnp.ndarray], new_S: int) -> Dict[str, jnp.ndarray]:
    """Pad the KV capacity of a decode state up to new_S slots."""
    S = state["kv_k"].shape[2]
    if new_S <= S:
        return state
    pad = [(0, 0)] * state["kv_k"].ndim
    pad[2] = (0, new_S - S)
    return {
        **state,
        "kv_k": jnp.pad(state["kv_k"], pad),
        "kv_v": jnp.pad(state["kv_v"], pad),
    }


def slice_state(state: Dict[str, jnp.ndarray], i: int) -> Dict[str, jnp.ndarray]:
    """Row i of a batched decode state (keeps a batch axis of 1)."""
    return {
        "kv_k": state["kv_k"][:, i:i + 1],
        "kv_v": state["kv_v"][:, i:i + 1],
        "last_logits": state["last_logits"][i:i + 1],
        "cur_len": state["cur_len"][i:i + 1],
    }


def stack_states(states) -> Dict[str, jnp.ndarray]:
    """Concatenate single-row decode states along the batch axis."""
    return {
        "kv_k": jnp.concatenate([s["kv_k"] for s in states], axis=1),
        "kv_v": jnp.concatenate([s["kv_v"] for s in states], axis=1),
        "last_logits": jnp.concatenate([s["last_logits"] for s in states]),
        "cur_len": jnp.concatenate([s["cur_len"] for s in states]),
    }


def pad_prompts(
    prompt_list, pad_token_id: int, bucket: int = 64
) -> Tuple[np.ndarray, np.ndarray]:
    """Right-pad a list of int lists/arrays to a bucketed max length (static
    shapes → no recompilation churn; SURVEY §7 hard-part 6)."""
    lens = np.array([len(p) for p in prompt_list], dtype=np.int32)
    P = max(int(np.max(lens)), 1)
    P = ((P + bucket - 1) // bucket) * bucket
    out = np.full((len(prompt_list), P), pad_token_id, dtype=np.int32)
    for i, p in enumerate(prompt_list):
        out[i, : len(p)] = np.asarray(p, dtype=np.int32)
    return out, lens
