"""Mesh→mesh on-device pytree resharding (ROADMAP item 1).

The paper's parameter-sync mechanism: "parameter sync moves to ICI/DCN
all-gather with on-device reshard". This module is the one resharding
core, spent twice:

 - the ``device`` weight-sync transport (docs/weight_sync.md): the
   trainer reshards its live params into the generation fleet's layout
   and publishes them through an in-process registry — no d2h, no wire,
   no disk; the generation server swaps them in behind the same
   manifest/digest gate the streamed transport uses;
 - heterogeneous per-MFC meshes (docs/parallelism.md): when two model
   roles live on different sub-meshes or ParallelSpecs, params cross the
   MFC boundary through :func:`reshard_pytree` (trainer_worker's
   ``param_realloc`` hook).

Mechanics. A :class:`ReshardPlan` is computed per leaf from the source
array's live sharding and the target sharding: leaves whose sharding is
already equivalent are passed through untouched (zero-copy — the plan
must recognise a same-spec publish as a no-op), the rest are batched
into size-bounded *transfer groups*. Each group is dispatched with
``jax.device_put`` (XLA resolves the device→device copy; within one
``jax.distributed`` runtime that is the ICI/DCN path) and retired with a
``block_until_ready`` barrier before the next group dispatches, so peak
extra HBM is bounded by the group byte budget rather than the whole
tree. Under a multi-process runtime the move runs as a jitted identity
with ``out_shardings`` (a true on-device all-to-all); a pure-numpy host
fallback (:func:`reshard_via_host`) keeps the plan unit-testable under
``JAX_PLATFORMS=cpu`` and serves as the escape hatch for device pairs
``device_put`` cannot bridge.
"""

import dataclasses
import json
import logging
import os
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("reshard")

# Default transfer-group byte budget. Peak extra HBM during a reshard is
# ~one group of target-layout leaves (the source leaves stay live until
# the caller drops them), so this bounds the headroom the publish needs:
# a 64 MB group on top of params + opt state is noise even on a 16G chip.
DEFAULT_GROUP_MB = 64


class DeviceReshardError(RuntimeError):
    """A device-transport publication could not be consumed (missing,
    version skew, digest mismatch, or tree mismatch). The generation
    server maps this onto the same keep-old-weights + HTTP 500 contract
    stream failures use."""


# --------------------------------------------------------------------------
# flatten helpers (models.hf naming: '/'-joined dict paths) — imported
# lazily so parallel/ keeps no import edge into models/ at module load.
# --------------------------------------------------------------------------


def _flatten(tree) -> Dict[str, Any]:
    from areal_tpu.models.hf import flatten_pytree

    return flatten_pytree(tree)


def _unflatten(flat: Dict[str, Any]):
    from areal_tpu.models.hf import unflatten_pytree

    return unflatten_pytree(flat)


def _leaf_nbytes(leaf) -> int:
    size = int(np.prod(leaf.shape)) if leaf.shape else 1
    return size * np.dtype(leaf.dtype).itemsize


def _sharding_of(leaf):
    return getattr(leaf, "sharding", None)


def _equivalent(src_sharding, dst_sharding, ndim: int) -> bool:
    if src_sharding is None or dst_sharding is None:
        return False
    try:
        return bool(src_sharding.is_equivalent_to(dst_sharding, ndim))
    except Exception:  # noqa: BLE001 — conservative: treat as a move
        return False


# --------------------------------------------------------------------------
# plan
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    """Per-leaf decisions for one mesh→mesh move.

    ``identical`` leaves already satisfy the target sharding and MUST be
    passed through without a copy; ``groups`` batches the remaining
    leaves so each dispatch→barrier cycle stages at most ~``group_bytes``
    of new target-layout buffers."""

    identical: Tuple[str, ...]
    groups: Tuple[Tuple[str, ...], ...]
    moved_bytes: int
    total_bytes: int
    group_bytes: int

    @property
    def n_moved(self) -> int:
        return sum(len(g) for g in self.groups)

    def describe(self) -> Dict[str, Any]:
        return {
            "identical": len(self.identical),
            "moved": self.n_moved,
            "groups": len(self.groups),
            "moved_bytes": self.moved_bytes,
            "total_bytes": self.total_bytes,
        }


def plan_reshard(
    flat_src: Dict[str, Any],
    flat_dst: Dict[str, Any],
    group_bytes: int = DEFAULT_GROUP_MB << 20,
) -> ReshardPlan:
    """Compute the per-leaf move plan from live arrays to target shardings.

    ``flat_src`` maps '/'-joined names to (device) arrays; ``flat_dst``
    maps the same names to target ``Sharding``s. Names must match
    exactly — a reshard never invents or drops tensors."""
    if set(flat_src) != set(flat_dst):
        missing = sorted(set(flat_src) ^ set(flat_dst))
        raise ValueError(
            f"reshard plan: source/target trees differ on {len(missing)} "
            f"leaves (e.g. {missing[:3]})"
        )
    identical: List[str] = []
    moves: List[Tuple[str, int]] = []
    moved_bytes = total_bytes = 0
    for name in sorted(flat_src):
        leaf = flat_src[name]
        nbytes = _leaf_nbytes(leaf)
        total_bytes += nbytes
        if _equivalent(_sharding_of(leaf), flat_dst[name],
                       len(leaf.shape)):
            identical.append(name)
        else:
            moves.append((name, nbytes))
            moved_bytes += nbytes
    groups: List[Tuple[str, ...]] = []
    cur: List[str] = []
    cur_bytes = 0
    for name, nbytes in moves:
        if cur and cur_bytes + nbytes > group_bytes:
            groups.append(tuple(cur))
            cur, cur_bytes = [], 0
        cur.append(name)
        cur_bytes += nbytes
    if cur:
        groups.append(tuple(cur))
    return ReshardPlan(
        identical=tuple(identical), groups=tuple(groups),
        moved_bytes=moved_bytes, total_bytes=total_bytes,
        group_bytes=group_bytes,
    )


# --------------------------------------------------------------------------
# execute
# --------------------------------------------------------------------------


def _move_group(names: Sequence[str], flat_src, flat_dst) -> Dict[str, Any]:
    """One transfer group: dispatch every leaf, then one barrier so the
    next group's staging buffers don't stack on top of this one's."""
    import jax

    out = {}
    for name in names:
        leaf, dst = flat_src[name], flat_dst[name]
        try:
            out[name] = jax.device_put(leaf, dst)
        except Exception:  # noqa: BLE001 — device pair XLA can't bridge
            # Pure-numpy host fallback: gather the addressable value and
            # rebuild per-shard on the target. Correctness over speed.
            out[name] = _host_transfer(leaf, dst)
    jax.block_until_ready(list(out.values()))
    return out


def _host_transfer(leaf, dst_sharding):
    import jax

    host = np.asarray(leaf)
    return jax.make_array_from_callback(
        host.shape, dst_sharding, lambda idx: host[idx]
    )


def execute_reshard(
    flat_src: Dict[str, Any],
    flat_dst: Dict[str, Any],
    plan: Optional[ReshardPlan] = None,
) -> Dict[str, Any]:
    """Run ``plan`` (computed if None). Identical leaves are returned AS
    IS — the same array objects, zero-copy; moved leaves come back in the
    target sharding, transferred group by group."""
    if plan is None:
        plan = plan_reshard(flat_src, flat_dst)
    out = {name: flat_src[name] for name in plan.identical}
    for group in plan.groups:
        out.update(_move_group(group, flat_src, flat_dst))
    return out


def reshard_pytree(
    params,
    dst_shardings,
    group_mb: int = DEFAULT_GROUP_MB,
) -> Tuple[Any, ReshardPlan]:
    """Reshard a pytree into ``dst_shardings`` (a matching pytree of
    ``Sharding``s). Returns ``(new_tree, plan)``. Same-sharding leaves
    are passed through zero-copy.

    Under a multi-process ``jax.distributed`` runtime the moved leaves go
    through a jitted identity with ``out_shardings`` — the compiler emits
    the ICI/DCN collective — because ``device_put`` cannot address remote
    source shards. Single-process (including CPU test meshes) uses the
    grouped ``device_put`` path, which bounds peak HBM."""
    flat_src = _flatten(params)
    flat_dst = _flatten(dst_shardings)
    plan = plan_reshard(flat_src, flat_dst, group_bytes=group_mb << 20)
    from areal_tpu.parallel import distributed as dist

    if plan.groups and dist.is_multiprocess():
        import jax

        from areal_tpu.base import compile_watch

        out = dict(flat_src)
        for group in plan.groups:
            moved = compile_watch.watched_jit(
                "reshard/identity",
                jax.jit(
                    lambda *xs: xs,
                    out_shardings=tuple(flat_dst[n] for n in group),
                ),
            )(*(flat_src[n] for n in group))
            jax.block_until_ready(moved)
            out.update(zip(group, moved))
        for name in plan.identical:
            out[name] = flat_src[name]
        return _unflatten(out), plan
    return _unflatten(execute_reshard(flat_src, flat_dst, plan)), plan


def reshard_via_host(params, dst_shardings) -> Any:
    """Pure host-path reshard: every leaf round-trips through numpy and is
    rebuilt shard-by-shard on the target. The slow-but-always-correct
    fallback (and the oracle the on-device path is tested against)."""
    flat_src = _flatten(params)
    flat_dst = _flatten(dst_shardings)
    if set(flat_src) != set(flat_dst):
        raise ValueError("reshard_via_host: source/target trees differ")
    return _unflatten({
        name: _host_transfer(flat_src[name], flat_dst[name])
        for name in flat_src
    })


def model_shardings(mesh, model_cfg):
    """The canonical target layout for a model on ``mesh``: the same
    PartitionSpec tree training uses (parallel/sharding.py), as
    NamedShardings. ``mesh=None`` → every leaf on the default device
    (the ungridded generation-server layout)."""
    import jax

    if mesh is None:
        dev = jax.devices()[0]
        sharding = jax.sharding.SingleDeviceSharding(dev)
        return sharding
    from areal_tpu.parallel import sharding as psh

    return psh.named_shardings(mesh, psh.param_partition_specs(model_cfg))


def shardings_like(params, target) -> Any:
    """Expand ``target`` (one Sharding, or a pytree of them) into a
    pytree matching ``params`` leaf-for-leaf."""
    import jax

    if isinstance(target, jax.sharding.Sharding):
        return jax.tree.map(lambda _: target, params)
    return target


def shardings_of(params) -> Any:
    """The live sharding of every leaf — the target tree for 'reshard
    into whatever this consumer already holds'."""
    import jax

    return jax.tree.map(lambda x: x.sharding, params)


# --------------------------------------------------------------------------
# device-transport publish registry (docs/weight_sync.md §device)
# --------------------------------------------------------------------------
#
# The device transport never serialises weights: the trainer reshards its
# live params into the generation fleet's layout and registers the
# resulting tree here, keyed (experiment, trial, role). The integrity
# gate mirrors the streamed transport's manifest+digest design with the
# wire legs deleted: the digest travels OUT OF BAND (name_resolve →
# gserver_manager fanout payload → HTTP) while the tensors stay in this
# registry, so a consumer always proves the publication it found is the
# one the control plane told it to swap in — a torn registry state
# (version skew, a republish racing the fanout) fails the gate and the
# server keeps its old weights.


@dataclasses.dataclass
class DevicePublication:
    role: str
    version: int
    params: Any  # target-layout pytree (device arrays)
    manifest: List[Dict[str, Any]]  # name/shape/dtype/nbytes per leaf
    digest: str
    plan: ReshardPlan
    publish_secs: float


_REGISTRY: Dict[Tuple[str, str, str], DevicePublication] = {}


def build_manifest(flat: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [
        {
            "name": name,
            "shape": list(flat[name].shape),
            "dtype": str(np.dtype(flat[name].dtype)),
            "nbytes": _leaf_nbytes(flat[name]),
        }
        for name in sorted(flat)
    ]


def manifest_digest(manifest: List[Dict[str, Any]], version: int) -> str:
    blob = json.dumps({"version": version, "tensors": manifest},
                      sort_keys=True, separators=(",", ":"))
    return f"{zlib.crc32(blob.encode()):08x}"


def publish_device(
    experiment: str,
    trial: str,
    role: str,
    params,
    target_shardings=None,
    version: int = 0,
    group_mb: int = DEFAULT_GROUP_MB,
) -> DevicePublication:
    """Trainer-side publish: reshard ``params`` into the fleet layout,
    register the result, and advertise ``names.weight_device`` so the
    manager's transport auto-detection routes fanouts here. Returns the
    publication (its ``digest`` is what consumers will be handed)."""
    from areal_tpu.base import name_resolve, names
    from areal_tpu.system import memwatch

    t0 = time.monotonic()
    if target_shardings is None:
        target_shardings = shardings_like(params, model_shardings(None, None))
    else:
        target_shardings = shardings_like(params, target_shardings)
    # The publish is a 2x-params moment on the trainer mesh (source +
    # resharded copies live until the old publication drops): record the
    # measured high-water mark the group_mb headroom math budgets for.
    with memwatch.watermark("reshard/publish"):
        new, plan = reshard_pytree(params, target_shardings,
                                   group_mb=group_mb)
    flat = _flatten(new)
    manifest = build_manifest(flat)
    digest = manifest_digest(manifest, version)
    pub = DevicePublication(
        role=role, version=version, params=new, manifest=manifest,
        digest=digest, plan=plan, publish_secs=time.monotonic() - t0,
    )
    # Latest-wins: the manager only ever fans out the newest version, and
    # reconcile pushes re-send that same version, so one slot suffices —
    # and the previous publication's buffers free as soon as no in-flight
    # consume holds them.
    _REGISTRY[(experiment, trial, role)] = pub
    name_resolve.add(
        names.weight_device(experiment, trial, role),
        json.dumps({
            "pid": os.getpid(), "version": version, "digest": digest,
        }),
        replace=True,
    )
    logger.info(
        f"device publish {role} v{version}: {plan.n_moved} leaves moved "
        f"({plan.moved_bytes >> 20} MB) in {len(plan.groups)} groups, "
        f"{len(plan.identical)} zero-copy, {pub.publish_secs:.3f}s"
    )
    return pub


def lookup_publication(experiment: str, trial: str,
                       role: str) -> Optional[DevicePublication]:
    return _REGISTRY.get((experiment, trial, role))


def clear_publication(experiment: str, trial: str, role: str) -> None:
    """Drop the registry slot and the discovery key (trainer teardown, or
    a transport switch away from ``device``)."""
    from areal_tpu.base import name_resolve, names

    _REGISTRY.pop((experiment, trial, role), None)
    try:
        name_resolve.delete(names.weight_device(experiment, trial, role))
    except Exception:  # noqa: BLE001 — normally absent
        pass


def consume_device(
    experiment: str,
    trial: str,
    role: str,
    version: int,
    digest: str,
    live_params,
    group_mb: int = DEFAULT_GROUP_MB,
):
    """Generation-server-side consume: find the publication, verify the
    out-of-band digest + tree compatibility against the LIVE pytree, and
    return the weights resharded into the live tree's shardings (zero-copy
    when the trainer already published in this layout). Raises
    :class:`DeviceReshardError` on any gate failure — the caller keeps
    its old weights."""
    pub = lookup_publication(experiment, trial, role)
    if pub is None:
        raise DeviceReshardError(
            f"no device publication for ({experiment}, {trial}, {role}) in "
            f"this process — the device transport requires the trainer and "
            f"generation fleet to share one JAX runtime (docs/weight_sync.md)"
        )
    if pub.version != version:
        raise DeviceReshardError(
            f"device publication version skew: registry holds v{pub.version}"
            f", fanout asked for v{version}"
        )
    if manifest_digest(pub.manifest, version) != digest:
        raise DeviceReshardError(
            f"device publication digest mismatch for v{version}: the "
            f"registered tensors are not the ones the control plane "
            f"advertised"
        )
    live_flat = _flatten(live_params)
    pub_names = {t["name"]: t for t in pub.manifest}
    if set(pub_names) != set(live_flat):
        missing = sorted(set(live_flat) ^ set(pub_names))
        raise DeviceReshardError(
            f"device publication tree mismatch: {len(missing)} leaves "
            f"differ (e.g. {missing[:3]})"
        )
    for name, old in live_flat.items():
        if tuple(pub_names[name]["shape"]) != tuple(old.shape):
            raise DeviceReshardError(
                f"tensor {name!r}: published shape "
                f"{pub_names[name]['shape']} != live {list(old.shape)}"
            )
    from areal_tpu.system import memwatch

    with memwatch.watermark("reshard/consume"):
        new, plan = reshard_pytree(
            pub.params,
            _unflatten({n: v.sharding for n, v in live_flat.items()}),
            group_mb=group_mb,
        )
    # The publication travels in the trainer's compute dtype; a consumer
    # holding a different dtype casts on device (the streamed path casts
    # on the h2d upload — same contract, no host hop here).
    import jax

    new = jax.tree.map(
        lambda n, old: n if n.dtype == old.dtype else n.astype(old.dtype),
        new, live_params,
    )
    if plan.n_moved:
        logger.info(
            f"device consume {role} v{version}: {plan.n_moved} leaves "
            f"resharded ({plan.moved_bytes >> 20} MB), "
            f"{len(plan.identical)} zero-copy"
        )
    return new
