"""Device meshes and the allocation-mode vocabulary.

Replaces the reference's ``ProcessTopology``/``ParallelGrid``
(``realhf/base/topology.py:86,369``) and the ``AllocationMode`` parser
(``realhf/experiments/common/utils.py:245-375``). On TPU there are no NCCL
process groups to build — a ``jax.sharding.Mesh`` plus named axes subsumes
them; GSPMD inserts the collectives.

Axis convention (order fixed so ICI-neighbour axes get the innermost dims):

    ("dp", "fsdp", "ep", "pp", "sp", "tp")

 - ``dp``    pure data parallel (params replicated)
 - ``fsdp``  data parallel with params/opt-state sharded (ZeRO-3 style)
 - ``ep``    expert parallel: a slice of the data dimension whose shards
             own disjoint experts (models/moe.py all-to-alls tokens over it)
 - ``pp``    pipeline stages over the stacked-layer axis
 - ``sp``    sequence/context parallel (ring attention over this axis)
 - ``tp``    tensor parallel (heads / ffn sharded)

Parallelism of one model role is a ``ParallelSpec``; an experiment-wide
``AllocationMode`` string assigns specs per role, with a TPU vocabulary:

    "d2t4"                      → dp=2, tp=4 (one global spec)
    "d2f2s2t2"                  → dp=2, fsdp=2, sp=2, tp=2
    "gen.d4t2+train.f8t2"       → decoupled generation vs trainer slices
    "actor_gen:d4t2,actor_train:f4t4"  → per-MFC specs
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("dp", "fsdp", "ep", "pp", "sp", "tp")
# Short letter used in allocation strings per axis.
_AXIS_LETTER = {"d": "dp", "f": "fsdp", "p": "pp", "s": "sp", "t": "tp", "e": "ep"}


@dataclasses.dataclass(frozen=True)
class ParallelSpec:
    """Degrees along each mesh axis for one model role.

    ``ep`` (expert parallel) is a REAL mesh axis: the batch dim shards over
    it like dp/fsdp (DATA_AXES), expert weights shard their expert axis
    over it (sharding.py), and models/moe.py all-to-alls tokens to the
    shard owning their expert. Validated against num_experts at parse time
    (api/cli_args.validate_config).
    """

    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    @property
    def world_size(self) -> int:
        return self.dp * self.fsdp * self.ep * self.pp * self.sp * self.tp

    @property
    def data_degree(self) -> int:
        """Number of distinct data shards (dp × fsdp × ep)."""
        return self.dp * self.fsdp * self.ep

    def mesh_shape(self) -> Tuple[int, ...]:
        return (self.dp, self.fsdp, self.ep, self.pp, self.sp, self.tp)

    @classmethod
    def parse(cls, s: str) -> "ParallelSpec":
        """Parse e.g. "d2f2s1t4" / "d2m2p1" (reference letters: m=tp, p=pp)."""
        s = s.strip().lower()
        if not re.fullmatch(r"(?:[a-z]\d+)+", s):
            raise ValueError(f"malformed parallel spec '{s}'")
        out: Dict[str, int] = {}
        for letter, num in re.findall(r"([a-z])(\d+)", s):
            if letter == "m":  # reference spelling for tensor(model)-parallel
                axis = "tp"
            else:
                axis = _AXIS_LETTER.get(letter)
            if axis is None:
                raise ValueError(f"unknown axis letter '{letter}' in '{s}'")
            if axis in out:
                raise ValueError(f"duplicate axis '{letter}' in '{s}'")
            out[axis] = int(num)
        if not out:
            raise ValueError(f"cannot parse parallel spec '{s}'")
        return cls(**out)

    def __str__(self) -> str:
        return "".join(
            f"{l}{getattr(self, a)}"
            for l, a in (
                ("d", "dp"), ("f", "fsdp"), ("p", "pp"), ("s", "sp"),
                ("t", "tp"), ("e", "ep"),
            )
            if getattr(self, a) != 1
        ) or "d1"


def make_mesh(
    spec: ParallelSpec,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh with the canonical axis order.

    Axis order puts ``tp`` innermost so tensor-parallel collectives ride
    nearest-neighbour ICI links; ``dp``/``fsdp`` outermost so gradient
    reductions use the remaining (possibly DCN) links — the standard layout
    from the scaling-book recipe.
    """
    if devices is None:
        devices = jax.devices()
    n = spec.world_size
    if len(devices) < n:
        raise ValueError(f"spec {spec} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(spec.mesh_shape())
    return Mesh(arr, AXIS_ORDER)


# Composite axis names used in PartitionSpecs (sharding.py): the batch dim
# shards over every DP flavour — ep included, since expert parallelism is
# a slice of the data dimension (tokens arrive ep-partitioned and the MoE
# all-to-all moves them to their expert's shard).
DATA_AXES = ("dp", "fsdp", "ep")


@dataclasses.dataclass(frozen=True)
class AllocationMode:
    """Experiment-wide device allocation (reference utils.py:245-375).

    ``global_spec`` — one spec for every MFC (colocated);
    ``gen_spec`` — when decoupled, the generation fleet's spec;
    ``per_mfc`` — optional per-MFC overrides.
    """

    global_spec: ParallelSpec
    gen_spec: Optional[ParallelSpec] = None
    per_mfc: Dict[str, ParallelSpec] = dataclasses.field(default_factory=dict)

    @property
    def decoupled(self) -> bool:
        return self.gen_spec is not None

    @classmethod
    def parse(cls, s: str) -> "AllocationMode":
        s = s.strip()
        if ":" in s:  # per-MFC: "actor_gen:d4t2,actor_train:f4t4"
            per = {}
            for part in s.split(","):
                name, sep, spec = part.partition(":")
                if not sep or not name.strip() or not spec.strip():
                    raise ValueError(
                        f"malformed per-MFC allocation entry '{part}' in '{s}'"
                    )
                name = name.strip()
                if name in per:
                    raise ValueError(
                        f"duplicate MFC '{name}' in allocation mode '{s}'"
                    )
                per[name] = ParallelSpec.parse(spec)
            train = per.get("actor_train") or next(iter(per.values()))
            gen = per.get("actor_gen")
            return cls(global_spec=train, gen_spec=gen, per_mfc=per)
        if "+" in s:  # decoupled: "gen.d4t2+train.f8t2" or "sglang.d4+d2t2"
            gen_part, train_part = s.split("+")
            gen_part = gen_part.split(".")[-1]
            train_part = train_part.split(".")[-1]
            return cls(
                global_spec=ParallelSpec.parse(train_part),
                gen_spec=ParallelSpec.parse(gen_part),
            )
        return cls(global_spec=ParallelSpec.parse(s))
