"""Multi-host runtime: jax.distributed bootstrap + host-side broadcast.

Parity target: ``realhf/impl/model/comm/global_comm.py:48`` (setup_global_comm
— workers publish peer indices in name_resolve, rank 0 publishes the store
address, torch.distributed joins) and ``realhf/apps/main.py:80`` (per-host
worker launch). TPU-first shape: ONE trainer process per host joins a single
SPMD program via ``jax.distributed.initialize``; ``jax.devices()`` then spans
every host and one ``Mesh`` covers the pod. Control flow stays
single-controller: rank 0 talks to the master/streams and broadcasts each
(request, data) pair to the other ranks, which execute the same jitted steps
in the same order (a GSPMD program must be dispatched identically on every
process).

CPU testing: each process sets ``--xla_force_host_platform_device_count=K``
so N processes × K virtual devices form an N·K-device global mesh — the
reference's gloo-on-CPU trick, JAX-style (SURVEY §4).
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Optional

from areal_tpu.base import logging, name_resolve, names, network

logger = logging.getLogger("parallel.distributed")

_INITIALIZED = False


def coordinator_key(experiment: str, trial: str, group: str = "trainer") -> str:
    return names.distributed_peer(experiment, trial, f"coordinator/{group}")


def initialize(
    experiment: str,
    trial: str,
    process_id: int,
    num_processes: int,
    group: str = "trainer",
    local_device_count: Optional[int] = None,
    timeout: float = 120.0,
) -> None:
    """Join the group's single SPMD program.

    Rank 0 picks a free port and publishes ``ip:port`` under name_resolve
    (the reference's rank-0 store publish, global_comm.py:60-75); other
    ranks poll for it. No-op when num_processes == 1.
    """
    global _INITIALIZED
    if num_processes <= 1 or _INITIALIZED:
        return
    import jax

    key = coordinator_key(experiment, trial, group)
    if process_id == 0:
        addr = f"{network.gethostip()}:{network.find_free_port()}"
        name_resolve.add(key, addr, replace=True)
    else:
        deadline = time.monotonic() + timeout
        addr = None
        while time.monotonic() < deadline:
            try:
                addr = name_resolve.get(key)
                break
            except Exception:  # noqa: BLE001 — not yet published
                time.sleep(0.1)
        if addr is None:
            raise TimeoutError(f"no coordinator under {key}")
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=(
            list(range(local_device_count)) if local_device_count else None
        ),
    )
    _INITIALIZED = True
    logger.info(
        f"jax.distributed up: process {process_id}/{num_processes} "
        f"coordinator {addr}, {jax.device_count()} global / "
        f"{jax.local_device_count()} local devices"
    )


def is_multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def broadcast_bytes(data: Optional[bytes]) -> bytes:
    """Broadcast a byte string from process 0 to every process (length
    first, then a padded buffer — non-source processes don't know the
    size). Host-side collective over the global device set."""
    import jax
    import numpy as np
    from jax.experimental import multihost_utils as mhu

    if jax.process_count() == 1:
        return data  # type: ignore[return-value]
    src = jax.process_index() == 0
    n = np.asarray([len(data) if src and data is not None else 0], np.int64)
    n = int(mhu.broadcast_one_to_all(n)[0])
    buf = np.zeros(n, np.uint8)
    if src:
        buf[:] = np.frombuffer(data, np.uint8)
    buf = mhu.broadcast_one_to_all(buf)
    return bytes(np.asarray(buf).tobytes())


def broadcast_pyobj(obj: Any) -> Any:
    """Pickle-broadcast an arbitrary host object from process 0 (the
    reference broadcasts request payloads over its store; here it rides
    the device fabric)."""
    import jax

    if jax.process_count() == 1:
        return obj
    data = pickle.dumps(obj) if jax.process_index() == 0 else None
    return pickle.loads(broadcast_bytes(data))


def allgather_params(params: Any) -> Any:
    """Gather a (possibly multi-host-sharded) param pytree to host numpy on
    every process — used by checkpoint/HF-export paths where rank 0 writes.
    Single-process: plain device_get. Multi-process: replicate through a
    jitted identity (XLA all-gathers over ICI/DCN), then read locally."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    if jax.process_count() == 1:
        # Queue every d2h copy before the first blocking read so later
        # transfers overlap earlier ones (and any host-side serialization
        # the caller does per tensor).
        for leaf in jax.tree_util.tree_leaves(params):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        return jax.device_get(params)
    leaves = jax.tree_util.tree_leaves(params)
    mesh = leaves[0].sharding.mesh
    rep = NamedSharding(mesh, P())
    out_shardings = jax.tree.map(lambda _: rep, params)
    replicated = jax.jit(lambda x: x, out_shardings=out_shardings)(params)
    return jax.device_get(replicated)
