"""Sharding rules: PartitionSpec trees for params and activations.

This module replaces the reference's entire tensor/sequence-parallel module
zoo (``realhf/impl/model/parallelism/tensor_parallel/modules.py`` — Column/
RowParallelLinear, ``mappings.py`` autograd collectives): on TPU the model
code stays pure (models/transformer.py) and parallelism is *data layout* —
a PartitionSpec pytree mirroring the param pytree plus a handful of
activation ``with_sharding_constraint`` points. XLA/GSPMD inserts the
all-reduces/all-gathers/reduce-scatters that Megatron hand-writes.

Conventions (axes from mesh.AXIS_ORDER):
 - batch dim of activations: ("dp", "fsdp", "ep")
 - sequence dim: "sp" (ring attention over this axis, parallel/ring.py)
 - heads / ffn dim of weights: "tp"; hidden dim of weights: "fsdp" (ZeRO-3)
 - stacked-layer axis: "pp"; expert axis of MoE weights: "ep" (the MoE
   layer all-to-alls tokens to their expert's shard, models/moe.py)
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from areal_tpu.models.config import TransformerConfig
from areal_tpu.parallel.mesh import DATA_AXES

Params = Dict[str, Any]


def param_partition_specs(cfg: TransformerConfig) -> Params:
    """PartitionSpec tree with the same structure as
    ``models.transformer.init_params(cfg, ...)``.

    Megatron-equivalences (reference modules.py): wq/wk/wv/w_gate/w_up are
    ColumnParallelLinear → output dim on "tp"; wo/w_down are
    RowParallelLinear → input dim on "tp"; embedding is ParallelEmbedding →
    vocab on "tp". The *other* matrix dim goes to "fsdp" (ZeRO-3; the
    reference's DistributedOptimizer ZeRO-1 analogue, strengthened).
    """
    layers: Params = {
        "ln1": P("pp", None),
        "ln2": P("pp", None),
        "wq": P("pp", "fsdp", "tp"),
        "wk": P("pp", "fsdp", "tp"),
        "wv": P("pp", "fsdp", "tp"),
        "wo": P("pp", "tp", "fsdp"),
        "w_gate": P("pp", "fsdp", "tp"),
        "w_up": P("pp", "fsdp", "tp"),
        "w_down": P("pp", "tp", "fsdp"),
    }
    if cfg.use_attention_bias:
        layers["bq"] = P("pp", "tp")
        layers["bk"] = P("pp", "tp")
        layers["bv"] = P("pp", "tp")
    if cfg.use_attn_output_bias:
        layers["bo"] = P("pp", None)
    if cfg.use_qk_norm:
        layers["q_norm"] = P("pp", None)
        layers["k_norm"] = P("pp", None)
    if cfg.norm_type == "layer":
        layers["ln1_b"] = P("pp", None)
        layers["ln2_b"] = P("pp", None)
    if cfg.mlp_type == "plain" and cfg.moe is None:
        layers["b_up"] = P("pp", "tp")
        layers["b_down"] = P("pp", None)
        for k in ("w_gate",):
            layers.pop(k, None)
    if cfg.moe is not None:
        # Experts stack on a leading axis [n, E, ...]; shard E over the
        # REAL "ep" axis (expert parallelism — each ep shard owns E/ep
        # experts, moe.py all-to-alls tokens to them), the ffn dim on tp,
        # and ZeRO-3 the remaining matrix dim over fsdp.
        layers["router"] = P("pp", None, None)
        layers["e_gate"] = P("pp", "ep", "fsdp", "tp")
        layers["e_up"] = P("pp", "ep", "fsdp", "tp")
        layers["e_down"] = P("pp", "ep", "tp", "fsdp")
        if cfg.moe.shared_intermediate_dim:
            layers["s_gate"] = P("pp", None, "tp")
            layers["s_up"] = P("pp", None, "tp")
            layers["s_down"] = P("pp", "tp", None)
        # Dense-MLP weights are absent in MoE layers.
        for k in ("w_gate", "w_up", "w_down"):
            del layers[k]

    specs: Params = {
        "embedding": P("tp", "fsdp"),
        "layers": layers,
        "final_ln": P(None),
    }
    if cfg.norm_type == "layer":
        specs["final_ln_b"] = P(None)
    if cfg.pos_embedding == "learned":
        specs["pos_embedding"] = P(None, "fsdp")
    if cfg.is_critic:
        specs["value_head"] = P("fsdp", None)
    elif not cfg.tie_word_embeddings:
        specs["lm_head"] = P("fsdp", "tp")
    return specs


def named_shardings(mesh: Mesh, spec_tree: Params) -> Params:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Params, mesh: Mesh, cfg: TransformerConfig) -> Params:
    """Place a host/param pytree onto the mesh with the canonical layout."""
    shardings = named_shardings(mesh, param_partition_specs(cfg))
    return jax.tree.map(jax.device_put, params, shardings)


# ---------------- activation constraints ----------------
#
# Standard GSPMD sharding-hint points. The model code calls
# ``constrain(x, kind)``; outside a mesh context this is the identity, so
# models stay runnable without any parallelism setup (tests, CPU).

ACTIVATION_RULES: Dict[str, P] = {
    "tokens": P(DATA_AXES, "sp"),  # [B, T]
    "hidden": P(DATA_AXES, "sp", None),  # [B, T, D]
    "logits": P(DATA_AXES, "sp", "tp"),  # [B, T, V]
    "heads": P(DATA_AXES, "sp", "tp", None),  # [B, T, H, Dh]
    "kv_cache": P(None, DATA_AXES, None, "tp", None),  # [n, B, S, Hkv, Dh]
    # Decode mode: T == new-token count (typically 1) — never shard it.
    "hidden_decode": P(DATA_AXES, None, None),
    "logits_decode": P(DATA_AXES, None, "tp"),
}

def rules_without_axes(axes, rules: Optional[Dict[str, P]] = None
                       ) -> Dict[str, P]:
    """ACTIVATION_RULES with the given mesh axes stripped from every spec
    — for code traced inside a shard_map that is manual over ``axes``
    (parallel/pipeline.py's PP∘SP stages), where a
    with_sharding_constraint naming a manual axis is an error. Tuple
    entries drop the stripped members; entries that become empty turn into
    None."""
    axes = frozenset(axes)
    out: Dict[str, P] = {}
    for kind, spec in (rules or ACTIVATION_RULES).items():
        parts = []
        for p in spec:
            if isinstance(p, (tuple, list)):
                kept = tuple(a for a in p if a not in axes)
                parts.append(kept if kept else None)
            else:
                parts.append(None if p in axes else p)
        out[kind] = P(*parts)
    return out


@contextmanager
def strip_manual_axes(axes):
    """Re-push the innermost activation_sharding context with ``axes``
    stripped from every rule (no-op when no context is active). For code
    traced inside a shard_map manual over ``axes`` whose trace point is
    NOT lexically inside the caller's own stripped-rules push — e.g. a
    custom_vjp backward traced long after the forward's context popped,
    with only the engine's full-rules context left on the stack."""
    if not _ACTIVE:
        yield
        return
    mesh, rules = _ACTIVE[-1]
    with activation_sharding(mesh, rules_without_axes(axes, rules)):
        yield


_ACTIVE: list = []  # stack of (mesh, rules)


@contextmanager
def activation_sharding(mesh: Mesh, rules: Optional[Dict[str, P]] = None):
    _ACTIVE.append((mesh, rules or ACTIVATION_RULES))
    try:
        yield
    finally:
        _ACTIVE.pop()


def current_mesh() -> Optional[Mesh]:
    """The mesh of the innermost activation_sharding context (or None)."""
    return _ACTIVE[-1][0] if _ACTIVE else None


def constrain(x: jnp.ndarray, kind: str) -> jnp.ndarray:
    if not _ACTIVE:
        return x
    mesh, rules = _ACTIVE[-1]
    spec = rules.get(kind)
    if spec is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
