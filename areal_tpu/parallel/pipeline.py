"""Pipeline parallelism — micro-batch streaming over the mesh's "pp" axis.

Parity target: ``realhf/impl/model/parallelism/pipeline_parallel/`` (the
PipeInstruction VM + static GPipe/1F1B schedules) and its executor
``realhf/impl/model/backend/pipe_runner.py:148``. TPU-first re-design: no
instruction VM, no p2p send/recv threads — the schedule IS a ``lax.scan``
over pipeline steps inside a ``shard_map`` that is *manual over "pp" only*
(``axis_names={"pp"}``): each stage holds ``n_layers/pp`` layers of the
stacked param tree (the "pp"-sharded leading axis, parallel/sharding.py),
runs them on its resident micro-batch, and hands the activation to the next
stage with a nearest-neighbour ``lax.ppermute`` riding the ICI ring. The
dp/fsdp/tp/sp shardings of everything INSIDE a stage stay automatic
(GSPMD) — stages compose with tensor/data parallelism without any manual
collectives.

Two schedules share the step equation (at step ``s`` stage ``k`` processes
micro-batch ``s - k``; ``steps = n_micro + pp - 1``; bubble fraction
``(pp-1)/steps``):

``"gpipe"`` — the original formulation and the parity ORACLE. Backward
needs no schedule code: ``ppermute`` has a transpose rule, so ``jax.grad``
of the scan IS the reverse pipeline. Memory cost: autodiff saves residuals
for every scan step and the per-step outputs stack to ``[steps, mb, T, D]``
per stage, so live activations scale with ``steps = n_micro + pp - 1`` —
the extra ``(pp-1)/n_micro`` factor is exactly what blocked larger token
caps under PP (VERDICT round-5 "known memory cost").

``"1f1b"`` (default) — the memory-bounded rewrite, mirroring why the
reference runs a one-forward-one-backward schedule (SURVEY §2.4): a
``jax.custom_vjp`` whose forward keeps ONLY each stage's ``n_micro``
micro-batch inputs (a carry buffer written by masked dynamic-update — no
``[steps, ...]`` stacking anywhere), and whose backward is a hand-written
reverse carry: at backward step ``t`` stage ``k`` re-runs its layers on
saved input ``t + k - (pp-1)`` (rematerialization, the same trade the
reference's 1F1B+checkpointing makes), vjp's them against the cotangent
arriving from its successor, and ppermutes the input-cotangent to its
predecessor — the grad of ``ppermute`` stays the transposed ``ppermute``,
written explicitly. Live activations therefore scale with ``n_micro``, not
``steps``, which is what unlocks cap-4096+ under PP (and, once ring-SP
composes into the manual-pp region, PP∘SP at long context).

The 1F1B backward declares ZERO cotangents for cos/sin: rope tables are
pure functions of integer positions (models/transformer.rope_tables), so
their upstream cotangent dead-ends at an int cast in every caller.

Generation (decode mode) intentionally does NOT pipeline: the decode hot
loop is latency-bound and the generation fleet runs on its own mesh without
a "pp" axis (SURVEY §2.4 note; the reference's GenerateSchedule exists
because its trainer must also generate — our async design moves that to
the server).
"""

from __future__ import annotations

import os
from contextlib import nullcontext
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from areal_tpu.base import logging, telemetry
from areal_tpu.models.config import TransformerConfig
from areal_tpu.parallel import ring as ring_mod
from areal_tpu.parallel import sharding as psh
from areal_tpu.parallel.compat import shard_map

logger = logging.getLogger("parallel.pipeline")

# One-time-per-reason WARN dedup for the GSPMD fallback (process-global:
# the gate runs per trace, the operator needs the reason once).
_WARNED_FALLBACKS: set = set()

_FALLBACK_HINTS = {
    "layers_indivisible": "n_layers must divide the pp axis",
    "batch_too_small": "batch has no divisor in [pp, 2*pp]",
    "requested_indivisible": "requested micro-batch count must divide batch",
    "old_jax_mixed_mesh": "this jax only pipelines pure pp/pp×sp meshes",
    "sp_seq_indivisible": "seq_len must divide the sp axis to ring",
    "sp_sliding_window": "sliding-window attention is not ring-expressible",
}


def _fallback(reason: str) -> None:
    """GSPMD-fallback bookkeeping: a counter per reason plus a one-time
    WARN naming the failed gate (ROADMAP item 2 — the silent fallback)."""
    telemetry.inc(f"parallel/pp_fallback{{reason={reason}}}")
    if reason not in _WARNED_FALLBACKS:
        _WARNED_FALLBACKS.add(reason)
        logger.warning(
            "pipeline disengaged, falling back to GSPMD layer sharding: "
            "%s (%s)", reason, _FALLBACK_HINTS.get(reason, "")
        )
    return None


def pick_pp_microbatches(
    mesh: Optional[Mesh],
    cfg: TransformerConfig,
    batch: int,
    requested: Optional[int] = None,
    seq_len: Optional[int] = None,
) -> Optional[int]:
    """The pipeline-eligibility gate: returns the micro-batch count, or
    None when the GSPMD scan path should run instead.

    Requirements: a "pp" axis > 1, layers divisible across stages, and a
    batch divisible into >= pp micro-batches. Meshes with sp > 1 pipeline
    too (PP∘SP): ring attention runs *inside* each stage, manual over
    {"pp","sp"}, which additionally needs the sequence to shard over the
    ring (``seq_len % sp == 0``) and a ring-expressible attention pattern
    (no sliding window). Every fallback WARNs once and bumps the
    ``parallel/pp_fallback{reason=...}`` counter.
    """
    if mesh is None:
        return None
    pp = mesh.shape.get("pp", 1)
    if pp <= 1:
        return None  # no pipeline requested — not a fallback
    sp = mesh.shape.get("sp", 1)
    if sp > 1:
        if seq_len is None or seq_len % sp != 0:
            return _fallback("sp_seq_indivisible")
        if cfg.sliding_window is not None:
            return _fallback("sp_sliding_window")
    if cfg.n_layers % pp != 0:
        return _fallback("layers_indivisible")
    if getattr(jax, "shard_map", None) is None:
        # jax 0.4.x: partial-manual shard_map over the pipeline axes
        # composed with auto (GSPMD) axes crashes the XLA CPU compiler on
        # mixed meshes; only pure pp (and pp×sp — both manual) meshes
        # pipeline there. Mixed meshes keep the correct GSPMD
        # layer-sharding path (just not pipelined).
        other = 1
        for name, size in mesh.shape.items():
            if name not in ("pp", "sp"):
                other *= size
        if other > 1:
            return _fallback("old_jax_mixed_mesh")
    if requested is not None:
        n_micro = requested
        if batch % n_micro != 0:
            return _fallback("requested_indivisible")
        return n_micro
    # Auto: the largest divisor of the batch in [pp, 2*pp] — >= pp keeps
    # the bubble <= 1/2; > 2*pp only shrinks it further at more dispatch.
    for n_micro in range(min(2 * pp, batch), 0, -1):
        if batch % n_micro == 0 and n_micro >= pp:
            return n_micro
    return _fallback("batch_too_small")


def pp_engagement(
    mesh: Optional[Mesh],
    cfg: TransformerConfig,
    batch: int,
    seq_len: int,
    requested: Optional[int] = None,
) -> Tuple[float, float]:
    """(pp_engaged, ring_engaged) as 0/1 gauge values for this shape —
    the same gates the forward path applies, evaluated outside the jit so
    backend/jax_train.py can export ``train/pp_engaged`` /
    ``train/ring_engaged`` without tracing anything."""
    n_micro = pick_pp_microbatches(mesh, cfg, batch, requested,
                                   seq_len=seq_len)
    pp_on = n_micro is not None
    if pp_on:
        ring_on = mesh.shape.get("sp", 1) > 1
    else:
        ring_on = ring_mod.ring_eligible(mesh, cfg, batch, seq_len)
    return float(pp_on), float(ring_on)


def _scale_aux(aux: Dict[str, jnp.ndarray], cfg: TransformerConfig,
               n_micro: int) -> Dict[str, jnp.ndarray]:
    """Per-stage aux sums -> the apply_layer_stack contract: aux_total =
    total over layers (averaged over micro-batches), others = layer means
    (averaged over micro-batches)."""
    if not aux:
        return aux
    n_layers = float(cfg.n_layers)
    return {
        k: v / n_micro if k == "aux_total" else v / (n_layers * n_micro)
        for k, v in aux.items()
    }


def pipeline_apply_layers(
    cfg: TransformerConfig,
    layer_params: Dict[str, jnp.ndarray],  # stacked [L, ...], "pp"-sharded
    h: jnp.ndarray,  # [B, T, D]
    cos: jnp.ndarray,  # [B, T, dh]
    sin: jnp.ndarray,
    segment_ids: Optional[jnp.ndarray],  # [B, T]
    positions: Optional[jnp.ndarray],  # [B, T]
    mesh: Mesh,
    n_micro: int,
    attn_impl: str = "auto",
    remat: bool = False,
    schedule: Optional[str] = None,  # "1f1b" (default) | "gpipe" (oracle)
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Run the stacked layers as a ``pp``-stage pipeline.

    Returns (h, aux) matching apply_layer_stack: aux values are reduced so
    that downstream's sum/mean post-processing is an identity.

    ``schedule`` selects the memory-bounded 1F1B custom-vjp path (default)
    or the GPipe scan oracle; ``AREAL_PP_SCHEDULE`` overrides the default.

    PP∘SP: on meshes with sp > 1 the stages are manual over {"pp","sp"}
    and run ring attention inline (ring_mod.ring_attention_inline). The
    zig-zag ring layout is applied here — a static gather on the global
    sequence dim, inverted on the way out — so the stage bodies see the
    striped shard order while callers keep natural-order semantics.
    """
    if schedule is None:
        schedule = os.environ.get("AREAL_PP_SCHEDULE", "1f1b")
    if schedule not in ("1f1b", "gpipe"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    sp = mesh.shape.get("sp", 1)
    ring_schedule, inv = None, None
    if sp > 1:
        B, T, _ = h.shape
        ring_schedule = ring_mod.resolve_schedule(None, T, sp, causal=True)
        if segment_ids is None:
            # The ring body masks by segment; "everything is one document"
            # reproduces plain causal attention.
            segment_ids = jnp.ones((B, T), jnp.int32)
        if ring_schedule == "zigzag":
            fwd_p = ring_mod.zigzag_permutation(T, sp)
            inv = jnp.asarray(ring_mod.inverse_permutation(fwd_p))
            fwd_p = jnp.asarray(fwd_p)
            take = lambda x: None if x is None else jnp.take(x, fwd_p, axis=1)
            h, cos, sin = take(h), take(cos), take(sin)
            segment_ids, positions = take(segment_ids), take(positions)
    fn = _gpipe_apply_layers if schedule == "gpipe" else _1f1b_apply_layers
    # Inside a manual-{"pp","sp"} region a with_sharding_constraint must
    # not name the manual axes — push rules with them stripped for the
    # duration of the (trace-time) stage bodies.
    ctx = (psh.activation_sharding(mesh, psh.rules_without_axes(("pp", "sp")))
           if sp > 1 else nullcontext())
    with ctx:
        out, aux = fn(cfg, layer_params, h, cos, sin, segment_ids, positions,
                      mesh, n_micro, attn_impl, remat, ring_schedule)
    if inv is not None:
        out = jnp.take(out, inv, axis=1)
    return out, aux


# ---------------- GPipe scan (the parity oracle) ----------------


def _stage_specs(layer_params, sp_manual):
    """(manual_axes, in_spec pieces) shared by the three shard_maps: the
    stage iota, ring iota, layer stack, [n_micro, mb, T, ...] activations
    and [n_micro, mb, T] token arrays. With sp manual the sequence dim
    shards over the ring; otherwise the specs are exactly the pp-only
    originals."""
    layer_specs = jax.tree.map(lambda _: P("pp"), layer_params)
    if sp_manual:
        return ({"pp", "sp"}, P("sp"), layer_specs,
                P(None, None, "sp", None), P(None, None, "sp"))
    return ({"pp"}, P(), layer_specs, P(), P())


def _ring_ctx(ring_arr, sp, ring_schedule):
    """RingCtx from the P("sp")-sharded iota (None when sp is not manual);
    see ring_mod.RingCtx for why the rank can't come from axis_index."""
    if sp <= 1:
        return None
    return ring_mod.RingCtx("sp", sp, ring_arr[0], ring_schedule)


def _gpipe_apply_layers(
    cfg, layer_params, h, cos, sin, segment_ids, positions,
    mesh, n_micro, attn_impl, remat, ring_schedule=None,
):
    from areal_tpu.models import transformer as tfm

    pp = mesh.shape["pp"]
    sp = mesh.shape.get("sp", 1)
    B, T, D = h.shape
    assert B % n_micro == 0 and cfg.n_layers % pp == 0
    mb = B // n_micro
    steps = n_micro + pp - 1

    def to_mbs(x):
        return x.reshape((n_micro, mb) + x.shape[1:]) if x is not None else None

    h_mbs = to_mbs(h)
    cos_mbs, sin_mbs = to_mbs(cos), to_mbs(sin)
    seg_mbs = to_mbs(segment_ids)
    pos_mbs = to_mbs(positions)

    def stage_body(stage_arr, ring_arr, local_layers, h_mbs, cos_mbs,
                   sin_mbs, seg_mbs, pos_mbs):
        # Stage id arrives as a P("pp")-sharded iota rather than
        # jax.lax.axis_index: under partial-manual shard_map on older jax
        # the latter lowers to a PartitionId instruction the SPMD
        # partitioner rejects when auto axes are present.
        stage = stage_arr[0]
        ring_ctx = _ring_ctx(ring_arr, sp, ring_schedule)
        fwd_perm = [(k, k + 1) for k in range(pp - 1)]
        Tl = h_mbs.shape[2]  # local sequence shard (T/sp when sp manual)

        def step(carry, s):
            state, aux_acc = carry
            # Stage 0 ingests micro-batch s; others consume the activation
            # permuted from their predecessor at the previous step.
            mb_idx = jnp.clip(s - stage, 0, n_micro - 1)
            take = lambda x: (
                jax.lax.dynamic_index_in_dim(x, mb_idx, 0, keepdims=False)
                if x is not None else None
            )
            inp = jax.lax.dynamic_index_in_dim(
                h_mbs, jnp.clip(s, 0, n_micro - 1), 0, keepdims=False
            )
            x = jnp.where(stage == 0, inp, state)
            y, aux = tfm.apply_layer_stack(
                cfg, x, local_layers, take(cos_mbs), take(sin_mbs),
                take(seg_mbs), take(pos_mbs), attn_impl=attn_impl,
                remat=remat, allow_ring=True, ring_ctx=ring_ctx,
                allow_ep=False,  # no nested shard_map inside the pp stages
            )
            # Bubble steps run garbage (their ys are never sliced out);
            # MoE aux must not count them.
            valid = ((s - stage >= 0) & (s - stage < n_micro)).astype(
                jnp.float32
            )
            # Index by aux_acc's (scalar) keys: aux may carry extra
            # vector-valued stats the pipeline cannot accumulate.
            aux_acc = {
                k: aux_acc[k] + valid * jnp.sum(aux[k].astype(jnp.float32))
                for k in aux_acc
            } if aux else aux_acc
            state = jax.lax.ppermute(y, "pp", fwd_perm)
            return (state, aux_acc), y

        aux0 = {k: jnp.zeros((), jnp.float32) for k in _aux_keys(cfg)}
        state0 = jnp.zeros((mb, Tl, D), h_mbs.dtype)
        (_, aux_acc), ys = jax.lax.scan(
            step, (state0, aux0), jnp.arange(steps)
        )
        aux_out = {
            k: jax.lax.psum(v, ("pp", "sp") if sp > 1 else "pp")
            for k, v in aux_acc.items()
        }
        # KNOWN COST (why this schedule is only the oracle): ys stacks each
        # stage's per-step outputs ([steps, mb, T, D] per device ≈
        # (1 + (pp-1)/n_micro)·[B, T, D]) although only the last stage's
        # n_micro blocks are consumed, and scan autodiff saves residuals
        # for all ``steps`` iterations. The 1F1B path below fixes both.
        return ys, aux_out

    # Manual over the pipeline axes only: layer stacks arrive as local
    # [L/pp, ...] slices (and activations as T/sp sequence shards when sp
    # rings); dp/fsdp/tp inside each stage stay automatic (GSPMD).
    manual, iota_spec, layer_specs, act_spec, tok_spec = _stage_specs(
        layer_params, sp > 1
    )
    ys_spec = P("pp", None, "sp", None) if sp > 1 else P("pp")
    ys, aux = shard_map(
        stage_body,
        mesh=mesh,
        in_specs=(P("pp"), iota_spec, layer_specs, act_spec, act_spec,
                  act_spec, tok_spec, tok_spec),
        out_specs=(ys_spec, P()),
        axis_names=manual,
    )(jnp.arange(pp, dtype=jnp.int32), jnp.arange(sp, dtype=jnp.int32),
      layer_params, h_mbs, cos_mbs, sin_mbs, seg_mbs, pos_mbs)

    # ys is the per-stage step outputs concatenated over "pp":
    # [pp*steps, mb, T, D]; the finished micro-batch i left the LAST stage
    # at step (pp-1) + i.
    last = (pp - 1) * steps + (pp - 1)
    out = jax.lax.dynamic_slice_in_dim(ys, last, n_micro, axis=0)
    out = out.reshape(B, T, D)
    return out, _scale_aux(aux, cfg, n_micro)


# ---------------- 1F1B custom-vjp (memory-bounded, the default) ----------


def _aux_keys(cfg) -> Tuple[str, ...]:
    """The SCALAR MoE aux keys the pipeline carries (accumulated across
    micro-batches and psummed across stages). Vector-valued aux — the
    per-expert ``expert_load`` histogram — is deliberately absent: the
    pipeline's aux plumbing (scan carries, 1F1B cotangents) is
    scalar-only, and the engine recomputes nothing it can't carry."""
    return (("aux_total", "load_balance_loss", "z_loss", "dropped_frac",
             "expert_load_ratio")
            if cfg.moe is not None else ())


def _make_stage_fn(cfg, attn_impl, remat):
    """One stage's layer application, shared VERBATIM by the 1F1B forward
    and its hand-written backward (the backward re-runs it under jax.vjp):
    any drift between the two would break gradient parity silently, so
    there is exactly one definition."""

    def stage_fn(local_layers, x, cos_j, sin_j, seg_j, pos_j,
                 ring_ctx=None):
        from areal_tpu.models import transformer as tfm

        # Grouped-dispatch MoE stages unroll the per-stage layer loop:
        # on jax 0.4.x CPU the layer scan's transpose, nested inside the
        # 1F1B backward's step scan within the custom-vjp program,
        # silently mis-computes the cotangents of the grouped path's
        # sort/gather ops (~1e-2 off; the einsum oracle through the
        # identical nesting is exact, as is this path with remat=True or
        # with either scan replaced by a loop). A stage holds only
        # n_layers/pp layers, so the unroll is cheap.
        unroll = False
        if cfg.moe is not None:
            from areal_tpu.models import moe as moemod

            unroll = moemod.resolve_dispatch() == "grouped"

        # Stage bodies trace inside a shard_map manual over {"pp"} or
        # {"pp","sp"}, but the trace POINT varies: the 1F1B custom-vjp
        # backward traces after pipeline_apply_layers' stripped-rules
        # context has popped, leaving whatever outer activation_sharding
        # the engine holds (full rules naming "sp") innermost — strip the
        # manual axes here, at the constrain calls themselves.
        with psh.strip_manual_axes(("pp", "sp")):
            y, aux = tfm.apply_layer_stack(
                cfg, x, local_layers, cos_j, sin_j, seg_j, pos_j,
                attn_impl=attn_impl, remat=remat, allow_ring=True,
                ring_ctx=ring_ctx,
                allow_ep=False,  # no nested shard_map inside the pp stages
                unroll=unroll,
            )
        # Only the scalar keys: the 1F1B backward builds cotangents from
        # _aux_keys, and vector stats (expert_load) don't pipeline.
        aux_sums = {k: jnp.sum(aux[k].astype(jnp.float32))
                    for k in _aux_keys(cfg)} if aux else {}
        return y, aux_sums

    return stage_fn


def _1f1b_parts(cfg, mesh, n_micro, attn_impl, remat,
                layer_params, h_mbs, cos_mbs, sin_mbs, seg_mbs, pos_mbs,
                ring_schedule=None):
    """The 1F1B forward: returns (out_blocks, aux, saved_x) where
    ``saved_x`` — each stage's n_micro micro-batch INPUTS, ``[pp*n_micro,
    mb, T, D]`` sharded P("pp") — is the complete activation residual set
    the backward needs (everything else is rematerialized per stage-step;
    under PP∘SP that includes the stage's ring steps)."""
    pp = mesh.shape["pp"]
    sp = mesh.shape.get("sp", 1)
    n_micro_, mb, T, D = h_mbs.shape
    assert n_micro_ == n_micro
    steps = n_micro + pp - 1
    aux_keys = _aux_keys(cfg)
    stage_fn = _make_stage_fn(cfg, attn_impl, remat)

    def fwd_body(stage_arr, ring_arr, local_layers, h_mbs, cos_mbs,
                 sin_mbs, seg_mbs, pos_mbs):
        stage = stage_arr[0]  # P("pp") iota; see _gpipe stage_body note
        ring_ctx = _ring_ctx(ring_arr, sp, ring_schedule)
        fwd_perm = [(k, k + 1) for k in range(pp - 1)]
        Tl = h_mbs.shape[2]

        def step(carry, s):
            state, aux_acc, saved_x, out_buf = carry
            mb_idx = jnp.clip(s - stage, 0, n_micro - 1)
            take = lambda a: (
                jax.lax.dynamic_index_in_dim(a, mb_idx, 0, keepdims=False)
                if a is not None else None
            )
            inp = jax.lax.dynamic_index_in_dim(
                h_mbs, jnp.clip(s, 0, n_micro - 1), 0, keepdims=False
            )
            x = jnp.where(stage == 0, inp, state)
            valid = (s - stage >= 0) & (s - stage < n_micro)
            # Guarded writes: tail-bubble steps clip mb_idx onto slot
            # n_micro-1, which holds real data — keep it.
            prev_x = jax.lax.dynamic_index_in_dim(
                saved_x, mb_idx, 0, keepdims=False
            )
            saved_x = jax.lax.dynamic_update_index_in_dim(
                saved_x, jnp.where(valid, x, prev_x), mb_idx, 0
            )
            y, aux_sums = stage_fn(local_layers, x, take(cos_mbs),
                                   take(sin_mbs), take(seg_mbs),
                                   take(pos_mbs), ring_ctx)
            vf = valid.astype(jnp.float32)
            aux_acc = {
                k: aux_acc[k] + vf * aux_sums[k] for k in aux_acc
            } if aux_acc else aux_acc
            write = valid & (stage == pp - 1)
            prev_o = jax.lax.dynamic_index_in_dim(
                out_buf, mb_idx, 0, keepdims=False
            )
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(write, y, prev_o), mb_idx, 0
            )
            state = jax.lax.ppermute(y, "pp", fwd_perm)
            return (state, aux_acc, saved_x, out_buf), None

        aux0 = {k: jnp.zeros((), jnp.float32) for k in aux_keys}
        state0 = jnp.zeros((mb, Tl, D), h_mbs.dtype)
        saved0 = jnp.zeros((n_micro, mb, Tl, D), h_mbs.dtype)
        out0 = jnp.zeros((n_micro, mb, Tl, D), h_mbs.dtype)
        (_, aux_acc, saved_x, out_buf), _ = jax.lax.scan(
            step, (state0, aux0, saved0, out0), jnp.arange(steps)
        )
        aux_out = {k: jax.lax.psum(v, ("pp", "sp") if sp > 1 else "pp")
                   for k, v in aux_acc.items()}
        return out_buf, aux_out, saved_x

    manual, iota_spec, layer_specs, act_spec, tok_spec = _stage_specs(
        layer_params, sp > 1
    )
    buf_spec = P("pp", None, "sp", None) if sp > 1 else P("pp")
    return shard_map(
        fwd_body,
        mesh=mesh,
        in_specs=(P("pp"), iota_spec, layer_specs, act_spec, act_spec,
                  act_spec, tok_spec, tok_spec),
        out_specs=(buf_spec, P(), buf_spec),
        axis_names=manual,
    )(jnp.arange(pp, dtype=jnp.int32), jnp.arange(sp, dtype=jnp.int32),
      layer_params, h_mbs, cos_mbs, sin_mbs, seg_mbs, pos_mbs)


def _1f1b_bwd_impl(cfg, mesh, n_micro, attn_impl, remat,
                   layer_params, saved_x, cos_mbs, sin_mbs, seg_mbs,
                   pos_mbs, d_out, d_aux, ring_schedule=None):
    """Hand-written reverse pipeline: at backward step ``t`` stage ``k``
    rematerializes micro-batch ``j = t + k - (pp-1)`` from its saved input
    and vjp's it (under PP∘SP the re-run includes the stage's ring steps —
    ppermute has a transpose rule, so the vjp is exact); the
    input-cotangent rides the transposed ppermute to the predecessor while
    param-cotangents accumulate in place."""
    pp = mesh.shape["pp"]
    sp = mesh.shape.get("sp", 1)
    steps = n_micro + pp - 1
    aux_keys = _aux_keys(cfg)
    stage_fn = _make_stage_fn(cfg, attn_impl, remat)

    def bwd_body(stage_arr, ring_arr, local_layers, saved_x, cos_mbs,
                 sin_mbs, seg_mbs, pos_mbs, d_out, d_aux):
        stage = stage_arr[0]  # P("pp") iota; see _gpipe stage_body note
        ring_ctx = _ring_ctx(ring_arr, sp, ring_schedule)
        bwd_perm = [(k, k - 1) for k in range(1, pp)]
        _, mb, Tl, D = saved_x.shape

        def step(carry, t):
            dstate, dtheta, d_h_buf = carry
            j = t + stage - (pp - 1)
            valid = (j >= 0) & (j < n_micro)
            jc = jnp.clip(j, 0, n_micro - 1)
            take = lambda a: (
                jax.lax.dynamic_index_in_dim(a, jc, 0, keepdims=False)
                if a is not None else None
            )
            x = jax.lax.dynamic_index_in_dim(saved_x, jc, 0, keepdims=False)
            # The last stage reads its cotangent from the output buffer's
            # cotangent (its local d_out block); inner stages receive it
            # from their successor over the reverse ring.
            dy_tail = jax.lax.dynamic_index_in_dim(
                d_out, jc, 0, keepdims=False
            )
            dy = jnp.where(stage == pp - 1, dy_tail, dstate)
            dy = jnp.where(valid, dy, jnp.zeros_like(dy))
            cos_j, sin_j, seg_j, pos_j = (take(cos_mbs), take(sin_mbs),
                                          take(seg_mbs), take(pos_mbs))
            fn = lambda p, xx: stage_fn(p, xx, cos_j, sin_j, seg_j, pos_j,
                                        ring_ctx)
            _, vjp_fn = jax.vjp(fn, local_layers, x)
            vf = valid.astype(jnp.float32)
            d_aux_t = {k: d_aux[k].astype(jnp.float32) * vf
                       for k in aux_keys}
            dp, dx = vjp_fn((dy, d_aux_t))
            # vjp is linear in the cotangent: the masked (zero) dy/d_aux of
            # bubble steps yields exactly-zero dp/dx, so plain accumulation
            # is already bubble-safe.
            dtheta = jax.tree.map(jnp.add, dtheta, dp)
            w0 = valid & (stage == 0)
            prev = jax.lax.dynamic_index_in_dim(
                d_h_buf, jc, 0, keepdims=False
            )
            d_h_buf = jax.lax.dynamic_update_index_in_dim(
                d_h_buf, jnp.where(w0, dx, prev), jc, 0
            )
            dstate = jax.lax.ppermute(dx, "pp", bwd_perm)
            return (dstate, dtheta, d_h_buf), None

        dstate0 = jnp.zeros((mb, Tl, D), saved_x.dtype)
        dtheta0 = jax.tree.map(jnp.zeros_like, local_layers)
        dh0 = jnp.zeros((n_micro, mb, Tl, D), saved_x.dtype)
        (_, dtheta, d_h_buf), _ = jax.lax.scan(
            step, (dstate0, dtheta0, dh0), jnp.arange(steps)
        )
        if sp > 1:
            # Layer params are replicated over the ring: each sp shard's
            # dtheta covers only its sequence shard's tokens — the total
            # is their sum. This backward is hand-written (no shard_map
            # transpose runs), so the psum must be explicit here.
            dtheta = jax.tree.map(
                lambda g: jax.lax.psum(g, "sp"), dtheta
            )
        return dtheta, d_h_buf

    manual, iota_spec, layer_specs, act_spec, tok_spec = _stage_specs(
        layer_params, sp > 1
    )
    buf_spec = P("pp", None, "sp", None) if sp > 1 else P("pp")
    d_layers, d_h_blocks = shard_map(
        bwd_body,
        mesh=mesh,
        in_specs=(P("pp"), iota_spec, layer_specs, buf_spec, act_spec,
                  act_spec, tok_spec, tok_spec, buf_spec, P()),
        out_specs=(P("pp"), buf_spec),
        axis_names=manual,
    )(jnp.arange(pp, dtype=jnp.int32), jnp.arange(sp, dtype=jnp.int32),
      layer_params, saved_x, cos_mbs, sin_mbs, seg_mbs, pos_mbs, d_out,
      d_aux)
    # d_h_blocks concatenates per-stage buffers over "pp"; only stage 0
    # ingests h, so its block (the first) is the input cotangent — a lazy
    # slice, no collective.
    d_h_mbs = jax.lax.slice_in_dim(d_h_blocks, 0, n_micro, axis=0)
    return d_layers, d_h_mbs


def _zero_cotangent(x):
    """Symbolic-zero cotangent: float0 for int leaves (jax's tangent type
    for non-differentiable dtypes), zeros for float leaves, None for None."""
    if x is None:
        return None
    if jnp.issubdtype(x.dtype, jnp.floating):
        return jnp.zeros_like(x)
    return np.zeros(x.shape, jax.dtypes.float0)


def _1f1b_apply_layers(
    cfg, layer_params, h, cos, sin, segment_ids, positions,
    mesh, n_micro, attn_impl, remat, ring_schedule=None,
):
    pp = mesh.shape["pp"]
    B, T, D = h.shape
    assert B % n_micro == 0 and cfg.n_layers % pp == 0
    mb = B // n_micro

    def to_mbs(x):
        return x.reshape((n_micro, mb) + x.shape[1:]) if x is not None else None

    @jax.custom_vjp
    def run(layer_params, h_mbs, cos_mbs, sin_mbs, seg_mbs, pos_mbs):
        out, aux, _ = _1f1b_parts(
            cfg, mesh, n_micro, attn_impl, remat,
            layer_params, h_mbs, cos_mbs, sin_mbs, seg_mbs, pos_mbs,
            ring_schedule,
        )
        return out, aux

    def run_fwd(layer_params, h_mbs, cos_mbs, sin_mbs, seg_mbs, pos_mbs):
        out, aux, saved_x = _1f1b_parts(
            cfg, mesh, n_micro, attn_impl, remat,
            layer_params, h_mbs, cos_mbs, sin_mbs, seg_mbs, pos_mbs,
            ring_schedule,
        )
        res = (layer_params, saved_x, cos_mbs, sin_mbs, seg_mbs, pos_mbs)
        return (out, aux), res

    def run_bwd(res, cts):
        layer_params, saved_x, cos_mbs, sin_mbs, seg_mbs, pos_mbs = res
        d_out, d_aux = cts
        d_layers, d_h_mbs = _1f1b_bwd_impl(
            cfg, mesh, n_micro, attn_impl, remat,
            layer_params, saved_x, cos_mbs, sin_mbs, seg_mbs, pos_mbs,
            d_out, d_aux, ring_schedule,
        )
        return (d_layers, d_h_mbs, _zero_cotangent(cos_mbs),
                _zero_cotangent(sin_mbs), _zero_cotangent(seg_mbs),
                _zero_cotangent(pos_mbs))

    run.defvjp(run_fwd, run_bwd)

    out_blocks, aux = run(layer_params, to_mbs(h), to_mbs(cos), to_mbs(sin),
                          to_mbs(segment_ids), to_mbs(positions))
    # Only the last stage's output buffer holds the pipeline output.
    out = jax.lax.slice_in_dim(
        out_blocks, (pp - 1) * n_micro, pp * n_micro, axis=0
    )
    return out.reshape(B, T, D), _scale_aux(aux, cfg, n_micro)


def backward_residual_bytes(
    cfg: TransformerConfig,
    layer_params,
    h: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    segment_ids: Optional[jnp.ndarray],
    positions: Optional[jnp.ndarray],
    mesh: Mesh,
    n_micro: int,
    attn_impl: str = "auto",
    remat: bool = False,
) -> int:
    """PER-STAGE bytes of activation residuals the 1F1B backward keeps live
    between forward and backward, measured from the ABSTRACT shapes of the
    actual forward (``jax.eval_shape`` of ``_1f1b_parts``) — not a formula
    that can drift from the implementation. Excludes layer params (shared
    with forward, schedule-independent).

    The GPipe oracle has no comparable hook (its residuals are implicit in
    scan autodiff): its per-stage cost is the same set of per-step inputs
    PLUS the ``[steps, mb, T, D]`` stacked output and its cotangent —
    ``>= (steps / n_micro)`` times this number; tests assert the scaling.
    """
    pp = mesh.shape["pp"]
    sp = mesh.shape.get("sp", 1)
    B = h.shape[0]
    mb = B // n_micro
    ring_schedule = (
        ring_mod.resolve_schedule(None, h.shape[1], sp) if sp > 1 else None
    )
    if sp > 1 and segment_ids is None:
        segment_ids = jnp.ones(h.shape[:2], jnp.int32)

    def to_mbs(x):
        return x.reshape((n_micro, mb) + x.shape[1:]) if x is not None else None

    def fwd(lp, h_mbs, cos_mbs, sin_mbs, seg_mbs, pos_mbs):
        _, _, saved_x = _1f1b_parts(
            cfg, mesh, n_micro, attn_impl, remat,
            lp, h_mbs, cos_mbs, sin_mbs, seg_mbs, pos_mbs, ring_schedule,
        )
        return saved_x

    saved = jax.eval_shape(
        fwd, layer_params, to_mbs(h), to_mbs(cos), to_mbs(sin),
        to_mbs(segment_ids), to_mbs(positions),
    )
    total = sum(
        int(np.prod(s.shape)) * s.dtype.itemsize
        for s in jax.tree.leaves(saved)
    )
    return total // pp  # global [pp*n_micro, ...] -> one stage's share
