"""Pipeline parallelism — micro-batch streaming over the mesh's "pp" axis.

Parity target: ``realhf/impl/model/parallelism/pipeline_parallel/`` (the
PipeInstruction VM + static GPipe/1F1B schedules) and its executor
``realhf/impl/model/backend/pipe_runner.py:148``. TPU-first re-design: no
instruction VM, no p2p send/recv threads — the schedule IS a ``lax.scan``
over pipeline steps inside a ``shard_map`` that is *manual over "pp" only*
(``axis_names={"pp"}``): each stage holds ``n_layers/pp`` layers of the
stacked param tree (the "pp"-sharded leading axis, parallel/sharding.py),
runs them on its resident micro-batch, and hands the activation to the next
stage with a nearest-neighbour ``lax.ppermute`` riding the ICI ring. The
dp/fsdp/tp/sp shardings of everything INSIDE a stage stay automatic
(GSPMD) — stages compose with tensor/data parallelism without any manual
collectives.

Schedule: GPipe. ``steps = n_micro + pp - 1``; at step ``s`` stage ``k``
processes micro-batch ``s-k`` (bubble fraction ``(pp-1)/steps``). The
backward pass needs no schedule code at all: ``ppermute`` has a transpose
rule, so ``jax.grad`` of this function IS the reverse pipeline, and
``remat=True`` recomputes each stage's layers in it (GPipe + remat — the
same memory/compute trade the reference's 1F1B+checkpointing makes;
a 1F1B variant would only shrink peak activation memory, not the bubble).

Generation (decode mode) intentionally does NOT pipeline: the decode hot
loop is latency-bound and the generation fleet runs on its own mesh without
a "pp" axis (SURVEY §2.4 note; the reference's GenerateSchedule exists
because its trainer must also generate — our async design moves that to
the server).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from areal_tpu.models.config import TransformerConfig


def pick_pp_microbatches(
    mesh: Optional[Mesh],
    cfg: TransformerConfig,
    batch: int,
    requested: Optional[int] = None,
) -> Optional[int]:
    """The pipeline-eligibility gate: returns the micro-batch count, or
    None when the GSPMD scan path should run instead.

    Requirements: a "pp" axis > 1, layers divisible across stages, a batch
    divisible into >= pp micro-batches, and sp == 1 (ring attention runs
    its own shard_map; composing it inside a manual-pp region is future
    work — such meshes fall back to GSPMD layer sharding, which is correct,
    just not pipelined).
    """
    if mesh is None:
        return None
    pp = mesh.shape.get("pp", 1)
    if pp <= 1 or mesh.shape.get("sp", 1) > 1:
        return None
    if cfg.n_layers % pp != 0:
        return None
    if requested is not None:
        n_micro = requested
        if batch % n_micro != 0:
            return None
        return n_micro
    # Auto: the largest divisor of the batch in [pp, 2*pp] — >= pp keeps
    # the bubble <= 1/2; > 2*pp only shrinks it further at more dispatch.
    for n_micro in range(min(2 * pp, batch), 0, -1):
        if batch % n_micro == 0 and n_micro >= pp:
            return n_micro
    return None  # batch too small to feed every stage


def pipeline_apply_layers(
    cfg: TransformerConfig,
    layer_params: Dict[str, jnp.ndarray],  # stacked [L, ...], "pp"-sharded
    h: jnp.ndarray,  # [B, T, D]
    cos: jnp.ndarray,  # [B, T, dh]
    sin: jnp.ndarray,
    segment_ids: Optional[jnp.ndarray],  # [B, T]
    positions: Optional[jnp.ndarray],  # [B, T]
    mesh: Mesh,
    n_micro: int,
    attn_impl: str = "auto",
    remat: bool = False,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Run the stacked layers as a ``pp``-stage GPipe pipeline.

    Returns (h, aux) matching apply_layer_stack: aux values are reduced so
    that downstream's sum/mean post-processing is an identity — aux_total =
    sum over all layers (averaged over micro-batches), others = mean over
    layers (averaged over micro-batches).
    """
    from areal_tpu.models import transformer as tfm

    pp = mesh.shape["pp"]
    B, T, D = h.shape
    assert B % n_micro == 0 and cfg.n_layers % pp == 0
    mb = B // n_micro
    steps = n_micro + pp - 1

    def to_mbs(x):
        return x.reshape((n_micro, mb) + x.shape[1:]) if x is not None else None

    h_mbs = to_mbs(h)
    cos_mbs, sin_mbs = to_mbs(cos), to_mbs(sin)
    seg_mbs = to_mbs(segment_ids)
    pos_mbs = to_mbs(positions)

    def stage_body(local_layers, h_mbs, cos_mbs, sin_mbs, seg_mbs, pos_mbs):
        stage = jax.lax.axis_index("pp")
        fwd_perm = [(k, k + 1) for k in range(pp - 1)]

        def step(carry, s):
            state, aux_acc = carry
            # Stage 0 ingests micro-batch s; others consume the activation
            # permuted from their predecessor at the previous step.
            mb_idx = jnp.clip(s - stage, 0, n_micro - 1)
            take = lambda x: (
                jax.lax.dynamic_index_in_dim(x, mb_idx, 0, keepdims=False)
                if x is not None else None
            )
            inp = jax.lax.dynamic_index_in_dim(
                h_mbs, jnp.clip(s, 0, n_micro - 1), 0, keepdims=False
            )
            x = jnp.where(stage == 0, inp, state)
            y, aux = tfm.apply_layer_stack(
                cfg, x, local_layers, take(cos_mbs), take(sin_mbs),
                take(seg_mbs), take(pos_mbs), attn_impl=attn_impl,
                remat=remat, allow_ring=False,
            )
            # Bubble steps run garbage (their ys are never sliced out);
            # MoE aux must not count them.
            valid = ((s - stage >= 0) & (s - stage < n_micro)).astype(
                jnp.float32
            )
            aux_acc = {
                k: aux_acc[k] + valid * jnp.sum(v.astype(jnp.float32))
                for k, v in aux.items()
            } if aux else aux_acc
            state = jax.lax.ppermute(y, "pp", fwd_perm)
            return (state, aux_acc), y

        aux0 = {
            k: jnp.zeros((), jnp.float32)
            for k in ("aux_total", "load_balance_loss", "z_loss",
                      "dropped_frac")
        } if cfg.moe is not None else {}
        state0 = jnp.zeros((mb, T, D), h_mbs.dtype)
        (_, aux_acc), ys = jax.lax.scan(
            step, (state0, aux0), jnp.arange(steps)
        )
        # Per-stage aux sums -> totals over all layers/micro-batches.
        aux_out = {
            k: jax.lax.psum(v, "pp") for k, v in aux_acc.items()
        }
        # KNOWN COST: ys stacks each stage's per-step outputs
        # ([steps, mb, T, D] per device ≈ (1 + (pp-1)/n_micro)·[B, T, D])
        # although only the last stage's n_micro blocks are consumed. A
        # carry-buffer formulation (dynamic_update masked to the last
        # stage) removes the overhead but currently trips partial-manual
        # shard_map autodiff (mesh-consistency check in the transpose);
        # revisit when jax's manual-axes vjp handles it.
        return ys, aux_out

    # Manual over "pp" ONLY: layer stacks arrive as local [L/pp, ...]
    # slices; activations stay full-shaped with dp/fsdp/tp handled by
    # GSPMD inside each stage.
    layer_specs = jax.tree.map(lambda _: P("pp"), layer_params)
    n_opt = 4  # cos/sin/segs/pos
    ys, aux = jax.shard_map(
        stage_body,
        mesh=mesh,
        in_specs=(layer_specs, P()) + (P(),) * n_opt,
        out_specs=(P("pp"), P()),
        axis_names=frozenset({"pp"}),
        check_vma=False,
    )(layer_params, h_mbs, cos_mbs, sin_mbs, seg_mbs, pos_mbs)

    # ys is the per-stage step outputs concatenated over "pp":
    # [pp*steps, mb, T, D]; the finished micro-batch i left the LAST stage
    # at step (pp-1) + i.
    last = (pp - 1) * steps + (pp - 1)
    out = jax.lax.dynamic_slice_in_dim(ys, last, n_micro, axis=0)
    out = out.reshape(B, T, D)

    if aux:
        n_layers = float(cfg.n_layers)
        aux = {
            k: v / n_micro if k == "aux_total" else v / (n_layers * n_micro)
            for k, v in aux.items()
        }
    return out, aux
