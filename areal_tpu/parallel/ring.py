"""Ring attention — context parallelism over the mesh's "sp" axis.

Fills the reference's explicit long-context gap (SURVEY §5: "No ring
attention, no Ulysses, no context parallelism anywhere in the repo" — the
reference leans on Megatron-SP + flash-attn only). Design:

 - the sequence dim of q/k/v/segment_ids is sharded over "sp" via
   ``shard_map``; each of the N ring steps computes local attention of the
   resident q block against one rotating KV block and merges it with the
   online-softmax rule (m, l, acc); ``lax.ppermute`` rotates KV around the
   ring so every shard sees every block after N steps while only ever
   holding 1/N of the KV in memory;
 - collectives ride the "sp" ICI ring (nearest-neighbour ppermute), which
   is exactly the topology TPU meshes provide;
 - masking: block-causal by GLOBAL grid column (column order == temporal
   order per document in the packed layout) + same-segment, so packed
   multi-document rows work unchanged;
 - fully differentiable (ppermute has a transpose rule) — no custom VJP
   needed for v1; a Pallas intra-block kernel is the follow-up.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from areal_tpu.parallel.mesh import DATA_AXES

_NEG_INF = -1e30


def _block_attention_online(
    q,  # [B, Tq, Hkv, G, D] (grouped query heads)
    k,  # [B, Tk, Hkv, D]
    v,  # [B, Tk, Hkv, D]
    mask,  # [B, Tq, Tk] bool
    scale: float,
    m,  # [B, Hkv, G, Tq] running max
    l,  # [B, Hkv, G, Tq] running denom
    acc,  # [B, Tq, Hkv, G, D] running numerator
):
    scores = jnp.einsum("btkgd,bskd->bkgts", (q * scale).astype(jnp.float32),
                        k.astype(jnp.float32))
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)
    blk_m = jnp.max(scores, axis=-1)
    new_m = jnp.maximum(m, blk_m)
    # guard fully-masked rows (new_m == -inf): keep them at zero weight
    safe_m = jnp.where(new_m <= _NEG_INF / 2, 0.0, new_m)
    alpha = jnp.exp(m - safe_m) * (m > _NEG_INF / 2)
    p = jnp.exp(scores - safe_m[..., None]) * (scores > _NEG_INF / 2)
    new_l = l * alpha + jnp.sum(p, axis=-1)
    blk_out = jnp.einsum("bkgts,bskd->btkgd", p, v.astype(jnp.float32))
    new_acc = acc * alpha.transpose(0, 3, 1, 2)[..., None] + blk_out
    return new_m, new_l, new_acc


def _ring_attention_local(
    q, k, v, q_seg, kv_seg, axis_name: str, causal: bool, scale: float
):
    """Body run per-shard under shard_map. Shapes are the LOCAL shards:
    q [B, Tl, Hq, D], k/v [B, Tl, Hkv, D], segs [B, Tl]."""
    B, Tl, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)

    qg = q.reshape(B, Tl, Hkv, G, D)
    cols = jax.lax.broadcasted_iota(jnp.int32, (1, Tl), 1)
    q_cols = my * Tl + cols  # [1, Tl] global columns of resident q

    m0 = jnp.full((B, Hkv, G, Tl), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, Tl), jnp.float32)
    acc0 = jnp.zeros((B, Tl, Hkv, G, D), jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        k_blk, v_blk, seg_blk, m, l, acc = carry
        src = (my - i) % n  # ring position this KV block originated from
        kv_cols = src * Tl + cols
        mask = (seg_blk[:, None, :] == q_seg[:, :, None]) & (
            q_seg[:, :, None] > 0
        )
        if causal:
            mask = mask & (q_cols[:, :, None] >= kv_cols[:, None, :])
        m, l, acc = _block_attention_online(
            qg, k_blk, v_blk, mask, scale, m, l, acc
        )
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        seg_blk = jax.lax.ppermute(seg_blk, axis_name, perm)
        return k_blk, v_blk, seg_blk, m, l, acc

    carry = (k, v, kv_seg, m0, l0, acc0)
    for i in range(n):  # static unroll: n is the mesh axis size
        carry = step(i, carry)
    _, _, _, m, l, acc = carry
    denom = jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    out = (acc / denom).reshape(B, Tl, Hq, D)
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # [B, T, Hq, D] — GLOBAL shapes (sharded by GSPMD)
    k: jnp.ndarray,  # [B, T, Hkv, D]
    v: jnp.ndarray,
    segment_ids: jnp.ndarray,  # [B, T]
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Context-parallel attention: sequence dim sharded over ``axis_name``."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    qkv_spec = P(DATA_AXES, axis_name, "tp", None)
    seg_spec = P(DATA_AXES, axis_name)
    fn = partial(
        _ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
    )
    from areal_tpu.parallel.compat import shard_map

    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec, seg_spec),
        out_specs=qkv_spec,
    )(q, k, v, segment_ids, segment_ids)
