"""Ring attention v2 — context parallelism over the mesh's "sp" axis.

Fills the reference's explicit long-context gap (SURVEY §5: "No ring
attention, no Ulysses, no context parallelism anywhere in the repo" — the
reference leans on Megatron-SP + flash-attn only). The v1 contiguous
schedule (every step computes the full local attention einsum) is kept as
the parity ORACLE behind ``AREAL_RING_SCHEDULE=naive``; the default
``zigzag`` schedule is the production path:

 - **zig-zag (striped) layout** — the global sequence splits into ``2n``
   chunks of ``c = T/(2n)``; ring rank ``r`` holds chunk ``r`` (early) and
   chunk ``2n-1-r`` (late), so causal work balances across the ring
   (contiguous layout leaves rank 0 with one visible KV block and rank
   n-1 with all n). The layout is a pure index permutation applied to the
   global sequence dim at the shard boundary (and inverted on the way
   out), so callers see identical global semantics — packed
   multi-document ``segment_ids`` masking included;
 - **masked-block skip** — at ring step ``i > 0`` the visiting KV block's
   origin differs from the resident rank, and under the zig-zag layout
   exactly two of the four (q-half × kv-half) tiles are causally visible:
   ``q_late × kv_early`` always, plus ``q_early × kv_early`` when the
   block came from a lower rank or ``q_late × kv_late`` from a higher
   one. The *count* of executed tiles is a trace-time constant — the
   fully-masked tiles are never built — so per step only half the naive
   area runs and the total is ``(n+1)/2n`` of v1's FLOPs (the step-0
   diagonal still needs the full causal mask). Which tile runs is traced
   (``jnp.where`` on operands and accumulators), keeping shapes static;
 - **comm/compute overlap** — the ``lax.ppermute`` rotating KV+segments to
   the next rank is issued *before* the current block's compute
   (double-buffering), so XLA's latency-hiding scheduler can fly the
   transfer under the einsums; the final (useless) rotation is dropped
   (``n-1`` rotations vs v1's ``n``);
 - masking: block-causal by GLOBAL grid column + same-segment, padding
   (segment 0) always masked; fully differentiable (``ppermute`` has a
   transpose rule) — no custom VJP.

Two entry points: :func:`ring_attention` wraps its own full-manual
``shard_map`` (the GSPMD forward path), while :func:`ring_attention_inline`
runs the same local body for callers *already inside* a manual region over
the ring axis — the PP∘SP pipeline stages (parallel/pipeline.py), which
build a :class:`RingCtx` from their sharded-iota rank.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from areal_tpu.parallel.compat import shard_map
from areal_tpu.parallel.mesh import DATA_AXES

_NEG_INF = -1e30

SCHEDULES = ("zigzag", "naive")

# Trace-time structural counters: incremented while the schedule is being
# traced (plain Python), so tests can prove the masked-block skip without
# inspecting HLO — executed_area counts q×kv cells actually handed to
# _block_attention_online, naive_area what the v1 schedule would run.
_COUNTERS: Dict[str, int] = {
    "block_calls": 0, "executed_area": 0, "naive_area": 0,
}


def reset_ring_counters() -> None:
    for k in _COUNTERS:
        _COUNTERS[k] = 0


def ring_counters() -> Dict[str, int]:
    return dict(_COUNTERS)


def ring_skip_ratio() -> float:
    """executed/naive attention area of everything traced since the last
    reset: 1.0 for the naive schedule, (n+1)/2n for zig-zag at sp=n."""
    if not _COUNTERS["naive_area"]:
        return 1.0
    return _COUNTERS["executed_area"] / _COUNTERS["naive_area"]


def resolve_schedule(schedule: Optional[str], seq_len: int, n: int,
                     causal: bool = True) -> str:
    """The schedule actually run: explicit arg > ``AREAL_RING_SCHEDULE`` >
    "zigzag"; downgrades to "naive" when zig-zag can't apply (non-causal
    attention skips nothing; the layout needs ``T % 2n == 0``)."""
    if schedule is None:
        schedule = os.environ.get("AREAL_RING_SCHEDULE", "zigzag")
    if schedule not in SCHEDULES:
        raise ValueError(
            f"unknown ring schedule {schedule!r} (one of {SCHEDULES})"
        )
    if schedule == "zigzag" and (not causal or n < 2 or seq_len % (2 * n)):
        schedule = "naive"
    return schedule


def zigzag_permutation(seq_len: int, n: int) -> np.ndarray:
    """Gather indices mapping the natural sequence order to the zig-zag
    shard layout: position block ``r`` of the permuted sequence holds
    chunks ``(r, 2n-1-r)`` of the original. An involution it is not —
    invert with :func:`inverse_permutation`."""
    assert seq_len % (2 * n) == 0, (seq_len, n)
    c = seq_len // (2 * n)
    idx = [
        np.arange(r * c, (r + 1) * c)
        for rank in range(n)
        for r in (rank, 2 * n - 1 - rank)
    ]
    return np.concatenate(idx)


def inverse_permutation(perm: np.ndarray) -> np.ndarray:
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.size)
    return inv


@dataclass(frozen=True)
class RingCtx:
    """Ring parameters for callers already inside a manual shard_map region
    over ``axis_name`` (the PP∘SP pipeline stages): ``n`` is the static
    ring size, ``my`` the traced rank of this shard — derived from a
    sharded iota, because ``lax.axis_index`` lowers to a PartitionId
    instruction older partial-manual partitioners reject."""
    axis_name: str
    n: int
    my: jnp.ndarray
    schedule: str


def _block_attention_online(
    q,  # [B, Tq, Hkv, G, D] (grouped query heads)
    k,  # [B, Tk, Hkv, D]
    v,  # [B, Tk, Hkv, D]
    mask,  # [B, Tq, Tk] bool
    scale: float,
    m,  # [B, Hkv, G, Tq] running max
    l,  # [B, Hkv, G, Tq] running denom
    acc,  # [B, Tq, Hkv, G, D] running numerator
):
    _COUNTERS["block_calls"] += 1
    _COUNTERS["executed_area"] += int(q.shape[1]) * int(k.shape[1])
    scores = jnp.einsum("btkgd,bskd->bkgts", (q * scale).astype(jnp.float32),
                        k.astype(jnp.float32))
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)
    blk_m = jnp.max(scores, axis=-1)
    new_m = jnp.maximum(m, blk_m)
    # guard fully-masked rows (new_m == -inf): keep them at zero weight
    safe_m = jnp.where(new_m <= _NEG_INF / 2, 0.0, new_m)
    alpha = jnp.exp(m - safe_m) * (m > _NEG_INF / 2)
    p = jnp.exp(scores - safe_m[..., None]) * (scores > _NEG_INF / 2)
    new_l = l * alpha + jnp.sum(p, axis=-1)
    blk_out = jnp.einsum("bkgts,bskd->btkgd", p, v.astype(jnp.float32))
    new_acc = acc * alpha.transpose(0, 3, 1, 2)[..., None] + blk_out
    return new_m, new_l, new_acc


def _seg_mask(q_seg, kv_seg):
    """[B, Tq, Tk] same-segment mask with padding (segment 0) excluded."""
    return (kv_seg[:, None, :] == q_seg[:, :, None]) & (q_seg[:, :, None] > 0)


def _finish(acc, l, B, Tq, Hq, D):
    denom = jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    return (acc / denom).reshape(B, Tq, Hq, D)


def _ring_local_naive(q, k, v, q_seg, axis_name, n, my, causal, scale):
    """The v1 contiguous schedule, kept verbatim as the parity oracle:
    every step runs the full Tl×Tl block with causal+segment masking and
    rotates afterwards. Shapes are the LOCAL shards: q [B, Tl, Hq, D],
    k/v [B, Tl, Hkv, D], q_seg [B, Tl]."""
    B, Tl, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    _COUNTERS["naive_area"] += n * Tl * Tl

    qg = q.reshape(B, Tl, Hkv, G, D)
    cols = jax.lax.broadcasted_iota(jnp.int32, (1, Tl), 1)
    q_cols = my * Tl + cols  # [1, Tl] global columns of resident q

    m0 = jnp.full((B, Hkv, G, Tl), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, Tl), jnp.float32)
    acc0 = jnp.zeros((B, Tl, Hkv, G, D), jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        k_blk, v_blk, seg_blk, m, l, acc = carry
        src = (my - i) % n  # ring position this KV block originated from
        kv_cols = src * Tl + cols
        mask = _seg_mask(q_seg, seg_blk)
        if causal:
            mask = mask & (q_cols[:, :, None] >= kv_cols[:, None, :])
        m, l, acc = _block_attention_online(
            qg, k_blk, v_blk, mask, scale, m, l, acc
        )
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        seg_blk = jax.lax.ppermute(seg_blk, axis_name, perm)
        return k_blk, v_blk, seg_blk, m, l, acc

    # step 0's KV block is the shard's own: kv_seg == q_seg.
    carry = (k, v, q_seg, m0, l0, acc0)
    for i in range(n):  # static unroll: n is the mesh axis size
        carry = step(i, carry)
    _, _, _, m, l, acc = carry
    return _finish(acc, l, B, Tl, Hq, D).astype(q.dtype)


def _ring_local_zigzag(q, k, v, q_seg, axis_name, n, my, scale):
    """The production schedule (causal only). The local shard is two
    chunks of c = Tl/2: early (global chunk ``my``) and late (chunk
    ``2n-1-my``), each with its own online-softmax accumulator. Step 0
    runs the resident diagonal — two half-height calls against the full
    local KV under the real causal mask. Every later step's visiting
    block (origin ``src != my``) decomposes into exactly two fully-visible
    c×c tiles: ``q_late × kv_early`` (kv chunk ``src < n <= 2n-1-my``)
    always, and ``q_early × kv_early`` when ``src < my`` (kv chunk
    ``src < my``) else ``q_late × kv_late`` (kv chunk ``2n-1-src <
    2n-1-my``) — so those tiles need only the segment mask, and the other
    two tiles of the naive schedule are never built. Executed area:
    ``Tl² + (n-1)·Tl²/2 = (n+1)/2n`` of naive's ``n·Tl²``."""
    B, Tl, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    c = Tl // 2
    _COUNTERS["naive_area"] += n * Tl * Tl

    qg = q.reshape(B, Tl, Hkv, G, D)
    qg_e, qg_l = qg[:, :c], qg[:, c:]
    seg_e, seg_l = q_seg[:, :c], q_seg[:, c:]

    # Global columns of the local zig-zag layout (my is traced; the mask
    # contents are data, only the tile structure must be static).
    j = jnp.arange(Tl, dtype=jnp.int32)
    gcols = jnp.where(j < c, my * c + j, (2 * n - 1 - my) * c + (j - c))

    def fresh():
        m = jnp.full((B, Hkv, G, c), _NEG_INF, jnp.float32)
        l = jnp.zeros((B, Hkv, G, c), jnp.float32)
        acc = jnp.zeros((B, c, Hkv, G, D), jnp.float32)
        return m, l, acc

    m_e, l_e, acc_e = fresh()
    m_l, l_l, acc_l = fresh()

    perm = [(r, (r + 1) % n) for r in range(n)]
    k_cur, v_cur, s_cur = k, v, q_seg
    for i in range(n):  # static unroll: n is the mesh axis size
        if i + 1 < n:
            # Double buffering: the rotation for step i+1 is issued before
            # this step's compute, which does not depend on it — the
            # latency-hiding scheduler overlaps transfer with the einsums.
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            s_nxt = jax.lax.ppermute(s_cur, axis_name, perm)
        if i == 0:
            # Resident diagonal: both q halves against the full local KV
            # under the true causal mask (the only step that needs one).
            causal_e = gcols[:c][None, :, None] >= gcols[None, None, :]
            causal_l = gcols[c:][None, :, None] >= gcols[None, None, :]
            m_e, l_e, acc_e = _block_attention_online(
                qg_e, k_cur, v_cur, _seg_mask(seg_e, s_cur) & causal_e,
                scale, m_e, l_e, acc_e,
            )
            m_l, l_l, acc_l = _block_attention_online(
                qg_l, k_cur, v_cur, _seg_mask(seg_l, s_cur) & causal_l,
                scale, m_l, l_l, acc_l,
            )
        else:
            src = (my - i) % n
            k_be, k_bl = k_cur[:, :c], k_cur[:, c:]
            v_be, v_bl = v_cur[:, :c], v_cur[:, c:]
            ks_e, ks_l = s_cur[:, :c], s_cur[:, c:]
            # Tile 1 — resident late rows × visiting early chunk: fully
            # causally visible for every src, segment mask only.
            m_l, l_l, acc_l = _block_attention_online(
                qg_l, k_be, v_be, _seg_mask(seg_l, ks_e),
                scale, m_l, l_l, acc_l,
            )
            # Tile 2 — which q/kv halves pair up depends on the (traced)
            # origin, but either pairing is fully visible; select the
            # operands and the matching accumulator with where.
            low = src < my
            qs = jnp.where(low, qg_e, qg_l)
            kk = jnp.where(low, k_be, k_bl)
            vv = jnp.where(low, v_be, v_bl)
            qsg = jnp.where(low, seg_e, seg_l)
            ksg = jnp.where(low, ks_e, ks_l)
            m_s = jnp.where(low, m_e, m_l)
            l_s = jnp.where(low, l_e, l_l)
            a_s = jnp.where(low, acc_e, acc_l)
            m2, l2, a2 = _block_attention_online(
                qs, kk, vv, _seg_mask(qsg, ksg), scale, m_s, l_s, a_s,
            )
            m_e = jnp.where(low, m2, m_e)
            l_e = jnp.where(low, l2, l_e)
            acc_e = jnp.where(low, a2, acc_e)
            m_l = jnp.where(low, m_l, m2)
            l_l = jnp.where(low, l_l, l2)
            acc_l = jnp.where(low, acc_l, a2)
        if i + 1 < n:
            k_cur, v_cur, s_cur = k_nxt, v_nxt, s_nxt

    out = jnp.concatenate(
        [_finish(acc_e, l_e, B, c, Hq, D), _finish(acc_l, l_l, B, c, Hq, D)],
        axis=1,
    )
    return out.astype(q.dtype)


def _ring_local(q, k, v, q_seg, axis_name, n, my, causal, scale, schedule):
    """Schedule dispatch for the per-shard body. ``my=None`` means "ask
    the axis" (full-manual regions, where lax.axis_index lowers fine)."""
    if my is None:
        my = jax.lax.axis_index(axis_name)
    if schedule == "zigzag" and causal:
        return _ring_local_zigzag(q, k, v, q_seg, axis_name, n, my, scale)
    return _ring_local_naive(q, k, v, q_seg, axis_name, n, my, causal, scale)


def ring_attention_inline(
    q, k, v, segment_ids, ctx: RingCtx,
    causal: bool = True, scale: Optional[float] = None,
):
    """Local-shard ring attention for callers already inside a manual
    shard_map region over ``ctx.axis_name`` (the PP∘SP pipeline stages).
    Shapes are the LOCAL shards; for the zig-zag schedule the layout
    permutation is the caller's responsibility — pipeline_apply_layers
    applies it (and its inverse) globally at the region boundary."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _ring_local(
        q, k, v, segment_ids, ctx.axis_name, ctx.n, ctx.my,
        causal, scale, ctx.schedule,
    )


def ring_eligible(mesh: Optional[Mesh], cfg, batch: int, seq_len: int,
                  axis_name: str = "sp") -> bool:
    """Whether the shapes admit ring attention on this mesh: shard_map
    needs divisible shapes (e.g. generate()'s unbucketed batch dim does
    not divide), and sliding-window attention is not ring-expressible."""
    if mesh is None or mesh.shape.get(axis_name, 1) <= 1:
        return False
    return (
        cfg.sliding_window is None
        and batch % (mesh.shape["dp"] * mesh.shape["fsdp"]
                     * dict(mesh.shape).get("ep", 1)) == 0
        and seq_len % mesh.shape[axis_name] == 0
        and cfg.n_q_heads % mesh.shape["tp"] == 0
        and cfg.n_kv_heads % mesh.shape["tp"] == 0
    )


def ring_attention(
    q: jnp.ndarray,  # [B, T, Hq, D] — GLOBAL shapes (sharded by GSPMD)
    k: jnp.ndarray,  # [B, T, Hkv, D]
    v: jnp.ndarray,
    segment_ids: jnp.ndarray,  # [B, T]
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    schedule: Optional[str] = None,  # None → AREAL_RING_SCHEDULE → "zigzag"
) -> jnp.ndarray:
    """Context-parallel attention: sequence dim sharded over ``axis_name``."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    T = q.shape[1]
    n = mesh.shape[axis_name]
    schedule = resolve_schedule(schedule, T, n, causal)
    if schedule == "zigzag":
        # Shard-boundary layout permutation: a static gather on the global
        # sequence dim, inverted on the way out — global semantics are
        # untouched, only which rank holds which chunks changes.
        fwd = zigzag_permutation(T, n)
        inv = jnp.asarray(inverse_permutation(fwd))
        fwd = jnp.asarray(fwd)
        q, k, v = (jnp.take(x, fwd, axis=1) for x in (q, k, v))
        segment_ids = jnp.take(segment_ids, fwd, axis=1)
    qkv_spec = P(DATA_AXES, axis_name, "tp", None)
    seg_spec = P(DATA_AXES, axis_name)
    fn = partial(
        _ring_local, axis_name=axis_name, n=n, my=None, causal=causal,
        scale=scale, schedule=schedule,
    )
    out = shard_map(
        fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec),
        out_specs=qkv_spec,
    )(q, k, v, segment_ids)
    if schedule == "zigzag":
        out = jnp.take(out, inv, axis=1)
    return out
