"""Version compatibility for manual-collective APIs.

``jax.shard_map`` graduated out of ``jax.experimental.shard_map`` after the
0.4 series, renaming two knobs on the way:

 - ``axis_names={"pp"}``  (manual axes)   was ``auto=<complement>``
 - ``check_vma=False``    (per-value rep) was ``check_rep=False``

areal_tpu supports both spellings so the parallel layer (pipeline.py,
ring.py) runs on the jax baked into the TPU image *and* on the 0.4.3x CPU
test image. All call sites go through :func:`shard_map` below, which takes
the modern signature and translates when only the experimental API exists.
"""

from __future__ import annotations

from typing import Optional, Set

import jax

__all__ = ["shard_map"]


def shard_map(f, mesh, in_specs, out_specs,
              axis_names: Optional[Set[str]] = None):
    """``jax.shard_map(..., check_vma=False)`` with a fallback to
    ``jax.experimental.shard_map.shard_map(..., check_rep=False)``.

    ``axis_names`` is the set of mesh axes the body is MANUAL over (None =
    all of them); the experimental API expresses the same thing inverted,
    as the ``auto`` complement set.
    """
    modern = getattr(jax, "shard_map", None)
    if modern is not None:
        kwargs = {}
        if axis_names is not None:
            kwargs["axis_names"] = frozenset(axis_names)
        return modern(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False, **kwargs)
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
            if axis_names is not None else frozenset())
    return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False, auto=auto)
