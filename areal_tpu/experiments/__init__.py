"""Experiment definitions (reference ``realhf/experiments/``).

Each experiment class is a dataclass config (merged from YAML + CLI by
``areal_tpu.api.cli_args``) whose ``initial_setup()`` turns the declarative
pieces — model roles, MFC knobs, dataset, allocation mode — into the
concrete DFG + worker configs the launcher spawns.
"""

from typing import Dict, Type

_REGISTRY: Dict[str, type] = {}


def register_experiment(name: str, cls: type) -> None:
    _REGISTRY[name] = cls


def make_experiment_cls(name: str) -> Type:
    # import for registration side effects
    import areal_tpu.experiments.async_ppo_math_exp  # noqa: F401
    import areal_tpu.experiments.ppo_math_exp  # noqa: F401
    import areal_tpu.experiments.sft_exp  # noqa: F401

    if name not in _REGISTRY:
        raise ValueError(
            f"unknown experiment '{name}'; have {sorted(_REGISTRY)}"
        )
    return _REGISTRY[name]


def registered_name_of(cfg) -> str:
    """Reverse registry lookup for a config instance — the most-derived
    registered class wins (AsyncPPOMATHConfig subclasses PPOMATHConfig)."""
    import areal_tpu.experiments.async_ppo_math_exp  # noqa: F401
    import areal_tpu.experiments.ppo_math_exp  # noqa: F401
    import areal_tpu.experiments.sft_exp  # noqa: F401

    best = None
    for name, cls in _REGISTRY.items():
        if isinstance(cfg, cls) and (
            best is None or issubclass(cls, _REGISTRY[best])
        ):
            best = name
    if best is None:
        raise ValueError(f"{type(cfg).__name__} is not a registered "
                         "experiment config")
    return best
