"""Async PPO (math/code) experiment definition.

Parity target: ``realhf/experiments/async_exp/async_ppo_math_exp.py:26`` +
``async_rl_exp.py:60`` — generation leaves the DFG (the master never sees
``actor_gen``; rollout workers drive the generation fleet and push
trajectories over ZMQ into the trainer's stream dataset), rewards are
computed rollout-side by the env, and ``version_start/version_end`` keys
ride along for the decoupled loss. The 4 rollout-side worker groups
(generation servers, gserver manager, rollout workers + the trainer's
puller) are generated here from the allocation mode.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

from areal_tpu.experiments import register_experiment
from areal_tpu.experiments import common as C
from areal_tpu.experiments.ppo_math_exp import PPOMATHConfig
from areal_tpu.system import serving


@dataclasses.dataclass
class AsyncPPOMATHConfig(PPOMATHConfig):
    """Adds the reference's AsyncRLOptions (cli_args.py:1104)."""

    new_tokens_per_chunk: int = 1 << 10
    max_head_offpolicyness: int = 0
    n_rollout_workers: int = 1
    max_concurrent_rollouts: int = 64
    flush_request_timeout: int = 120
    schedule_policy: str = "round_robin"
    # generation-server knobs (the reference's SGLangConfig analogue)
    gen_batch_window_ms: int = 5
    gen_max_batch_size: int = 64
    gen_prompt_bucket: int = 128

    def initial_setup(self) -> Dict[str, Any]:
        from areal_tpu.system.generation_server import GenerationServerConfig
        from areal_tpu.system.gserver_manager import GserverManagerConfig
        from areal_tpu.system.rollout_worker import RolloutWorkerConfig

        alloc = C.resolve_allocation(self)
        n_gen = 1
        if alloc.decoupled and alloc.gen_spec is not None:
            # One in-process server per gen data-parallel replica; tp/sp of
            # the gen spec shard each server's decode over its slice.
            n_gen = alloc.gen_spec.data_degree
        paths = C.experiment_paths(self)
        # The shared experiment->policy mapping (system/serving.py): the
        # SAME kwargs cli_args.validate_config already front-ran at parse
        # time, so the spawned servers construct exactly the validated
        # shape policy.
        shape_kw = serving.experiment_policy_kwargs(self)
        gen_servers = [
            GenerationServerConfig(
                experiment=self.experiment_name, trial=self.trial_name,
                server_id=f"gen{i}",
                chunk_tokens=shape_kw["chunk_tokens"],
                batch_window_ms=self.gen_batch_window_ms,
                max_batch_size=shape_kw["max_batch_size"],
                prompt_bucket=shape_kw["prompt_bucket"],
                kv_bucket=shape_kw["kv_bucket"],
                weight_stream_pipeline_depth=self.weight_sync.pipeline_depth,
                serving=self.serving,
                telemetry=self._telemetry(),
                goodput=self.goodput,
                compile_watch=self.compile_watch,
                keepalive_ttl_secs=self.fault_tolerance.keepalive_ttl_secs,
            )
            for i in range(n_gen)
        ]
        manager = GserverManagerConfig(
            experiment=self.experiment_name, trial=self.trial_name,
            model_role="actor", n_servers=n_gen,
            # Staleness counts in SAMPLE (trajectory) units — reference
            # async_rl_exp.py:327 passes train_rpcs[0].n_seqs.
            train_batch_size=self.dataset.train_bs_n_seqs * self.group_size,
            max_head_offpolicyness=self.max_head_offpolicyness,
            max_concurrent_rollouts=self.max_concurrent_rollouts,
            schedule_policy=self.schedule_policy,
            realloc_dir=paths["realloc"],
            telemetry=self._telemetry(),
            keepalive_ttl_secs=self.fault_tolerance.keepalive_ttl_secs,
            # Elastic fleet (docs/fault_tolerance.md §Autoscaling): the
            # manager hosts the scaling loop; the launcher-side executor
            # reads the same config to spawn dynamic servers.
            autoscale=self.autoscale,
        )
        rollout_workers = [
            RolloutWorkerConfig(
                experiment=self.experiment_name, trial=self.trial_name,
                worker_index=i, n_workers=self.n_rollout_workers,
                dataset_path=self.dataset.path,
                gconfig=dataclasses.replace(
                    self.ppo.gen, n=self.group_size
                ),
                group_size=self.group_size,
                chunk_tokens=self.new_tokens_per_chunk,
                max_concurrent=max(
                    1, self.max_concurrent_rollouts // self.n_rollout_workers
                ),
                seed=self.seed + i,
                # Async-recovery skiplist lives next to the master's
                # recover checkpoints (rollout_worker.ConsumedLog).
                recover_dir=paths["recover"],
                telemetry=self._telemetry(),
                goodput=self.goodput,
                # Sandbox reward fleet (docs/rewards.md): enabled, agent
                # reward callbacks grade over HTTP on the reward workers
                # below instead of in the rollout process.
                reward_service=self.reward_service,
                # Durable trajectory spool (docs/fault_tolerance.md §Data
                # durability): off by default; when enabled each worker
                # spools under recover_dir before marking prompts consumed.
                durability=self.durability,
            )
            for i in range(self.n_rollout_workers)
        ]
        setup = {
            "dfg": self.build_dfg(self.dataset.train_bs_n_seqs,
                                  async_mode=True),
            "master": self.build_master_config(async_mode=True),
            "trainer": self.build_trainer_config(async_mode=True),
            "gen_servers": gen_servers,
            "gserver_manager": manager,
            "rollout_workers": rollout_workers,
        }
        if self.reward_service.enabled:
            setup["reward_workers"] = self.build_reward_workers()
        return setup


register_experiment("async-ppo-math", AsyncPPOMATHConfig)
