"""SFT experiment definition (reference ``realhf/experiments/common/sft_exp.py``).

One-node DFG: ``trainDefault`` TRAIN_STEP on the packed CE interface over
``packed_input_ids`` + ``prompt_mask`` batches from the prompt-answer
dataset.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

from areal_tpu.api.cli_args import (
    BaseExperimentConfig,
    MFCConfig,
    ModelTrainEvalConfig,
    PromptAnswerDatasetConfig,
)
from areal_tpu.api.dfg import (
    MFCDef,
    MFCInterfaceType,
    ModelInterfaceAbstraction,
    build_graph,
)
from areal_tpu.api.model import FinetuneSpec
from areal_tpu.experiments import register_experiment
from areal_tpu.experiments import common as C


@dataclasses.dataclass
class SFTConfig(BaseExperimentConfig):
    model: ModelTrainEvalConfig = dataclasses.field(
        default_factory=ModelTrainEvalConfig
    )
    allocation: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    dataset: PromptAnswerDatasetConfig = dataclasses.field(
        default_factory=PromptAnswerDatasetConfig
    )

    def initial_setup(self) -> Dict[str, Any]:
        from areal_tpu.system.master_worker import MasterWorkerConfig
        from areal_tpu.system.trainer_worker import (
            MFCRuntimeConfig,
            ModelRoleConfig,
            TrainerWorkerConfig,
        )

        alloc = C.resolve_allocation(self)
        paths = C.experiment_paths(self)
        dfg = build_graph([MFCDef(
            name="trainDefault", model_name="default",
            interface_type=MFCInterfaceType.TRAIN_STEP,
            interface_impl=ModelInterfaceAbstraction("sft"),
            input_keys=("packed_input_ids", "prompt_mask"),
            n_seqs=self.dataset.train_bs_n_seqs,
            mb_spec=self.allocation.mb_spec,
        )])
        trainer = TrainerWorkerConfig(
            experiment=self.experiment_name, trial=self.trial_name,
            handler="trainer",
            models={"default": ModelRoleConfig(
                init=C.model_init_dict(self.model),
                backend_args=C.backend_args_for(
                    self.model, alloc.global_spec, 10000
                ),
            )},
            mfcs={"trainDefault": MFCRuntimeConfig(
                interface="sft", model_name="default"
            )},
            dataset="prompt_answer",
            dataset_args={"dataset_path": self.dataset.path,
                          "max_length": self.dataset.max_seqlen},
            batch_size=self.dataset.train_bs_n_seqs,
            ft_spec=FinetuneSpec(
                total_train_epochs=self.exp_ctrl.total_train_epochs,
                dataset_size=10000,
                train_batch_size=self.dataset.train_bs_n_seqs,
            ),
            realloc_dir=paths["realloc"],
        )
        master = MasterWorkerConfig(
            experiment=self.experiment_name, trial=self.trial_name,
            trainer_handler="trainer",
            train_batch_size=self.dataset.train_bs_n_seqs,
            exp_ctrl=self.exp_ctrl,
            save_dir=paths["save"],
        )
        return {"dfg": dfg, "master": master, "trainer": trainer}


register_experiment("sft", SFTConfig)
