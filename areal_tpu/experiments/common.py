"""Shared experiment-building helpers.

Parity target: ``realhf/experiments/common/common.py:72``
(CommonExperimentConfig) — resolving the allocation mode, turning model
role configs into worker configs, and sanity-checking the result. The TPU
collapse: no RPCAllocation search over GPU sub-meshes; one trainer process
owns the whole trainer mesh (GSPMD shards inside it), and the generation
fleet owns a disjoint slice when the allocation mode is decoupled.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from areal_tpu.api.cli_args import (
    BaseExperimentConfig,
    ModelTrainEvalConfig,
)
from areal_tpu.base import constants
from areal_tpu.parallel.mesh import AllocationMode, ParallelSpec


def resolve_allocation(cfg: BaseExperimentConfig) -> AllocationMode:
    """Parse ``allocation_mode`` (default: all chips, pure dp)."""
    total = cfg.n_nodes * cfg.n_gpus_per_node
    if not cfg.allocation_mode:
        return AllocationMode(global_spec=ParallelSpec(dp=total))
    return AllocationMode.parse(cfg.allocation_mode)


# Which MFCs a model role serves, train-MFC first: a per-MFC allocation
# override for any of these steers the whole role's engine (one engine per
# role; the train layout wins when both a train and an inf MFC are named).
ROLE_MFCS: Dict[str, tuple] = {
    "actor": ("actor_train", "actor_inf", "actor_gen"),
    "critic": ("critic_train", "critic_inf"),
    "ref": ("ref_inf", "fused_rew_ref_inf"),
    "rew": ("rew_inf", "fused_rew_ref_inf"),
}


def spec_for_role(alloc: AllocationMode, role: str) -> Optional[ParallelSpec]:
    """The ParallelSpec a model role's engine runs under.

    Heterogeneous per-MFC allocations (``AllocationMode.per_mfc``, e.g.
    ``actor_train:f2t2,ref_inf:d2``) place each named MFC on its own
    sub-mesh; roles without an override inherit ``global_spec``. Data
    crossing between differently-sharded roles (param realloc, device
    weight sync) is moved on device by parallel/reshard.py at the MFC
    boundary.
    """
    for mfc in ROLE_MFCS.get(role, ()):
        spec = alloc.per_mfc.get(mfc)
        if spec is not None:
            return spec
    return alloc.global_spec


def model_init_dict(mc: ModelTrainEvalConfig) -> Dict[str, Any]:
    """ModelTrainEvalConfig → TrainerWorker ModelRoleConfig.init dict."""
    if mc.tiny:
        return {"tiny": dict(mc.tiny)}
    if mc.type._class == "null" or (not mc.path and not mc.init_from_scratch):
        return {"null": True}
    if mc.path:
        return {"hf_dir": mc.path}
    raise ValueError(
        f"model config {mc} has init_from_scratch but no size spec; "
        "use `tiny` or provide a path"
    )


def backend_args_for(
    mc: ModelTrainEvalConfig,
    spec: Optional[ParallelSpec],
    total_train_steps: int,
) -> Dict[str, Any]:
    args: Dict[str, Any] = {
        "optimizer": mc.optimizer,
        "compute_dtype": "bfloat16" if mc.bf16 else "float32",
        "remat": mc.gradient_checkpointing,
    }
    if mc.tiny:
        # CPU-test scale: small buckets so tiny batches don't pad to 128.
        args.update(compute_dtype="float32", length_bucket=16,
                    rows_bucket=2, seqs_bucket=4, remat=False)
    if spec is not None and spec.world_size > 1:
        args["parallel_spec"] = str(spec)
    return args


def make_tokenizer(cfg: BaseExperimentConfig, model_path: str):
    if cfg.mock_tokenizer:
        from areal_tpu.base.testing import MockTokenizer

        return MockTokenizer()
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(model_path)


def experiment_paths(cfg: BaseExperimentConfig) -> Dict[str, str]:
    paths = constants.experiment_paths(
        cfg.experiment_name, cfg.trial_name, fileroot=cfg.cluster.fileroot
    )
    if cfg.cluster.name_resolve.nfs_record_root:
        paths["name_resolve"] = cfg.cluster.name_resolve.nfs_record_root
    return paths


def setup_name_resolve(cfg: BaseExperimentConfig) -> None:
    """Configure the process-global name-resolve repo.

    Child worker processes must call this again (module globals don't cross
    a spawn boundary). NFS roots default under the experiment fileroot.
    """
    import dataclasses as dc

    from areal_tpu.base import name_resolve

    constants.set_experiment_trial_names(cfg.experiment_name, cfg.trial_name)
    nr = cfg.cluster.name_resolve
    if nr.type == "nfs" and not nr.nfs_record_root:
        nr = dc.replace(nr, nfs_record_root=experiment_paths(cfg)["name_resolve"])
    name_resolve.reconfigure(nr)
