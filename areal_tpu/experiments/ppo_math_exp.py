"""Sync PPO (math/code) experiment definition.

Parity target: ``realhf/experiments/common/ppo_math_exp.py:30``
(PPOMATHConfig) — builds the up-to-7-node PPO DFG

    actor_gen → {rew_inf, ref_inf, actor_inf, critic_inf}
              → {actor_train, critic_train}

with the reference's conditional pruning:
 - ``ppo.disable_value``     (GRPO) drops critic_inf/critic_train,
 - ``ppo.kl_ctl == 0``       drops ref_inf,
 - ``ppo.recompute_logprob or ppo.use_decoupled_loss`` adds actor_inf
   (proximal-logprob recompute, the decoupled-loss center),
 - ref-EMA via a ParamReallocHook on actor_train (``:345-364``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from areal_tpu.algorithms.ppo import PPOHyperparameters
from areal_tpu.api.cli_args import (
    BaseExperimentConfig,
    MFCConfig,
    ModelTrainEvalConfig,
    PromptOnlyDatasetConfig,
)
from areal_tpu.api.dfg import (
    DataFlowGraph,
    MFCDef,
    MFCInterfaceType,
    ModelInterfaceAbstraction,
    ParamReallocHook,
    WeightUpdateHook,
    build_graph,
)
from areal_tpu.api.model import FinetuneSpec
from areal_tpu.base import logging
from areal_tpu.experiments import register_experiment
from areal_tpu.experiments import common as C

logger = logging.getLogger("experiments.ppo_math")

# Keys produced by the generate MFC (trajectory contract, §2.9 of SURVEY).
TRAJ_KEYS = (
    "packed_input_ids", "prompt_mask", "packed_logprobs",
    "seq_no_eos_mask", "task_ids", "version_start", "version_end",
)


@dataclasses.dataclass
class PPOMATHConfig(BaseExperimentConfig):
    """CLI surface mirrors the reference so run scripts port verbatim."""

    actor: ModelTrainEvalConfig = dataclasses.field(
        default_factory=ModelTrainEvalConfig
    )
    ref: ModelTrainEvalConfig = dataclasses.field(
        default_factory=ModelTrainEvalConfig
    )
    critic: ModelTrainEvalConfig = dataclasses.field(
        default_factory=lambda: ModelTrainEvalConfig()
    )
    rew: ModelTrainEvalConfig = dataclasses.field(
        default_factory=lambda: ModelTrainEvalConfig()
    )

    actor_train: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    actor_gen: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    actor_inf: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    critic_train: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    critic_inf: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    rew_inf: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    ref_inf: MFCConfig = dataclasses.field(default_factory=MFCConfig)

    dataset: PromptOnlyDatasetConfig = dataclasses.field(
        default_factory=PromptOnlyDatasetConfig
    )
    ppo: PPOHyperparameters = dataclasses.field(
        default_factory=PPOHyperparameters
    )
    group_size: int = 1
    mask_too_long: bool = False
    ref_ema_eta: Optional[float] = None  # ref := eta*actor + (1-eta)*ref
    # Fuse ref-logprob inference + rule-based reward into ONE DFG node
    # (reference fused_interface.py "fused-threading"): the TPU-bound ref
    # forward overlaps the CPU-bound verification. Sync mode only (async
    # rollout workers already compute rewards off the trainer path).
    fuse_rew_ref: bool = False

    # ---------------- derived pieces ----------------

    @property
    def _use_critic(self) -> bool:
        return not self.ppo.disable_value

    @property
    def _use_ref(self) -> bool:
        return self.ppo.kl_ctl != 0.0

    @property
    def _use_actor_inf(self) -> bool:
        return self.ppo.recompute_logprob or self.ppo.use_decoupled_loss

    def _hp(self) -> PPOHyperparameters:
        hp = dataclasses.replace(self.ppo)
        hp.group_size = self.group_size
        return hp

    def build_dfg(self, n_prompts: int, async_mode: bool = False) -> DataFlowGraph:
        """n_prompts = train_bs_n_seqs; downstream nodes see
        n_prompts*group_size flattened trajectories."""
        n_traj = n_prompts * self.group_size
        fuse = self.fuse_rew_ref and self._use_ref and not async_mode
        mfcs: List[MFCDef] = []
        if not async_mode:
            mfcs.append(MFCDef(
                name="actor_gen", model_name="actor",
                interface_type=MFCInterfaceType.GENERATE,
                interface_impl=ModelInterfaceAbstraction("ppo_actor"),
                input_keys=("packed_prompts", "task_ids"),
                output_keys=TRAJ_KEYS,
                n_seqs=n_prompts, mb_spec=self.actor_gen.mb_spec,
            ))
            if not fuse:
                mfcs.append(MFCDef(
                    name="rew_inf", model_name="rew",
                    interface_type=MFCInterfaceType.INFERENCE,
                    interface_impl=ModelInterfaceAbstraction("rw_math_code"),
                    input_keys=("packed_input_ids", "prompt_mask"),
                    output_keys=("rewards",),
                    n_seqs=n_traj, mb_spec=self.rew_inf.mb_spec,
                ))
        if fuse:
            mfcs.append(MFCDef(
                name="fused_rew_ref_inf", model_name="ref",
                interface_type=MFCInterfaceType.INFERENCE,
                interface_impl=ModelInterfaceAbstraction("fused_forward"),
                input_keys=("packed_input_ids", "prompt_mask"),
                output_keys=("rewards", "packed_ref_logprobs"),
                n_seqs=n_traj, mb_spec=self.ref_inf.mb_spec,
            ))
        elif self._use_ref:
            mfcs.append(MFCDef(
                name="ref_inf", model_name="ref",
                interface_type=MFCInterfaceType.INFERENCE,
                interface_impl=ModelInterfaceAbstraction("ref_logprob"),
                input_keys=("packed_input_ids",),
                output_keys=("packed_ref_logprobs",),
                n_seqs=n_traj, mb_spec=self.ref_inf.mb_spec,
            ))
        if self._use_actor_inf:
            mfcs.append(MFCDef(
                name="actor_inf", model_name="actor",
                interface_type=MFCInterfaceType.INFERENCE,
                interface_impl=ModelInterfaceAbstraction("ppo_actor"),
                input_keys=("packed_input_ids",),
                output_keys=("prox_logprobs",),
                n_seqs=n_traj, mb_spec=self.actor_inf.mb_spec,
            ))
        if self._use_critic:
            mfcs.append(MFCDef(
                name="critic_inf", model_name="critic",
                interface_type=MFCInterfaceType.INFERENCE,
                interface_impl=ModelInterfaceAbstraction("ppo_critic"),
                input_keys=("packed_input_ids",),
                output_keys=("values",),
                n_seqs=n_traj, mb_spec=self.critic_inf.mb_spec,
            ))
        train_inputs = ["packed_input_ids", "prompt_mask", "packed_logprobs",
                        "rewards", "seq_no_eos_mask"]
        if self._use_ref:
            train_inputs.append("packed_ref_logprobs")
        if self._use_actor_inf:
            train_inputs.append("prox_logprobs")
        if self._use_critic:
            train_inputs.append("values")
        actor_post = [WeightUpdateHook(role="actor")]
        if self.ref_ema_eta is not None:
            actor_post.append(ParamReallocHook(
                source="actor", target="ref", eta=self.ref_ema_eta
            ))
        mfcs.append(MFCDef(
            name="actor_train", model_name="actor",
            interface_type=MFCInterfaceType.TRAIN_STEP,
            interface_impl=ModelInterfaceAbstraction("ppo_actor"),
            input_keys=tuple(train_inputs),
            n_seqs=n_traj, mb_spec=self.actor_train.mb_spec,
            post_hooks=actor_post,
        ))
        if self._use_critic:
            mfcs.append(MFCDef(
                name="critic_train", model_name="critic",
                interface_type=MFCInterfaceType.TRAIN_STEP,
                interface_impl=ModelInterfaceAbstraction("ppo_critic"),
                input_keys=tuple(
                    k for k in train_inputs if k != "prox_logprobs"
                ),
                n_seqs=n_traj, mb_spec=self.critic_train.mb_spec,
            ))
        return build_graph(mfcs)

    def _dataset_size(self) -> int:
        """Actual dataset length (JSONL line count) so epoch accounting and
        the LR schedule's total_steps are right (advisor r2: the previous
        10000 placeholder skewed both for any real dataset)."""
        try:
            with open(self.dataset.path, "rb") as f:
                return max(1, sum(1 for line in f if line.strip()))
        except OSError:
            logger.warning(
                f"cannot read dataset {self.dataset.path}; "
                "assuming 10000 samples for schedule math"
            )
            return 10000

    def _telemetry(self):
        """``self.telemetry`` with ``flight_dir`` defaulted under the
        run's log dir — crash/eviction flight dumps land next to
        telemetry.jsonl unless the operator pointed them elsewhere."""
        if not self.telemetry.enabled or self.telemetry.flight_dir:
            return self.telemetry
        import os

        paths = C.experiment_paths(self)
        return dataclasses.replace(
            self.telemetry,
            flight_dir=os.path.join(paths["log"], "flight"),
        )

    def build_trainer_config(self, async_mode: bool = False):
        from areal_tpu.system.trainer_worker import (
            MFCRuntimeConfig,
            ModelRoleConfig,
            TrainerWorkerConfig,
        )

        alloc = C.resolve_allocation(self)
        # Heterogeneous per-MFC allocations (e.g. actor_train:f2t2,ref_inf:d2)
        # give each role the spec of its own MFC — the role's engine then
        # builds a sub-mesh over devices[:world_size] and parallel/reshard.py
        # moves tensors across the MFC boundary on device.
        actor_spec = C.spec_for_role(alloc, "actor")
        ref_spec = C.spec_for_role(alloc, "ref")
        critic_spec = C.spec_for_role(alloc, "critic")
        paths = C.experiment_paths(self)
        dataset_size = self._dataset_size()
        steps_per_epoch = max(
            1, dataset_size // max(self.dataset.train_bs_n_seqs, 1)
        )
        total_steps = self.exp_ctrl.total_train_epochs * steps_per_epoch
        hp = self._hp()

        models: Dict[str, ModelRoleConfig] = {
            "actor": ModelRoleConfig(
                init=C.model_init_dict(self.actor),
                backend_args=C.backend_args_for(self.actor, actor_spec,
                                                total_steps),
            ),
        }
        if self._use_ref:
            models["ref"] = ModelRoleConfig(
                init=C.model_init_dict(self.ref),
                backend_args=C.backend_args_for(self.ref, ref_spec,
                                                total_steps),
                train=False,
            )
        if self._use_critic:
            critic = self.critic
            if not critic.tiny and not critic.path:
                critic = self.actor  # default: init critic from actor shape
            models["critic"] = ModelRoleConfig(
                init=C.model_init_dict(critic),
                backend_args=C.backend_args_for(critic, critic_spec,
                                                total_steps),
            )
        fuse = self.fuse_rew_ref and self._use_ref and not async_mode
        mfcs: Dict[str, MFCRuntimeConfig] = {}
        if not async_mode:
            mfcs["actor_gen"] = MFCRuntimeConfig(
                interface="ppo_actor", interface_args={"hp": hp},
                model_name="actor",
            )
            if not fuse:
                models["rew"] = ModelRoleConfig(
                    init={"null": True}, backend="null"
                )
                mfcs["rew_inf"] = MFCRuntimeConfig(
                    interface="rw_math_code",
                    interface_args={"dataset_path": self.dataset.path,
                                    "group_size": self.group_size},
                    model_name="rew",
                )
        if fuse:
            mfcs["fused_rew_ref_inf"] = MFCRuntimeConfig(
                interface="fused_forward",
                interface_args={"interfaces": {
                    "rew": ("rw_math_code",
                            {"dataset_path": self.dataset.path,
                             "group_size": self.group_size}),
                    "ref": ("ref_logprob", {}),
                }},
                model_name="ref",
            )
        elif self._use_ref:
            mfcs["ref_inf"] = MFCRuntimeConfig(
                interface="ref_logprob", model_name="ref"
            )
        if self._use_actor_inf:
            mfcs["actor_inf"] = MFCRuntimeConfig(
                interface="ppo_actor", interface_args={"hp": hp},
                model_name="actor",
            )
        if self._use_critic:
            mfcs["critic_inf"] = MFCRuntimeConfig(
                interface="ppo_critic", interface_args={"hp": hp},
                model_name="critic",
            )
            mfcs["critic_train"] = MFCRuntimeConfig(
                interface="ppo_critic", interface_args={"hp": hp},
                model_name="critic",
            )
        mfcs["actor_train"] = MFCRuntimeConfig(
            interface="ppo_actor", interface_args={"hp": hp},
            model_name="actor",
        )
        weight_sync = self.weight_sync
        if (weight_sync.transport == "device"
                and not weight_sync.gen_parallel_spec
                and alloc.gen_spec is not None):
            # Decoupled allocation: the device publish reshards straight into
            # the generation fleet's layout so the consumer-side swap is a
            # zero-copy lookup.
            weight_sync = dataclasses.replace(
                weight_sync, gen_parallel_spec=str(alloc.gen_spec)
            )
        return TrainerWorkerConfig(
            experiment=self.experiment_name, trial=self.trial_name,
            handler="trainer",
            models=models, mfcs=mfcs,
            dataset=None if async_mode else "math_code_prompt",
            dataset_args={} if async_mode else {
                "dataset_path": self.dataset.path,
                "max_length": self.dataset.max_prompt_len,
            },
            batch_size=self.dataset.train_bs_n_seqs,
            ft_spec=FinetuneSpec(
                total_train_epochs=self.exp_ctrl.total_train_epochs,
                dataset_size=dataset_size,
                train_batch_size=self.dataset.train_bs_n_seqs,
            ),
            tokenizer=None,  # resolved in-process by the launcher entry
            stream_dataset=async_mode,
            realloc_dir=paths["realloc"],
            weight_sync=weight_sync,
            telemetry=self._telemetry(),
            goodput=self.goodput,
            compile_watch=self.compile_watch,
            reward_service=self.reward_service,
            durability=self.durability,
        )

    def build_master_config(self, async_mode: bool = False):
        from areal_tpu.system.master_worker import MasterWorkerConfig

        paths = C.experiment_paths(self)
        # Sync mode: the master fetches PROMPTS (actor_gen flattens them into
        # group_size trajectories in-graph). Async mode: the stream dataset
        # yields already-flattened TRAJECTORIES, so one step consumes
        # n_prompts*group_size samples (the train MFC's n_seqs — reference
        # async_rl_exp.py:327 uses train_rpcs[0].n_seqs the same way).
        bs = self.dataset.train_bs_n_seqs
        if async_mode:
            bs *= self.group_size
        import os

        # The master hosts the aggregator; its telemetry.jsonl defaults
        # next to the run's tensorboard stream under the log dir.
        tel = dataclasses.replace(
            self.telemetry,
            jsonl_path=(
                self.telemetry.jsonl_path
                or os.path.join(paths["log"], "telemetry.jsonl")
            ),
        )
        return MasterWorkerConfig(
            experiment=self.experiment_name, trial=self.trial_name,
            trainer_handler="trainer",
            train_batch_size=bs,
            exp_ctrl=self.exp_ctrl,
            save_dir=paths["save"],
            src_is_stream=async_mode,
            tensorboard_path=(
                self.tensorboard.path
                or os.path.join(paths["log"], "tensorboard")
            ),
            wandb_mode=self.wandb.mode,
            telemetry=tel,
            # Training-health sentinel rides in the master's aggregator;
            # its alerts.jsonl/evidence default next to telemetry.jsonl.
            sentinel=self.sentinel,
            # Fleet-goodput stitching rides in the same aggregator.
            goodput=self.goodput,
            # Arms the compile-aware sentinel rules (recompile_storm,
            # hbm_pressure, compile_stall) when the observatory is on.
            compile_watch=self.compile_watch,
            # Arms the sentinel's sample_loss rule when the durable
            # spool is on (the freed-id forwarding is the ack trigger).
            durability=self.durability,
            recover_dir=paths["recover"],
            recover=self.recover_mode == "resume",
        )

    def build_reward_workers(self) -> List[Any]:
        """Sandbox reward-worker configs (empty when the service is off);
        shared by the sync and async experiment setups."""
        if not self.reward_service.enabled:
            return []
        from areal_tpu.system.reward_worker import RewardWorkerConfig

        return [
            RewardWorkerConfig(
                experiment=self.experiment_name, trial=self.trial_name,
                worker_index=i,
                port=self.reward_service.port,
                reward=self.reward_service,
                telemetry=self._telemetry(),
                keepalive_ttl_secs=self.fault_tolerance.keepalive_ttl_secs,
            )
            for i in range(self.reward_service.n_workers)
        ]

    def initial_setup(self) -> Dict[str, Any]:
        """→ {dfg, master, trainer} (sync: everything on the trainer mesh)."""
        setup = {
            "dfg": self.build_dfg(self.dataset.train_bs_n_seqs,
                                  async_mode=False),
            "master": self.build_master_config(async_mode=False),
            "trainer": self.build_trainer_config(async_mode=False),
        }
        if self.reward_service.enabled:
            # Sync mode grades on the trainer's rw_inf MFC — the fleet
            # keeps untrusted code out of the trainer process too.
            setup["reward_workers"] = self.build_reward_workers()
        return setup


register_experiment("ppo-math", PPOMATHConfig)
