"""Local math answer extraction + grading.

Parity target: ``realhf/impl/dataset/math_parser.py`` (869 LoC) and
``functioncall/math/verify.py:12`` — the rule-based math reward. This is a
native reimplementation of the same contract: extract the final answer from
a generated solution (\\boxed{}, "the answer is", last standalone math
expression) and grade it against any of the ground-truth solutions,
tolerant to formatting (fractions, percents, commas, units, LaTeX noise).
"""

from __future__ import annotations

import re
from fractions import Fraction
from typing import List, Optional, Tuple

__all__ = ["extract_answer", "math_equal", "verify_math", "batch_verify_math"]


_BOXED = re.compile(r"\\boxed\s*\{")
_ANSWER_IS = re.compile(
    r"(?:final answer|answer)\s*(?:is|:|=)\s*\$?([^\n$]+)", re.IGNORECASE
)
# Trailing prose after an inline answer ("5, which is prime").
_TRAILING_PROSE = re.compile(r"[,;]?\s+(?:which|because|since|so|as|and)\b.*$")


def _find_boxed(text: str) -> Optional[str]:
    """Last \\boxed{...} with balanced braces."""
    out = None
    for m in _BOXED.finditer(text):
        depth = 1
        i = m.end()
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        if depth == 0:
            out = text[m.end() : i - 1]
    return out


def extract_answer(text: str) -> Optional[str]:
    boxed = _find_boxed(text)
    if boxed is not None:
        return boxed.strip()
    m = None
    for m in _ANSWER_IS.finditer(text):
        pass
    if m is not None:
        ans = _TRAILING_PROSE.sub("", m.group(1))
        return ans.strip().rstrip(".").strip()
    # Fall back to the last number in the text.
    nums = re.findall(r"-?\d+(?:/\d+)?(?:\.\d+)?", text)
    return nums[-1] if nums else None


_UNIT_WORDS = (
    "degrees?", "percent", "dollars?", "cents?", "units?", "square", "cubic",
    "meters?", "cm", "mm", "km", "inches", "feet", "ft", "miles?", "hours?",
    "minutes?", "seconds?", "\\\\text\\{[^}]*\\}", "\\\\mathrm\\{[^}]*\\}",
    "\\\\,", "\\\\!", "\\\\;", "\\\\ ",
)


def normalize(ans: str) -> str:
    s = ans.strip()
    s = re.sub(r"\\left|\\right", "", s)
    # Innermost-out rewriting: \sqrt/\frac args may nest ({\sqrt{2}} inside
    # \frac) — iterate until fixpoint, each pass resolving brace-free args.
    prev = None
    while prev != s:
        prev = s
        s = re.sub(r"\\sqrt\s*\{([^{}]+)\}", r"sqrt(\1)", s)
        s = re.sub(
            r"\\[dt]?frac\s*\{([^{}]+)\}\s*\{([^{}]+)\}", r"(\1)/(\2)", s
        )
    s = re.sub(r"\\frac\s*(\d)\s*(\d)", r"\1/\2", s)  # \frac12
    s = re.sub(r"\\pi", "pi", s)
    s = re.sub(r"\\cdot|\\times", "*", s)
    s = re.sub("|".join(_UNIT_WORDS), "", s)
    s = s.replace("\\%", "%").replace("$", "").replace("°", "")
    s = s.replace("{", "(").replace("}", ")").replace("^", "**")
    s = re.sub(r"(?<=\d),(?=\d{3}\b)", "", s)  # thousands separators
    s = re.sub(r"\s+", "", s)
    s = s.rstrip(".")
    return s


def _as_number(s: str) -> Optional[Fraction]:
    s = s.strip()
    neg = False
    if s.startswith("(") and s.endswith(")"):
        s = s[1:-1]
    if s.startswith("-"):
        neg, s = True, s[1:]
    pct = s.endswith("%")
    if pct:
        s = s[:-1]
    # mixed numbers: "1(1)/(2)" (normalized "1\frac{1}{2}") → 3/2; parens
    # required — "12/5" must stay 12/5, not 1+2/5
    m = re.fullmatch(r"(\d+)\((\d+)\)/\((\d+)\)", s)
    if m:
        whole, num, den = map(int, m.groups())
        v: Optional[Fraction] = Fraction(whole) + Fraction(num, den)
    else:
        try:
            m = re.fullmatch(r"\(?([^()/]+)\)?/\(?([^()/]+)\)?", s)
            if m:
                v = Fraction(m.group(1)) / Fraction(m.group(2))
            elif re.fullmatch(r"-?\d+(?:\.\d+)?[eE][+-]?\d+", s):
                v = Fraction(float(s))  # scientific notation
            else:
                v = Fraction(s)
        except (ValueError, ZeroDivisionError, OverflowError):
            return None
    if pct:
        v /= 100
    return -v if neg else v


_CHOICES = ("a", "b", "c", "d", "e")
_MATRIX = re.compile(
    r"\\begin\{[pb]matrix\}(.*)\\end\{[pb]matrix\}", re.DOTALL
)


def _choice_clean(pred: str) -> Optional[str]:
    """Last standalone choice letter in the prediction (reference
    choice_answer_clean)."""
    hits = re.findall(r"\b([A-Ea-e])\b", pred.strip().strip(".:()"))
    return hits[-1].lower() if hits else None


def _numeric_equal(vp: Fraction, vr: Fraction, rel_tol: float) -> bool:
    # Percentage ambiguity (reference math_equal include_percentage): accept
    # the reference at 1x, /100 and *100 scales.
    for item in (vr, vr / 100, vr * 100):
        if vp == item:
            return True
        try:
            denom = max(abs(float(item)), 1e-12)
            if abs(float(vp - item)) / denom < rel_tol:
                return True
        except OverflowError:
            # >~308-digit integers overflow float(); exact equality was
            # already checked above, and values this size differing by
            # less than rel_tol·value cannot be distinguished anyway —
            # treat as unequal rather than crash the reward path.
            continue
    return False


def _split_top_level(s: str) -> List[str]:
    """Split on commas not nested in brackets."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def _symbolic_equal_inprocess(a: str, b: str) -> bool:
    try:
        import sympy
        from sympy.parsing.sympy_parser import (
            implicit_multiplication_application,
            parse_expr,
            standard_transformations,
        )

        tf = standard_transformations + (
            implicit_multiplication_application,
        )

        def p(s):
            return parse_expr(normalize(s), transformations=tf)

        ea, eb = p(a), p(b)
        if ea == eb:
            return True
        return sympy.simplify(ea - eb) == 0
    except Exception:  # noqa: BLE001 — unparseable ⇒ not equal
        return False


def _symbolic_child(a: str, b: str, q) -> None:
    q.put(_symbolic_equal_inprocess(a, b))


def _symbolic_equal(a: str, b: str, timeout: float = 3.0) -> bool:
    """sympy difference-is-zero check in a KILLABLE subprocess (reference
    math_parser.py:686 call_with_timeout): even short inputs can explode —
    '3^3^3^3' parses to 3**3**27 and sympy eagerly evaluates the integer —
    so a length cap alone cannot bound CPU. A hung grader would stall the
    whole rollout/reward path."""
    if len(a) > 192 or len(b) > 192:
        return False
    import multiprocessing as mp

    # Pre-import sympy in the PARENT: forked children inherit the loaded
    # module. Without this every fork re-imports sympy from disk (~1s),
    # eating the timeout and nondeterministically failing genuinely-equal
    # symbolic answers on a loaded host.
    import sympy  # noqa: F401
    import sympy.parsing.sympy_parser  # noqa: F401

    ctx = mp.get_context("fork")
    q = ctx.Queue()
    proc = ctx.Process(target=_symbolic_child, args=(a, b, q), daemon=True)
    proc.start()
    proc.join(timeout)
    if proc.is_alive():
        proc.terminate()
        proc.join(1.0)
        if proc.is_alive():
            proc.kill()
        return False
    try:
        return bool(q.get_nowait())
    except Exception:  # noqa: BLE001 — child died without an answer
        return False


def math_equal(pred: str, ref: str, rel_tol: float = 1e-4) -> bool:
    """Semantic parity with the reference grader (math_parser.py:497):
    string/MC/numeric(+percent)/tuple/matrix/equation/symbolic, in order."""
    if pred is None or ref is None:
        return False
    pred, ref = str(pred).strip(), str(ref).strip()
    if pred.lower() == ref.lower():
        return True
    # multiple choice
    if ref.strip(".:() ").lower() in _CHOICES and len(ref.strip(".:() ")) == 1:
        return _choice_clean(pred) == ref.strip(".:() ").lower()

    np_, nr = normalize(pred), normalize(ref)
    if np_ == nr:
        return True
    vp, vr = _as_number(np_), _as_number(nr)
    if vp is not None and vr is not None:
        return _numeric_equal(vp, vr, rel_tol)

    # Bracket-sensitive comparison. NOTE (deviation from the reference,
    # which strips all brackets): "(0,1]" and "[0,1)" are DIFFERENT
    # intervals — equal content with different bracket types must not
    # grade 1.0, so stripping/element-wise paths require the SAME bracket
    # characters at both ends.
    both_bracketed = (
        re.fullmatch(r"[\[(].+[\])]", np_) and re.fullmatch(r"[\[(].+[\])]", nr)
    )
    same_brackets = (
        not both_bracketed or (np_[0] == nr[0] and np_[-1] == nr[-1])
    )
    if (
        same_brackets
        and np_.strip("[]()") == nr.strip("[]()")
        and np_.strip("[]()")
    ):
        return True

    # tuples / intervals / coordinate lists: element-wise, order-sensitive
    if both_bracketed and same_brackets:
        pp, rr = _split_top_level(np_[1:-1]), _split_top_level(nr[1:-1])
        if len(pp) == len(rr) and len(pp) > 1:
            if all(math_equal(a, b, rel_tol) for a, b in zip(pp, rr)):
                return True

    # pmatrix/bmatrix: element-wise over rows (\\\\) and cols (&)
    mp_, mr = _MATRIX.search(pred), _MATRIX.search(ref)
    if mp_ and mr:
        rows_p = [r for r in mp_.group(1).split("\\\\") if r.strip()]
        rows_r = [r for r in mr.group(1).split("\\\\") if r.strip()]
        if len(rows_p) == len(rows_r):
            ok = True
            for rp, rr_ in zip(rows_p, rows_r):
                cp, cr = rp.split("&"), rr_.split("&")
                if len(cp) != len(cr) or not all(
                    math_equal(a, b, rel_tol) for a, b in zip(cp, cr)
                ):
                    ok = False
                    break
            if ok:
                return True

    # equations: "lhs = rhs" on both sides → difference equivalence (either
    # sign); single short-LHS assignment vs bare value → compare the value
    if pred.count("=") == 1 and ref.count("=") == 1:
        pl, pr_ = (x.strip() for x in pred.split("="))
        rl, rr_ = (x.strip() for x in ref.split("="))
        da, db = f"({pl})-({pr_})", f"({rl})-({rr_})"
        if _symbolic_equal(da, db) or _symbolic_equal(f"-({da})", db):
            return True
    elif pred.count("=") == 1 and len(pred.split("=")[0].strip()) <= 2:
        if math_equal(pred.split("=")[1], ref, rel_tol):
            return True
    elif ref.count("=") == 1 and len(ref.split("=")[0].strip()) <= 2:
        if math_equal(pred, ref.split("=")[1], rel_tol):
            return True

    return _symbolic_equal(np_, nr)


def verify_math(generated: str, solutions: List[str]) -> float:
    """1.0 if the extracted answer matches ANY ground-truth solution.
    Ground-truth entries may themselves contain \\boxed{} (full solutions)
    or be bare answers."""
    pred = extract_answer(generated)
    if pred is None:
        return 0.0
    for sol in solutions:
        ref = extract_answer(sol) if ("\\boxed" in sol or len(sol) > 64) else sol
        if ref is not None and math_equal(pred, ref):
            return 1.0
    return 0.0


def batch_verify_math(
    pairs: List[Tuple[str, List[str]]],
) -> List[float]:
    return [verify_math(g, s) for g, s in pairs]
