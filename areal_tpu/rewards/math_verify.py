"""Local math answer extraction + grading.

Parity target: ``realhf/impl/dataset/math_parser.py`` (869 LoC) and
``functioncall/math/verify.py:12`` — the rule-based math reward. This is a
native reimplementation of the same contract: extract the final answer from
a generated solution (\\boxed{}, "the answer is", last standalone math
expression) and grade it against any of the ground-truth solutions,
tolerant to formatting (fractions, percents, commas, units, LaTeX noise).
"""

from __future__ import annotations

import re
from fractions import Fraction
from typing import List, Optional, Tuple

__all__ = ["extract_answer", "math_equal", "verify_math", "batch_verify_math"]


_BOXED = re.compile(r"\\boxed\s*\{")
_ANSWER_IS = re.compile(
    r"(?:final answer|answer)\s*(?:is|:|=)\s*\$?([^\n$]+)", re.IGNORECASE
)
# Trailing prose after an inline answer ("5, which is prime").
_TRAILING_PROSE = re.compile(r"[,;]?\s+(?:which|because|since|so|as|and)\b.*$")


def _find_boxed(text: str) -> Optional[str]:
    """Last \\boxed{...} with balanced braces."""
    out = None
    for m in _BOXED.finditer(text):
        depth = 1
        i = m.end()
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        if depth == 0:
            out = text[m.end() : i - 1]
    return out


def extract_answer(text: str) -> Optional[str]:
    boxed = _find_boxed(text)
    if boxed is not None:
        return boxed.strip()
    m = None
    for m in _ANSWER_IS.finditer(text):
        pass
    if m is not None:
        ans = _TRAILING_PROSE.sub("", m.group(1))
        return ans.strip().rstrip(".").strip()
    # Fall back to the last number in the text.
    nums = re.findall(r"-?\d+(?:/\d+)?(?:\.\d+)?", text)
    return nums[-1] if nums else None


_UNIT_WORDS = (
    "degrees?", "percent", "dollars?", "cents?", "units?", "square", "cubic",
    "meters?", "cm", "mm", "km", "inches", "feet", "ft", "miles?", "hours?",
    "minutes?", "seconds?", "\\\\text\\{[^}]*\\}", "\\\\mathrm\\{[^}]*\\}",
    "\\\\,", "\\\\!", "\\\\;", "\\\\ ",
)


def normalize(ans: str) -> str:
    s = ans.strip()
    s = re.sub(r"\\left|\\right", "", s)
    s = re.sub(r"\\(d)?frac\s*\{([^{}]+)\}\s*\{([^{}]+)\}", r"(\2)/(\3)", s)
    s = re.sub(r"\\frac\s*(\d)\s*(\d)", r"\1/\2", s)  # \frac12
    s = re.sub(r"\\sqrt\s*\{([^{}]+)\}", r"sqrt(\1)", s)
    s = re.sub(r"\\pi", "pi", s)
    s = re.sub(r"\\cdot|\\times", "*", s)
    s = re.sub("|".join(_UNIT_WORDS), "", s)
    s = s.replace("\\%", "%").replace("$", "").replace("°", "")
    s = s.replace("{", "(").replace("}", ")").replace("^", "**")
    s = re.sub(r"(?<=\d),(?=\d{3}\b)", "", s)  # thousands separators
    s = re.sub(r"\s+", "", s)
    s = s.rstrip(".")
    return s


def _as_number(s: str) -> Optional[Fraction]:
    s = s.strip()
    neg = False
    if s.startswith("(") and s.endswith(")"):
        s = s[1:-1]
    if s.startswith("-"):
        neg, s = True, s[1:]
    pct = s.endswith("%")
    if pct:
        s = s[:-1]
    try:
        m = re.fullmatch(r"\(?([^()/]+)\)?/\(?([^()/]+)\)?", s)
        if m:
            v = Fraction(m.group(1)) / Fraction(m.group(2))
        else:
            v = Fraction(s)
    except (ValueError, ZeroDivisionError):
        return None
    if pct:
        v /= 100
    return -v if neg else v


def math_equal(pred: str, ref: str, rel_tol: float = 1e-4) -> bool:
    np_, nr = normalize(pred), normalize(ref)
    if np_ == nr:
        return True
    vp, vr = _as_number(np_), _as_number(nr)
    if vp is not None and vr is not None:
        if vp == vr:
            return True
        denom = max(abs(float(vr)), 1e-12)
        return abs(float(vp - vr)) / denom < rel_tol
    # Symbolic fallback when sympy is available (kept optional).
    try:
        import sympy

        return sympy.simplify(
            sympy.sympify(np_.replace("sqrt", "sqrt")) - sympy.sympify(nr)
        ) == 0
    except Exception:
        return False


def verify_math(generated: str, solutions: List[str]) -> float:
    """1.0 if the extracted answer matches ANY ground-truth solution.
    Ground-truth entries may themselves contain \\boxed{} (full solutions)
    or be bare answers."""
    pred = extract_answer(generated)
    if pred is None:
        return 0.0
    for sol in solutions:
        ref = extract_answer(sol) if ("\\boxed" in sol or len(sol) > 64) else sol
        if ref is not None and math_equal(pred, ref):
            return 1.0
    return 0.0


def batch_verify_math(
    pairs: List[Tuple[str, List[str]]],
) -> List[float]:
    return [verify_math(g, s) for g, s in pairs]
