"""Sandboxed reward service — HTTP grading core of the reward worker.

Parity target: the reference's standalone functioncall service (the 3k-LoC
deployment behind ``FUNCTIONCALL_SERVICE_DOMAIN``; SURVEY §2.13): a fleet
of sandbox workers that grade math/code tasks over HTTP so untrusted model
code never executes inside the process that drives generation or training.

This module is the jax-free grading core: an aiohttp application exposing

  POST /math_verify    {generated, solutions}            -> {score, verdict}
  POST /code_verify    {generated, input_output, ...}    -> {score, verdict}
  POST /batch_reward   {tasks: [...]}                    -> {scores, verdicts}
  GET  /health                                           liveness + load
  GET  /metrics[.json]                                   Prometheus / JSON

Grading runs on a bounded thread pool; every code grade additionally runs
inside rewards/code_verify.py's rlimit-guarded subprocess (the sandbox
proper), and per-task ``language`` dispatch goes through its GRADERS
registry. A grade that overruns ``grade_timeout_secs`` returns a 0.0 score
with verdict="timeout" and bumps ``reward_timeouts_total`` — the worker
thread is abandoned to finish on its own (the code sandbox enforces its
own rlimits underneath, so an abandoned slot cannot spin forever).

The process-level worker wrapping this core (discovery, supervision,
WorkerControl) is system/reward_worker.py — the sixth worker kind.
"""

from __future__ import annotations

import asyncio
import concurrent.futures as cf
import json
import time
from typing import Any, Dict, List, Optional

from areal_tpu.base import logging, telemetry
from areal_tpu.rewards import code_verify, math_verify

logger = logging.getLogger("rewards.service")

# Verdict vocabulary exported per task kind through telemetry
# (reward_verdicts_total{task=...,verdict=...}).
VERDICTS = ("pass", "fail", "timeout", "error", "unsupported_language")

_LATENCY_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

# Worst-case sampled test cases per code grade — the code-task
# wall-budget floor derives from the grader's own cap.
_CODE_MAX_CASES = code_verify.MAX_CASES_DEFAULT


def task_budget_secs(task: Dict[str, Any], base_secs: float) -> float:
    """Wall budget for ONE task, shared by the service's grade timeout
    and the client's per-task HTTP timeout (rewards/client.py) so the
    two can never disagree: ``base_secs`` bounds a WEDGED grader, while
    a code task floors at its legal worst case (per-case timeout x the
    cases it actually carries, capped at the grader's sample bound,
    + slack) — otherwise correct-but-slow programs get spuriously
    abandoned/zero-scored. Scaling by the real case count matters for
    the pass-rate path's single-case tasks: a hung one-case grade must
    pin its slot ~13s, not ~133s."""
    budget = float(base_secs)
    if task.get("task", "math") == "code":
        n_cases = _CODE_MAX_CASES
        io = task.get("input_output")
        try:
            d = json.loads(io) if isinstance(io, str) else io
            n = len(d.get("inputs", []))
            if n:
                n_cases = min(n, _CODE_MAX_CASES)
        except Exception:  # noqa: BLE001 — malformed io grades 0.0 fast
            pass
        worst = float(task.get("timeout", 8.0)) * n_cases + 5.0
        budget = max(budget, worst)
    return budget


def grade_task(task: Dict[str, Any],
               languages: Optional[List[str]] = None) -> Dict[str, Any]:
    """Grade ONE {task, generated, solutions|input_output} dict ->
    {score, verdict}. Synchronous — the service runs it on its pool; the
    local fallback path (rewards/client.py) runs it on the caller's
    thread. The SAME dispatch both sides, so fallback outputs are
    bit-identical to fleet outputs for supported tasks."""
    kind = task.get("task", "math")
    try:
        if kind in ("math", "stem"):
            score = math_verify.verify_math(
                task["generated"], task.get("solutions", [])
            )
        elif kind == "code":
            language = task.get("language", "python")
            if (languages is not None and language not in languages) or \
                    language not in code_verify.GRADERS:
                return {"score": 0.0, "verdict": "unsupported_language"}
            score = code_verify.verify_code(
                task["generated"], task.get("input_output", "{}"),
                timeout=float(task.get("timeout", 8.0)),
                language=language,
            )
        else:
            logger.warning(f"unknown reward task kind {kind}; 0 reward")
            return {"score": 0.0, "verdict": "error"}
    except Exception as e:  # noqa: BLE001 — a bad task must not 500
        logger.warning(f"grading failed ({kind}): {e}")
        return {"score": 0.0, "verdict": "error"}
    return {"score": float(score),
            "verdict": "pass" if score > 0 else "fail"}


class RewardService:
    """One sandbox fleet member: bounded concurrent grading + telemetry.

    ``grade_fn`` is the test seam (chaos tests arm slow/failing graders
    without real subprocesses); production uses :func:`grade_task`.
    """

    def __init__(self, cfg, telemetry_sink=None,
                 grade_fn=None):  # cfg: RewardServiceConfig
        self.cfg = cfg
        self.telemetry = telemetry_sink if telemetry_sink is not None \
            else telemetry.NULL
        self._grade_fn = grade_fn or (
            lambda task: grade_task(task, languages=list(cfg.languages))
        )
        self._pool = cf.ThreadPoolExecutor(
            max_workers=max(int(cfg.pool_size), 1),
            thread_name_prefix="reward-grade",
        )
        # Admission bound AND the self-heal threshold: with every
        # admitted grade wedged (each withholding its permit) the pool
        # must be replaced — comparing against pool_size alone would
        # deadlock configs with max_inflight < pool_size (admission
        # exhausted at max_inflight zombies, trigger never reached).
        self._admit_limit = max(
            1, min(int(cfg.max_inflight), int(cfg.pool_size))
        )
        # Created lazily inside the serving loop (asyncio primitives bind
        # the running loop).
        self._sem: Optional[asyncio.Semaphore] = None
        self._inflight = 0
        self._graded = 0
        self._timeouts = 0
        # Timed-out grades whose pool thread is still running (wait_for
        # cannot kill a thread). Each WITHHOLDS its admission permit —
        # released only when the zombie thread finishes or the pool is
        # replaced — so admitted work always has a free thread and the
        # wall budget never times executor-queue wait. At pool_size
        # zombies the pool is replaced wholesale (_replace_pool).
        self._withheld = 0
        # Bumped on pool replacement: a stale zombie's completion
        # callback must not release a permit the replacement already
        # restored.
        self._pool_gen = 0
        self._t_start = time.monotonic()

    # ---------------- grading ----------------

    def _replace_pool(self) -> None:
        """Self-heal from grader-thread leakage: a timed-out grade's
        thread cannot be killed (wait_for abandons, the thread runs on);
        once EVERY thread is a zombie the worker would brick — each new
        grade queuing behind the wedge and timing out in turn. Swap in a
        fresh executor (old one drains unawaited in the background,
        bounded by the sandbox rlimits underneath) and carry on."""
        old = self._pool
        self._pool = cf.ThreadPoolExecutor(
            max_workers=max(int(self.cfg.pool_size), 1),
            thread_name_prefix="reward-grade",
        )
        # The fresh pool has free threads again: restore every withheld
        # permit and invalidate the old zombies' completion callbacks.
        self._pool_gen += 1
        for _ in range(self._withheld):
            self._sem.release()
        self._withheld = 0
        self.telemetry.set_gauge("reward/abandoned_threads", 0)
        self.telemetry.inc("reward/pool_replaced")
        logger.warning(
            "reward grader pool replaced: every thread was wedged past "
            "its grade budget (zombie graders keep draining off-pool)"
        )
        old.shutdown(wait=False)

    async def grade(self, task: Dict[str, Any]) -> Dict[str, Any]:
        """Grade one task under the inflight cap + wall budget."""
        if self._sem is None:
            # Admission is clamped to the thread count: an admitted task
            # starts grading IMMEDIATELY, so the wall budget below times
            # actual grading, never executor-queue wait (tasks admitted
            # beyond the pool would burn their budget queueing and
            # time out without ever running).
            self._sem = asyncio.Semaphore(self._admit_limit)
        kind = task.get("task", "math")
        loop = asyncio.get_running_loop()
        await self._sem.acquire()
        withheld = False
        try:
            self._inflight += 1
            self.telemetry.set_gauge("reward/inflight", self._inflight)
            t0 = time.monotonic()
            try:
                fut = loop.run_in_executor(self._pool, self._grade_fn, task)
                try:
                    out = await asyncio.wait_for(
                        fut,
                        timeout=task_budget_secs(
                            task, self.cfg.grade_timeout_secs
                        ),
                    )
                except asyncio.TimeoutError:
                    # The pool thread cannot be killed (the code
                    # sandbox's own rlimits bound it underneath). Its
                    # admission permit stays WITHHELD until the zombie
                    # finishes — releasing now would admit a grade with
                    # no free thread, which would burn its wall budget
                    # in executor-queue wait and time out spuriously.
                    self._timeouts += 1
                    self.telemetry.inc("reward/timeouts")
                    self._withhold_permit(fut, loop)
                    withheld = True
                    out = {"score": 0.0, "verdict": "timeout"}
                except asyncio.CancelledError:
                    # Client disconnect / handler cancellation: the
                    # grader thread keeps running just like a timeout —
                    # the permit must ride the thread, not the request.
                    if not fut.done():
                        self._withhold_permit(fut, loop)
                        withheld = True
                    raise
            finally:
                self._inflight -= 1
                self.telemetry.set_gauge("reward/inflight", self._inflight)
        finally:
            if not withheld:
                self._sem.release()
        dt = time.monotonic() - t0
        self._graded += 1
        self.telemetry.inc("reward/requests")
        self.telemetry.inc(
            f"reward/verdicts{{task={kind},verdict={out['verdict']}}}"
        )
        self.telemetry.observe(
            f"reward/grade_latency_secs{{task={kind}}}", dt,
            buckets=_LATENCY_BUCKETS,
        )
        return out

    def _withhold_permit(self, fut, loop) -> None:
        """An admitted grade's thread outlived its request (timeout or
        cancellation): keep its admission permit withheld until the
        thread actually finishes, restoring it via the future's done
        callback — generation-guarded so a pool replacement (which
        restores all withheld permits itself) invalidates stale
        callbacks. Replacement triggers at the ADMISSION limit: the
        point where every admittable slot is withheld and the worker
        would otherwise brick."""
        self._withheld += 1
        self.telemetry.set_gauge("reward/abandoned_threads",
                                 self._withheld)
        gen = self._pool_gen

        def _zombie_done(_f, gen=gen, loop=loop):
            def _restore():
                if self._pool_gen == gen and self._withheld:
                    self._withheld -= 1
                    self.telemetry.set_gauge("reward/abandoned_threads",
                                             self._withheld)
                    self._sem.release()
            try:
                loop.call_soon_threadsafe(_restore)
            except RuntimeError:
                pass  # loop closed: worker shutting down

        fut.add_done_callback(_zombie_done)
        if self._withheld >= self._admit_limit:
            self._replace_pool()

    async def grade_batch(self, tasks: List[Dict[str, Any]]) -> List[Dict]:
        return list(await asyncio.gather(*[self.grade(t) for t in tasks]))

    # ---------------- http handlers ----------------

    async def _handle_verify(self, request, kind: str):
        from aiohttp import web

        try:
            task = await request.json()
        except Exception:  # noqa: BLE001 — malformed body
            return web.json_response(
                {"score": 0.0, "verdict": "error", "error": "bad json"},
                status=400,
            )
        task.setdefault("task", kind)
        return web.json_response(await self.grade(task))

    async def handle_math_verify(self, request):
        return await self._handle_verify(request, "math")

    async def handle_code_verify(self, request):
        return await self._handle_verify(request, "code")

    async def handle_batch(self, request):
        from aiohttp import web

        try:
            body = await request.json()
            tasks = body["tasks"] if isinstance(body, dict) else body
            assert isinstance(tasks, list)
        except Exception:  # noqa: BLE001 — malformed body
            return web.json_response(
                {"error": "expected {tasks: [...]} or a JSON list"},
                status=400,
            )
        outs = await self.grade_batch(tasks)
        return web.json_response({
            "scores": [o["score"] for o in outs],
            "verdicts": [o["verdict"] for o in outs],
        })

    async def handle_health(self, request):
        from aiohttp import web

        return web.json_response({
            "ok": True,
            "inflight": self._inflight,
            "graded_total": self._graded,
            "timeouts_total": self._timeouts,
            "languages": list(self.cfg.languages),
            "uptime_secs": time.monotonic() - self._t_start,
        })

    def metrics_dict(self) -> Dict[str, Any]:
        return {
            "reward_graded": self._graded,
            "reward_timeout_count": self._timeouts,
            "reward_inflight": self._inflight,
            "reward_pool_size": self.cfg.pool_size,
        }

    def build_app(self, extra_metrics=None, labels=None):
        """The aiohttp application. ``extra_metrics``/``labels`` let the
        wrapping worker (system/reward_worker.py) add its identity to the
        Prometheus exposition without this core knowing about workers."""
        from aiohttp import web

        async def handle_metrics(request):
            body = telemetry.render_prometheus(
                self.telemetry.snapshot(reset=False),
                extra_gauges={**self.metrics_dict(),
                              **((extra_metrics() if extra_metrics else {}))},
                labels=labels,
            )
            return web.Response(
                text=body, content_type="text/plain", charset="utf-8",
                headers={"X-Prometheus-Version": "0.0.4"},
            )

        async def handle_metrics_json(request):
            return web.json_response({
                **self.metrics_dict(),
                **((extra_metrics() if extra_metrics else {})),
            })

        app = web.Application(client_max_size=64 * 1024 * 1024)
        app.router.add_post("/math_verify", self.handle_math_verify)
        app.router.add_post("/code_verify", self.handle_code_verify)
        app.router.add_post("/batch_reward", self.handle_batch)
        app.router.add_get("/health", self.handle_health)
        app.router.add_get("/metrics", handle_metrics)
        app.router.add_get("/metrics.json", handle_metrics_json)
        return app

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
