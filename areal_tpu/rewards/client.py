"""Reward evaluation fanout — remote sandbox service or local fallback.

Parity target: ``functioncall/base/call.py:81-235`` (``batch_function_call``:
aiohttp fanout to FUNCTIONCALL_SERVICE_DOMAIN with retries and concurrency
caps) + the dispatch in ``math_rw_interface.py:127`` (math vs code by task).
With no service configured, grading runs locally (rewards/math_verify.py,
rewards/code_verify.py) on a thread pool — the default for TPU pods where
the reward sandbox is a separate deployment.
"""

from __future__ import annotations

import asyncio
import concurrent.futures as cf
import json
import os
from typing import Any, Dict, List

import dataclasses

from areal_tpu.base import logging
from areal_tpu.base.retry import RetryPolicy, aretry
from areal_tpu.rewards import code_verify, math_verify

logger = logging.getLogger("rewards.client")

SERVICE_ENV = "FUNCTIONCALL_SERVICE_DOMAIN"

# Shared fleet-wide backoff vocabulary (base/retry.py): sandbox calls retry
# on the same capped-exponential schedule as generation failover.
_REMOTE_RETRY = RetryPolicy(base_delay_secs=0.5, max_delay_secs=5.0)


def _run_coro_blocking(coro):
    """Run a coroutine to completion from ANY calling context. Plain
    ``asyncio.run`` raises RuntimeError when the caller's thread already
    hosts a running event loop (the async rollout path calls reward grading
    from agent callbacks) — in that case run it on a dedicated thread with
    its own loop instead."""
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro)
    logger.warning(
        "batch_reward called on a running event loop; grading on a "
        "dedicated thread BLOCKS this loop until the batch completes — "
        "prefer asyncio.to_thread(batch_reward, ...) from async code"
    )
    with cf.ThreadPoolExecutor(max_workers=1) as pool:
        return pool.submit(asyncio.run, coro).result()


def _grade_local(task: Dict[str, Any]) -> float:
    kind = task.get("task", "math")
    if kind in ("math", "stem"):
        return math_verify.verify_math(task["generated"], task.get("solutions", []))
    if kind == "code":
        return code_verify.verify_code(
            task["generated"], task.get("input_output", "{}"),
            timeout=float(task.get("timeout", 8.0)),
        )
    logger.warning(f"unknown reward task kind {kind}; 0 reward")
    return 0.0


def batch_reward(
    tasks: List[Dict[str, Any]],
    max_workers: int = 8,
    max_retries: int = 2,
) -> List[float]:
    """Grade a batch of {task, generated, solutions|input_output} dicts.

    Uses the remote sandbox when FUNCTIONCALL_SERVICE_DOMAIN is set
    (one POST per chunk, retried), else the local thread-pool path."""
    if not tasks:
        return []
    domain = os.getenv(SERVICE_ENV, "")
    if domain:
        return _batch_remote(tasks, domain, max_retries)
    if len(tasks) == 1:
        return [_grade_local(tasks[0])]
    with cf.ThreadPoolExecutor(max_workers=max_workers) as pool:
        return list(pool.map(_grade_local, tasks))


def _batch_remote(tasks, domain: str, max_retries: int) -> List[float]:
    try:
        import aiohttp
    except ImportError:
        logger.warning(f"{SERVICE_ENV} set but aiohttp unavailable; local grading")
        return [_grade_local(t) for t in tasks]

    policy = dataclasses.replace(_REMOTE_RETRY, max_attempts=max_retries + 1)

    async def call_one(session, task, sem):
        url = f"http://{domain}/{'math_verify' if task.get('task','math') in ('math','stem') else 'code_verify'}"

        async def post_once():
            async with session.post(url, json=task, timeout=aiohttp.ClientTimeout(total=120)) as r:
                body = await r.text()
                return float(json.loads(body).get("score", 0.0))

        async with sem:
            try:
                return await aretry(post_once, policy)
            except Exception as e:  # noqa: BLE001 — retries exhausted
                logger.warning(f"remote reward failed ({e}); local fallback")
                return _grade_local(task)

    async def run():
        sem = asyncio.Semaphore(64)
        async with aiohttp.ClientSession() as session:
            return await asyncio.gather(*[call_one(session, t, sem) for t in tasks])

    return list(_run_coro_blocking(run()))
