"""Reward evaluation fanout — sandbox reward fleet or local fallback.

Parity target: ``functioncall/base/call.py:81-235`` (``batch_function_call``:
aiohttp fanout to FUNCTIONCALL_SERVICE_DOMAIN with retries and concurrency
caps) + the dispatch in ``math_rw_interface.py:127`` (math vs code by task).

Three grading modes, in precedence order (docs/rewards.md):

 1. **Reward-service fleet** (``configure_service`` with an enabled
    RewardServiceConfig): tasks fan out over the reward workers discovered
    through name_resolve (system/reward_worker.py) with bounded in-flight
    concurrency, capped-exponential retry across SURVIVING replicas, a
    per-task timeout, and partial-batch degradation to local grading when
    the fleet is unreachable.
 2. **Legacy remote domain** (``FUNCTIONCALL_SERVICE_DOMAIN`` env): one
    fixed host, same retry semantics — kept so reference-style deployments
    keep working unchanged.
 3. **Local** (the default): grading runs in this process
    (rewards/math_verify.py, rewards/code_verify.py) — bit-identical to
    the pre-service behavior.

Entrypoints: :func:`abatch_reward` (async — what agent callbacks await so
grading never blocks the rollout event loop) and :func:`batch_reward`
(sync — trainer-side interfaces and offline eval). Calling the sync form
from a running event loop raises: that was the old loop-blocking
``_run_coro_blocking`` path, replaced by the real async entrypoint.
"""

from __future__ import annotations

import asyncio
import concurrent.futures as cf
import json
import os
from typing import Any, Dict, List, Optional

import dataclasses

from areal_tpu.base import logging, telemetry
from areal_tpu.base.retry import RetryPolicy, aretry
from areal_tpu.rewards.service import grade_task, task_budget_secs

logger = logging.getLogger("rewards.client")

SERVICE_ENV = "FUNCTIONCALL_SERVICE_DOMAIN"

# Shared fleet-wide backoff vocabulary (base/retry.py): sandbox calls retry
# on the same capped-exponential schedule as generation failover.
_REMOTE_RETRY = RetryPolicy(base_delay_secs=0.5, max_delay_secs=5.0)


def task_from_record(record: Dict[str, Any], generated: str) -> Dict[str, Any]:
    """The ONE dataset-record → grading-task builder, shared by the
    rollout envs, the trainer's rw interface, and the eval harness — so
    per-task fields (``input_output``, ``language``) cannot silently be
    forwarded by some callers and dropped by others."""
    kind = record.get("task", "math")
    task: Dict[str, Any] = {"task": kind, "generated": generated}
    if kind == "code":
        task["input_output"] = record.get("input_output", "{}")
        if "language" in record:
            task["language"] = record["language"]
    else:
        task["solutions"] = record.get("solutions", [])
    return task


def _grade_local(task: Dict[str, Any],
                 languages: Optional[List[str]] = None) -> float:
    """Local grading — the SAME dispatch the fleet runs
    (rewards/service.py grade_task), so fallback outputs are
    bit-identical to fleet outputs; only the tripwire counter differs.
    ``languages`` carries the service's language policy into the
    FALLBACK path (an excluded language must not execute locally just
    because the fleet was unreachable); None = no policy (legacy local
    mode)."""
    if task.get("task", "math") == "code":
        # In-calling-process code execution is exactly what the reward
        # service exists to remove — count it so a healthy service run
        # can assert zero (docs/rewards.md).
        telemetry.inc("reward_client/local_graded{task=code}")
    return float(grade_task(task, languages=languages)["score"])


# --------------------------------------------------------------------------
# reward-service fleet client
# --------------------------------------------------------------------------


class RewardServiceClient:
    """Fanout client for the sandbox reward fleet (docs/rewards.md).

    Worker discovery is lazy and refreshed on failure: a task whose POST
    fails marks that URL tried and retries on a DIFFERENT live replica
    (re-resolving the fleet between attempts, so a respawned worker's
    fresh URL is picked up mid-batch). The retry budget exhausted —
    or no replica reachable at all — degrades that TASK to local grading
    when ``local_fallback`` allows, else scores it 0.0; either way one
    dead worker never fails a whole batch.
    """

    def __init__(self, cfg, experiment: str = "", trial: str = "",
                 urls: Optional[List[str]] = None,
                 resolver=None):  # cfg: RewardServiceConfig
        self.cfg = cfg
        self.experiment = experiment
        self.trial = trial
        self._urls: List[str] = list(urls or [])
        self._rr = 0  # round-robin cursor
        if resolver is not None:
            self._resolver = resolver
        elif experiment:
            from areal_tpu.system.reward_worker import resolve_fleet

            self._resolver = lambda: resolve_fleet(experiment, trial)
        else:
            self._resolver = lambda: []
        self.policy = RetryPolicy(
            max_attempts=max(int(cfg.max_retries) + 1, 1),
            base_delay_secs=cfg.retry_base_delay_secs,
            max_delay_secs=cfg.retry_max_delay_secs,
        )
        # Externally-owned ClientSession (use_session): the rollout
        # worker attaches its long-lived session so fleet POSTs reuse
        # keepalive connections instead of building a pool per batch.
        self._ext_session = None
        # Shared in-flight resolve (arefresh): when a replica dies with
        # 64 grades in flight, ONE name-resolve walk serves them all
        # instead of a 64-way NFS stampede.
        self._refresh_task: Optional[asyncio.Task] = None
        # Cold start: first-ever resolve gets bounded patience (the
        # fleet may still be registering at launch).
        self._fleet_seen = bool(urls)

    COLD_START_WAIT_SECS = 10.0

    async def _await_fleet(self) -> None:
        """Bounded wait for the FIRST registration. Before any worker
        has ever been seen, degrading to local code execution because
        the fleet is 0.5s late registering would defeat the sandbox —
        poll for up to COLD_START_WAIT_SECS instead. Once a fleet has
        been seen, dead-fleet handling belongs to the normal retry
        budget (a vanished fleet should degrade promptly, not stall
        every batch ten seconds)."""
        if self._fleet_seen:
            return
        deadline = (asyncio.get_running_loop().time()
                    + self.COLD_START_WAIT_SECS)
        while not self._urls:
            await self.arefresh()
            if self._urls:
                break
            telemetry.inc("reward_client/fleet_empty")
            if asyncio.get_running_loop().time() >= deadline:
                break
            await asyncio.sleep(0.25)
        # The window is consumed either way — a fleet that never comes
        # up must not re-stall EVERY later batch ten seconds; from here
        # on, dead-fleet handling belongs to the normal retry budget.
        self._fleet_seen = True

    def use_session(self, session) -> None:
        """Attach an externally-owned aiohttp session (closed by its
        owner, never by this client); ``abatch`` reuses it while open."""
        self._ext_session = session

    def refresh(self) -> List[str]:
        """Re-resolve the fleet (BLOCKING name_resolve I/O — async
        callers go through :meth:`arefresh`)."""
        fresh = self._resolver()
        if fresh:
            self._urls = list(fresh)
        return self._urls

    async def arefresh(self) -> List[str]:
        """Re-resolve off the loop (name_resolve walks an NFS tree —
        the loop-blocking this client's async entrypoint exists to
        avoid), sharing ONE walk among concurrent callers."""
        loop = asyncio.get_running_loop()
        t = self._refresh_task
        if t is None or t.done() or t.get_loop() is not loop:
            t = self._refresh_task = asyncio.ensure_future(
                asyncio.to_thread(self.refresh)
            )
        # Shield: one cancelled awaiter must not kill the walk the
        # other 63 in-flight grades are waiting on.
        return await asyncio.shield(t)

    def _pick(self, exclude=()) -> Optional[str]:
        """Next replica round-robin, skipping already-tried URLs; with
        every replica tried, fall back to any (a blip may have passed)."""
        pool = [u for u in self._urls if u not in exclude] or self._urls
        if not pool:
            return None
        self._rr += 1
        return pool[self._rr % len(pool)]

    @staticmethod
    def _endpoint(task: Dict[str, Any]) -> str:
        return "math_verify" \
            if task.get("task", "math") in ("math", "stem") else "code_verify"

    async def grade_one(self, session, task: Dict[str, Any],
                        sem: asyncio.Semaphore) -> float:
        import aiohttp

        async with sem:
            await self._await_fleet()  # cold start only; no-op after
            # Budget computed ONCE per task (task_budget_secs parses
            # input_output, which can be multi-MB for competitive-
            # programming records — not per retry attempt on the loop).
            http_total = task_budget_secs(task, max(
                float(self.cfg.request_timeout_secs),
                float(self.cfg.grade_timeout_secs),
            )) + 15.0
            tried: set = set()
            for attempt in range(1, self.policy.max_attempts + 1):
                if not self._urls:
                    await self.arefresh()
                url = self._pick(exclude=tried)
                if url is None:
                    # Fleet not (yet) resolvable — the cold-start race:
                    # workers may still be registering. Burn an attempt
                    # WITH backoff (same budget as a connect failure)
                    # instead of degrading to local code execution on
                    # the first miss.
                    telemetry.inc("reward_client/fleet_empty")
                    if attempt < self.policy.max_attempts:
                        await asyncio.sleep(self.policy.delay(attempt))
                    continue
                try:
                    async with session.post(
                        f"{url}/{self._endpoint(task)}", json=task,
                        # Same per-task floor as the server's grade
                        # budget (+queue/network headroom): the client
                        # must never abandon a grade the server is
                        # still legally running — that retry would run
                        # a duplicate grade per replica and end in
                        # local execution of the very code being boxed.
                        # The base takes grade_timeout_secs too: a
                        # raised server budget (slow sympy math) must
                        # raise the client's patience with it.
                        timeout=aiohttp.ClientTimeout(total=http_total),
                    ) as r:
                        if 400 <= r.status < 500 and r.status not in (
                            408, 429,
                        ):
                            # Deterministic rejection (malformed task):
                            # no replica will accept it — fail fast to
                            # the degradation path instead of burning
                            # the whole retry budget fleet-wide.
                            telemetry.inc("reward_client/bad_request")
                            logger.warning(
                                f"reward worker {url} rejected task "
                                f"(http {r.status}); not retrying"
                            )
                            break
                        if r.status != 200:
                            raise RuntimeError(f"http {r.status}")
                        out = await r.json()
                    telemetry.inc("reward_client/remote")
                    self._fleet_seen = True
                    return float(out.get("score", 0.0))
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — replica failed
                    # Mid-batch worker death: mark THIS url tried so the
                    # next attempt lands on a surviving replica, and
                    # re-resolve (a respawn registers a fresh URL).
                    tried.add(url)
                    telemetry.inc("reward_client/retries")
                    logger.warning(
                        f"reward worker {url} failed ({e}); "
                        f"attempt {attempt}/{self.policy.max_attempts}"
                    )
                    await self.arefresh()
                    if attempt < self.policy.max_attempts:
                        await asyncio.sleep(self.policy.delay(attempt))
            # Partial-batch degradation: only the tasks whose budget ran
            # out leave the fleet path.
            telemetry.inc("reward_client/local_fallback")
            if not self.cfg.local_fallback:
                logger.warning(
                    "reward fleet unreachable and local_fallback=false; "
                    "scoring 0.0"
                )
                return 0.0
            return await asyncio.to_thread(
                _grade_local, task, list(self.cfg.languages)
            )

    async def abatch(self, tasks: List[Dict[str, Any]]) -> List[float]:
        import aiohttp

        sem = asyncio.Semaphore(max(int(self.cfg.max_concurrency), 1))
        # Hot path (rollout worker): reuse the owner-attached session so
        # keepalive connections persist across batches. Without one
        # (trainer's per-batch asyncio.run, tools), a per-call session
        # is correct — a cached session cannot outlive its loop.
        session = self._ext_session
        if session is not None and not session.closed:
            return list(await asyncio.gather(
                *[self.grade_one(session, t, sem) for t in tasks]
            ))
        async with aiohttp.ClientSession() as session:
            return list(await asyncio.gather(
                *[self.grade_one(session, t, sem) for t in tasks]
            ))


# Module-level service mode: configured once per worker process
# (rollout worker / trainer startup), consumed by every batch_reward /
# abatch_reward call site without threading a client through.
_SERVICE_CLIENT: Optional[RewardServiceClient] = None


def configure_service(cfg, experiment: str = "", trial: str = "",
                      urls: Optional[List[str]] = None,
                      resolver=None) -> Optional[RewardServiceClient]:
    """Install (or clear) the process-wide reward-service client. A None
    or disabled config clears it — grading returns to the local path."""
    global _SERVICE_CLIENT
    if cfg is None or not getattr(cfg, "enabled", False):
        _SERVICE_CLIENT = None
        return None
    _SERVICE_CLIENT = RewardServiceClient(
        cfg, experiment, trial, urls=urls, resolver=resolver
    )
    logger.info(
        f"reward grading in service mode ({cfg.n_workers} workers, "
        f"concurrency {cfg.max_concurrency}, retries {cfg.max_retries})"
    )
    return _SERVICE_CLIENT


def service_client() -> Optional[RewardServiceClient]:
    return _SERVICE_CLIENT


# --------------------------------------------------------------------------
# entrypoints
# --------------------------------------------------------------------------


async def abatch_reward(
    tasks: List[Dict[str, Any]],
    max_workers: int = 8,
    max_retries: int = 2,
) -> List[float]:
    """Async grading of a batch of {task, generated, solutions|input_output}
    dicts — the entrypoint agent callbacks await, so grading never blocks
    the rollout event loop (no dedicated-thread bridge, no loop warning).

    Service mode (configure_service) fans out over the reward fleet; the
    legacy FUNCTIONCALL_SERVICE_DOMAIN env falls back to the fixed-host
    remote path; otherwise tasks grade locally on a bounded to_thread
    fanout (the event loop stays responsive either way)."""
    if not tasks:
        return []
    if _SERVICE_CLIENT is not None:
        return await _SERVICE_CLIENT.abatch(tasks)
    domain = os.getenv(SERVICE_ENV, "")
    if domain:
        return await _abatch_domain(tasks, domain, max_retries)
    sem = asyncio.Semaphore(max(int(max_workers), 1))

    async def one(t):
        async with sem:
            return await asyncio.to_thread(_grade_local, t)

    return list(await asyncio.gather(*[one(t) for t in tasks]))


def batch_reward(
    tasks: List[Dict[str, Any]],
    max_workers: int = 8,
    max_retries: int = 2,
) -> List[float]:
    """Synchronous grading (trainer-side interfaces, offline eval).

    Calling this from a running event loop raises — await
    :func:`abatch_reward` there instead (the old behavior silently
    BLOCKED the loop on a dedicated grading thread)."""
    if not tasks:
        return []
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        pass
    else:
        raise RuntimeError(
            "batch_reward called on a running event loop; "
            "await abatch_reward(tasks) instead — the sync form would "
            "block every in-flight rollout until the batch completes"
        )
    if _SERVICE_CLIENT is not None or os.getenv(SERVICE_ENV, ""):
        return asyncio.run(abatch_reward(tasks, max_workers, max_retries))
    # Local path: bit-identical to the pre-service behavior.
    if len(tasks) == 1:
        return [_grade_local(tasks[0])]
    with cf.ThreadPoolExecutor(max_workers=max_workers) as pool:
        return list(pool.map(_grade_local, tasks))


async def _abatch_domain(tasks, domain: str, max_retries: int) -> List[float]:
    """Legacy fixed-host remote path (FUNCTIONCALL_SERVICE_DOMAIN)."""
    try:
        import aiohttp
    except ImportError:
        logger.warning(f"{SERVICE_ENV} set but aiohttp unavailable; local grading")
        return [await asyncio.to_thread(_grade_local, t) for t in tasks]

    policy = dataclasses.replace(_REMOTE_RETRY, max_attempts=max_retries + 1)

    async def call_one(session, task, sem):
        url = f"http://{domain}/{'math_verify' if task.get('task','math') in ('math','stem') else 'code_verify'}"

        async def post_once():
            async with session.post(url, json=task, timeout=aiohttp.ClientTimeout(total=120)) as r:
                body = await r.text()
                return float(json.loads(body).get("score", 0.0))

        async with sem:
            try:
                return await aretry(post_once, policy)
            except Exception as e:  # noqa: BLE001 — retries exhausted
                logger.warning(f"remote reward failed ({e}); local fallback")
                return await asyncio.to_thread(_grade_local, task)

    sem = asyncio.Semaphore(64)
    async with aiohttp.ClientSession() as session:
        return list(await asyncio.gather(
            *[call_one(session, t, sem) for t in tasks]
        ))
