"""Reward evaluation fanout — remote sandbox service or local fallback.

Parity target: ``functioncall/base/call.py:81-235`` (``batch_function_call``:
aiohttp fanout to FUNCTIONCALL_SERVICE_DOMAIN with retries and concurrency
caps) + the dispatch in ``math_rw_interface.py:127`` (math vs code by task).
With no service configured, grading runs locally (rewards/math_verify.py,
rewards/code_verify.py) on a thread pool — the default for TPU pods where
the reward sandbox is a separate deployment.
"""

from __future__ import annotations

import asyncio
import concurrent.futures as cf
import json
import os
from typing import Any, Dict, List

from areal_tpu.base import logging
from areal_tpu.rewards import code_verify, math_verify

logger = logging.getLogger("rewards.client")

SERVICE_ENV = "FUNCTIONCALL_SERVICE_DOMAIN"


def _grade_local(task: Dict[str, Any]) -> float:
    kind = task.get("task", "math")
    if kind in ("math", "stem"):
        return math_verify.verify_math(task["generated"], task.get("solutions", []))
    if kind == "code":
        return code_verify.verify_code(
            task["generated"], task.get("input_output", "{}"),
            timeout=float(task.get("timeout", 8.0)),
        )
    logger.warning(f"unknown reward task kind {kind}; 0 reward")
    return 0.0


def batch_reward(
    tasks: List[Dict[str, Any]],
    max_workers: int = 8,
    max_retries: int = 2,
) -> List[float]:
    """Grade a batch of {task, generated, solutions|input_output} dicts.

    Uses the remote sandbox when FUNCTIONCALL_SERVICE_DOMAIN is set
    (one POST per chunk, retried), else the local thread-pool path."""
    if not tasks:
        return []
    domain = os.getenv(SERVICE_ENV, "")
    if domain:
        return _batch_remote(tasks, domain, max_retries)
    if len(tasks) == 1:
        return [_grade_local(tasks[0])]
    with cf.ThreadPoolExecutor(max_workers=max_workers) as pool:
        return list(pool.map(_grade_local, tasks))


def _batch_remote(tasks, domain: str, max_retries: int) -> List[float]:
    try:
        import aiohttp
    except ImportError:
        logger.warning(f"{SERVICE_ENV} set but aiohttp unavailable; local grading")
        return [_grade_local(t) for t in tasks]

    async def call_one(session, task, sem):
        url = f"http://{domain}/{'math_verify' if task.get('task','math') in ('math','stem') else 'code_verify'}"
        async with sem:
            for attempt in range(max_retries + 1):
                try:
                    async with session.post(url, json=task, timeout=aiohttp.ClientTimeout(total=120)) as r:
                        body = await r.text()
                        return float(json.loads(body).get("score", 0.0))
                except Exception as e:  # noqa: BLE001 — retry then fall back
                    if attempt == max_retries:
                        logger.warning(f"remote reward failed ({e}); local fallback")
                        return _grade_local(task)
                    await asyncio.sleep(0.5 * (attempt + 1))

    async def run():
        sem = asyncio.Semaphore(64)
        async with aiohttp.ClientSession() as session:
            return await asyncio.gather(*[call_one(session, t, sem) for t in tasks])

    return list(asyncio.run(run()))
