"""Local sandboxed code verification.

Parity target: ``functioncall/code/local_verify.py`` + ``testing_util.py``
(the reference's local fallback when no remote FUNCTIONCALL_SERVICE_DOMAIN
is configured). Runs a generated python solution against the dataset's
``input_output`` test cases in a subprocess with time/output limits.

Two test-case styles (same as the reference / LiveCodeBench):
 - stdin/stdout: inputs/outputs are raw text, the program reads stdin;
 - fn_name: inputs are argument lists, outputs the expected return values.
"""

from __future__ import annotations

import json
import os
import re
import resource
import signal
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from areal_tpu.base import logging

logger = logging.getLogger("rewards.code")

_CODE_BLOCK = re.compile(r"```(?:python|py)?\n(.*?)```", re.DOTALL)
MAX_OUTPUT_BYTES = 4 * 1024 * 1024  # cap read-back of graded program output

# Sandbox limits for the graded program (reference
# functioncall/code/function/testing_util.py:702-760 reliability_guard:
# rlimits + os/builtins disarm before running untrusted model code).
MEM_LIMIT_BYTES = 1024 * 1024 * 1024  # RLIMIT_AS
FSIZE_LIMIT_BYTES = 64 * 1024 * 1024  # RLIMIT_FSIZE

# Default cap on test cases sampled per grade. THE shared constant: the
# reward service's wall-budget floor (rewards/service.py task_budget_secs)
# and the pass-rate agent's fanout cap (agents/code_single_step.py)
# derive from it — a larger per-call max_cases must come with a larger
# grade/request budget.
MAX_CASES_DEFAULT = 16

# Injected ABOVE the untrusted code: disarm os-level footguns and
# escape hatches inside the child (belt; the rlimits below are braces).
_GUARD = """\
import builtins as _b
import os as _os
import sys as _s
_s.setrecursionlimit(100000)
for _name in (
    "system", "popen", "execv", "execve", "execvp", "execvpe", "fork",
    "forkpty", "spawnl", "spawnv", "spawnve", "killpg", "kill", "rename",
    "renames", "truncate", "replace", "unlink", "removedirs", "rmdir",
    "remove", "chmod", "chown", "chroot", "lchown", "setuid", "setgid",
    "fchmod", "fchown", "putenv",
):
    if hasattr(_os, _name):
        setattr(_os, _name, None)
_b.exit = None
_b.quit = None
try:
    import shutil as _sh
    _sh.rmtree = None
    _sh.move = None
    _sh.chown = None
except Exception:
    pass
try:
    import subprocess as _sp
    _sp.Popen = None
    _sp.run = None
    _sp.call = None
    _sp.check_output = None
except Exception:
    pass
del _b, _os, _s, _name
"""


def _child_limits(cpu_seconds: int):
    """preexec_fn for the grading subprocess: hard rlimits. Runs between
    fork and exec, so it must not import or allocate — ``resource`` is
    captured from the module scope (imported at module load) and the
    session split is done by ``start_new_session=True``, not os.setsid
    here (fork-safety in a multithreaded parent)."""

    def fn():
        resource.setrlimit(resource.RLIMIT_CPU, (cpu_seconds, cpu_seconds + 1))
        resource.setrlimit(
            resource.RLIMIT_FSIZE, (FSIZE_LIMIT_BYTES, FSIZE_LIMIT_BYTES)
        )
        resource.setrlimit(resource.RLIMIT_CORE, (0, 0))
        try:
            resource.setrlimit(
                resource.RLIMIT_AS, (MEM_LIMIT_BYTES, MEM_LIMIT_BYTES)
            )
        except ValueError:
            pass

    return fn


def extract_code(text: str) -> Optional[str]:
    blocks = _CODE_BLOCK.findall(text)
    if blocks:
        return blocks[-1].strip()
    if "def " in text or "print(" in text or "input(" in text:
        return text.strip()
    return None


_FN_RUNNER = """
import json, sys
{code}
_args = json.loads(sys.stdin.read())
_res = {fn_name}(*_args)
print(json.dumps(_res))
"""


def _run_one(
    code: str,
    stdin: str,
    timeout: float,
    fn_name: Optional[str] = None,
) -> Tuple[bool, str]:
    if fn_name:
        src = _FN_RUNNER.format(code=code, fn_name=fn_name)
    else:
        src = code
    src = _GUARD + src
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(src)
        path = f.name
    # Spool stdout/stderr to files so a print-flood program can't balloon
    # the trainer host's RSS; read back capped.
    out_f = tempfile.NamedTemporaryFile("w+", delete=False)
    err_f = tempfile.NamedTemporaryFile("w+", delete=False)
    scratch = tempfile.mkdtemp(prefix="areal_sbx_")
    proc = None

    def _reap_group(p) -> None:
        """SIGKILL the graded program's whole session, then reap the
        leader. Callers guarantee the leader is alive or an UNREAPED
        zombie — the zombie pins the pid/pgid, so this killpg can never
        hit an unrelated (recycled) process group."""
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        p.wait()

    try:
        proc = subprocess.Popen(
            [sys.executable, path],
            stdin=subprocess.PIPE,
            stdout=out_f,
            stderr=err_f,
            text=True,
            cwd=scratch,
            env={"PATH": os.environ.get("PATH", ""), "HOME": scratch,
                 "OMP_NUM_THREADS": "1"},
            start_new_session=True,
            preexec_fn=_child_limits(int(timeout) + 1),
        )
        # stdin fed from a side thread (communicate()'s deadlock
        # avoidance) because the wait below must NOT reap the child:
        # communicate/wait/poll all reap on exit, and killing the
        # process group through a REAPED leader's pid would race pid
        # recycling. waitid(WNOWAIT) observes exit while leaving the
        # zombie in place, so the group sweep in _reap_group — which
        # must run on EVERY exit path: fn_name solutions that spawned
        # children, or a leader that exited leaving grandchildren,
        # cannot outlive their grading slot — always targets OUR group.
        import threading

        def _feed():
            try:
                if stdin:
                    proc.stdin.write(stdin)
                proc.stdin.close()
            except (BrokenPipeError, OSError, ValueError):
                pass  # child exited without reading; its verdict decides

        threading.Thread(target=_feed, daemon=True).start()

        def _exited() -> bool:
            return os.waitid(
                os.P_PID, proc.pid,
                os.WEXITED | os.WNOHANG | os.WNOWAIT,
            ) is not None

        deadline = time.monotonic() + timeout
        while not (exited := _exited()) and time.monotonic() < deadline:
            time.sleep(0.005)
        # One FINAL check past the deadline: a program that exited during
        # the last sleep slice (or a GIL-delayed wakeup) finished within
        # its budget and must not be misgraded as a timeout.
        timed_out = not exited and not _exited()
        _reap_group(proc)  # group sweep + reap (sets returncode)
        if timed_out:
            return False, "timeout"
        err_f.seek(0)
        if proc.returncode != 0:
            return False, err_f.read(500)
        out_f.seek(0)
        return True, out_f.read(MAX_OUTPUT_BYTES)
    finally:
        import shutil

        # Exception path (spawn/waitid raised): the leader, if any, was
        # never reaped — the sweep is still pid-safe.
        if proc is not None and proc.returncode is None:
            _reap_group(proc)
        for fh in (out_f, err_f):
            fh.close()
            os.unlink(fh.name)
        os.unlink(path)
        shutil.rmtree(scratch, ignore_errors=True)


def sample_cases(inputs: List, outputs: List,
                 max_cases: int = MAX_CASES_DEFAULT) -> List[Tuple]:
    """Deterministic (input, output) sample honoring ``max_cases`` for
    EVERY length via a ceil-division stride (floor division let sizes
    just above the cap through at full count). THE sampling policy —
    the strict grader here and the pass-rate agent
    (agents/code_single_step.py) must pick the same cases."""
    cases = list(zip(inputs, outputs))
    if not cases:
        return []
    step = -(-len(cases) // max(int(max_cases), 1))
    return cases[::step]


def _outputs_match(got: str, want: str) -> bool:
    g = [l.rstrip() for l in got.strip().splitlines()]
    w = [l.rstrip() for l in want.strip().splitlines()]
    if g == w:
        return True
    # numeric comparison fallback (whitespace/format tolerant)
    try:
        gn = [float(x) for x in got.split()]
        wn = [float(x) for x in want.split()]
        return len(gn) == len(wn) and all(
            abs(a - b) <= 1e-6 * max(1.0, abs(b)) for a, b in zip(gn, wn)
        )
    except ValueError:
        return False


def verify_code(
    generated: str,
    input_output: str | Dict,
    timeout: float = 8.0,
    max_cases: int = MAX_CASES_DEFAULT,
    language: str = "python",
) -> float:
    """1.0 iff the extracted program passes ALL (sampled) test cases.

    ``language`` dispatches through :data:`GRADERS`; an unregistered
    language grades 0.0 (logged) instead of raising, so a mixed-language
    dataset degrades per task rather than killing the reward path."""
    grader = GRADERS.get(language)
    if grader is None:
        logger.warning(
            f"no grader registered for language {language!r} "
            f"(available: {', '.join(sorted(GRADERS))}); 0 reward"
        )
        return 0.0
    return grader(generated, input_output, timeout=timeout,
                  max_cases=max_cases)


def _verify_code_python(
    generated: str,
    input_output: str | Dict,
    timeout: float = 8.0,
    max_cases: int = MAX_CASES_DEFAULT,
) -> float:
    code = extract_code(generated)
    if code is None:
        return 0.0
    io = json.loads(input_output) if isinstance(input_output, str) else input_output
    inputs = io.get("inputs", [])
    outputs = io.get("outputs", [])
    fn_name = io.get("fn_name")
    if not inputs:
        return 0.0
    for inp, want in sample_cases(inputs, outputs, max_cases):
        if fn_name:
            stdin = inp if isinstance(inp, str) else json.dumps(inp)
            ok, got = _run_one(code, stdin, timeout, fn_name=fn_name)
            if not ok:
                return 0.0
            try:
                want_v = json.loads(want) if isinstance(want, str) else want
                got_v = json.loads(got)
                if got_v != want_v and not (
                    isinstance(want_v, list) and got_v == want_v[0]
                ):
                    return 0.0
            except (json.JSONDecodeError, IndexError):
                return 0.0
        else:
            ok, got = _run_one(code, inp, timeout)
            if not ok or not _outputs_match(got, want):
                return 0.0
    return 1.0


# Per-task language dispatch (docs/rewards.md): the reward service routes
# each code task's ``language`` field (default "python") through this
# registry, so C++/bash graders slot in as new entries — subprocess +
# rlimit guard included — without touching the service or client.
GRADERS: Dict[str, Any] = {"python": _verify_code_python}


def register_grader(language: str, fn) -> None:
    """Register a code grader: ``fn(generated, input_output, *, timeout,
    max_cases) -> float``. New-language graders MUST sandbox like the
    python one (subprocess + ``_child_limits`` rlimits +
    ``start_new_session`` with a finally-killpg sweep)."""
    GRADERS[language] = fn


def batch_verify_code(
    pairs: List[Tuple[str, str | Dict]], timeout: float = 8.0
) -> List[float]:
    return [verify_code(g, io, timeout=timeout) for g, io in pairs]
