"""Token sampling: temperature / top-k / top-p logits warping.

Parity target: ``realhf/impl/model/utils/logits_warper.py`` + genstep
(``realhf/impl/model/nn/real_llm_generate.py:30``). All ops are vectorized
over the batch and jit-safe (static top_k).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from areal_tpu.api.model import GenerationHyperparameters

_NEG_INF = -1e30


def apply_temperature(logits: jnp.ndarray, temperature: float) -> jnp.ndarray:
    return logits / jnp.maximum(temperature, 1e-6)


def apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    if k <= 0:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG_INF, logits)


def apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep tokens while cumulative prob (exclusive) < p: always keep the top-1.
    keep_sorted = (cum - probs) < p
    cutoff = jnp.sum(keep_sorted, axis=-1, keepdims=True)  # number kept
    kth = jnp.take_along_axis(sorted_logits, cutoff - 1, axis=-1)
    return jnp.where(logits < kth, _NEG_INF, logits)


def warp_logits(logits: jnp.ndarray, g: GenerationHyperparameters) -> jnp.ndarray:
    logits = apply_temperature(logits, g.temperature)
    logits = apply_top_k(logits, g.top_k)
    logits = apply_top_p(logits, g.top_p)
    return logits


def sample_token(
    logits: jnp.ndarray,  # [B, V] raw logits
    key: jax.Array,
    g: GenerationHyperparameters,
):
    """Returns (tokens [B], logprobs [B]) — logprob of the sampled token under
    the *warped* distribution (what the behavior policy actually sampled from;
    reference genstep records these as packed_logprobs)."""
    warped = warp_logits(logits, g)
    logp = jax.nn.log_softmax(warped, axis=-1)
    if g.greedy:
        tokens = jnp.argmax(warped, axis=-1)
    else:
        tokens = jax.random.categorical(key, warped, axis=-1)
    chosen = jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
    return tokens.astype(jnp.int32), chosen


# ---------------------------------------------------------------------------
# Per-row sampling — temperature/top-k/top-p/greedy as [B] ARRAYS, so one
# compiled decode kernel serves a batch of requests with different sampling
# hyperparameters (the server batches by computation shape only; mixed
# temperatures no longer serialize or recompile).
# ---------------------------------------------------------------------------


def sampling_from_gconfigs(gconfigs) -> dict:
    """Per-row sampling-parameter arrays from a list of gconfigs (one per
    batch row). The dict is a pytree of [B] arrays — a dynamic jit arg."""
    import numpy as np

    return {
        "temperature": np.asarray(
            [g.temperature for g in gconfigs], np.float32
        ),
        "top_k": np.asarray([g.top_k for g in gconfigs], np.int32),
        "top_p": np.asarray([g.top_p for g in gconfigs], np.float32),
        "greedy": np.asarray([g.greedy for g in gconfigs], bool),
        "min_new_tokens": np.asarray(
            [g.min_new_tokens for g in gconfigs], np.int32
        ),
    }


def warp_logits_rows(
    logits: jnp.ndarray,  # [B, V]
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int; <=0 disables
    top_p: jnp.ndarray,  # [B] float; >=1 disables
) -> jnp.ndarray:
    """Row-wise equivalent of sequential apply_temperature → top_k → top_p.

    One sort serves both filters: top-k keeps the first k sorted slots;
    top-p renormalizes over those and keeps the nucleus prefix."""
    V = logits.shape[-1]
    logits = logits / jnp.maximum(temperature[:, None], 1e-6)
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    idx = jnp.arange(V)[None, :]
    keep_k = (top_k[:, None] <= 0) | (idx < top_k[:, None])
    probs = jax.nn.softmax(
        jnp.where(keep_k, sorted_desc, _NEG_INF), axis=-1
    )
    cum = jnp.cumsum(probs, axis=-1)
    # Keep while exclusive-cumulative < p (always keeps top-1), within top-k.
    # p>=1 disables nucleus filtering outright (cum can round to exactly 1.0
    # on near-zero tail probs, which would otherwise clip them spuriously).
    keep = (
        ((cum - probs) < top_p[:, None]) | (top_p[:, None] >= 1.0)
    ) & keep_k
    n_keep = jnp.maximum(keep.sum(axis=-1, keepdims=True), 1)
    kth = jnp.take_along_axis(sorted_desc, n_keep - 1, axis=-1)
    return jnp.where(logits < kth, _NEG_INF, logits)


def sample_token_rows(
    logits: jnp.ndarray,  # [B, V] raw logits
    key: jax.Array,
    sampling: dict,  # per-row arrays from sampling_from_gconfigs
):
    """Row-wise sample_token: each row uses its own sampling params."""
    warped = warp_logits_rows(
        logits, sampling["temperature"], sampling["top_k"], sampling["top_p"]
    )
    logp = jax.nn.log_softmax(warped, axis=-1)
    sampled = jax.random.categorical(key, warped, axis=-1)
    greedy_tok = jnp.argmax(warped, axis=-1)
    tokens = jnp.where(sampling["greedy"], greedy_tok, sampled)
    chosen = jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
    return tokens.astype(jnp.int32), chosen
