"""Token sampling: temperature / top-k / top-p logits warping.

Parity target: ``realhf/impl/model/utils/logits_warper.py`` + genstep
(``realhf/impl/model/nn/real_llm_generate.py:30``). All ops are vectorized
over the batch and jit-safe (static top_k).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from areal_tpu.api.model import GenerationHyperparameters

_NEG_INF = -1e30


def apply_temperature(logits: jnp.ndarray, temperature: float) -> jnp.ndarray:
    return logits / jnp.maximum(temperature, 1e-6)


def apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    if k <= 0:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG_INF, logits)


def apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep tokens while cumulative prob (exclusive) < p: always keep the top-1.
    keep_sorted = (cum - probs) < p
    cutoff = jnp.sum(keep_sorted, axis=-1, keepdims=True)  # number kept
    kth = jnp.take_along_axis(sorted_logits, cutoff - 1, axis=-1)
    return jnp.where(logits < kth, _NEG_INF, logits)


def warp_logits(logits: jnp.ndarray, g: GenerationHyperparameters) -> jnp.ndarray:
    logits = apply_temperature(logits, g.temperature)
    logits = apply_top_k(logits, g.top_k)
    logits = apply_top_p(logits, g.top_p)
    return logits


def sample_token(
    logits: jnp.ndarray,  # [B, V] raw logits
    key: jax.Array,
    g: GenerationHyperparameters,
):
    """Returns (tokens [B], logprobs [B]) — logprob of the sampled token under
    the *warped* distribution (what the behavior policy actually sampled from;
    reference genstep records these as packed_logprobs)."""
    warped = warp_logits(logits, g)
    logp = jax.nn.log_softmax(warped, axis=-1)
    if g.greedy:
        tokens = jnp.argmax(warped, axis=-1)
    else:
        tokens = jax.random.categorical(key, warped, axis=-1)
    chosen = jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
    return tokens.astype(jnp.int32), chosen
