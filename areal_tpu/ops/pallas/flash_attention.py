"""TPU flash attention for packed segment batches.

Role parity: the reference's flash-attn varlen path
(``realhf/impl/model/modules/attn.py:24-27``). The hot op is delegated to
JAX's Pallas TPU flash-attention kernel
(``jax.experimental.pallas.ops.tpu.flash_attention``) — block-streamed
online-softmax with fused forward/backward kernels — wrapped here with
areal_tpu's packed-batch semantics:

 - inputs are [B, T, H, D] (time-major heads-minor, the model layout);
 - GQA: kv heads are expanded to the q head count before the kernel (the
   kernel wants matching head counts; the expansion is O(B·S·Hq·D) HBM but
   keeps the inner loop dense on the MXU);
 - document masking via SegmentIds — block-causal by grid column, which
   equals per-document causal order because packing keeps documents
   contiguous within a row (models/packing.py);
 - head_dim is padded up to the lane width (128) when needed.

Block-size selection (the device-efficiency lever named in
docs/benchmarks.md "Where the time goes"): ``pick_block_sizes`` resolves
(block_q, block_kv) for a (T, S) geometry from, in precedence order,

 1. ``AREAL_FLASH_BLOCKS="bq,bkv"`` — a global pin (debug/experiments);
 2. a geometry-keyed table: entries recorded at runtime via
    :func:`set_block_sizes`, or loaded from the JSON file named by
    ``AREAL_FLASH_BLOCK_TABLE`` (written by ``perf_probe blocksweep``,
    format ``{"T,S": [bq, bkv]}``);
 3. the built-in heuristic — the largest 128-multiple divisor ≤ 512.

Table/env entries are validated against the kernel's divisibility
constraint and snap DOWN to the nearest dividing 128-multiple rather than
failing at dispatch time.

Sequence dims with NO 128-multiple divisor no longer raise: the call falls
back to the XLA reference attention (ops/attention.py) with a once-per-
process log line. Training shapes never hit this (the packing
length_bucket guarantees 128-aligned rows); the fallback exists so ad-hoc
shapes (eval, probes) degrade gracefully instead of crashing.

CPU/testing: wrap calls in ``interpret_mode()`` — on jax versions shipping
``pltpu.force_tpu_interpret_mode`` the parity test
(tests/test_pallas_attention.py) runs the same kernel interpreted; on
jax 0.4.x the pallas interpreter cannot execute this kernel (its
load-discharge rule chokes on scalar block indices) and the helper
returns None so tests skip with a reason instead of failing.
"""

from __future__ import annotations

import functools
import json
import logging
import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.pallas.ops.tpu.flash_attention import (
    BlockSizes,
    SegmentIds,
)
from jax.experimental.pallas.ops.tpu.flash_attention import (
    flash_attention as _jax_flash,
)

LANE = 128
DEFAULT_BLOCK_TARGET = 512

logger = logging.getLogger("areal_tpu")

# Geometry-keyed (T, S) -> (block_q, block_kv). Populated by
# set_block_sizes() / the AREAL_FLASH_BLOCK_TABLE JSON (perf_probe
# blocksweep writes it); empty by default — the heuristic below is the
# fallback, and recorded sweep results override it per geometry.
_BLOCK_TABLE: Dict[Tuple[int, int], Tuple[int, int]] = {}
_TABLE_FILE_LOADED: Optional[str] = None  # set only on a SUCCESSFUL load
_TABLE_FILE_WARNED: set = set()
_WARNED_REF_FALLBACK = False


def _block(n: int, target: int) -> Optional[int]:
    """Largest multiple of 128 that divides n and is ≤ target (the kernel
    requires block sizes to divide the sequence dims exactly). None when no
    such divisor exists — callers fall back to the reference path."""
    for b in range(min(target, n), 0, -LANE):
        if n % b == 0 and b % LANE == 0:
            return b
    return None


def set_block_sizes(T: int, S: int, block_q: int, block_kv: int) -> None:
    """Record tuned block sizes for a (T, S) geometry (process-local)."""
    _BLOCK_TABLE[(int(T), int(S))] = (int(block_q), int(block_kv))


def clear_block_table() -> None:
    """Drop runtime + file-loaded entries (tests / re-sweeps)."""
    global _TABLE_FILE_LOADED
    _BLOCK_TABLE.clear()
    _TABLE_FILE_LOADED = None


def _load_table_file() -> None:
    """Merge ``AREAL_FLASH_BLOCK_TABLE`` (if set) into the table once per
    path; runtime set_block_sizes entries win over file entries. A missing
    or unreadable file warns once but is retried on later calls (the
    documented workflow writes the file with ``perf_probe blocksweep``
    AFTER the env var is already exported), and only a successful load
    pins the path as done."""
    global _TABLE_FILE_LOADED
    path = os.environ.get("AREAL_FLASH_BLOCK_TABLE")
    if not path or path == _TABLE_FILE_LOADED:
        return
    try:
        with open(path) as f:
            raw = json.load(f)
        for key, val in raw.items():
            t, s = (int(x) for x in key.split(","))
            _BLOCK_TABLE.setdefault((t, s), (int(val[0]), int(val[1])))
        _TABLE_FILE_LOADED = path
        _TABLE_FILE_WARNED.discard(path)
    except (OSError, ValueError, KeyError, IndexError) as e:
        if path not in _TABLE_FILE_WARNED:
            _TABLE_FILE_WARNED.add(path)
            logger.warning("AREAL_FLASH_BLOCK_TABLE %r unreadable (%s); "
                           "using heuristic block sizes until it appears",
                           path, e)


def pick_block_sizes(T: int, S: int) -> Optional[Tuple[int, int]]:
    """Resolve (block_q, block_kv) for a geometry; None when either dim has
    no 128-multiple divisor (caller must use the reference path). Env pin >
    table (runtime or file) > heuristic; every source is snapped down to
    the nearest dividing 128-multiple."""
    if _block(T, T) is None or _block(S, S) is None:
        return None
    # Any 128-multiple divisor of n implies 128 | n, so once the checks
    # above pass the heuristic (target 512 >= 128) can never miss — it is
    # the safe landing spot for out-of-range pins/table entries (a sub-128
    # pin must NOT snap up to a whole-sequence tile: bq*bkv scores alone
    # would blow VMEM).
    heur_q = _block(T, DEFAULT_BLOCK_TARGET)
    heur_kv = _block(S, DEFAULT_BLOCK_TARGET)
    env = os.environ.get("AREAL_FLASH_BLOCKS")
    if env:
        try:
            bq, bkv = (int(x) for x in env.split(","))
            return (_block(T, min(bq, T)) or heur_q,
                    _block(S, min(bkv, S)) or heur_kv)
        except ValueError:
            logger.warning("AREAL_FLASH_BLOCKS=%r not 'bq,bkv'; ignoring",
                           env)
    _load_table_file()
    hit = _BLOCK_TABLE.get((T, S))
    if hit is not None:
        return (_block(T, min(hit[0], T)) or heur_q,
                _block(S, min(hit[1], S)) or heur_kv)
    return (heur_q, heur_kv)


def interpret_mode():
    """``pltpu.force_tpu_interpret_mode()`` when this jax ships it, else
    None (jax 0.4.x: the pallas interpreter cannot execute this kernel —
    ``pl.pallas_call(interpret=True)`` dies in its load-discharge rule on
    scalar block indices — so CPU parity tests must skip, with this as the
    single version gate they consult)."""
    from jax.experimental.pallas import tpu as pltpu

    ctx = getattr(pltpu, "force_tpu_interpret_mode", None)
    return ctx() if ctx is not None else None


def _reference_fallback(q, k, v, q_segment_ids, kv_segment_ids,
                        q_positions, kv_positions, causal, scale, why):
    global _WARNED_REF_FALLBACK
    if not _WARNED_REF_FALLBACK:
        _WARNED_REF_FALLBACK = True
        logger.warning(
            "pallas flash attention: %s; falling back to the O(S^2) XLA "
            "reference for this shape (further fallbacks logged at debug)",
            why,
        )
    else:
        logger.debug("pallas flash attention fallback: %s", why)
    # One definition of the reference recipe: route back through the
    # dispatcher with impl="reference" (no recursion — that path never
    # re-enters this module).
    from areal_tpu.ops import attention as attn

    return attn.packed_attention(
        q, k, v, q_segment_ids, kv_segment_ids, q_positions=q_positions,
        kv_positions=kv_positions, causal=causal, impl="reference",
        scale=scale,
    )


@functools.partial(
    jax.named_call, name="pallas_flash_attention"
)
def flash_attention(
    q: jnp.ndarray,  # [B, T, Hq, D]
    k: jnp.ndarray,  # [B, S, Hkv, D]
    v: jnp.ndarray,  # [B, S, Hkv, D]
    q_segment_ids: jnp.ndarray,  # [B, T] int, 0 = pad
    kv_segment_ids: jnp.ndarray,  # [B, S]
    q_positions: Optional[jnp.ndarray] = None,  # accepted for API parity
    kv_positions: Optional[jnp.ndarray] = None,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    B, T, Hq, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    blocks = pick_block_sizes(T, S)
    if blocks is None:
        # No 128-multiple divisor: the kernel cannot tile this shape.
        # Degrade to the reference instead of raising (training shapes are
        # length_bucket-aligned and never land here).
        return _reference_fallback(
            q, k, v, q_segment_ids, kv_segment_ids, q_positions,
            kv_positions, causal, scale,
            f"sequence dims T={T} S={S} have no 128-multiple block",
        )
    if scale is None:
        scale = D ** -0.5
    if Hq != Hkv:
        G = Hq // Hkv
        k = jnp.repeat(k, G, axis=2)
        v = jnp.repeat(v, G, axis=2)

    # [B, T, H, D] → [B, H, T, D] kernel layout.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if D < LANE:
        pad = [(0, 0), (0, 0), (0, 0), (0, LANE - D)]
        qt, kt, vt = (jnp.pad(x, pad) for x in (qt, kt, vt))

    # Padding rows (segment id 0) must not alias into a real segment; the
    # kernel's segment mask handles it as long as pad ids differ between a
    # q pad and kv real token — id 0 == id 0 would attend pad→pad only,
    # which is harmless (output rows for pad queries are discarded), but we
    # keep them NaN-free by masking afterwards instead.
    seg = SegmentIds(q=q_segment_ids, kv=kv_segment_ids)

    bq, bkv = blocks
    sizes = BlockSizes(
        block_q=bq, block_k_major=bkv, block_k=bkv, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bkv,
        block_k_dkv=bkv, block_q_dkv=bq,
        block_k_major_dq=bkv, block_k_dq=bkv, block_q_dq=bq,
    )
    out = _jax_flash(
        qt, kt, vt, segment_ids=seg, causal=causal, sm_scale=scale,
        block_sizes=sizes,
    )
    if D < LANE:
        out = out[..., :D]
    out = out.transpose(0, 2, 1, 3)
    # Zero pad-query rows (the kernel leaves them unspecified-but-finite).
    return out * (q_segment_ids > 0)[:, :, None, None].astype(out.dtype)
