"""TPU flash attention for packed segment batches.

Role parity: the reference's flash-attn varlen path
(``realhf/impl/model/modules/attn.py:24-27``). The hot op is delegated to
JAX's Pallas TPU flash-attention kernel
(``jax.experimental.pallas.ops.tpu.flash_attention``) — block-streamed
online-softmax with fused forward/backward kernels — wrapped here with
areal_tpu's packed-batch semantics:

 - inputs are [B, T, H, D] (time-major heads-minor, the model layout);
 - GQA: kv heads are expanded to the q head count before the kernel (the
   kernel wants matching head counts; the expansion is O(B·S·Hq·D) HBM but
   keeps the inner loop dense on the MXU);
 - document masking via SegmentIds — block-causal by grid column, which
   equals per-document causal order because packing keeps documents
   contiguous within a row (models/packing.py);
 - head_dim is padded up to the lane width (128) when needed.

CPU/testing: wrap calls in ``pltpu.force_tpu_interpret_mode()`` — the parity
test (tests/test_pallas_attention.py) runs the same kernel interpreted.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental.pallas.ops.tpu.flash_attention import (
    BlockSizes,
    SegmentIds,
)
from jax.experimental.pallas.ops.tpu.flash_attention import (
    flash_attention as _jax_flash,
)

LANE = 128


def _block(n: int, target: int) -> int:
    """Largest multiple of 128 that divides n and is ≤ target (the kernel
    requires block sizes to divide the sequence dims exactly)."""
    for b in range(min(target, n), 0, -LANE):
        if n % b == 0 and b % LANE == 0:
            return b
    raise NotImplementedError(f"no 128-multiple block divides {n}")


@functools.partial(
    jax.named_call, name="pallas_flash_attention"
)
def flash_attention(
    q: jnp.ndarray,  # [B, T, Hq, D]
    k: jnp.ndarray,  # [B, S, Hkv, D]
    v: jnp.ndarray,  # [B, S, Hkv, D]
    q_segment_ids: jnp.ndarray,  # [B, T] int, 0 = pad
    kv_segment_ids: jnp.ndarray,  # [B, S]
    q_positions: Optional[jnp.ndarray] = None,  # accepted for API parity
    kv_positions: Optional[jnp.ndarray] = None,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    B, T, Hq, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    if T % LANE or S % LANE:
        raise NotImplementedError(
            f"flash kernel needs 128-aligned sequence dims, got T={T} S={S} "
            "(the packing length_bucket guarantees this for training shapes)"
        )
    if Hq != Hkv:
        G = Hq // Hkv
        k = jnp.repeat(k, G, axis=2)
        v = jnp.repeat(v, G, axis=2)
    if scale is None:
        scale = D ** -0.5

    # [B, T, H, D] → [B, H, T, D] kernel layout.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if D < LANE:
        pad = [(0, 0), (0, 0), (0, 0), (0, LANE - D)]
        qt, kt, vt = (jnp.pad(x, pad) for x in (qt, kt, vt))

    # Padding rows (segment id 0) must not alias into a real segment; the
    # kernel's segment mask handles it as long as pad ids differ between a
    # q pad and kv real token — id 0 == id 0 would attend pad→pad only,
    # which is harmless (output rows for pad queries are discarded), but we
    # keep them NaN-free by masking afterwards instead.
    seg = SegmentIds(q=q_segment_ids, kv=kv_segment_ids)

    bq = _block(T, 512)
    bkv = _block(S, 512)
    sizes = BlockSizes(
        block_q=bq, block_k_major=bkv, block_k=bkv, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bkv,
        block_k_dkv=bkv, block_q_dkv=bq,
        block_k_major_dq=bkv, block_k_dq=bkv, block_q_dq=bq,
    )
    out = _jax_flash(
        qt, kt, vt, segment_ids=seg, causal=causal, sm_scale=scale,
        block_sizes=sizes,
    )
    if D < LANE:
        out = out[..., :D]
    out = out.transpose(0, 2, 1, 3)
    # Zero pad-query rows (the kernel leaves them unspecified-but-finite).
    return out * (q_segment_ids > 0)[:, :, None, None].astype(out.dtype)
