"""ctypes loader for the native host ops (csrc/interval_ops.cpp).

The extension is compiled ON DEMAND with the system g++ into a per-user
cache dir (no pybind11 / setuptools dependency, per the environment) and
keyed by source hash, so editing the .cpp rebuilds automatically. Every
entry point has a pure-NumPy fallback — machines without a compiler just
run the Python path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from areal_tpu.base import logging

logger = logging.getLogger("ops.native")

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "csrc", "interval_ops.cpp",
)
_CACHE = os.path.expanduser(
    os.environ.get("AREAL_NATIVE_CACHE", "~/.cache/areal_tpu/native")
)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> Optional[str]:
    try:
        with open(_SRC, "rb") as f:
            src = f.read()
        tag = hashlib.sha256(src).hexdigest()[:16]
        out = os.path.join(_CACHE, f"interval_ops_{tag}.so")
        if os.path.exists(out):
            return out
        os.makedirs(_CACHE, exist_ok=True)
        tmp = out + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
               "-o", tmp]
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        # Source missing (packaged install without csrc/), unwritable
        # cache dir, no compiler — all mean "use the NumPy fallback",
        # never a crash in the packing hot path.
        logger.info(f"native build unavailable ({e}); using numpy fallback")
        return None
    if r.returncode != 0:
        logger.warning(f"native build failed:\n{r.stderr[-500:]}")
        return None
    os.replace(tmp, out)
    return out


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            logger.warning(f"native lib load failed ({e}); numpy fallback")
            return None
        I64P = ctypes.POINTER(ctypes.c_int64)
        U8P = ctypes.POINTER(ctypes.c_uint8)
        for fn in (lib.scatter_intervals, lib.gather_intervals):
            fn.argtypes = [U8P, U8P, I64P, I64P, I64P, I64P,
                           ctypes.c_int64, ctypes.c_int64, ctypes.c_int64]
            fn.restype = None
        lib.ffd_assign.argtypes = [I64P, I64P, ctypes.c_int64,
                                   ctypes.c_int64, I64P, I64P, I64P]
        lib.ffd_assign.restype = ctypes.c_int64
        _lib = lib
        logger.info(f"native interval ops loaded from {path}")
        return _lib


def available() -> bool:
    return _load() is not None


def _i64(arr) -> np.ndarray:
    return np.ascontiguousarray(arr, dtype=np.int64)


def _p(arr: np.ndarray, typ):
    return arr.ctypes.data_as(typ)


def _check_intervals(rows, cols, lens, offs, n_rows: int, n_cols: int,
                     flat_total: int) -> None:
    """Validate every interval against the [R, L] grid and the flat buffer
    BEFORE handing pointers to the C memcpy loop. The NumPy fallback would
    raise an IndexError on the same inputs; the raw C path would silently
    corrupt memory instead — so mirror the fallback and raise."""
    if len(rows) == 0:
        return
    if not (len(rows) == len(cols) == len(lens) == len(offs)):
        raise ValueError(
            f"interval arrays disagree on length: rows={len(rows)} "
            f"cols={len(cols)} lens={len(lens)} offs={len(offs)}"
        )
    if int(lens.min()) < 0 or int(cols.min()) < 0 or int(offs.min()) < 0:
        raise ValueError("negative interval length/column/offset")
    if int(rows.min()) < 0 or int(rows.max()) >= n_rows:
        raise ValueError(
            f"row index out of range [0, {n_rows}): "
            f"[{rows.min()}, {rows.max()}]"
        )
    if int((cols + lens).max()) > n_cols:
        raise ValueError(
            f"interval exceeds grid width {n_cols}: "
            f"max col+len {(cols + lens).max()}"
        )
    if int((offs + lens).max()) > flat_total:
        raise ValueError(
            f"interval exceeds flat buffer size {flat_total}: "
            f"max off+len {(offs + lens).max()}"
        )


def scatter_intervals(
    packed: np.ndarray,  # [total] contiguous (1-D per-token key)
    out: np.ndarray,  # [R, L] contiguous, pre-filled
    rows, cols, lens, offs,
) -> bool:
    """out[rows[i], cols[i]:cols[i]+lens[i]] = packed[offs[i]:...]; returns
    False (caller must fall back) when the native lib is unavailable or
    the arrays aren't the simple 1-D-key / 2-D-grid shape."""
    lib = _load()
    if lib is None or out.ndim != 2 or packed.ndim != 1:
        return False
    rows, cols, lens, offs = map(_i64, (rows, cols, lens, offs))
    _check_intervals(rows, cols, lens, offs, out.shape[0], out.shape[1],
                     packed.shape[0])
    U8P = ctypes.POINTER(ctypes.c_uint8)
    I64P = ctypes.POINTER(ctypes.c_int64)
    lib.scatter_intervals(
        _p(packed, U8P), _p(out, U8P),
        _p(rows, I64P), _p(cols, I64P), _p(lens, I64P), _p(offs, I64P),
        len(rows), out.shape[1], packed.dtype.itemsize,
    )
    return True


def gather_intervals(
    grid: np.ndarray,  # [R, L] contiguous
    out: np.ndarray,  # [total] contiguous
    rows, cols, lens, offs,
) -> bool:
    lib = _load()
    if lib is None or grid.ndim != 2 or out.ndim != 1:
        return False
    rows, cols, lens, offs = map(_i64, (rows, cols, lens, offs))
    _check_intervals(rows, cols, lens, offs, grid.shape[0], grid.shape[1],
                     out.shape[0])
    U8P = ctypes.POINTER(ctypes.c_uint8)
    I64P = ctypes.POINTER(ctypes.c_int64)
    lib.gather_intervals(
        _p(grid, U8P), _p(out, U8P),
        _p(rows, I64P), _p(cols, I64P), _p(lens, I64P), _p(offs, I64P),
        len(rows), grid.shape[1], grid.dtype.itemsize,
    )
    return True


def ffd_assign(sizes, capacity: int) -> Optional[np.ndarray]:
    """First-fit-decreasing bin ids per item (None → fall back)."""
    lib = _load()
    if lib is None:
        return None
    sizes = _i64(sizes)
    n = len(sizes)
    order = _i64(np.argsort(-sizes, kind="stable"))
    bin_of = np.empty(n, np.int64)
    loads = np.zeros(max(n, 1), np.int64)
    n_bins = np.zeros(1, np.int64)
    I64P = ctypes.POINTER(ctypes.c_int64)
    lib.ffd_assign(
        _p(sizes, I64P), _p(order, I64P), n, int(capacity),
        _p(bin_of, I64P), _p(loads, I64P), _p(n_bins, I64P),
    )
    return bin_of
