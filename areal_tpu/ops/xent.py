"""Memory-lean cross-entropy primitives shared by training and generation.

Role parity: the reference's fused vocab-parallel cross entropy
(``realhf/impl/model/parallelism/tensor_parallel/modules.py:1060-1195``) —
on TPU the fusion comes from XLA (gather + fused logsumexp reduction, no
[B, L, V] f32 materialization) instead of a hand-written kernel; under a
"tp"-sharded vocab GSPMD inserts the same all-reduces Megatron hand-codes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gather_logprobs(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """log p(labels) per position. logits [..., V], labels [...] → [...] f32.

    Gather + fused logsumexp: logits stay in their compute dtype (bf16 on
    the MXU); only the label-shaped outputs are f32. With a 152k vocab this
    is the difference between fitting in HBM and not.
    """
    tok = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
    # XLA fuses exp(astype(f32)) into the reduce; the f32 tensor never lands.
    lse = (
        jnp.log(
            jnp.sum(jnp.exp((logits - m[..., None]).astype(jnp.float32)), axis=-1)
        )
        + m.astype(jnp.float32)
    )
    return tok.astype(jnp.float32) - lse
