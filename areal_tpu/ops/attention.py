"""Segment-aware attention for document-packed fixed-shape batches.

Replaces the reference's flash-attn varlen path
(``realhf/impl/model/modules/attn.py:24-27``): instead of 1-D ragged batches,
areal_tpu packs sequences into ``[B, L]`` rows with per-token segment ids
(0 = padding) and uses block-causal same-segment masking — the layout TPU
splash-attention kernels natively support. A Pallas flash kernel backs the
TPU path (``areal_tpu/ops/pallas/flash_attention.py``); this module holds the
pure-XLA reference used on CPU and for parity tests.

Shapes: q ``[B, T, Hq, D]``; k, v ``[B, S, Hkv, D]`` with Hq = G * Hkv (GQA).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
_WARNED_FALLBACK = False


def segment_mask(
    q_segment_ids: jnp.ndarray,  # [B, T] int, 0 = padding
    kv_segment_ids: jnp.ndarray,  # [B, S]
    q_positions: Optional[jnp.ndarray] = None,  # [B, T] global position in row
    kv_positions: Optional[jnp.ndarray] = None,  # [B, S]
    causal: bool = True,
    sliding_window: Optional[int] = None,
) -> jnp.ndarray:
    """Boolean mask [B, 1, T, S]: attend iff same (non-zero) segment and,
    when causal, kv position <= q position (and within the sliding window
    when one is configured: q_pos - kv_pos < window, HF mistral semantics)."""
    same = (q_segment_ids[:, :, None] == kv_segment_ids[:, None, :]) & (
        q_segment_ids[:, :, None] > 0
    )
    if causal or sliding_window is not None:
        if q_positions is None:
            q_positions = jnp.arange(q_segment_ids.shape[1])[None, :] * jnp.ones_like(
                q_segment_ids
            )
        if kv_positions is None:
            kv_positions = jnp.arange(kv_segment_ids.shape[1])[None, :] * jnp.ones_like(
                kv_segment_ids
            )
        rel = q_positions[:, :, None] - kv_positions[:, None, :]
        if causal:
            same = same & (rel >= 0)
        if sliding_window is not None:
            same = same & (rel < sliding_window)
    return same[:, None, :, :]


@partial(jax.named_call, name="attention_ref")
def attention_reference(
    q: jnp.ndarray,  # [B, T, Hq, D]
    k: jnp.ndarray,  # [B, S, Hkv, D]
    v: jnp.ndarray,  # [B, S, Hkv, D]
    mask: jnp.ndarray,  # [B, 1, T, S] bool
    scale: Optional[float] = None,
) -> jnp.ndarray:
    B, T, Hq, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    if scale is None:
        scale = D ** -0.5
    qg = q.reshape(B, T, Hkv, G, D)
    # scores: [B, Hkv, G, T, S]
    scores = jnp.einsum("btkgd,bskd->bkgts", qg * scale, k)
    m = jnp.broadcast_to(mask[:, :, None, :, :], scores.shape)
    scores = jnp.where(m, scores, _NEG_INF)
    # Safe softmax: rows that are fully masked (padding queries) produce zeros.
    smax = jnp.max(scores, axis=-1, keepdims=True)
    unnorm = jnp.exp(scores - jax.lax.stop_gradient(smax)) * m
    denom = jnp.sum(unnorm, axis=-1, keepdims=True)
    probs = unnorm / jnp.maximum(denom, 1e-30)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(B, T, Hq, D)


def packed_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_segment_ids: jnp.ndarray,
    kv_segment_ids: jnp.ndarray,
    q_positions: Optional[jnp.ndarray] = None,
    kv_positions: Optional[jnp.ndarray] = None,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    impl: str = "auto",
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Dispatch between the XLA reference and the Pallas TPU kernel.

    The kernel wrapper itself degrades to the reference for shapes it
    cannot tile (no 128-multiple block divisor — see
    ops/pallas/flash_attention.pick_block_sizes) by calling back into this
    function with ``impl="reference"``, so the except-clause below only
    handles a missing/broken pallas import. ``scale`` defaults to
    ``head_dim ** -0.5`` in both implementations."""
    explicit = impl == "pallas"
    if explicit and sliding_window is not None:
        raise NotImplementedError(
            "pallas flash attention does not support sliding_window yet; "
            "use impl='reference'"
        )
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "reference"
    if impl == "pallas" and sliding_window is None:
        try:
            from areal_tpu.ops.pallas.flash_attention import flash_attention

            return flash_attention(
                q, k, v, q_segment_ids, kv_segment_ids,
                q_positions=q_positions, kv_positions=kv_positions,
                causal=causal, scale=scale,
            )
        except (ImportError, NotImplementedError) as e:
            if explicit:
                raise
            global _WARNED_FALLBACK
            if not _WARNED_FALLBACK:
                _WARNED_FALLBACK = True
                import logging

                logging.getLogger("areal_tpu").warning(
                    "pallas flash attention unavailable (%s); falling back to "
                    "the O(S^2) XLA reference", e,
                )
    mask = segment_mask(
        q_segment_ids, kv_segment_ids, q_positions, kv_positions, causal,
        sliding_window=sliding_window,
    )
    return attention_reference(q, k, v, mask, scale=scale)


def decode_attention(
    q: jnp.ndarray,  # [B, T, Hq, D] — current step(s); T > 1 = extension
    k_cache: jnp.ndarray,  # [B, S, Hkv, D]
    v_cache: jnp.ndarray,  # [B, S, Hkv, D]
    kv_valid: jnp.ndarray,  # [B, S] bool — or [B, T, S] per-query-token
) -> jnp.ndarray:
    # A [B, T, S] kv_valid gives each of the T new tokens its own valid
    # set — the causal mask of a multi-token cache extension (prefix
    # seeding, models/generate.extend_state). [B, S] broadcasts the same
    # set over every query token (the single-step decode path).
    if kv_valid.ndim == 3:
        mask = kv_valid[:, None, :, :]  # [B, 1, T, S]
    else:
        mask = kv_valid[:, None, None, :]  # [B, 1, 1, S]
    return attention_reference(q, k_cache, v_cache, mask)
