"""Single-step code-RL agent.

Proof that the Agent/EnvironmentService queue contract (SURVEY §2.9,
api/agent.py) is the workload extension point rather than a math-only
special case: this agent rides the SAME rollout worker, staleness gate,
partial-rollout failover, and reward path as the math agent — the only
code here is what is genuinely code-specific.

Differences from MathSingleStepAgent:

 - **Format gate**: a sample that never emitted a fenced code block is
   scored 0.0 WITHOUT entering the sandbox (no subprocess spawned for
   prose), and the gate is counted so training metrics separate
   "didn't write code" from "wrote failing code".
 - **Partial credit** (``pass_rate_reward=True``): reward is the fraction
   of test cases passed instead of the all-or-nothing verdict — the
   denser signal most code-RL recipes start from. Off by default: the
   default reward is bit-identical to the strict verifier.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from areal_tpu.agents.math_single_step import MathSingleStepAgent
from areal_tpu.api.agent import EnvironmentService
from areal_tpu.api.model import register_agent, register_env
from areal_tpu.base import logging, telemetry
from areal_tpu.rewards import code_verify
from areal_tpu.rewards.client import abatch_reward, task_from_record
from areal_tpu.rewards.code_verify import extract_code

logger = logging.getLogger("agents.code")

# Per-generation cap on pass-rate case fanout — the SAME bound the
# strict grader applies (and the reward service budgets for).
MAX_PASS_RATE_CASES = code_verify.MAX_CASES_DEFAULT


class CodeSingleStepEnv(EnvironmentService):
    """step((qid, texts)) grades generated programs against the record's
    ``input_output`` cases, with the format gate and optional per-case
    partial credit."""

    def __init__(self, id2info: Dict[str, Dict[str, Any]],
                 pass_rate_reward: bool = False):
        self.id2info = id2info
        self.pass_rate_reward = pass_rate_reward

    async def step(self, action):
        qid, texts = action
        info = self.id2info.get(str(qid).split("@", 1)[0], {})
        io_raw = info.get("input_output", "{}")
        tasks, slots = [], []
        scores: List[float] = [0.0] * len(texts)
        for i, t in enumerate(texts):
            if extract_code(t) is None:
                telemetry.inc("agent/code_format_gate")
                continue  # no code block: 0.0 without touching the sandbox
            base = task_from_record({**info, "task": "code"}, t)
            io = None
            if self.pass_rate_reward:
                try:
                    io = json.loads(io_raw) if isinstance(io_raw, str) \
                        else io_raw
                except (ValueError, TypeError):
                    io = None
                if not isinstance(io, dict):
                    # Malformed record: degrade to the strict path (the
                    # grader returns verdict=error, 0.0) exactly like
                    # pass_rate_reward=False would — one bad dataset
                    # line must not raise out of the rollout loop.
                    io = None
            if io is not None:
                # One task per SAMPLED test case; the reward is the pass
                # fraction over the sample. The SAME sampling policy as
                # the strict grader (code_verify.sample_cases) — a
                # 500-case record must not fan 500 sandbox tasks per
                # generation and starve the fleet, and both paths must
                # pick the same cases.
                sampled = code_verify.sample_cases(
                    io.get("inputs", []), io.get("outputs", []),
                    MAX_PASS_RATE_CASES,
                )
                for inp, out in sampled:
                    case = {"inputs": [inp], "outputs": [out]}
                    if io.get("fn_name"):
                        case["fn_name"] = io["fn_name"]
                    tasks.append({**base, "input_output": json.dumps(case)})
                    slots.append((i, len(sampled) or 1))
            else:
                tasks.append(base)
                slots.append((i, 1))
        if tasks:
            verdicts = await abatch_reward(tasks)
            for (i, denom), v in zip(slots, verdicts):
                scores[i] += float(v) / denom
        return None, scores, True, {}


class CodeSingleStepAgent(MathSingleStepAgent):
    """One obs → one grouped generation → sandboxed code rewards.

    Inherits the whole trajectory/filtering machinery; only the reward
    environment differs — which is exactly the extension contract."""


register_agent("code_single_step", CodeSingleStepAgent)
register_env("code_single_step", CodeSingleStepEnv)
