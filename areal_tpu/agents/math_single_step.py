"""Single-step math/code agent + verifier environment.

Parity targets: ``realhf/impl/agent/math_single_step_agent.py:23``
(MathSingleStepAgent: prompt → grouped generation → env reward →
success-rate filtering → trajectory samples) and
``realhf/impl/environment/math_code_single_step_env.py:41``
(MathCodeSingleStepEnv: step = math/code verification).
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Tuple

import numpy as np

from areal_tpu.api.agent import Agent, EnvironmentService
from areal_tpu.api.data import SequenceSample
from areal_tpu.api.model import register_agent, register_env
from areal_tpu.base import logging
from areal_tpu.rewards.client import abatch_reward, task_from_record

logger = logging.getLogger("agents.math")


class MathCodeSingleStepEnv(EnvironmentService):
    """step(action) grades generated texts against the dataset record."""

    def __init__(self, id2info: Dict[str, Dict[str, Any]]):
        self.id2info = id2info

    async def step(self, action: Tuple[str, List[str]]):
        qid, texts = action
        # ids carry "@"-separated suffixes (group index, epoch-pass tag);
        # the dataset key is everything before the first "@".
        info = self.id2info.get(str(qid).split("@", 1)[0], {})
        tasks = [task_from_record(info, t) for t in texts]
        # Real async entrypoint (rewards/client.py): grading — local,
        # legacy-domain, or reward-service fanout — never blocks the
        # rollout event loop on a dedicated grading thread.
        scores = await abatch_reward(tasks)
        return None, scores, True, {}


class MathSingleStepAgent(Agent):
    """One obs → one grouped generation → rewards → trajectories."""

    def __init__(
        self,
        tokenizer=None,
        success_rate_lb: float = 0.0,
        success_rate_ub: float = 1.0,
        reward_scaling: float = 1.0,
        reward_bias: float = 0.0,
    ):
        self.tokenizer = tokenizer
        self.success_rate_lb = success_rate_lb
        self.success_rate_ub = success_rate_ub
        self.reward_scaling = reward_scaling
        self.reward_bias = reward_bias

    async def collect_trajectory(
        self,
        prompt: SequenceSample,
        env: EnvironmentService,
        obs_queue: asyncio.Queue,
        act_queue: asyncio.Queue,
    ) -> List[SequenceSample]:
        qid = prompt.ids[0]
        prompt_ids = prompt.data["packed_prompts"]
        await obs_queue.put((qid, prompt_ids, None))
        # trajectory samples assembled by the generation client side
        trajs: List[SequenceSample] = await act_queue.get()
        if not trajs:
            return []
        texts = []
        for t in trajs:
            toks = t.data["packed_input_ids"]
            pm = t.data["prompt_mask"]
            gen = toks[pm == 0]
            texts.append(self.tokenizer.decode(gen) if self.tokenizer else "")
        _, scores, _, _ = await env.step((qid, texts))
        scores = np.asarray(scores, np.float32)
        # filter prompts that are too easy/hard for the whole group
        # (reference agent :44 success-rate bounds)
        rate = float((scores > 0).mean())
        if not (self.success_rate_lb <= rate <= self.success_rate_ub):
            logger.info(f"{qid}: success rate {rate:.2f} out of bounds; drop")
            return []
        out = []
        for t, s in zip(trajs, scores):
            t.update_(SequenceSample.from_default(
                ids=list(t.ids),
                data={"rewards": np.asarray(
                    [(s - self.reward_bias) * self.reward_scaling], np.float32
                )},
                seqlens=[1],
            ))
            out.append(t)
        return out


register_agent("math_single_step", MathSingleStepAgent)
register_env("math_code_single_step", MathCodeSingleStepEnv)
