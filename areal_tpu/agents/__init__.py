# Importing the package registers all built-in agents/envs (the reference
# does this in realhf/impl/__init__.py with its register_* calls).
from areal_tpu.agents import (  # noqa: F401
    code_single_step,
    math_multi_turn,
    math_single_step,
)
