"""Multi-turn math agent: generate → grade → feedback → retry.

Parity target: ``realhf/impl/agent/math_multi_turn_agent.py:23``
(MathMultiTurnAgent): up to ``num_turns`` rounds where the model answers,
the environment grades the answer, and a textual verdict is appended to the
context before the next attempt; per-turn rewards are credited backwards
with ``turn_level_discount`` (turn t's reward includes the discounted
successes of later retries, so early turns learn to set up late wins).

TPU-shape deviation (by design): the reference packs all turns into ONE
multi-segment SequenceSample (seqlens=[l1..lT]); here each turn becomes its
OWN trajectory sample — turn t's sequence already contains the full
accumulated context as its prompt (prompt_mask covers it), so token-level
credit assignment is identical, and the fixed-shape packing layer
(backend/microbatch.py) keeps its one-segment-per-sample contract.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import time
from typing import Any, List, Optional

import numpy as np

from areal_tpu.api.agent import Agent, EnvironmentService
from areal_tpu.api.data import SequenceSample
from areal_tpu.api.model import GenerationHyperparameters, register_agent
from areal_tpu.base import logging

logger = logging.getLogger("agents.math_multi_turn")

_FEEDBACK_OK = "Congratulations! You are correct!"
_FEEDBACK_RETRY = "Unfortunately your answer is wrong. Let's try again."


class MathMultiTurnAgent(Agent):
    """num_turns obs→act rounds per prompt, one sample per turn."""

    def __init__(
        self,
        tokenizer=None,
        num_turns: int = 4,
        turn_level_discount: float = 1.0,
        reward_scaling: float = 1.0,
        reward_bias: float = 0.0,
        max_new_tokens_per_turn: int = 1024,
        stop_on_success: bool = True,
        answer_save_path: Optional[str] = None,
        gconfig: Optional[GenerationHyperparameters] = None,
    ):
        assert tokenizer is not None, "multi-turn agent needs a tokenizer"
        self.tokenizer = tokenizer
        self.num_turns = num_turns
        self.turn_level_discount = turn_level_discount
        self.reward_scaling = reward_scaling
        self.reward_bias = reward_bias
        self.stop_on_success = stop_on_success
        self.answer_save_path = answer_save_path
        self.gconfig = dataclasses.replace(
            gconfig or GenerationHyperparameters(), n=1,
            max_new_tokens=max_new_tokens_per_turn,
        )

    def _feedback_ids(self, success: bool) -> List[int]:
        text = _FEEDBACK_OK if success else _FEEDBACK_RETRY
        tok = self.tokenizer
        if hasattr(tok, "apply_chat_template"):
            try:
                text = "\n" + tok.apply_chat_template(
                    [{"content": text, "role": "user"}],
                    add_generation_prompt=True, tokenize=False,
                )
            except Exception:  # noqa: BLE001 — template-less tokenizers
                text = f"\nUser: {text}\nAssistant:"
        else:
            text = f"\nUser: {text}\nAssistant:"
        return list(tok.encode(text))

    async def collect_trajectory(
        self,
        prompt: SequenceSample,
        env: EnvironmentService,
        obs_queue: asyncio.Queue,
        act_queue: asyncio.Queue,
    ) -> List[SequenceSample]:
        await env.reset()
        qid = prompt.ids[0]
        token_ids = list(map(int, prompt.data["packed_prompts"]))

        turns: List[SequenceSample] = []
        rewards: List[float] = []
        log: List[dict] = []
        for turn in range(self.num_turns):
            await obs_queue.put((qid, token_ids, self.gconfig))
            trajs: List[SequenceSample] = await act_queue.get()
            if not trajs:
                break
            t = trajs[0]
            toks = np.asarray(t.data["packed_input_ids"])
            pm = np.asarray(t.data["prompt_mask"])
            answer = self.tokenizer.decode(list(map(int, toks[pm == 0])))
            _, success, *_ = await env.step((qid, [answer]))
            ok = bool(np.asarray(success).reshape(-1)[0] > 0)
            rewards.append((float(ok) - 0.5) * 2 - self.reward_bias)
            turns.append(t)
            log.append({
                "turn": turn, "success": ok,
                "prompt_len": int(pm.sum()),
                "answer_len": int((pm == 0).sum()),
            })
            if ok and self.stop_on_success:
                break
            # Next turn continues from the full sequence + a graded verdict.
            token_ids = list(map(int, toks)) + self._feedback_ids(ok)

        # Turn-level credit: reward[t] += γ_turn · reward[t+1] (reference
        # :208-211), then scale.
        for i in reversed(range(len(rewards) - 1)):
            rewards[i] = rewards[i] + rewards[i + 1] * self.turn_level_discount
        out = []
        for t, r in zip(turns, rewards):
            t.update_(SequenceSample.from_default(
                ids=list(t.ids),
                data={"rewards": np.asarray(
                    [r * self.reward_scaling], np.float32
                )},
                seqlens=[1],
            ))
            out.append(t)
        self._log_to_file(qid, log)
        return out

    def _log_to_file(self, qid, log: List[dict]) -> None:
        """Per-qid pass/fail monitor jsonl (reference log_rewards_to_file)."""
        if not self.answer_save_path:
            return
        try:
            os.makedirs(self.answer_save_path, exist_ok=True)
            path = os.path.join(self.answer_save_path, f"{qid}.jsonl")
            with open(path, "a") as f:
                for rec in log:
                    f.write(json.dumps({**rec, "time": time.time()}) + "\n")
        except OSError as e:
            logger.warning(f"answer log write failed: {e}")


register_agent("math_multi_turn", MathMultiTurnAgent)
