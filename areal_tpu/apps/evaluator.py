"""Automatic checkpoint evaluator.

Parity target: ``realhf/scheduler/evaluator.py:160`` (AutomaticEvaluator +
EvaluationStep): a watcher thread scans the experiment's persistent save
directory for new checkpoints, runs at most ``max_concurrent_jobs`` eval
subprocesses (``apps/eval_ckpt.py``) over them in step order, and logs the
returned scores through the metric writer (wandb/tensorboard).

Consumes ``BaseExperimentConfig.auto_eval`` / ``auto_eval_config`` — the
launcher starts one evaluator when ``auto_eval=True``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from areal_tpu.base import logging

logger = logging.getLogger("apps.evaluator")

_STEP_DIR = re.compile(r"^step(\d+)$")


@dataclasses.dataclass
class EvaluationStep:
    """One checkpoint's eval lifecycle (reference evaluator.py:34)."""

    step: int
    ckpt_dir: str
    status: str = "pending"  # pending | running | done | failed
    scores: Optional[Dict] = None


def discover_new_steps(
    save_dir: str, role: str, seen: set
) -> List[EvaluationStep]:
    root = os.path.join(save_dir, role)
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _STEP_DIR.match(name)
        if not m or name in seen:
            continue
        d = os.path.join(root, name)
        # Only pick up completed saves. save_hf_checkpoint writes
        # areal_tpu_config.json LAST (models/hf.py:573) and
        # load_hf_checkpoint prefers it — gating on the HF config.json
        # would race a half-written checkpoint.
        if os.path.exists(os.path.join(d, "areal_tpu_config.json")):
            seen.add(name)
            out.append(EvaluationStep(step=int(m.group(1)), ckpt_dir=d))
    return sorted(out, key=lambda s: s.step)


class AutomaticEvaluator:
    """Watch → evaluate → log. The eval command is injectable for tests;
    the default spawns ``python -m areal_tpu.apps.eval_ckpt``."""

    def __init__(
        self,
        cfg,  # AutomaticEvaluatorConfig
        save_dir: str,
        dataset_path: str,
        role: str = "actor",
        metric_writer=None,
        run_eval: Optional[Callable[[EvaluationStep], Dict]] = None,
        poll_secs: float = 5.0,
        mock_tokenizer: bool = False,
        reward_service: Optional[tuple] = None,
    ):
        self.cfg = cfg
        self.save_dir = save_dir
        self.dataset_path = dataset_path
        self.role = role
        self.writer = metric_writer
        self.poll_secs = poll_secs
        self.mock_tokenizer = mock_tokenizer
        # (experiment, trial, nfs_name_resolve_root|"", config_json|"")
        # when the sandbox reward fleet should grade eval generations too
        # (docs/rewards.md) — the eval subprocess discovers the fleet
        # through name_resolve and rebuilds the OPERATOR'S
        # RewardServiceConfig from config_json (local_fallback and
        # language policy must hold there too).
        self.reward_service = reward_service
        self._run_eval = run_eval or self._subprocess_eval
        # poll_once runs _eval_one on a thread pool; tensorboard's event
        # writer is not thread-safe, so metric writes are serialized here
        # (interleaved writes corrupt the event-record framing).
        self._writer_lock = threading.Lock()
        self._seen: set = set()
        self.steps: List[EvaluationStep] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -------------- eval execution --------------

    def _subprocess_eval(self, step: EvaluationStep) -> Dict:
        with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
            out_path = f.name
        cmd = [
            sys.executable, "-m", "areal_tpu.apps.eval_ckpt",
            "--ckpt", step.ckpt_dir,
            "--dataset", self.dataset_path,
            "--output", out_path,
            "--max-gen-tokens", str(self.cfg.max_gen_tokens),
        ]
        # pass@k sampling eval (docs/rewards.md §pass@k): k>1 publishes
        # pass@1/pass@k/pass^k per task kind to tensorboard per saved
        # checkpoint; k=1 keeps the legacy greedy accuracy.
        k = int(getattr(self.cfg, "eval_k", 1) or 1)
        if k > 1:
            cmd += ["--k", str(k),
                    "--temperature",
                    str(getattr(self.cfg, "temperature", 0.6))]
        if self.mock_tokenizer:
            cmd.append("--mock-tokenizer")
        env = dict(os.environ)
        if self.reward_service is not None:
            exp, trial, nr_root, cfg_json = self.reward_service
            cmd += ["--reward-service", exp, trial]
            if cfg_json:
                cmd += ["--reward-service-config", cfg_json]
            if nr_root:
                env["AREAL_NAME_RESOLVE_ROOT"] = nr_root
        # Eval shares the host with training: keep it off the TPU.
        env.setdefault("JAX_PLATFORMS", "cpu")
        try:
            r = subprocess.run(cmd, capture_output=True, text=True, env=env,
                               timeout=3600)
            if r.returncode != 0:
                raise RuntimeError(r.stderr[-800:])
            with open(out_path) as f:
                return json.load(f)
        finally:
            os.unlink(out_path)

    # -------------- watcher loop --------------

    def _eval_one(self, step: EvaluationStep) -> bool:
        try:
            step.scores = self._run_eval(step)
            step.status = "done"
            logger.info(f"eval step {step.step}: {step.scores}")
            if self.writer is not None:
                metrics = {
                    f"eval/{k}": v
                    for k, v in (step.scores or {}).items()
                    if isinstance(v, (int, float))
                }
                # MetricWriter API (base/monitor.py:115): write(stats, step)
                with self._writer_lock:
                    self.writer.write(metrics, step.step)
            return True
        except Exception as e:  # noqa: BLE001 — eval must not kill training
            step.status = "failed"
            logger.error(f"eval step {step.step} failed: {e}")
            return False

    def poll_once(self) -> int:
        """Discover + evaluate new checkpoints; returns #evaluated.

        Up to ``max_concurrent_jobs`` evals run concurrently (reference
        AutomaticEvaluator runs EvaluationSteps in parallel); failed evals
        count toward the per-poll cap so a flaky checkpoint can't retry
        unboundedly within one poll.
        """
        from concurrent.futures import ThreadPoolExecutor

        fresh = discover_new_steps(self.save_dir, self.role, self._seen)
        self.steps.extend(fresh)
        pending = [s for s in self.steps if s.status == "pending"]
        cap = max(1, self.cfg.max_concurrent_jobs)
        batch = pending[:cap]
        if not batch:
            return 0
        for s in batch:
            s.status = "running"
        if len(batch) == 1:
            return int(self._eval_one(batch[0]))
        with ThreadPoolExecutor(max_workers=cap,
                                thread_name_prefix="eval") as pool:
            results = list(pool.map(self._eval_one, batch))
        return sum(results)

    def run_forever(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.poll_secs)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run_forever, daemon=True, name="auto-eval"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        if self.writer is not None and hasattr(self.writer, "close"):
            self.writer.close()
