"""Per-node worker entrypoint for cluster schedulers.

Parity target: ``realhf/apps/remote.py:54`` (main_worker) — a scheduler
(slurm, or any launcher that can run a command on a node) starts ONE process
per worker via this module; the process reconstructs the experiment config
from the dumped ``config.yaml``, then runs exactly one worker role. Worker
discovery happens through name_resolve exactly as in local mode, so the
system fabric is identical — only process placement changes.

Usage (what the slurm scripts generate):

    python -m areal_tpu.apps.remote --experiment-cls async-ppo-math \
        --config <run>/config.yaml --role trainer --rank $SLURM_PROCID \
        --world $SLURM_NTASKS
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional

from areal_tpu.base import logging

logger = logging.getLogger("apps.remote")

ROLES = ("master", "trainer", "gen_fleet", "rollout")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


def build_config(experiment_cls: str, config_path: str):
    import areal_tpu.experiments  # noqa: F401 — populates the registry
    from areal_tpu.api import cli_args as CA
    from areal_tpu.experiments import make_experiment_cls

    cfg = make_experiment_cls(experiment_cls)()
    CA.load_yaml(cfg, config_path)
    cfg.resolve_trial_name()
    return cfg


def run_role(
    exp_cfg,
    role: str,
    rank: int = 0,
    world: int = 1,
    index: int = 0,
    force_cpu: bool = False,
) -> None:
    """Run one worker role to completion (the scheduler owns the process)."""
    from areal_tpu.apps import launcher as L

    setup = exp_cfg.initial_setup()
    if role == "master":
        L._child_init(exp_cfg, force_cpu)
        from areal_tpu.system.master_worker import MasterWorker

        MasterWorker(setup["master"], setup["dfg"]).run()
    elif role == "trainer":
        tc = setup["trainer"]
        tc.dist_rank = rank
        tc.dist_world = world
        L.trainer_entry(exp_cfg, tc, force_cpu)
    elif role == "gen_fleet":
        if "gen_servers" not in setup:
            raise SystemExit("experiment has no generation fleet (sync mode)")
        L.gen_fleet_entry(
            exp_cfg, setup["gen_servers"], setup["gserver_manager"], force_cpu
        )
    elif role == "rollout":
        rcs = setup.get("rollout_workers", [])
        if not 0 <= index < len(rcs):
            raise SystemExit(
                f"rollout index {index} out of range (have {len(rcs)})"
            )
        L.rollout_entry(exp_cfg, rcs[index], force_cpu)
    else:
        raise SystemExit(f"unknown role {role!r}; have {ROLES}")


def main(argv: Optional[List[str]] = None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--experiment-cls", required=True,
                    help="registered experiment name (experiments registry)")
    ap.add_argument("--config", required=True, help="path to config.yaml")
    ap.add_argument("--role", required=True, choices=ROLES)
    ap.add_argument("--rank", type=int,
                    default=_env_int("SLURM_PROCID", 0))
    ap.add_argument("--world", type=int,
                    default=_env_int("SLURM_NTASKS", 1))
    ap.add_argument("--index", type=int,
                    default=_env_int("SLURM_PROCID", 0),
                    help="worker index within the role group (rollout); "
                         "defaults to SLURM_PROCID inside srun tasks")
    ap.add_argument("--force-cpu", action="store_true")
    args = ap.parse_args(argv)

    cfg = build_config(args.experiment_cls, args.config)
    logger.info(
        f"remote worker: role={args.role} rank={args.rank}/{args.world} "
        f"index={args.index} experiment={cfg.experiment_name}/"
        f"{cfg.trial_name}"
    )
    run_role(cfg, args.role, rank=args.rank, world=args.world,
             index=args.index, force_cpu=args.force_cpu)


if __name__ == "__main__":
    main()
