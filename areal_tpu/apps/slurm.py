"""Slurm scheduler client + launcher.

Parity target: ``realhf/scheduler/client.py:53`` (SchedulerClient ABC),
``realhf/scheduler/slurm/client.py:78`` (SlurmSchedulerClient — sbatch
script generation, submit, poll, cancel) and ``realhf/apps/main.py:80``
(one scheduler job per worker group).

TPU shape: one sbatch job per worker group. The trainer job runs N tasks
(one SPMD process per host; they rendezvous through name_resolve →
``jax.distributed``, parallel/distributed.py); the generation fleet,
rollout workers and master are single- or multi-task CPU/TPU jobs. Every
task execs ``python -m areal_tpu.apps.remote`` with the dumped config.yaml,
so worker code is identical to local mode.

The subprocess runner is injectable for tests (no slurm on dev machines).
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import time
from typing import Any, Callable, Dict, List, Optional

from areal_tpu.base import logging

logger = logging.getLogger("apps.slurm")

Runner = Callable[..., "subprocess.CompletedProcess"]

# squeue job-state codes that mean "still going" (reference
# scheduler/slurm/utils.py status mapping).
ACTIVE_STATES = {"PENDING", "RUNNING", "CONFIGURING", "COMPLETING",
                 "SUSPENDED", "REQUEUED"}
FAILED_STATES = {"FAILED", "CANCELLED", "TIMEOUT", "NODE_FAIL",
                 "OUT_OF_MEMORY", "PREEMPTED", "BOOT_FAIL", "DEADLINE"}


@dataclasses.dataclass
class SlurmJobSpec:
    """One worker group = one sbatch job."""

    name: str
    cmd: str  # the per-task command line (srun runs it ntasks times)
    ntasks: int = 1
    nodes: Optional[int] = None  # default: let slurm pack
    cpus_per_task: int = 2
    mem_per_task_mb: int = 8192
    tpus_per_task: int = 0  # rendered as a gres request when > 0
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    time_limit: Optional[str] = None
    partition: Optional[str] = None
    container: Optional[str] = None  # pyxis image, if the cluster uses one
    exclusive: bool = False


def render_sbatch_script(spec: SlurmJobSpec, log_dir: str) -> str:
    """The sbatch file for one worker group (reference
    slurm/utils.py:144 SlurmLaunchInfo.commit)."""
    lines = ["#!/bin/bash"]
    lines.append(f"#SBATCH --job-name={spec.name}")
    lines.append(f"#SBATCH --ntasks={spec.ntasks}")
    if spec.nodes:
        lines.append(f"#SBATCH --nodes={spec.nodes}")
    lines.append(f"#SBATCH --cpus-per-task={spec.cpus_per_task}")
    lines.append(f"#SBATCH --mem-per-cpu="
                 f"{max(1, spec.mem_per_task_mb // spec.cpus_per_task)}M")
    if spec.tpus_per_task:
        lines.append(f"#SBATCH --gres=tpu:{spec.tpus_per_task}")
    if spec.partition:
        lines.append(f"#SBATCH --partition={spec.partition}")
    if spec.time_limit:
        lines.append(f"#SBATCH --time={spec.time_limit}")
    if spec.exclusive:
        lines.append("#SBATCH --exclusive")
    lines.append(f"#SBATCH --output={log_dir}/{spec.name}.%j.out")
    lines.append(f"#SBATCH --error={log_dir}/{spec.name}.%j.err")
    lines.append("")
    for k, v in sorted(spec.env.items()):
        lines.append(f"export {k}={v!r}")
    srun = "srun"
    if spec.container:
        srun += f" --container-image={spec.container}"
    lines.append(f"{srun} {spec.cmd}")
    lines.append("")
    return "\n".join(lines)


class SlurmClient:
    """submit / poll / cancel sbatch jobs (runner injectable for tests)."""

    def __init__(self, log_dir: str, runner: Optional[Runner] = None):
        self.log_dir = log_dir
        self.runner = runner or subprocess.run
        self.jobs: Dict[str, str] = {}  # name -> job id

    def _run(self, cmd: List[str]) -> "subprocess.CompletedProcess":
        r = self.runner(cmd, capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd)} failed rc={r.returncode}: {r.stderr}"
            )
        return r

    def submit(self, spec: SlurmJobSpec) -> str:
        os.makedirs(self.log_dir, exist_ok=True)
        path = os.path.join(self.log_dir, f"{spec.name}.sbatch")
        with open(path, "w") as f:
            f.write(render_sbatch_script(spec, self.log_dir))
        r = self._run(["sbatch", "--parsable", path])
        job_id = r.stdout.strip().split(";")[0]
        self.jobs[spec.name] = job_id
        logger.info(f"submitted {spec.name} as slurm job {job_id}")
        return job_id

    def _sacct_states(self, ids: List[str]) -> Dict[str, str]:
        """Terminal states for jobs that already left the queue. sacct may
        be unavailable (no accounting storage) — then we can't do better
        than COMPLETED."""
        try:
            r = self.runner(
                ["sacct", "-j", ",".join(ids), "-n", "-X", "-P",
                 "-o", "JobID,State"],
                capture_output=True, text=True, timeout=120,
            )
        except Exception as e:  # noqa: BLE001 — sacct is best-effort
            logger.warning(f"sacct unavailable: {e}")
            return {}
        if r.returncode != 0:
            logger.warning(f"sacct rc={r.returncode}: {r.stderr.strip()}")
            return {}
        out = {}
        for line in r.stdout.strip().splitlines():
            parts = line.split("|")
            if len(parts) >= 2:
                # "CANCELLED by 1234" → CANCELLED
                out[parts[0]] = parts[1].split()[0] if parts[1] else ""
        return out

    def states(self) -> Dict[str, str]:
        """name -> slurm state. Jobs absent from squeue are checked against
        sacct to distinguish COMPLETED from FAILED/OOM (a crashed job ages
        out of squeue after MinJobAge and must not read as success)."""
        if not self.jobs:
            return {}
        ids = ",".join(self.jobs.values())
        # squeue exits nonzero ("Invalid job id specified") when ANY listed
        # id has been purged, reporting nothing about the others — retry
        # per-id in that case so one purged job can't mask still-RUNNING
        # ones as complete.
        r = self.runner(["squeue", "-j", ids, "-h", "-o", "%i %T"],
                        capture_output=True, text=True, timeout=120)
        by_id = {}
        if r.returncode == 0:
            for line in r.stdout.strip().splitlines():
                parts = line.split()
                if len(parts) >= 2:
                    by_id[parts[0]] = parts[1]
        elif "invalid job id" not in (r.stderr or "").lower():
            raise RuntimeError(
                f"squeue failed rc={r.returncode}: {r.stderr}"
            )
        else:
            for jid in self.jobs.values():
                ri = self.runner(["squeue", "-j", jid, "-h", "-o", "%i %T"],
                                 capture_output=True, text=True, timeout=120)
                if ri.returncode != 0:
                    continue  # purged — sacct below decides its fate
                for line in ri.stdout.strip().splitlines():
                    parts = line.split()
                    if len(parts) >= 2:
                        by_id[parts[0]] = parts[1]
        gone = [jid for jid in self.jobs.values() if jid not in by_id]
        sacct = self._sacct_states(gone) if gone else {}
        out = {}
        for name, jid in self.jobs.items():
            out[name] = by_id.get(jid) or sacct.get(jid) or "COMPLETED"
        return out

    def wait(
        self,
        poll_secs: float = 10.0,
        until_done: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> Dict[str, str]:
        """Block until a job fails, everything finishes, or (if
        ``until_done`` names a job) that job completes — the launcher waits
        on the master and then tears the rest down."""
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            st = self.states()
            failed = {n: s for n, s in st.items() if s in FAILED_STATES}
            if failed:
                raise RuntimeError(f"slurm jobs failed: {failed}")
            if until_done and st.get(until_done) == "COMPLETED":
                return st
            if all(s == "COMPLETED" for s in st.values()):
                return st
            if deadline and time.monotonic() > deadline:
                raise TimeoutError(f"slurm wait timed out; states={st}")
            time.sleep(poll_secs)

    def cancel_all(self) -> None:
        for name, jid in self.jobs.items():
            try:
                self._run(["scancel", jid])
            except RuntimeError as e:  # noqa: PERF203 — best-effort teardown
                logger.warning(f"scancel {name} ({jid}): {e}")


def build_job_specs(exp_cfg, config_path: str) -> List[SlurmJobSpec]:
    """Map an experiment's worker groups onto sbatch jobs."""
    from areal_tpu.experiments import registered_name_of
    from areal_tpu.parallel.mesh import AllocationMode

    exp = exp_cfg.experiment_name
    cls = registered_name_of(exp_cfg)
    base = (f"python -m areal_tpu.apps.remote --experiment-cls {cls} "
            f"--config {config_path}")
    am = AllocationMode.parse(getattr(exp_cfg, "allocation_mode", "") or "d1")
    chips_per_host = max(1, getattr(exp_cfg, "n_gpus_per_node", 4))
    train_chips = am.global_spec.world_size
    train_hosts = max(1, -(-train_chips // chips_per_host))
    specs = [
        SlurmJobSpec(
            name=f"{exp}-master",
            cmd=f"{base} --role master",
            ntasks=1,
        ),
        SlurmJobSpec(
            name=f"{exp}-trainer",
            cmd=f"{base} --role trainer",
            ntasks=train_hosts,
            nodes=train_hosts,
            tpus_per_task=min(train_chips, chips_per_host),
            cpus_per_task=8,
            mem_per_task_mb=64 * 1024,
            exclusive=train_hosts > 1,
        ),
    ]
    if am.decoupled:
        gen_chips = am.gen_spec.world_size
        gen_hosts = max(1, -(-gen_chips // chips_per_host))
        specs.append(SlurmJobSpec(
            name=f"{exp}-gen",
            cmd=f"{base} --role gen_fleet",
            ntasks=gen_hosts,
            nodes=gen_hosts,
            tpus_per_task=min(gen_chips, chips_per_host),
            cpus_per_task=8,
            mem_per_task_mb=64 * 1024,
        ))
        n_rollout = max(1, getattr(exp_cfg, "n_rollout_workers", 1))
        # No --index flag: the sbatch batch shell would expand $SLURM_PROCID
        # before srun spawns tasks (always 0). remote.py defaults --index
        # from the SLURM_PROCID env inside each srun task instead.
        specs.append(SlurmJobSpec(
            name=f"{exp}-rollout",
            cmd=f"{base} --role rollout",
            ntasks=n_rollout,
        ))
    return specs


class SlurmLauncher:
    """mode="slurm": dump config.yaml, submit one job per worker group,
    wait on the master, tear down (reference apps/main.py:80)."""

    def __init__(self, exp_cfg, runner: Optional[Runner] = None):
        self.exp_cfg = exp_cfg
        self.runner = runner

    def run(self) -> Dict[str, Any]:
        from areal_tpu.api import cli_args as CA
        from areal_tpu.experiments import common as C

        exp = self.exp_cfg
        exp.resolve_trial_name()
        C.setup_name_resolve(exp)
        log_dir = CA.get_log_path(exp)
        config_path = os.path.join(log_dir, "config.yaml")
        CA.save_yaml(exp, config_path)
        client = SlurmClient(log_dir, runner=self.runner)
        master_job = f"{exp.experiment_name}-master"
        try:
            for spec in build_job_specs(exp, config_path):
                client.submit(spec)
            client.wait(until_done=master_job)
            return {"steps": None, "slurm_jobs": dict(client.jobs)}
        finally:
            client.cancel_all()
