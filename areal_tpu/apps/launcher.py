"""Local experiment launcher.

Parity target: ``realhf/apps/main.py:80`` (main_start) +
``realhf/scheduler/local/client.py:71`` (LocalSchedulerClient) +
``training/utils.py:123`` (_run_experiment): spawn one process per worker,
run the master loop in the launcher process, monitor children, tear down.

TPU shape: the *trainer* is ONE process owning the whole trainer mesh
(single-controller SPMD — the reference's per-GPU model workers collapse);
the async generation fleet (servers + manager) is a second process group on
its own slice; rollout workers are CPU asyncio processes. ``mode="local"``
covers single-host; multi-host adds ``jax.distributed`` (launcher-side
support lands with the multi-host runtime).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
import time
from typing import Any, Dict, List, Optional

from areal_tpu.base import logging

logger = logging.getLogger("apps.launcher")

# Persistent XLA compilation cache shared by every worker process: the async
# experiment spawns 4+ JAX processes that would otherwise each recompile the
# same graphs from scratch — on a busy host that made the e2e launch a
# 165-420s coin flip (VERDICT r2 weak #4). Override with
# AREAL_COMPILATION_CACHE; set to "" to disable. The default path lives in
# base/compile_watch.py so the observatory's cache-hit/miss probe watches the
# same directory the launcher arms.
from areal_tpu.base.compile_watch import (  # noqa: E402
    DEFAULT_COMPILATION_CACHE, compilation_cache_dir,
)


def enable_compilation_cache() -> None:
    path = compilation_cache_dir()
    if not path:
        return
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Cache everything (default only caches >1s compiles) and never
        # burn cycles deciding: tiny test graphs dominate the e2e launch.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # noqa: BLE001 — cache is best-effort
        logger.warning(f"compilation cache unavailable: {e}")


# ---------------------------------------------------------------------------
# child-process entries (must be module-level for mp spawn pickling)
# ---------------------------------------------------------------------------


def derive_chip_assignment(
    alloc_mode: str, n_chips: int
) -> Dict[str, List[int]]:
    """Partition this host's TPU chips between the trainer and the
    generation fleet from the decoupled allocation mode (parity:
    LocalSchedulerClient's CUDA_VISIBLE_DEVICES bookkeeping, reference
    scheduler/local/client.py:87-98).

    Returns {"trainer": [...], "gen": [...]} chip-id lists. Raises with an
    actionable message when the layout cannot fit — two JAX processes must
    never initialize the same chip.
    """
    from areal_tpu.parallel.mesh import AllocationMode

    am = AllocationMode.parse(alloc_mode) if alloc_mode else None
    if am is None or not am.decoupled:
        return {"trainer": list(range(n_chips)), "gen": []}
    need_t = am.global_spec.world_size
    need_g = am.gen_spec.world_size
    if need_t + need_g > n_chips:
        raise RuntimeError(
            f"allocation mode '{alloc_mode}' needs "
            f"{need_t} trainer + {need_g} generation chips but this host has "
            f"{n_chips}; shrink the specs (e.g. gen.d1+d1 needs 2 chips) or "
            "run sync mode (colocated) where trainer and generation share "
            "the same chips"
        )
    return {
        "trainer": list(range(need_t)),
        "gen": list(range(need_t, need_t + need_g)),
    }


def _apply_chip_env(chips: Optional[List[int]]) -> None:
    """Restrict THIS process to the given TPU chips (must run before jax
    initializes). PJRT reads TPU_VISIBLE_CHIPS; the process-bounds vars tell
    libtpu this is a single-process slice of the host."""
    if chips is None:
        return
    os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chips)
    os.environ.setdefault("TPU_PROCESS_BOUNDS", "1,1,1")
    os.environ.setdefault(
        "TPU_CHIPS_PER_PROCESS_BOUNDS", f"{len(chips)},1,1"
    )


def _child_init(exp_cfg, force_cpu: bool, chips: Optional[List[int]] = None) -> None:
    _apply_chip_env(None if force_cpu else chips)
    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    enable_compilation_cache()
    from areal_tpu.experiments import common as C

    C.setup_name_resolve(exp_cfg)
    # Registration side effects for every factory the configs reference.
    import areal_tpu.agents.math_single_step  # noqa: F401
    import areal_tpu.algorithms  # noqa: F401 — registers all interfaces
    import areal_tpu.backend.jax_train  # noqa: F401
    import areal_tpu.datasets.jsonl  # noqa: F401


def _resolve_tokenizer(exp_cfg):
    from areal_tpu.experiments import common as C

    path = getattr(exp_cfg, "actor", None)
    model_path = path.path if path is not None else getattr(
        exp_cfg, "model", None
    ).path
    return C.make_tokenizer(exp_cfg, model_path)


def trainer_entry(exp_cfg, trainer_cfg, force_cpu: bool) -> None:
    # Multi-process CPU testing: the virtual-device flag must land in the
    # environment BEFORE jax initializes in this (spawned, fresh) process.
    if trainer_cfg.dist_world > 1 and trainer_cfg.dist_local_devices:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{trainer_cfg.dist_local_devices}"
            ).strip()
    _child_init(exp_cfg, force_cpu, getattr(trainer_cfg, "chips", None))
    from areal_tpu.system.trainer_worker import TrainerWorker

    trainer_cfg.tokenizer = _resolve_tokenizer(exp_cfg)
    TrainerWorker(trainer_cfg).run()


def _build_gen_model(init: Dict):
    """Model config + params for a generation server, from the actor's
    init dict (tiny test config or an HF checkpoint dir)."""
    import jax

    if "tiny" in init:
        from areal_tpu.models import transformer
        from areal_tpu.models.config import tiny_config

        kw = dict(init["tiny"])
        seed = kw.pop("seed", 0)
        cfg = tiny_config(**kw)
        return cfg, transformer.init_params(cfg, jax.random.PRNGKey(seed))
    from areal_tpu.models import hf as hfmod

    cfg, params, _ = hfmod.load_hf_model(init["hf_dir"])
    return cfg, params


def gen_fleet_entry(exp_cfg, server_cfgs, manager_cfg, force_cpu: bool,
                    chips: Optional[List[int]] = None) -> None:
    """All generation servers + the gserver manager in one asyncio loop."""
    _child_init(exp_cfg, force_cpu, chips)
    import asyncio

    from areal_tpu.experiments.common import model_init_dict
    from areal_tpu.system.generation_server import GenerationServer
    from areal_tpu.system.gserver_manager import GserverManager

    init = model_init_dict(exp_cfg.actor)

    async def main():
        cfg, params = _build_gen_model(init)
        tok = _resolve_tokenizer(exp_cfg)
        eos = getattr(tok, "eos_token_id", None)
        servers = []
        for sc in server_cfgs:
            if eos is not None:
                sc.eos_token_id = int(eos)
            srv = GenerationServer(sc, cfg, params)
            await srv.start()
            servers.append(srv)
        mgr = GserverManager(manager_cfg)
        await mgr.start()
        while True:  # runs until the launcher terminates us
            await asyncio.sleep(3600)

    asyncio.run(main())


def gen_server_entry(exp_cfg, server_cfg, force_cpu: bool,
                     chips: Optional[List[int]] = None) -> None:
    """One supervised generation server — the autoscaler's scale-up unit
    (docs/fault_tolerance.md §Autoscaling).

    Spawned by the launcher's AutoscaleExecutor to satisfy the gserver
    manager's published plan. The server joins the fleet through the
    normal path: registers under names.gen_servers, passes the manager's
    health gate, and is reconciled to the current weight version over the
    streamed transport (no checkpoint round-trip). It also serves a
    WorkerControl endpoint (``genserver_<server_id>``) so a drained
    cordon ends in a commanded clean exit the supervisor expects."""
    _child_init(exp_cfg, force_cpu, chips)
    import asyncio

    from areal_tpu.base import name_resolve, names
    from areal_tpu.experiments.common import model_init_dict
    from areal_tpu.system.generation_server import GenerationServer
    from areal_tpu.system.worker_base import WorkerControl

    init = model_init_dict(exp_cfg.actor)

    async def main():
        cfg, params = _build_gen_model(init)
        tok = _resolve_tokenizer(exp_cfg)
        eos = getattr(tok, "eos_token_id", None)
        if eos is not None:
            server_cfg.eos_token_id = int(eos)
        srv = GenerationServer(server_cfg, cfg, params)
        await srv.start()
        ctrl = WorkerControl(
            exp_cfg.experiment_name, exp_cfg.trial_name,
            f"genserver_{server_cfg.server_id}",
        )
        try:
            while True:
                await asyncio.to_thread(
                    ctrl.step,
                    lambda: {
                        "server_id": server_cfg.server_id,
                        "version": srv.version,
                        "inflight": srv._inflight,
                    },
                    200,
                )
                if ctrl.should_exit:
                    break
        finally:
            await srv.stop()
            # Withdraw discovery NOW: the manager's next sweep forgets
            # this url instead of probing a corpse until the lease TTL.
            try:
                name_resolve.delete(names.gen_servers(
                    exp_cfg.experiment_name, exp_cfg.trial_name,
                    server_cfg.server_id,
                ))
            except Exception:  # noqa: BLE001 — already gone
                pass
            ctrl.close()

    asyncio.run(main())


def reward_worker_entry(exp_cfg, rw_cfg) -> None:
    """One sandbox reward worker (the sixth worker kind,
    system/reward_worker.py). Deliberately NOT _child_init: a reward
    worker is jax-free and must never initialize an accelerator —
    untrusted code grades on spare CPU, not on the chips that train."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # belt: even if imported
    from areal_tpu.experiments import common as C

    C.setup_name_resolve(exp_cfg)
    from areal_tpu.system.reward_worker import RewardWorker

    RewardWorker(rw_cfg).run()


def rollout_entry(exp_cfg, rollout_cfg, force_cpu: bool) -> None:
    _child_init(exp_cfg, force_cpu)
    import asyncio

    from areal_tpu.system.rollout_worker import RolloutWorker

    rollout_cfg.tokenizer = _resolve_tokenizer(exp_cfg)
    eos = getattr(rollout_cfg.tokenizer, "eos_token_id", None)
    if eos is not None:
        rollout_cfg.eos_token_id = int(eos)
    asyncio.run(RolloutWorker(rollout_cfg).run_async())


# ---------------------------------------------------------------------------
# launcher
# ---------------------------------------------------------------------------


class LocalLauncher:
    """Spawn workers, run the master inline, supervise, tear down.

    Child death is classified by failure domain (system/supervisor.py):
    rollout workers and the gen-fleet process are respawned in place with
    backoff behind a crash-loop circuit breaker; trainer death escalates
    to ``run_experiment``'s whole-experiment recovery loop. SIGTERM
    triggers a graceful drain (pause → out-of-band recover checkpoint →
    orderly exits) instead of raw terminate().
    """

    def __init__(self, exp_cfg, force_cpu: Optional[bool] = None):
        from areal_tpu.api.train_config import FaultToleranceConfig

        self.exp_cfg = exp_cfg
        # Tests force CPU everywhere; real runs use the native platform.
        self.force_cpu = (
            force_cpu if force_cpu is not None
            else bool(getattr(exp_cfg, "mock_tokenizer", False))
        )
        self.ft = (getattr(exp_cfg, "fault_tolerance", None)
                   or FaultToleranceConfig())
        self.supervisor = None  # built in run() once the trial resolves
        self._scaler = None  # AutoscaleExecutor, when autoscale.enabled
        self._drain_evt = threading.Event()
        self._drain_thread: Optional[threading.Thread] = None
        self._drain_deadline: Optional[float] = None
        self._drain_failed = False

    def request_drain(self) -> None:
        """Ask for a graceful drain (same path as SIGTERM): pause the
        rollout fleet, dump a recover checkpoint out-of-band, exit the
        workers in order. Safe from any thread / signal handler."""
        self._drain_evt.set()

    @property
    def procs(self) -> List[mp.process.BaseProcess]:
        return self.supervisor.procs() if self.supervisor else []

    def _spawn(self, target, *args, name: str, kind: str,
               required: bool = True, expendable: bool = False) -> None:
        from areal_tpu.system.supervisor import WorkerSpec

        self.supervisor.spawn(WorkerSpec(
            name=name, kind=kind, target=target, args=args,
            required=required, expendable=expendable,
        ))

    @staticmethod
    def _count_chips(exp) -> int:
        """TPU chips on this host: probe in a subprocess so the launcher
        process itself never initializes the TPU runtime (children own the
        chips)."""
        env_n = os.environ.get("AREAL_N_CHIPS")
        if env_n:
            return int(env_n)
        import subprocess
        import sys as _sys

        try:
            out = subprocess.run(
                [_sys.executable, "-c",
                 "import jax; print(jax.device_count())"],
                capture_output=True, text=True, timeout=120,
            )
            return int(out.stdout.strip().splitlines()[-1])
        except Exception:  # noqa: BLE001 — fall back to config
            return int(getattr(exp, "n_gpus_per_node", 1))

    def _check_children(self) -> None:
        """One supervision sweep. Stateless-domain deaths respawn in
        place; stateful deaths and crash loops raise
        SupervisorEscalation, which run_experiment's recover loop turns
        into a whole-experiment relaunch."""
        self.supervisor.check()

    def _install_sigterm(self):
        """Preemption hook: SIGTERM drives the graceful drain instead of
        killing children outright. Returns a restore callable; no-op off
        the main thread (in-process test launches)."""
        import signal

        try:
            prev = signal.getsignal(signal.SIGTERM)

            def on_term(signum, frame):
                logger.warning("SIGTERM: starting graceful drain")
                self._drain_evt.set()

            signal.signal(signal.SIGTERM, on_term)
            return lambda: signal.signal(signal.SIGTERM, prev)
        except ValueError:  # not the main thread
            return lambda: None

    def run(self) -> Dict[str, Any]:
        from areal_tpu.experiments import common as C
        from areal_tpu.system.master_worker import MasterWorker
        from areal_tpu.system.supervisor import RestartPolicy, Supervisor

        exp = self.exp_cfg
        exp.resolve_trial_name()
        C.setup_name_resolve(exp)
        enable_compilation_cache()  # master runs in-process
        self.supervisor = Supervisor(
            exp.experiment_name, exp.trial_name,
            policy=RestartPolicy.from_config(self.ft),
            keepalive_ttl=getattr(self.ft, "keepalive_ttl_secs", 0.0),
            heartbeat_interval=getattr(
                self.ft, "heartbeat_interval_secs", 0.0
            ),
            # supervise=False restores the legacy contract: any child
            # death (of any kind) escalates immediately.
            restartable_kinds=(
                ("rollout", "gen_fleet", "reward")
                if getattr(self.ft, "supervise", True) else ()
            ),
        )
        setup = exp.initial_setup()

        # Persist the merged config next to the run (reference main_*.py).
        from areal_tpu.api import cli_args as CA

        CA.save_yaml(exp, os.path.join(
            CA.get_log_path(exp), "config.yaml"
        ))

        # Per-worker chip partitioning (decoupled async mode on real TPU):
        # fail fast on impossible layouts instead of letting two processes
        # claim one chip. CPU-forced runs skip it.
        chips = {"trainer": None, "gen": None}
        if not self.force_cpu and "gen_servers" in setup:
            n_chips = self._count_chips(exp)
            asg = derive_chip_assignment(
                getattr(exp, "allocation_mode", ""), n_chips
            )
            chips = {"trainer": asg["trainer"], "gen": asg["gen"]}
            logger.info(f"chip assignment: {asg}")
        setup["trainer"].chips = chips["trainer"]

        n_dist = getattr(exp, "trainer_dist_procs", 1)
        if n_dist > 1:
            # One SPMD trainer process per (virtual) host; rank 0 owns the
            # control plane, the rest replay its broadcasts.
            import copy as _copy

            # On real TPU, partition the trainer chip list across the dist
            # processes — copying the same list would have every process
            # initialize the same chips (the double-claim the chip
            # assignment exists to prevent).
            chip_slices = [None] * n_dist
            # With trainer_dist_devices_per_proc set, trainer_entry forces
            # virtual CPU devices per process and the chip list is unused.
            if (chips["trainer"] is not None
                    and not getattr(exp, "trainer_dist_devices_per_proc",
                                    None)):
                tchips = list(chips["trainer"])
                if len(tchips) % n_dist != 0:
                    raise RuntimeError(
                        f"trainer_dist_procs={n_dist} does not divide the "
                        f"{len(tchips)} trainer chips {tchips}; pick a "
                        "divisor"
                    )
                per = len(tchips) // n_dist
                chip_slices = [
                    tchips[r * per:(r + 1) * per] for r in range(n_dist)
                ]
            for r in range(n_dist):
                tc = _copy.deepcopy(setup["trainer"])
                tc.dist_rank = r
                tc.dist_world = n_dist
                tc.chips = chip_slices[r]
                tc.dist_local_devices = getattr(
                    exp, "trainer_dist_devices_per_proc", None
                )
                self._spawn(trainer_entry, exp, tc, self.force_cpu,
                            name=f"trainer{r}", kind="trainer")
        else:
            self._spawn(trainer_entry, exp, setup["trainer"], self.force_cpu,
                        name="trainer", kind="trainer")
        # Sandbox reward fleet (docs/rewards.md): CPU-only, supervised
        # as a restartable stateless domain — a crashed reward worker
        # respawns in place while clients retry on surviving replicas.
        # Spawned BEFORE the rollout side: reward workers are jax-free
        # and register in well under the fleet's startup time, so the
        # first grade never races their registration into local
        # code execution.
        for i, rw in enumerate(setup.get("reward_workers", [])):
            self._spawn(reward_worker_entry, exp, rw,
                        name=f"reward{i}", kind="reward")
        if "gen_servers" in setup:
            self._spawn(
                gen_fleet_entry, exp, setup["gen_servers"],
                setup["gserver_manager"], self.force_cpu, chips["gen"],
                name="gen_fleet", kind="gen_fleet",
            )
            for i, rc in enumerate(setup["rollout_workers"]):
                # A bounded worker (max_rollouts set) finishing its quota
                # exits 0 by DESIGN — only unbounded workers' clean exits
                # are the silent data-starvation failure the supervisor
                # must catch.
                self._spawn(rollout_entry, exp, rc, self.force_cpu,
                            name=f"rollout{i}", kind="rollout",
                            required=getattr(rc, "max_rollouts",
                                             None) is None)
            asc = getattr(exp, "autoscale", None)
            if asc is not None and getattr(asc, "enabled", False):
                self._scaler = self._build_scaler(exp, setup)

        evaluator = None
        if getattr(exp, "auto_eval", False):
            from areal_tpu.apps.evaluator import AutomaticEvaluator

            from areal_tpu.api.cli_args import AutomaticEvaluatorConfig

            eval_data = exp.auto_eval_config.data_names
            default_names = AutomaticEvaluatorConfig().data_names
            if not os.path.isfile(eval_data):
                if eval_data and eval_data != default_names:
                    # An explicitly-set eval set that doesn't exist is a
                    # config error: silently scoring the TRAIN set would
                    # masquerade as held-out accuracy.
                    raise FileNotFoundError(
                        f"auto_eval_config.data_names={eval_data!r} does not "
                        f"exist; point it at a prompt jsonl (the default "
                        f"{default_names!r} falls back to the training set)"
                    )
                logger.warning(
                    "auto_eval_config.data_names=%r is not a local file — "
                    "evaluator will score the TRAINING dataset (%s); "
                    "eval/* metrics are NOT held-out numbers",
                    eval_data, exp.dataset.path,
                )
                eval_data = exp.dataset.path
            # eval/* metrics land in the run's tensorboard alongside the
            # master's training scalars (separate writer, same log dir).
            eval_writer = None
            tb = getattr(setup["master"], "tensorboard_path", None)
            if tb:
                from areal_tpu.base.monitor import MetricWriter

                eval_writer = MetricWriter(
                    tensorboard_path=os.path.join(tb, "eval")
                )
            # With the reward fleet up, eval generations grade there too
            # — untrusted checkpoint output must not execute in the eval
            # subprocess either. The NFS name-resolve root rides along so
            # the subprocess can discover the workers.
            rs = None
            if getattr(getattr(exp, "reward_service", None),
                       "enabled", False):
                import dataclasses as _dc
                import json as _json

                # The same derivation setup_name_resolve applies
                # (experiments/common.py): explicit nfs_record_root or
                # the per-experiment default. Non-NFS repos pass "" —
                # the eval subprocess then uses its environment's
                # default (memory repos cannot cross a process anyway).
                nr_cfg = exp.cluster.name_resolve
                nr_root = ""
                if getattr(nr_cfg, "type", "nfs") == "nfs":
                    nr_root = (nr_cfg.nfs_record_root
                               or C.experiment_paths(exp)["name_resolve"])
                rs = (exp.experiment_name, exp.trial_name, nr_root,
                      _json.dumps(_dc.asdict(exp.reward_service)))
            evaluator = AutomaticEvaluator(
                exp.auto_eval_config,
                save_dir=setup["master"].save_dir,
                dataset_path=eval_data,
                metric_writer=eval_writer,
                mock_tokenizer=bool(getattr(exp, "mock_tokenizer", False)),
                reward_service=rs,
            )
            evaluator.start()
            logger.info(f"automatic evaluator watching "
                        f"{setup['master'].save_dir} (data: {eval_data})")

        master = MasterWorker(setup["master"], setup["dfg"])
        restore_sigterm = self._install_sigterm()
        try:
            result = self._run_master_monitored(master)
        finally:
            restore_sigterm()
            if evaluator is not None:
                evaluator.stop()
            self.shutdown()
        return result

    def _build_scaler(self, exp, setup: Dict[str, Any]):
        """The launcher-side actuator of the manager's autoscale plan:
        spawns supervised single-server workers (gen_server_entry) from a
        clone of the baseline server spec. Dynamic servers are
        ``required=False`` (their WorkerControl-commanded exit after a
        drain is expected) and ``expendable`` (a crash loop removes them
        from the fleet instead of escalating — the plan replaces them)."""
        import copy

        from areal_tpu.system.autoscaler import AutoscaleExecutor

        template = setup["gen_servers"][0]
        if not self.force_cpu:
            # Dynamic servers have no reserved chips on this host: a
            # second JAX process claiming the baseline fleet's chips
            # would abort both. Multi-host/pod launchers place dynamic
            # servers on hosts with free capacity; locally the executor
            # still runs (the plan is visible in fleet-status) but spawn
            # capacity is whatever the platform tolerates.
            logger.warning(
                "autoscale: dynamic generation servers on a single TPU "
                "host share the gen chip set; scale-up beyond the "
                "baseline fleet is intended for CPU runs or multi-host "
                "placement (docs/operations.md §Capacity planning)"
            )

        def _spawn_dyn(server_id: str) -> None:
            sc = copy.deepcopy(template)
            sc.server_id = server_id
            sc.port = None
            # chips=None: dynamic servers are unpinned (see the warning
            # above for the single-host TPU caveat).
            self._spawn(
                gen_server_entry, exp, sc, self.force_cpu, None,
                name=f"genserver_{server_id}", kind="gen_server",
                required=False, expendable=True,
            )

        return AutoscaleExecutor(
            exp.experiment_name, exp.trial_name, self.supervisor,
            _spawn_dyn,
        )

    def _run_master_monitored(self, master) -> Dict[str, Any]:
        result: Dict[str, Any] = {}
        err: List[BaseException] = []

        def run():
            try:
                result.update(master.run())
            except BaseException as e:  # noqa: BLE001 — surfaced below
                err.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        while t.is_alive():
            if self._drain_evt.is_set() and self._drain_thread is None:
                self._start_drain()
            if self._drain_deadline is not None and (
                self._drain_failed
                or time.monotonic() > self._drain_deadline
            ):
                # The graceful path died or overran its budget while the
                # master kept running — a silently-dropped SIGTERM would
                # train until the preemptor SIGKILLs with no checkpoint.
                # Raise so the finally-path shutdown() tears the children
                # down now (the caller sees a failed run, as it should).
                raise RuntimeError(
                    "graceful drain failed or timed out; forcing teardown"
                )
            self._check_children()
            if self._scaler is not None:
                try:
                    self._scaler.step()
                except Exception as e:  # noqa: BLE001 — scaling is
                    # best-effort; the run must not die on a bad plan
                    logger.warning(f"autoscale executor step failed: {e}")
            t.join(timeout=1.0)
        if err:
            raise err[0]
        return result

    def _start_drain(self) -> None:
        """Graceful drain in a side thread: the monitor loop keeps
        watching children while the panel sequence (pause → checkpoint →
        exit) runs; the master thread returning normally ends the run.
        The monitor loop enforces the fallback: if this thread fails (or
        the master is still alive well past the drain budget), the run
        is torn down rather than left training through its preemption
        notice."""
        from areal_tpu.system.supervisor import drain_experiment

        exp = self.exp_cfg
        self.supervisor.begin_drain()
        budget = getattr(self.ft, "drain_timeout_secs", 60.0)
        # 2x: the drain sequence itself is bounded by `budget`; the extra
        # slack covers the master finishing its finalization afterwards.
        self._drain_deadline = time.monotonic() + 2 * budget

        def _drain():
            try:
                drain_experiment(
                    exp.experiment_name, exp.trial_name, timeout=budget,
                )
            except Exception as e:  # noqa: BLE001 — monitor loop enforces
                logger.warning(f"graceful drain failed ({e}); the monitor "
                               "loop will force teardown")
                self._drain_failed = True

        self._drain_thread = threading.Thread(target=_drain, daemon=True)
        self._drain_thread.start()

    def shutdown(self) -> None:
        if self._drain_thread is not None:
            self._drain_thread.join(
                timeout=getattr(self.ft, "drain_timeout_secs", 60.0)
            )
        if self.supervisor is not None:
            self.supervisor.shutdown(timeout=10.0)


def run_experiment(exp_cfg) -> Dict[str, Any]:
    """Entry used by training/main_*.py (reference training/utils.py:339).

    ``recover_mode`` ∈ {disabled, resume, auto, fault}: "resume" restores
    from the latest recover checkpoint immediately; "auto"/"fault"
    additionally re-launch the whole experiment (with recovery) when a
    worker dies, up to ``recover_retries`` times — the reference's
    launcher-level restart loop (``realhf/apps/main.py:118-180``).
    """
    # Belt-and-braces re-validation (training/_cli.py already validates at
    # parse time): programmatic callers get the same clear error for the
    # descoped mode=ray instead of a bare NotImplementedError.
    from areal_tpu.api.cli_args import validate_config

    validate_config(exp_cfg)
    mode = getattr(exp_cfg, "mode", "local")
    if mode == "slurm":
        from areal_tpu.apps.slurm import SlurmLauncher

        return SlurmLauncher(exp_cfg).run()
    recover_mode = getattr(exp_cfg, "recover_mode", "disabled")
    retries = (
        getattr(exp_cfg, "recover_retries", 1)
        if recover_mode in ("auto", "fault") else 0
    )
    ft = getattr(exp_cfg, "fault_tolerance", None)
    base = getattr(ft, "relaunch_backoff_secs", 5.0)
    cap = getattr(ft, "relaunch_backoff_max_secs", 60.0)
    attempt = 0
    while True:
        try:
            return LocalLauncher(exp_cfg).run()
        except Exception:
            attempt += 1
            if attempt > retries:
                raise
            backoff = min(base * 2 ** (attempt - 1), cap)
            logger.warning(
                f"experiment failed (attempt {attempt}/{retries}); "
                f"re-launching with recovery in {backoff:.1f}s"
            )
            # The dead incarnation's endpoints (streams, worker control,
            # server urls, model_version) are poison for the relaunch: a
            # new worker resolving them would hang against closed sockets.
            # Clear the whole trial subtree — every live registration
            # belongs to workers the launcher just tore down, and the new
            # incarnation re-registers everything it needs.
            try:
                from areal_tpu.base import name_resolve, names

                name_resolve.clear_subtree(names.trial_root(
                    exp_cfg.experiment_name, exp_cfg.trial_name
                ))
            except Exception as e:  # noqa: BLE001 — best-effort hygiene
                logger.warning(f"stale name_resolve clear failed: {e}")
            time.sleep(backoff)
            exp_cfg.recover_mode = "resume"
