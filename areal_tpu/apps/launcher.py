"""Local experiment launcher.

Parity target: ``realhf/apps/main.py:80`` (main_start) +
``realhf/scheduler/local/client.py:71`` (LocalSchedulerClient) +
``training/utils.py:123`` (_run_experiment): spawn one process per worker,
run the master loop in the launcher process, monitor children, tear down.

TPU shape: the *trainer* is ONE process owning the whole trainer mesh
(single-controller SPMD — the reference's per-GPU model workers collapse);
the async generation fleet (servers + manager) is a second process group on
its own slice; rollout workers are CPU asyncio processes. ``mode="local"``
covers single-host; multi-host adds ``jax.distributed`` (launcher-side
support lands with the multi-host runtime).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import time
from typing import Any, Dict, List, Optional

from areal_tpu.base import logging

logger = logging.getLogger("apps.launcher")

# Persistent XLA compilation cache shared by every worker process: the async
# experiment spawns 4+ JAX processes that would otherwise each recompile the
# same graphs from scratch — on a busy host that made the e2e launch a
# 165-420s coin flip (VERDICT r2 weak #4). Override with
# AREAL_COMPILATION_CACHE; set to "" to disable.
DEFAULT_COMPILATION_CACHE = os.path.expanduser(
    "~/.cache/areal_tpu/jax_compilation_cache"
)


def enable_compilation_cache() -> None:
    path = os.environ.get("AREAL_COMPILATION_CACHE",
                          DEFAULT_COMPILATION_CACHE)
    if not path:
        return
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Cache everything (default only caches >1s compiles) and never
        # burn cycles deciding: tiny test graphs dominate the e2e launch.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # noqa: BLE001 — cache is best-effort
        logger.warning(f"compilation cache unavailable: {e}")


# ---------------------------------------------------------------------------
# child-process entries (must be module-level for mp spawn pickling)
# ---------------------------------------------------------------------------


def derive_chip_assignment(
    alloc_mode: str, n_chips: int
) -> Dict[str, List[int]]:
    """Partition this host's TPU chips between the trainer and the
    generation fleet from the decoupled allocation mode (parity:
    LocalSchedulerClient's CUDA_VISIBLE_DEVICES bookkeeping, reference
    scheduler/local/client.py:87-98).

    Returns {"trainer": [...], "gen": [...]} chip-id lists. Raises with an
    actionable message when the layout cannot fit — two JAX processes must
    never initialize the same chip.
    """
    from areal_tpu.parallel.mesh import AllocationMode

    am = AllocationMode.parse(alloc_mode) if alloc_mode else None
    if am is None or not am.decoupled:
        return {"trainer": list(range(n_chips)), "gen": []}
    need_t = am.global_spec.world_size
    need_g = am.gen_spec.world_size
    if need_t + need_g > n_chips:
        raise RuntimeError(
            f"allocation mode '{alloc_mode}' needs "
            f"{need_t} trainer + {need_g} generation chips but this host has "
            f"{n_chips}; shrink the specs (e.g. gen.d1+d1 needs 2 chips) or "
            "run sync mode (colocated) where trainer and generation share "
            "the same chips"
        )
    return {
        "trainer": list(range(need_t)),
        "gen": list(range(need_t, need_t + need_g)),
    }


def _apply_chip_env(chips: Optional[List[int]]) -> None:
    """Restrict THIS process to the given TPU chips (must run before jax
    initializes). PJRT reads TPU_VISIBLE_CHIPS; the process-bounds vars tell
    libtpu this is a single-process slice of the host."""
    if chips is None:
        return
    os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chips)
    os.environ.setdefault("TPU_PROCESS_BOUNDS", "1,1,1")
    os.environ.setdefault(
        "TPU_CHIPS_PER_PROCESS_BOUNDS", f"{len(chips)},1,1"
    )


def _child_init(exp_cfg, force_cpu: bool, chips: Optional[List[int]] = None) -> None:
    _apply_chip_env(None if force_cpu else chips)
    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    enable_compilation_cache()
    from areal_tpu.experiments import common as C

    C.setup_name_resolve(exp_cfg)
    # Registration side effects for every factory the configs reference.
    import areal_tpu.agents.math_single_step  # noqa: F401
    import areal_tpu.algorithms  # noqa: F401 — registers all interfaces
    import areal_tpu.backend.jax_train  # noqa: F401
    import areal_tpu.datasets.jsonl  # noqa: F401


def _resolve_tokenizer(exp_cfg):
    from areal_tpu.experiments import common as C

    path = getattr(exp_cfg, "actor", None)
    model_path = path.path if path is not None else getattr(
        exp_cfg, "model", None
    ).path
    return C.make_tokenizer(exp_cfg, model_path)


def trainer_entry(exp_cfg, trainer_cfg, force_cpu: bool) -> None:
    # Multi-process CPU testing: the virtual-device flag must land in the
    # environment BEFORE jax initializes in this (spawned, fresh) process.
    if trainer_cfg.dist_world > 1 and trainer_cfg.dist_local_devices:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{trainer_cfg.dist_local_devices}"
            ).strip()
    _child_init(exp_cfg, force_cpu, getattr(trainer_cfg, "chips", None))
    from areal_tpu.system.trainer_worker import TrainerWorker

    trainer_cfg.tokenizer = _resolve_tokenizer(exp_cfg)
    TrainerWorker(trainer_cfg).run()


def gen_fleet_entry(exp_cfg, server_cfgs, manager_cfg, force_cpu: bool,
                    chips: Optional[List[int]] = None) -> None:
    """All generation servers + the gserver manager in one asyncio loop."""
    _child_init(exp_cfg, force_cpu, chips)
    import asyncio

    import jax

    from areal_tpu.experiments.common import model_init_dict
    from areal_tpu.system.generation_server import GenerationServer
    from areal_tpu.system.gserver_manager import GserverManager

    init = model_init_dict(exp_cfg.actor)

    def build_model():
        if "tiny" in init:
            from areal_tpu.models import transformer
            from areal_tpu.models.config import tiny_config

            kw = dict(init["tiny"])
            seed = kw.pop("seed", 0)
            cfg = tiny_config(**kw)
            return cfg, transformer.init_params(cfg, jax.random.PRNGKey(seed))
        from areal_tpu.models import hf as hfmod

        cfg, params, _ = hfmod.load_hf_model(init["hf_dir"])
        return cfg, params

    async def main():
        cfg, params = build_model()
        tok = _resolve_tokenizer(exp_cfg)
        eos = getattr(tok, "eos_token_id", None)
        servers = []
        for sc in server_cfgs:
            if eos is not None:
                sc.eos_token_id = int(eos)
            srv = GenerationServer(sc, cfg, params)
            await srv.start()
            servers.append(srv)
        mgr = GserverManager(manager_cfg)
        await mgr.start()
        while True:  # runs until the launcher terminates us
            await asyncio.sleep(3600)

    asyncio.run(main())


def rollout_entry(exp_cfg, rollout_cfg, force_cpu: bool) -> None:
    _child_init(exp_cfg, force_cpu)
    import asyncio

    from areal_tpu.system.rollout_worker import RolloutWorker

    rollout_cfg.tokenizer = _resolve_tokenizer(exp_cfg)
    eos = getattr(rollout_cfg.tokenizer, "eos_token_id", None)
    if eos is not None:
        rollout_cfg.eos_token_id = int(eos)
    asyncio.run(RolloutWorker(rollout_cfg).run_async())


# ---------------------------------------------------------------------------
# launcher
# ---------------------------------------------------------------------------


class LocalLauncher:
    """Spawn workers, run the master inline, monitor, tear down."""

    def __init__(self, exp_cfg, force_cpu: Optional[bool] = None):
        self.exp_cfg = exp_cfg
        # Tests force CPU everywhere; real runs use the native platform.
        self.force_cpu = (
            force_cpu if force_cpu is not None
            else bool(getattr(exp_cfg, "mock_tokenizer", False))
        )
        self.procs: List[mp.process.BaseProcess] = []

    def _spawn(self, target, *args, name: str) -> None:
        ctx = mp.get_context("spawn")
        p = ctx.Process(target=target, args=args, daemon=True, name=name)
        p.start()
        self.procs.append(p)
        logger.info(f"spawned {name} (pid {p.pid})")

    @staticmethod
    def _count_chips(exp) -> int:
        """TPU chips on this host: probe in a subprocess so the launcher
        process itself never initializes the TPU runtime (children own the
        chips)."""
        env_n = os.environ.get("AREAL_N_CHIPS")
        if env_n:
            return int(env_n)
        import subprocess
        import sys as _sys

        try:
            out = subprocess.run(
                [_sys.executable, "-c",
                 "import jax; print(jax.device_count())"],
                capture_output=True, text=True, timeout=120,
            )
            return int(out.stdout.strip().splitlines()[-1])
        except Exception:  # noqa: BLE001 — fall back to config
            return int(getattr(exp, "n_gpus_per_node", 1))

    def _check_children(self) -> None:
        for p in self.procs:
            if not p.is_alive() and p.exitcode not in (0, None):
                raise RuntimeError(
                    f"worker {p.name} died with exit code {p.exitcode}"
                )

    def run(self) -> Dict[str, Any]:
        from areal_tpu.experiments import common as C
        from areal_tpu.system.master_worker import MasterWorker

        exp = self.exp_cfg
        exp.resolve_trial_name()
        C.setup_name_resolve(exp)
        enable_compilation_cache()  # master runs in-process
        setup = exp.initial_setup()

        # Persist the merged config next to the run (reference main_*.py).
        from areal_tpu.api import cli_args as CA

        CA.save_yaml(exp, os.path.join(
            CA.get_log_path(exp), "config.yaml"
        ))

        # Per-worker chip partitioning (decoupled async mode on real TPU):
        # fail fast on impossible layouts instead of letting two processes
        # claim one chip. CPU-forced runs skip it.
        chips = {"trainer": None, "gen": None}
        if not self.force_cpu and "gen_servers" in setup:
            n_chips = self._count_chips(exp)
            asg = derive_chip_assignment(
                getattr(exp, "allocation_mode", ""), n_chips
            )
            chips = {"trainer": asg["trainer"], "gen": asg["gen"]}
            logger.info(f"chip assignment: {asg}")
        setup["trainer"].chips = chips["trainer"]

        n_dist = getattr(exp, "trainer_dist_procs", 1)
        if n_dist > 1:
            # One SPMD trainer process per (virtual) host; rank 0 owns the
            # control plane, the rest replay its broadcasts.
            import copy as _copy

            # On real TPU, partition the trainer chip list across the dist
            # processes — copying the same list would have every process
            # initialize the same chips (the double-claim the chip
            # assignment exists to prevent).
            chip_slices = [None] * n_dist
            # With trainer_dist_devices_per_proc set, trainer_entry forces
            # virtual CPU devices per process and the chip list is unused.
            if (chips["trainer"] is not None
                    and not getattr(exp, "trainer_dist_devices_per_proc",
                                    None)):
                tchips = list(chips["trainer"])
                if len(tchips) % n_dist != 0:
                    raise RuntimeError(
                        f"trainer_dist_procs={n_dist} does not divide the "
                        f"{len(tchips)} trainer chips {tchips}; pick a "
                        "divisor"
                    )
                per = len(tchips) // n_dist
                chip_slices = [
                    tchips[r * per:(r + 1) * per] for r in range(n_dist)
                ]
            for r in range(n_dist):
                tc = _copy.deepcopy(setup["trainer"])
                tc.dist_rank = r
                tc.dist_world = n_dist
                tc.chips = chip_slices[r]
                tc.dist_local_devices = getattr(
                    exp, "trainer_dist_devices_per_proc", None
                )
                self._spawn(trainer_entry, exp, tc, self.force_cpu,
                            name=f"trainer{r}")
        else:
            self._spawn(trainer_entry, exp, setup["trainer"], self.force_cpu,
                        name="trainer")
        if "gen_servers" in setup:
            self._spawn(
                gen_fleet_entry, exp, setup["gen_servers"],
                setup["gserver_manager"], self.force_cpu, chips["gen"],
                name="gen_fleet",
            )
            for i, rc in enumerate(setup["rollout_workers"]):
                self._spawn(rollout_entry, exp, rc, self.force_cpu,
                            name=f"rollout{i}")

        evaluator = None
        if getattr(exp, "auto_eval", False):
            from areal_tpu.apps.evaluator import AutomaticEvaluator

            from areal_tpu.api.cli_args import AutomaticEvaluatorConfig

            eval_data = exp.auto_eval_config.data_names
            default_names = AutomaticEvaluatorConfig().data_names
            if not os.path.isfile(eval_data):
                if eval_data and eval_data != default_names:
                    # An explicitly-set eval set that doesn't exist is a
                    # config error: silently scoring the TRAIN set would
                    # masquerade as held-out accuracy.
                    raise FileNotFoundError(
                        f"auto_eval_config.data_names={eval_data!r} does not "
                        f"exist; point it at a prompt jsonl (the default "
                        f"{default_names!r} falls back to the training set)"
                    )
                logger.warning(
                    "auto_eval_config.data_names=%r is not a local file — "
                    "evaluator will score the TRAINING dataset (%s); "
                    "eval/* metrics are NOT held-out numbers",
                    eval_data, exp.dataset.path,
                )
                eval_data = exp.dataset.path
            # eval/* metrics land in the run's tensorboard alongside the
            # master's training scalars (separate writer, same log dir).
            eval_writer = None
            tb = getattr(setup["master"], "tensorboard_path", None)
            if tb:
                from areal_tpu.base.monitor import MetricWriter

                eval_writer = MetricWriter(
                    tensorboard_path=os.path.join(tb, "eval")
                )
            evaluator = AutomaticEvaluator(
                exp.auto_eval_config,
                save_dir=setup["master"].save_dir,
                dataset_path=eval_data,
                metric_writer=eval_writer,
                mock_tokenizer=bool(getattr(exp, "mock_tokenizer", False)),
            )
            evaluator.start()
            logger.info(f"automatic evaluator watching "
                        f"{setup['master'].save_dir} (data: {eval_data})")

        master = MasterWorker(setup["master"], setup["dfg"])
        try:
            result = self._run_master_monitored(master)
        finally:
            if evaluator is not None:
                evaluator.stop()
            self.shutdown()
        return result

    def _run_master_monitored(self, master) -> Dict[str, Any]:
        import threading

        result: Dict[str, Any] = {}
        err: List[BaseException] = []

        def run():
            try:
                result.update(master.run())
            except BaseException as e:  # noqa: BLE001 — surfaced below
                err.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        while t.is_alive():
            self._check_children()
            t.join(timeout=1.0)
        if err:
            raise err[0]
        return result

    def shutdown(self) -> None:
        for p in self.procs:
            if p.is_alive():
                p.terminate()
        deadline = time.monotonic() + 10
        for p in self.procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():
                p.kill()


def run_experiment(exp_cfg) -> Dict[str, Any]:
    """Entry used by training/main_*.py (reference training/utils.py:339).

    ``recover_mode`` ∈ {disabled, resume, auto, fault}: "resume" restores
    from the latest recover checkpoint immediately; "auto"/"fault"
    additionally re-launch the whole experiment (with recovery) when a
    worker dies, up to ``recover_retries`` times — the reference's
    launcher-level restart loop (``realhf/apps/main.py:118-180``).
    """
    # Belt-and-braces re-validation (training/_cli.py already validates at
    # parse time): programmatic callers get the same clear error for the
    # descoped mode=ray instead of a bare NotImplementedError.
    from areal_tpu.api.cli_args import validate_config

    validate_config(exp_cfg)
    mode = getattr(exp_cfg, "mode", "local")
    if mode == "slurm":
        from areal_tpu.apps.slurm import SlurmLauncher

        return SlurmLauncher(exp_cfg).run()
    recover_mode = getattr(exp_cfg, "recover_mode", "disabled")
    retries = (
        getattr(exp_cfg, "recover_retries", 1)
        if recover_mode in ("auto", "fault") else 0
    )
    attempt = 0
    while True:
        try:
            return LocalLauncher(exp_cfg).run()
        except Exception:
            attempt += 1
            if attempt > retries:
                raise
            logger.warning(
                f"experiment failed (attempt {attempt}/{retries}); "
                "re-launching with recovery"
            )
            exp_cfg.recover_mode = "resume"
