"""Local experiment launcher.

Parity target: ``realhf/apps/main.py:80`` (main_start) +
``realhf/scheduler/local/client.py:71`` (LocalSchedulerClient) +
``training/utils.py:123`` (_run_experiment): spawn one process per worker,
run the master loop in the launcher process, monitor children, tear down.

TPU shape: the *trainer* is ONE process owning the whole trainer mesh
(single-controller SPMD — the reference's per-GPU model workers collapse);
the async generation fleet (servers + manager) is a second process group on
its own slice; rollout workers are CPU asyncio processes. ``mode="local"``
covers single-host; multi-host adds ``jax.distributed`` (launcher-side
support lands with the multi-host runtime).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import time
from typing import Any, Dict, List, Optional

from areal_tpu.base import logging

logger = logging.getLogger("apps.launcher")


# ---------------------------------------------------------------------------
# child-process entries (must be module-level for mp spawn pickling)
# ---------------------------------------------------------------------------


def _child_init(exp_cfg, force_cpu: bool) -> None:
    if force_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    from areal_tpu.experiments import common as C

    C.setup_name_resolve(exp_cfg)
    # Registration side effects for every factory the configs reference.
    import areal_tpu.agents.math_single_step  # noqa: F401
    import areal_tpu.algorithms.ppo  # noqa: F401
    import areal_tpu.algorithms.reward  # noqa: F401
    import areal_tpu.algorithms.sft  # noqa: F401
    import areal_tpu.backend.jax_train  # noqa: F401
    import areal_tpu.datasets.jsonl  # noqa: F401


def _resolve_tokenizer(exp_cfg):
    from areal_tpu.experiments import common as C

    path = getattr(exp_cfg, "actor", None)
    model_path = path.path if path is not None else getattr(
        exp_cfg, "model", None
    ).path
    return C.make_tokenizer(exp_cfg, model_path)


def trainer_entry(exp_cfg, trainer_cfg, force_cpu: bool) -> None:
    _child_init(exp_cfg, force_cpu)
    from areal_tpu.system.trainer_worker import TrainerWorker

    trainer_cfg.tokenizer = _resolve_tokenizer(exp_cfg)
    TrainerWorker(trainer_cfg).run()


def gen_fleet_entry(exp_cfg, server_cfgs, manager_cfg, force_cpu: bool) -> None:
    """All generation servers + the gserver manager in one asyncio loop."""
    _child_init(exp_cfg, force_cpu)
    import asyncio

    import jax

    from areal_tpu.experiments.common import model_init_dict
    from areal_tpu.system.generation_server import GenerationServer
    from areal_tpu.system.gserver_manager import GserverManager

    init = model_init_dict(exp_cfg.actor)

    def build_model():
        if "tiny" in init:
            from areal_tpu.models import transformer
            from areal_tpu.models.config import tiny_config

            kw = dict(init["tiny"])
            seed = kw.pop("seed", 0)
            cfg = tiny_config(**kw)
            return cfg, transformer.init_params(cfg, jax.random.PRNGKey(seed))
        from areal_tpu.models import hf as hfmod

        cfg, params, _ = hfmod.load_hf_model(init["hf_dir"])
        return cfg, params

    async def main():
        cfg, params = build_model()
        tok = _resolve_tokenizer(exp_cfg)
        eos = getattr(tok, "eos_token_id", None)
        servers = []
        for sc in server_cfgs:
            if eos is not None:
                sc.eos_token_id = int(eos)
            srv = GenerationServer(sc, cfg, params)
            await srv.start()
            servers.append(srv)
        mgr = GserverManager(manager_cfg)
        await mgr.start()
        while True:  # runs until the launcher terminates us
            await asyncio.sleep(3600)

    asyncio.run(main())


def rollout_entry(exp_cfg, rollout_cfg, force_cpu: bool) -> None:
    _child_init(exp_cfg, force_cpu)
    import asyncio

    from areal_tpu.system.rollout_worker import RolloutWorker

    rollout_cfg.tokenizer = _resolve_tokenizer(exp_cfg)
    eos = getattr(rollout_cfg.tokenizer, "eos_token_id", None)
    if eos is not None:
        rollout_cfg.eos_token_id = int(eos)
    asyncio.run(RolloutWorker(rollout_cfg).run_async())


# ---------------------------------------------------------------------------
# launcher
# ---------------------------------------------------------------------------


class LocalLauncher:
    """Spawn workers, run the master inline, monitor, tear down."""

    def __init__(self, exp_cfg, force_cpu: Optional[bool] = None):
        self.exp_cfg = exp_cfg
        # Tests force CPU everywhere; real runs use the native platform.
        self.force_cpu = (
            force_cpu if force_cpu is not None
            else bool(getattr(exp_cfg, "mock_tokenizer", False))
        )
        self.procs: List[mp.process.BaseProcess] = []

    def _spawn(self, target, *args, name: str) -> None:
        ctx = mp.get_context("spawn")
        p = ctx.Process(target=target, args=args, daemon=True, name=name)
        p.start()
        self.procs.append(p)
        logger.info(f"spawned {name} (pid {p.pid})")

    def _check_children(self) -> None:
        for p in self.procs:
            if not p.is_alive() and p.exitcode not in (0, None):
                raise RuntimeError(
                    f"worker {p.name} died with exit code {p.exitcode}"
                )

    def run(self) -> Dict[str, Any]:
        from areal_tpu.experiments import common as C
        from areal_tpu.system.master_worker import MasterWorker

        exp = self.exp_cfg
        exp.resolve_trial_name()
        C.setup_name_resolve(exp)
        setup = exp.initial_setup()

        # Persist the merged config next to the run (reference main_*.py).
        from areal_tpu.api import cli_args as CA

        CA.save_yaml(exp, os.path.join(
            CA.get_log_path(exp), "config.yaml"
        ))

        self._spawn(trainer_entry, exp, setup["trainer"], self.force_cpu,
                    name="trainer")
        if "gen_servers" in setup:
            self._spawn(
                gen_fleet_entry, exp, setup["gen_servers"],
                setup["gserver_manager"], self.force_cpu, name="gen_fleet",
            )
            for i, rc in enumerate(setup["rollout_workers"]):
                self._spawn(rollout_entry, exp, rc, self.force_cpu,
                            name=f"rollout{i}")

        master = MasterWorker(setup["master"], setup["dfg"])
        try:
            result = self._run_master_monitored(master)
        finally:
            self.shutdown()
        return result

    def _run_master_monitored(self, master) -> Dict[str, Any]:
        import threading

        result: Dict[str, Any] = {}
        err: List[BaseException] = []

        def run():
            try:
                result.update(master.run())
            except BaseException as e:  # noqa: BLE001 — surfaced below
                err.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        while t.is_alive():
            self._check_children()
            t.join(timeout=1.0)
        if err:
            raise err[0]
        return result

    def shutdown(self) -> None:
        for p in self.procs:
            if p.is_alive():
                p.terminate()
        deadline = time.monotonic() + 10
        for p in self.procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():
                p.kill()


def run_experiment(exp_cfg) -> Dict[str, Any]:
    """Entry used by training/main_*.py (reference training/utils.py:339).

    ``recover_mode`` ∈ {disabled, resume, auto, fault}: "resume" restores
    from the latest recover checkpoint immediately; "auto"/"fault"
    additionally re-launch the whole experiment (with recovery) when a
    worker dies, up to ``recover_retries`` times — the reference's
    launcher-level restart loop (``realhf/apps/main.py:118-180``).
    """
    mode = getattr(exp_cfg, "mode", "local")
    if mode != "local":
        raise NotImplementedError(
            f"mode={mode!r}: only 'local' (single-host) is implemented; "
            "multi-host launch lands with the jax.distributed runtime"
        )
    recover_mode = getattr(exp_cfg, "recover_mode", "disabled")
    retries = (
        getattr(exp_cfg, "recover_retries", 1)
        if recover_mode in ("auto", "fault") else 0
    )
    attempt = 0
    while True:
        try:
            return LocalLauncher(exp_cfg).run()
        except Exception:
            attempt += 1
            if attempt > retries:
                raise
            logger.warning(
                f"experiment failed (attempt {attempt}/{retries}); "
                "re-launching with recovery"
            )
            exp_cfg.recover_mode = "resume"
