"""Offline checkpoint evaluation harness.

Parity target: the reference's ``evaluation/`` harness as driven by
``realhf/scheduler/evaluator.py`` (one subprocess per saved checkpoint:
generate on a benchmark set, grade, emit scores). The reference vendors a
51k-LoC latex2sympy stack and uses vLLM; here the same framework that
trains also evaluates: checkpoints load through ``models/hf.py``, greedy
(or sampled) generation runs through ``models/generate.py`` on whatever
platform this process owns, and grading uses ``rewards/math_verify.py``.

Usage:
    python -m areal_tpu.apps.eval_ckpt --ckpt <hf_dir> --dataset <jsonl> \
        --output scores.json [--max-gen-tokens 512] [--mock-tokenizer]
"""

from __future__ import annotations

import argparse
import json
import time
from typing import List, Optional

import numpy as np

from areal_tpu.base import logging

logger = logging.getLogger("apps.eval")


def evaluate_checkpoint(
    ckpt_dir: str,
    dataset_path: str,
    max_gen_tokens: int = 512,
    batch_size: int = 16,
    mock_tokenizer: bool = False,
    limit: Optional[int] = None,
) -> dict:
    import jax

    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.datasets.jsonl import load_jsonl
    from areal_tpu.models import generate as G
    from areal_tpu.models import hf as hfmod
    from areal_tpu.rewards.math_verify import verify_math

    cfg, params = hfmod.load_hf_checkpoint(ckpt_dir)
    if mock_tokenizer:
        from areal_tpu.base.testing import MockTokenizer

        tok = MockTokenizer()
    else:
        import transformers

        tok = transformers.AutoTokenizer.from_pretrained(ckpt_dir)
    records = load_jsonl(dataset_path)
    if limit:
        records = records[:limit]
    eos = getattr(tok, "eos_token_id", None) or 1
    pad = getattr(tok, "pad_token_id", None) or eos
    gconfig = GenerationHyperparameters(greedy=True)
    n_correct, n_total = 0, 0
    t0 = time.time()
    for i in range(0, len(records), batch_size):
        chunk = records[i : i + batch_size]
        prompt_list: List[List[int]] = [
            list(map(int, tok.encode(r["prompt"]))) for r in chunk
        ]
        prompts, plens = G.pad_prompts(prompt_list, pad)
        out = G.generate_batch(
            params, cfg, prompts, plens,
            key=jax.random.PRNGKey(0),
            gconfig=gconfig,
            max_new_tokens=max_gen_tokens,
            eos_token_id=eos,
            pad_token_id=pad,
        )
        out_ids = np.asarray(out["output_ids"])
        out_lens = np.asarray(out["output_lens"])
        for rec, toks, n in zip(chunk, out_ids, out_lens):
            text = tok.decode(list(map(int, toks[: int(n)])))
            score = verify_math(text, rec.get("solutions", []))
            n_correct += int(score > 0)
            n_total += 1
    return {
        "ckpt": ckpt_dir,
        "dataset": dataset_path,
        "n": n_total,
        "accuracy": n_correct / max(n_total, 1),
        "eval_secs": round(time.time() - t0, 2),
    }


def main(argv: Optional[List[str]] = None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt", required=True)
    ap.add_argument("--dataset", required=True)
    ap.add_argument("--output", required=True)
    ap.add_argument("--max-gen-tokens", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--limit", type=int, default=None)
    ap.add_argument("--mock-tokenizer", action="store_true")
    args = ap.parse_args(argv)
    result = evaluate_checkpoint(
        args.ckpt, args.dataset,
        max_gen_tokens=args.max_gen_tokens,
        batch_size=args.batch_size,
        mock_tokenizer=args.mock_tokenizer,
        limit=args.limit,
    )
    with open(args.output, "w") as f:
        json.dump(result, f)
    logger.info(f"eval done: {result}")


if __name__ == "__main__":
    main()
