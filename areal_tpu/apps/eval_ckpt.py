"""Offline checkpoint evaluation harness — pass@k over mixed math+code.

Parity target: the reference's ``evaluation/`` harness as driven by
``realhf/scheduler/evaluator.py`` (one subprocess per saved checkpoint:
generate on a benchmark set, grade, emit scores). The reference vendors a
51k-LoC latex2sympy stack and uses vLLM; here the same framework that
trains also evaluates: checkpoints load through ``models/hf.py``,
generation runs through ``models/generate.py`` on whatever platform this
process owns, and grading dispatches per task kind through
``rewards/client.py`` (math_verify / the code sandbox — or the reward
fleet, when one is configured).

``--k 1`` (default) is the legacy greedy single-sample accuracy.
``--k N`` draws N temperature-sampled generations per prompt and reports
the unbiased pass@k estimator (Chen et al. 2021: 1 - C(n-c,k)/C(n,k))
plus pass^k (all k draws correct: C(c,k)/C(n,k)) — overall and per task
kind, so a mixed math+code eval set yields ``math/pass@1``,
``code/pass@4``, ... in one run (docs/rewards.md §pass@k).

Usage:
    python -m areal_tpu.apps.eval_ckpt --ckpt <hf_dir> --dataset <jsonl> \
        --output scores.json [--k 4] [--temperature 0.6] \
        [--max-gen-tokens 512] [--mock-tokenizer]
"""

from __future__ import annotations

import argparse
import json
import math
import time
from typing import Dict, List, Optional

import numpy as np

from areal_tpu.base import logging

logger = logging.getLogger("apps.eval")


def pass_at_k(n: int, c: int, k: int) -> float:
    """Unbiased pass@k from n samples with c correct (Codex paper eq. 1):
    1 - C(n-c, k) / C(n, k). Requires n >= k."""
    if n - c < k:
        return 1.0
    return 1.0 - math.comb(n - c, k) / math.comb(n, k)


def pass_hat_k(n: int, c: int, k: int) -> float:
    """pass^k — the probability that ALL k independent draws are correct:
    C(c, k) / C(n, k). The metric that matters when every sample must be
    right (agentic chains), as opposed to best-of-k."""
    if c < k:
        return 0.0
    return math.comb(c, k) / math.comb(n, k)


def evaluate_checkpoint(
    ckpt_dir: str,
    dataset_path: str,
    max_gen_tokens: int = 512,
    batch_size: int = 16,
    mock_tokenizer: bool = False,
    limit: Optional[int] = None,
    k: int = 1,
    temperature: float = 0.6,
    seed: int = 0,
    service_experiment: str = "",
    service_trial: str = "",
    service_config: Optional[Dict] = None,
) -> dict:
    import jax

    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.datasets.jsonl import load_jsonl
    from areal_tpu.models import generate as G
    from areal_tpu.models import hf as hfmod
    from areal_tpu.rewards.client import batch_reward, task_from_record

    if k < 1:
        raise ValueError(f"k={k} must be >= 1")
    if service_experiment:
        # Grade over the live sandbox reward fleet (docs/rewards.md):
        # this subprocess discovers the workers through name_resolve
        # (AREAL_NAME_RESOLVE_ROOT, exported by the evaluator), so
        # generated code never executes in the eval process while a
        # fleet is up. ``service_config`` carries the OPERATOR'S knobs
        # (the evaluator serializes the run's RewardServiceConfig) —
        # in particular local_fallback=false must hold here too: an
        # eval process is exactly as wrong a place for untrusted code
        # as a rollout worker.
        import dataclasses as _dc

        from areal_tpu.api.train_config import RewardServiceConfig
        from areal_tpu.rewards.client import configure_service

        known = {f.name for f in _dc.fields(RewardServiceConfig)}
        kw = {kk: v for kk, v in (service_config or {}).items()
              if kk in known}
        kw["enabled"] = True
        configure_service(RewardServiceConfig(**kw),
                          service_experiment, service_trial)
    cfg, params = hfmod.load_hf_checkpoint(ckpt_dir)
    if mock_tokenizer:
        from areal_tpu.base.testing import MockTokenizer

        tok = MockTokenizer()
    else:
        import transformers

        tok = transformers.AutoTokenizer.from_pretrained(ckpt_dir)
    records = load_jsonl(dataset_path)
    if limit:
        records = records[:limit]
    eos = getattr(tok, "eos_token_id", None) or 1
    pad = getattr(tok, "pad_token_id", None) or eos
    # k=1 keeps the legacy deterministic greedy eval; k>1 is the
    # temperature-sampled estimator (greedy k-way would draw k identical
    # samples and estimate nothing).
    gconfig = GenerationHyperparameters(
        greedy=(k == 1), temperature=temperature
    )
    # n_correct per record, task kind per record
    per_rec_correct: List[int] = [0] * len(records)
    kinds: List[str] = [r.get("task", "math") for r in records]
    t0 = time.time()
    # Tokenization/padding is draw-invariant — encode each batch once,
    # reuse the padded arrays across all k draws.
    batches = []
    for i in range(0, len(records), batch_size):
        chunk = records[i : i + batch_size]
        prompt_list: List[List[int]] = [
            list(map(int, tok.encode(r["prompt"]))) for r in chunk
        ]
        batches.append((i, chunk, G.pad_prompts(prompt_list, pad)))
    for draw in range(k):
        key = jax.random.PRNGKey(seed + draw)
        for i, chunk, (prompts, plens) in batches:
            out = G.generate_batch(
                params, cfg, prompts, plens,
                key=jax.random.fold_in(key, i),
                gconfig=gconfig,
                max_new_tokens=max_gen_tokens,
                eos_token_id=eos,
                pad_token_id=pad,
            )
            out_ids = np.asarray(out["output_ids"])
            out_lens = np.asarray(out["output_lens"])
            tasks = [
                task_from_record(
                    rec, tok.decode(list(map(int, toks[: int(n)])))
                )
                for rec, toks, n in zip(chunk, out_ids, out_lens)
            ]
            scores = batch_reward(tasks)
            for j, s in enumerate(scores):
                per_rec_correct[i + j] += int(s > 0)

    def _estimators(idxs: List[int]) -> Dict[str, float]:
        if not idxs:
            return {}
        out: Dict[str, float] = {
            "pass@1": float(np.mean(
                [per_rec_correct[i] / k for i in idxs]
            )),
        }
        if k > 1:
            out[f"pass@{k}"] = float(np.mean(
                [pass_at_k(k, per_rec_correct[i], k) for i in idxs]
            ))
            out[f"pass^{k}"] = float(np.mean(
                [pass_hat_k(k, per_rec_correct[i], k) for i in idxs]
            ))
        return out

    overall = _estimators(list(range(len(records))))
    result = {
        "ckpt": ckpt_dir,
        "dataset": dataset_path,
        "n": len(records),
        "k": k,
        "temperature": None if k == 1 else temperature,
        # Legacy field: pass@1 == greedy accuracy at k=1.
        "accuracy": overall.get("pass@1", 0.0),
        **overall,
        "eval_secs": round(time.time() - t0, 2),
    }
    for kind in sorted(set(kinds)):
        idxs = [i for i, kk in enumerate(kinds) if kk == kind]
        for name, v in _estimators(idxs).items():
            result[f"{kind}/{name}"] = v
        result[f"{kind}/n"] = len(idxs)
    return result


def main(argv: Optional[List[str]] = None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt", required=True)
    ap.add_argument("--dataset", required=True)
    ap.add_argument("--output", required=True)
    ap.add_argument("--max-gen-tokens", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--limit", type=int, default=None)
    ap.add_argument("--k", type=int, default=1,
                    help="samples per prompt (1 = legacy greedy accuracy)")
    ap.add_argument("--temperature", type=float, default=0.6,
                    help="sampling temperature for k > 1")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mock-tokenizer", action="store_true")
    ap.add_argument("--reward-service", nargs=2, default=None,
                    metavar=("EXPERIMENT", "TRIAL"),
                    help="grade through the live reward fleet of this "
                         "experiment/trial (docs/rewards.md)")
    ap.add_argument("--reward-service-config", default=None,
                    help="JSON of the run's RewardServiceConfig so the "
                         "operator's knobs (local_fallback, languages, "
                         "timeouts) hold in this subprocess too")
    args = ap.parse_args(argv)
    result = evaluate_checkpoint(
        args.ckpt, args.dataset,
        max_gen_tokens=args.max_gen_tokens,
        batch_size=args.batch_size,
        mock_tokenizer=args.mock_tokenizer,
        limit=args.limit,
        k=args.k,
        temperature=args.temperature,
        seed=args.seed,
        service_experiment=args.reward_service[0] if args.reward_service
        else "",
        service_trial=args.reward_service[1] if args.reward_service else "",
        service_config=(json.loads(args.reward_service_config)
                        if args.reward_service_config else None),
    )
    with open(args.output, "w") as f:
        json.dump(result, f)
    logger.info(f"eval done: {result}")


if __name__ == "__main__":
    main()
