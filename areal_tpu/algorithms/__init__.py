# Importing the package registers all built-in interfaces (the reference
# does this in realhf/impl/__init__.py with its register_* calls).
from areal_tpu.algorithms import (  # noqa: F401
    fused,
    ppo,
    reward,
    rw,
    sft,
)
