"""PPO math: losses, GAE, KL controllers, reward shaping, normalization.

Parity targets:
 - ``realhf/impl/model/utils/ppo_functional.py`` — ``actor_loss_fn:51``
   (decoupled clip center + behaviour importance weight cap + dual clip),
   ``critic_loss_fn:161``, KL controllers ``:14-48``, reward shaping
   ``:229-291``, python GAE ``:292``;
 - ``csrc/cugae/gae.cu:10`` (``gae_1d_nolp_misalign``) — here a segment-aware
   reversed ``lax.associative_scan`` over the fixed [B, L] grid: the linear
   recurrence ``adv_t = δ_t + γλ·adv_{t+1}`` is associative, so the whole GAE
   is one O(log L) scan on the VPU instead of a per-sequence CUDA thread loop;
 - ``realhf/impl/model/utils/functional.py`` — masked normalization,
   gather of shifted logprobs.

Everything operates on the [B, L] grid with a boolean ``mask`` (True = real
token position that contributes); host-side numpy references live next to
each jax function for kernel-parity tests (mirroring tests/cpp_extensions).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ---------------- logprob gathering ----------------

# Memory-lean CE gather shared with generation (reference
# gather_packed_shifted_log_probs, utils/functional.py; the shift is the
# caller's responsibility — labels[t] = token at t+1).
from areal_tpu.ops.xent import gather_logprobs  # noqa: E402,F401  (re-export)


def next_token_labels(tokens: jnp.ndarray) -> jnp.ndarray:
    """labels[t] = tokens[t+1] (last column wraps — masked out later)."""
    return jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)


def shift_mask_scores(
    s: jnp.ndarray,  # [B, L]: s[t] = log p(token_{t+1} | logits at t)
    segment_ids: jnp.ndarray,  # [B, L], 0 = pad
) -> jnp.ndarray:
    """Shift-right + same-doc masking: position t ends up holding
    log p(token_t | prefix), 0 at doc starts and padding."""
    tok_lp = jnp.concatenate([jnp.zeros_like(s[:, :1]), s[:, :-1]], axis=1)
    prev_seg = jnp.concatenate(
        [jnp.zeros_like(segment_ids[:, :1]), segment_ids[:, :-1]], axis=1
    )
    valid = (segment_ids > 0) & (prev_seg == segment_ids)
    return tok_lp * valid


def token_logprobs_from_logits(
    logits: jnp.ndarray,  # [B, L, V]
    tokens: jnp.ndarray,  # [B, L]
    segment_ids: jnp.ndarray,  # [B, L], 0 = pad
) -> jnp.ndarray:
    """[B, L] where position t holds log p(token_t | prefix), i.e. the
    model's score of token t from the logits at t−1 within the same doc;
    0 at each doc's first token and on padding. This is the grid version of
    the reference's gather_packed_shifted_log_probs (utils/functional.py)."""
    s = gather_logprobs(logits, next_token_labels(tokens))
    return shift_mask_scores(s, segment_ids)


def action_token_mask(segment_ids, prompt_mask):
    """Generated-token positions with a valid (non-doc-first) logprob — THE
    loss mask shared by actor/critic losses and host-side token counting.
    Accepts numpy or jax arrays; returns a bool array of the same kind."""
    xp = jnp if isinstance(segment_ids, jnp.ndarray) else np
    prev_seg = xp.concatenate(
        [xp.zeros_like(segment_ids[:, :1]), segment_ids[:, :-1]], axis=1
    )
    return (segment_ids > 0) & (prev_seg == segment_ids) & (prompt_mask == 0)


def shift_right_in_doc(x, segment_ids):
    """[B, L] → [B, L] with x shifted right by one inside each document:
    out[t] = x[t−1] when t−1 is in the same doc, else 0.

    Used to express the reference's value alignment (pygae1d_nolp_misalign,
    ppo_interface.py:575-579) in the full-length grid layout: the PPO
    baseline for the action at slot t is the critic value at slot t−1 (the
    state BEFORE the token was emitted). Accepts numpy or jax arrays."""
    xp = jnp if isinstance(x, jnp.ndarray) else np
    prev = xp.concatenate([xp.zeros_like(x[:, :1]), x[:, :-1]], axis=1)
    prev_seg = xp.concatenate(
        [xp.zeros_like(segment_ids[:, :1]), segment_ids[:, :-1]], axis=1
    )
    return prev * ((prev_seg == segment_ids) & (segment_ids > 0))


def masked_normalization(
    x: jnp.ndarray,
    mask: jnp.ndarray,
    eps: float = 1e-5,
    high_precision: bool = True,
    reduce_group_axes: Optional[tuple] = None,
) -> jnp.ndarray:
    """Whiten x over masked entries (reference functional.py masked_normalization).

    ``reduce_group_axes``: mesh axis names to psum over when called inside
    shard_map (the reference all-reduces over the DP group); under plain GSPMD
    jit the global mean is already global, so the default needs no collectives.
    """
    dt = jnp.float64 if high_precision and jax.config.jax_enable_x64 else jnp.float32
    x32 = x.astype(dt)
    m = mask.astype(dt)
    cnt = jnp.sum(m)
    ssum = jnp.sum(x32 * m)
    if reduce_group_axes:
        cnt = jax.lax.psum(cnt, reduce_group_axes)
        ssum = jax.lax.psum(ssum, reduce_group_axes)
    mean = ssum / jnp.maximum(cnt, 1.0)
    var_sum = jnp.sum(((x32 - mean) ** 2) * m)
    if reduce_group_axes:
        var_sum = jax.lax.psum(var_sum, reduce_group_axes)
    var = var_sum / jnp.maximum(cnt, 1.0)
    return ((x32 - mean) * jax.lax.rsqrt(var + eps) * m).astype(x.dtype)


# ---------------- GAE ----------------

def gae_grid(
    rewards: jnp.ndarray,  # [B, L] per-token rewards
    values: jnp.ndarray,  # [B, L] V(s_t) under the same layout
    segment_ids: jnp.ndarray,  # [B, L] int, 0 = pad — document boundaries
    bootstrap: Optional[jnp.ndarray] = None,  # [B, L] V(s_{t+1}) at seq ends
    gamma: float = 1.0,
    lam: float = 1.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Segment-aware GAE on the fixed grid; returns (advantages, returns).

    Documents are contiguous same-id runs of ``segment_ids`` within a row
    (the packing.py layout). δ_t = r_t + γ·V_{t+1} − V_t with V beyond the
    document end = 0 (truncated sequences can pass ``bootstrap`` holding
    V(s_{T}) at the last token). adv_t = δ_t + γλ·adv_{t+1}, reset across
    document boundaries.
    """
    f32 = jnp.float32
    mask = segment_ids > 0
    r = rewards.astype(f32)
    v = values.astype(f32) * mask
    # "continues": position t+1 exists and belongs to the same document.
    nxt_seg = jnp.concatenate(
        [segment_ids[:, 1:], jnp.zeros_like(segment_ids[:, :1])], axis=1
    )
    continues = (nxt_seg == segment_ids) & mask
    # V_{t+1}: next value within the same document, else bootstrap (default 0).
    v_next = jnp.concatenate([v[:, 1:], jnp.zeros_like(v[:, :1])], axis=1)
    v_next = jnp.where(continues, v_next, 0.0)
    if bootstrap is not None:
        last = mask & ~continues
        v_next = jnp.where(last, bootstrap.astype(f32), v_next)
    delta = (r + gamma * v_next - v) * mask

    # adv_t = δ_t + a_t · adv_{t+1},  a_t = γλ where t+1 continues the doc.
    a = (gamma * lam) * continues.astype(f32)

    # Reversed associative scan of the linear recurrence (y, pairs combine as
    # (a1·a2, b2 + a2·b1) in scan order; we scan the time-reversed arrays).
    def combine(x, y):
        ax, bx = x
        ay, by = y
        return ax * ay, by + ay * bx

    a_rev = a[:, ::-1]
    d_rev = delta[:, ::-1]
    _, adv_rev = jax.lax.associative_scan(combine, (a_rev, d_rev), axis=1)
    adv = adv_rev[:, ::-1] * mask
    return adv, adv + v


def gae_packed_np(
    rewards: np.ndarray,  # 1-D packed over sequences
    values: np.ndarray,  # 1-D packed, same layout
    seqlens,  # per-sequence lengths
    bootstrap: Optional[np.ndarray] = None,  # [n_seqs] V at truncation, 0 if done
    gamma: float = 1.0,
    lam: float = 1.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Numpy reference for 1-D packed GAE — parity with the reference's
    ``pygae1d_nolp_misalign`` (ppo_functional.py:292) / ``gae.cu:10``."""
    adv = np.zeros_like(values, dtype=np.float64)
    ret = np.zeros_like(values, dtype=np.float64)
    off = 0
    for i, n in enumerate(seqlens):
        n = int(n)
        acc = 0.0
        vnext = float(bootstrap[i]) if bootstrap is not None else 0.0
        for t in range(n - 1, -1, -1):
            delta = rewards[off + t] + gamma * vnext - values[off + t]
            acc = delta + gamma * lam * acc
            adv[off + t] = acc
            ret[off + t] = acc + values[off + t]
            vnext = values[off + t]
        off += n
    return adv.astype(np.float32), ret.astype(np.float32)


# ---------------- losses ----------------

def actor_loss(
    logprobs: jnp.ndarray,  # [B, L] π_θ logprobs of taken actions
    old_logprobs: jnp.ndarray,  # [B, L] behaviour policy (sampler) logprobs
    advantages: jnp.ndarray,  # [B, L]
    mask: jnp.ndarray,  # [B, L] bool
    eps_clip: float = 0.2,
    c_clip: Optional[float] = None,  # dual clip (> 1.0) for negative adv
    proximal_logprobs: Optional[jnp.ndarray] = None,  # decoupled clip center
    behav_imp_weight_cap: Optional[float] = None,
    loss_scale: Optional[jnp.ndarray] = None,  # denominator; default masked count
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Decoupled PPO actor loss (reference ppo_functional.py:51-158).

    With ``proximal_logprobs`` (π_prox, recomputed at train time), the clip
    ratio is centered on π_prox and the whole term is multiplied by the
    behaviour importance weight exp(π_prox − π_behav) (optionally capped) —
    the AReaL decoupled-loss objective that keeps training stable at high
    staleness. Without it, this reduces to standard PPO.
    """
    mask = mask.astype(jnp.bool_)
    denom = jnp.maximum(
        loss_scale if loss_scale is not None else jnp.sum(mask), 1.0
    )
    center = proximal_logprobs if proximal_logprobs is not None else old_logprobs
    ratio = jnp.exp(jnp.where(mask, logprobs - center, 0.0))
    clipped = jnp.clip(ratio, 1.0 - eps_clip, 1.0 + eps_clip)
    l1 = -advantages * ratio
    l2 = -advantages * clipped
    loss_tok = jnp.maximum(l1, l2)
    clip_mask = (l2 > l1) & mask
    if c_clip is not None:
        assert c_clip > 1.0
        l3 = -advantages * c_clip
        dual_mask = (advantages < 0) & mask
        dual = jnp.minimum(loss_tok, l3)
        dual_clip_mask = (l3 < loss_tok) & dual_mask
        loss_tok = jnp.where(dual_mask, dual, loss_tok)
    else:
        dual_clip_mask = jnp.zeros_like(clip_mask)
    # Importance-weight tail: the mass of action tokens the behaviour
    # cap DROPS — off-policyness beyond what the decoupled loss corrects,
    # one of the divergence signatures the training-health sentinel
    # watches (system/sentinel.py).
    behav_tail = jnp.zeros((), jnp.float32)
    if proximal_logprobs is not None:
        behav_w = jnp.exp(jnp.where(mask, center - old_logprobs, 0.0))
        if behav_imp_weight_cap is not None:
            # Reference drops tokens whose weight exceeds the cap.
            keep = behav_w <= behav_imp_weight_cap
            behav_tail = jnp.sum((~keep) & mask) / denom
            behav_w = jnp.where(keep, behav_w, 0.0)
        loss_tok = loss_tok * behav_w
    loss = jnp.sum(jnp.where(mask, loss_tok, 0.0)) / denom
    stats = {
        "importance_weight": jnp.sum(ratio * mask) / denom,
        "clip_ratio": jnp.sum(clip_mask) / denom,
        "dual_clip_ratio": jnp.sum(dual_clip_mask) / denom,
        # Training-dynamics series (exported per step as train/* gauges):
        # k1 approx-KL of the current policy against the BEHAVIOUR policy
        # (the thing PPO's trust region bounds), and the sampled-token
        # entropy estimate −E[log π(a_t)] — cheap under the chunked
        # logprob head, where the full distribution is never materialized.
        "approx_kl": jnp.sum(jnp.where(mask, old_logprobs - logprobs, 0.0))
                     / denom,
        "entropy": -jnp.sum(jnp.where(mask, logprobs, 0.0)) / denom,
        "behav_tail": behav_tail,
    }
    return loss, stats


def critic_loss(
    value: jnp.ndarray,  # [B, L] new value prediction
    old_value: jnp.ndarray,  # [B, L] value at rollout time
    returns: jnp.ndarray,  # [B, L] GAE returns (target)
    mask: jnp.ndarray,
    value_eps_clip: float = 0.2,
    loss_fn: str = "huber",
    huber_delta: float = 10.0,
    loss_scale: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Clipped value loss (reference ppo_functional.py:161-228; huber delta
    defaults to the reference's 10.0)."""
    mask = mask.astype(jnp.bool_)
    denom = jnp.maximum(
        loss_scale if loss_scale is not None else jnp.sum(mask), 1.0
    )

    if loss_fn == "huber":
        def base(x, y):
            d = jnp.abs(x - y)
            return jnp.where(
                d < huber_delta, 0.5 * d * d, huber_delta * (d - 0.5 * huber_delta)
            )
    else:
        def base(x, y):
            return 0.5 * (x - y) ** 2

    clipped = old_value + jnp.clip(
        value - old_value, -value_eps_clip, value_eps_clip
    )
    l1 = base(value, returns)
    l2 = base(clipped, returns)
    clip_mask = (l2 > l1) & mask
    loss_tok = jnp.maximum(l1, l2)
    loss = jnp.sum(jnp.where(mask, loss_tok, 0.0)) / denom
    return loss, {"value_clip_ratio": jnp.sum(clip_mask) / denom}


# ---------------- KL & rewards ----------------

@dataclasses.dataclass
class FixedKLController:
    """Reference ppo_functional.py:37-48."""

    kl_coef: float = 0.0

    @property
    def value(self) -> float:
        return self.kl_coef

    def update(self, current_kl: float, n_steps: int) -> None:
        pass


@dataclasses.dataclass
class AdaptiveKLController:
    """Reference ppo_functional.py:14-36 (Ziegler et al. adaptive KL)."""

    init_kl_coef: float
    target: float
    horizon: float
    _value: float = dataclasses.field(default=0.0, init=False)

    def __post_init__(self):
        self._value = self.init_kl_coef

    @property
    def value(self) -> float:
        return self._value

    def update(self, current_kl: float, n_steps: int) -> None:
        err = np.clip(current_kl / self.target - 1.0, -0.2, 0.2)
        self._value *= 1.0 + err * n_steps / self.horizon
