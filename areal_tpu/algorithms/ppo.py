"""PPO actor / critic interfaces — the algorithm layer.

Parity target: ``realhf/impl/model/interface/ppo_interface.py`` —
``PPOActorInterface`` (:210; generate :301, inference :474 recomputing
proximal logprobs, train_step :527 with GAE + reward shaping + advantage
normalization + minibatch loop) and ``PPOCriticInterface`` (:984), plus the
value-normalization running moments (``realhf/impl/model/modules/rms.py``).

Data contract (all per-token keys full-length aligned to
``packed_input_ids``; see backend/microbatch.py):
 - ``packed_input_ids`` int32, ``prompt_mask`` (1 on prompt tokens)
 - ``packed_logprobs`` f32 — behaviour-policy logprob of token t at slot t
   (0 on prompt slots and each doc's first token)
 - ``prox_logprobs`` f32 — recomputed under the trainer's current policy
   (decoupled PPO; produced by actor ``inference``)
 - ``packed_ref_logprobs`` f32 — reference-policy logprobs (KL penalty)
 - ``values`` f32 — critic values (denormalized; produced by critic
   ``inference``), absent/zero when ``disable_value`` (GRPO)
 - ``rewards`` f32 [1/sample] — task score; ``seq_no_eos_mask`` f32
   [1/sample] — 1.0 when generation was truncated (no EOS)
 - ``task_ids`` int32 [1/sample]

Deviation from the reference, by design: generated groups are FLATTENED into
independent samples (ids "qid@k", metadata ``group``) rather than grouped
seqlens inside one sample — packing/attention masks stay per-document and
GRPO group statistics use the metadata instead.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.algorithms import ppo_functional as F
from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import (
    GenerationHyperparameters,
    Model,
    ModelInterface,
    register_interface,
)
from areal_tpu.backend import microbatch as mbu
from areal_tpu.base import logging
from areal_tpu.models import packing

logger = logging.getLogger("algorithms.ppo")


@dataclasses.dataclass
class PPOHyperparameters:
    """Reference cli_args.py:597 (PPOHyperparameters)."""

    gen: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters
    )
    ppo_n_minibatches: int = 4
    eps_clip: float = 0.2
    c_clip: Optional[float] = None
    value_eps_clip: float = 0.2
    early_stop_imp_ratio: float = 5.0
    reward_output_scaling: float = 1.0
    reward_output_bias: float = 0.0
    max_reward_clip: float = 20.0
    mask_no_eos_with_zero: bool = False
    discount: float = 1.0
    gae_lambda: float = 1.0
    adv_norm: bool = True
    kl_ctl: float = 0.1
    use_adaptive_kl_ctl: bool = False
    adaptive_kl_target: float = 6.0
    adaptive_kl_horizon: float = 10000.0
    disable_value: bool = False  # GRPO: no critic
    value_norm: bool = True
    value_norm_beta: float = 0.99995
    value_norm_eps: float = 1e-5
    group_size: int = 1
    group_adv_norm: bool = False
    use_decoupled_loss: bool = False
    behav_imp_weight_cap: Optional[float] = None
    recompute_logprob: bool = False


class RunningMoments:
    """EMA mean/std for value normalization (reference rms.py)."""

    def __init__(self, beta: float = 0.99995, eps: float = 1e-5):
        self.beta = beta
        self.eps = eps
        self.mean = 0.0
        self.mean_sq = 1.0
        self._initialized = False

    def update(self, x: np.ndarray, mask: np.ndarray) -> None:
        m = mask.astype(bool)
        if m.sum() == 0:
            return
        bm, bsq = float(x[m].mean()), float((x[m] ** 2).mean())
        if not self._initialized:
            self.mean, self.mean_sq = bm, bsq
            self._initialized = True
        else:
            # EMA of mean and mean-square (reference rms.py): the variance
            # E[x^2]-E[x]^2 then includes batch-mean drift.
            self.mean = self.beta * self.mean + (1 - self.beta) * bm
            self.mean_sq = self.beta * self.mean_sq + (1 - self.beta) * bsq

    @property
    def var(self) -> float:
        return max(self.mean_sq - self.mean**2, self.eps)

    def normalize(self, x):
        return (x - self.mean) / np.sqrt(self.var + self.eps)

    def denormalize(self, x):
        return x * np.sqrt(self.var + self.eps) + self.mean

    def state_dict(self):
        return {
            "mean": self.mean, "mean_sq": self.mean_sq,
            "initialized": self._initialized,
        }

    def load_state_dict(self, d):
        self.mean, self.mean_sq = d["mean"], d["mean_sq"]
        self._initialized = d["initialized"]


# ---------------- shared prep ----------------

def _action_mask(grids: Dict[str, np.ndarray]) -> np.ndarray:
    """Host-side view of the shared loss mask (ppo_functional)."""
    return np.asarray(
        F.action_token_mask(grids["segment_ids"], grids["prompt_mask"])
    )


def compute_advantages_and_returns(
    sample: SequenceSample, hp: PPOHyperparameters, kl_coef: float
) -> Dict[str, np.ndarray]:
    """Full-batch grid pass: KL-shaped token rewards → GAE. Returns packed
    1-D arrays keyed advantages/returns/kl_rewards plus scalar stats.

    Mirrors reference train_step pre-processing (ppo_interface.py:560-690):
    sparse task reward on the last token, −kl_coef·KL(π_behav‖π_ref)
    everywhere, GAE over values (zeros under GRPO)."""
    mb = mbu.make_microbatch(sample, length_bucket=64, rows_bucket=1, seqs_bucket=1)
    g = mb.grids
    amask = _action_mask(g)
    behav = g["packed_logprobs"]
    ref = g.get("packed_ref_logprobs", np.zeros_like(behav))
    kl = (behav - ref) * amask  # k1 estimator, same as reference
    values = g.get("values", np.zeros_like(behav)) * (g["segment_ids"] > 0)

    score = np.asarray(sample.data["rewards"], np.float32).reshape(-1)
    no_eos = (
        np.asarray(sample.data["seq_no_eos_mask"]).reshape(-1) > 0
        if "seq_no_eos_mask" in sample.keys
        else np.zeros(sample.bs, bool)
    )
    if hp.mask_no_eos_with_zero:
        score = np.where(no_eos, 0.0, score)
    n = mb.n_seqs
    # KL-only penalty (this IS the logged kl_rewards key, as in the
    # reference where it is cloned BEFORE the task score lands).
    kl_rw = (-kl_coef * kl * amask).astype(np.float32)
    tok_score = np.clip(
        (score - hp.reward_output_bias) * hp.reward_output_scaling,
        -hp.max_reward_clip, hp.max_reward_clip,
    )
    rewards = kl_rw.copy()
    rewards[mb.seq_rows[:n], mb.seq_last_cols[:n]] += tok_score
    # Reference value alignment (pygae1d_nolp_misalign; ppo_interface.py:
    # 575-579): the baseline for the action at slot t is V at slot t−1 (the
    # pre-action state), so δ_t = r_t + γ·V_t − V_{t−1}. In the grid layout
    # that is gae_grid over right-shifted values, whose internal v_next[t]
    # = v_shifted[t+1] = V_t.
    v_prev = np.asarray(F.shift_right_in_doc(values, g["segment_ids"]))
    # The last action's next-value is V at the final token, kept only when
    # generation was truncated (no EOS): the reference both zeroes the EOS
    # value and multiplies by the bootstrap mask — one product covers both.
    boot = np.zeros_like(values)
    boot[mb.seq_rows[:n], mb.seq_last_cols[:n]] = (
        values[mb.seq_rows[:n], mb.seq_last_cols[:n]] * no_eos
    )
    # GAE over action tokens only: restrict the segment grid to them so
    # prompt positions neither receive advantage nor relay the recursion
    # (action slots are a contiguous suffix of each doc, so restricting
    # changes nothing the actor loss reads). v_prev at the first action slot
    # still holds the last-prompt-slot value — shift BEFORE restricting.
    act_seg = np.where(amask, g["segment_ids"], 0)
    # One jitted dispatch: eager gae_grid is ~20 separate device ops, which
    # costs >1.5s/step through a remote-device tunnel (measured r3).
    adv, ret = _gae_grid_jit(
        jnp.asarray(rewards), jnp.asarray(v_prev), jnp.asarray(act_seg),
        jnp.asarray(boot), hp.discount, hp.gae_lambda,
    )
    adv, ret = np.asarray(adv), np.asarray(ret)
    out = {}
    for key, grid in (("advantages", adv), ("returns", ret), ("kl_rewards", kl_rw)):
        out[key] = np.concatenate(
            mbu.scatter_back([mb], [grid], sample.bs)
        ).astype(np.float32)
    out["_mean_kl"] = float(kl.sum() / max(amask.sum(), 1))
    return out


@functools.partial(jax.jit, static_argnums=(4, 5))
def _gae_grid_jit(rewards, v_prev, act_seg, boot, gamma, lam):
    return F.gae_grid(
        rewards, v_prev, act_seg, bootstrap=boot, gamma=gamma, lam=lam
    )


def make_advantage_prep(hp: PPOHyperparameters):
    """Device-side advantage pipeline over an uploaded UniformBatch: the
    jnp mirror of compute_advantages_and_returns + normalize_advantages,
    fused into ONE dispatch with no host round trip (grids stay on device
    for the grad steps). Global advantage whitening only — group_adv_norm
    keeps the host path."""

    def prep(grids, seq, R, scalars):
        seg = grids["segment_ids"]
        amask = F.action_token_mask(seg, grids["prompt_mask"])
        amf = amask.astype(jnp.float32)
        behav = grids["packed_logprobs"]
        ref = grids.get("packed_ref_logprobs", jnp.zeros_like(behav))
        kl = (behav - ref) * amf
        values = grids.get("values", jnp.zeros_like(behav)) * (seg > 0)

        score = seq["rewards"].astype(jnp.float32)  # [n_mbs, S]
        no_eos = (
            seq["seq_no_eos_mask"] > 0
            if "seq_no_eos_mask" in seq
            else jnp.zeros_like(score, bool)
        )
        if hp.mask_no_eos_with_zero:
            score = jnp.where(no_eos, 0.0, score)
        tok_score = jnp.clip(
            (score - hp.reward_output_bias) * hp.reward_output_scaling,
            -hp.max_reward_clip, hp.max_reward_clip,
        )
        # Flatten [n_mbs, S] sequence coordinates into the [n_mbs*R, L] grid.
        n_mbs = seq["seq_rows"].shape[0]
        mb_off = (jnp.arange(n_mbs)[:, None] * R)
        rows_f = (seq["seq_rows"] + mb_off).reshape(-1)
        lasts_f = seq["seq_last_cols"].reshape(-1)
        valid_f = seq["seq_mask"].reshape(-1).astype(jnp.float32)

        kl_rw = -scalars["kl_coef"] * kl * amf
        rewards_grid = kl_rw.at[rows_f, lasts_f].add(
            tok_score.reshape(-1) * valid_f
        )
        v_prev = F.shift_right_in_doc(values, seg)
        boot = jnp.zeros_like(values).at[rows_f, lasts_f].add(
            values[rows_f, lasts_f]
            * no_eos.reshape(-1).astype(jnp.float32) * valid_f
        )
        act_seg = jnp.where(amask, seg, 0)
        adv, ret = F.gae_grid(
            rewards_grid, v_prev, act_seg, bootstrap=boot,
            gamma=hp.discount, lam=hp.gae_lambda,
        )
        out_scalars = {
            "_mean_kl": kl.sum() / jnp.maximum(amf.sum(), 1.0),
            # Advantage scale BEFORE whitening (post-norm it is ~1 by
            # construction): a collapsing or exploding raw advantage is a
            # reward/value-pipeline divergence signature the sentinel
            # watches as train/adv_scale.
            "_adv_scale": jnp.sum(jnp.abs(adv) * amf)
                          / jnp.maximum(amf.sum(), 1.0),
        }
        if hp.adv_norm:
            adv = F.masked_normalization(adv, amask)
        return (
            {"advantages": adv, "returns": ret, "kl_rewards": kl_rw},
            out_scalars,
        )

    return prep


def _group_keys(sample: SequenceSample) -> List[str]:
    if "group" in sample.metadata:
        return [str(x) for x in sample.metadata["group"]]
    return [str(i).rsplit("@", 1)[0] for i in sample.ids]


def normalize_advantages(
    sample: SequenceSample, hp: PPOHyperparameters
) -> None:
    """In-place advantage whitening: global, or per prompt-group (GRPO)."""
    adv = sample.data["advantages"]
    amask_packed = (
        (1 - np.asarray(sample.data["prompt_mask"])) > 0
    )  # includes doc-first token; its adv is 0 anyway
    if hp.group_adv_norm:
        groups = _group_keys(sample)
        offs = sample.offsets("advantages")
        lens = [int(x) for x in sample.total_lens("advantages")]
        for gkey in set(groups):
            idx = [i for i, g in enumerate(groups) if g == gkey]
            sel = np.concatenate(
                [np.arange(offs[i], offs[i] + lens[i]) for i in idx]
            )
            m = amask_packed[sel]
            vals = adv[sel]
            mu = vals[m].mean() if m.any() else 0.0
            sd = vals[m].std() + 1e-5
            adv[sel] = np.where(m, (vals - mu) / sd, 0.0)
    else:
        m = amask_packed
        mu = adv[m].mean() if m.any() else 0.0
        sd = adv[m].std() + 1e-5
        sample.data["advantages"] = np.where(m, (adv - mu) / sd, 0.0).astype(
            np.float32
        )


# ---------------- actor ----------------

class PPOActorInterface(ModelInterface):
    def __init__(self, hp: Optional[PPOHyperparameters] = None, **kw):
        self.hp = hp or PPOHyperparameters(**kw)
        if self.hp.use_adaptive_kl_ctl:
            self.kl_ctl = F.AdaptiveKLController(
                self.hp.kl_ctl, self.hp.adaptive_kl_target, self.hp.adaptive_kl_horizon
            )
        else:
            self.kl_ctl = F.FixedKLController(self.hp.kl_ctl)
        self._gen_calls = 0
        hp_ = self.hp

        def actor_loss_fn(logits, batch):
            # With the engine's chunked-logprob head (wants_token_logprobs)
            # this receives the [B, L] logprobs directly; otherwise raw
            # [B, L, V] logits.
            lp = logits if logits.ndim == 2 else F.token_logprobs_from_logits(
                logits, batch["tokens"], batch["segment_ids"]
            )
            amask = F.action_token_mask(
                batch["segment_ids"], batch["prompt_mask"]
            )
            prox = batch.get("prox_logprobs") if hp_.use_decoupled_loss else None
            loss, st = F.actor_loss(
                lp,
                batch["packed_logprobs"],
                batch["advantages"],
                amask,
                eps_clip=hp_.eps_clip,
                c_clip=hp_.c_clip,
                proximal_logprobs=prox,
                behav_imp_weight_cap=hp_.behav_imp_weight_cap,
                loss_scale=jnp.asarray(1.0),  # sum; engine divides by weight
            )
            stats = {f"{k}_sum": v * 1.0 for k, v in st.items()}
            stats["n_action_tokens"] = jnp.sum(amask)
            return loss, stats

        actor_loss_fn.wants_token_logprobs = True
        self._loss_fn = actor_loss_fn
        self._prep_fn = make_advantage_prep(self.hp)

    # ---- MFC methods ----

    def generate(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        """Prompt batch → flattened trajectory batch (group_size per prompt)."""
        hp = self.hp
        engine = model.module
        eos = getattr(model.tokenizer, "eos_token_id", 1) or 1
        pad = getattr(model.tokenizer, "pad_token_id", 0) or 0
        gconfig = dataclasses.replace(hp.gen, n=hp.group_size)
        # Distinct key per call even within one model version.
        key = jax.random.fold_in(
            jax.random.PRNGKey(model.version.global_step), self._gen_calls
        )
        self._gen_calls += 1
        out = engine.generate(
            data, mb_spec, gconfig, key=key,
            eos_token_id=eos, pad_token_id=pad,
        )
        return trajectories_from_gen_output(
            data, out, group_size=hp.group_size,
            version=model.version.global_step, eos_token_id=eos,
        )

    def inference(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        """Recompute logprobs under the current policy → prox_logprobs."""
        engine = model.module
        per_sample = engine.forward(data, mb_spec, post_hook=_logprob_hook)
        return SequenceSample(
            ids=list(data.ids),
            keys={"prox_logprobs"},
            seqlens={"prox_logprobs": [list(s) for s in
                                       data.seqlens["packed_input_ids"]]},
            data={"prox_logprobs": np.concatenate(per_sample).astype(np.float32)},
        )

    def train_step(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        hp = self.hp
        engine = model.module
        skip_rule = (
            "importance_weight_sum", "n_action_tokens",
            hp.early_stop_imp_ratio or 0.0,
        )
        agg: Dict[str, float] = {}
        n_steps = 0
        mean_kl = 0.0
        adv_scale = 0.0

        if not hp.group_adv_norm and hasattr(engine, "upload_uniform"):
            # Fast path: ONE h2d upload of the whole batch, GAE + advantage
            # whitening fused on device (make_advantage_prep), micro-batches
            # sliced on device by index — per step this is n_mb dispatches,
            # one apply and ONE host sync per PPO minibatch (critical
            # through a remote-device transport; also the best pipelining
            # locally).
            # Request at least ppo_n_minibatches micro-batches from the
            # packer: with the default MicroBatchSpec the whole batch packs
            # into ONE uniform micro-batch, which would silently collapse
            # the PPO minibatch loop (reference ppo_interface.py:698) to a
            # single optimizer step.
            ub = engine.upload_uniform(data, dataclasses.replace(
                mb_spec, n_mbs=max(mb_spec.n_mbs or 1, hp.ppo_n_minibatches)
            ))
            scalars = engine.run_prep(
                ub, self._prep_fn, self._prep_fn,
                scalars={"kl_coef": self.kl_ctl.value},
            )
            k = min(hp.ppo_n_minibatches, ub.n_mbs)
            # Contiguous micro-batch groups, one optimizer step each
            # (reference ppo_interface.py:698-760 minibatch loop).
            bounds = np.linspace(0, ub.n_mbs, k + 1).astype(int)
            groups = [
                list(range(bounds[i], bounds[i + 1]))
                for i in range(k) if bounds[i + 1] > bounds[i]
            ]
            for gi, g in enumerate(groups):
                stats = engine.train_uniform(
                    ub, self._loss_fn, _action_token_weight, mb_indices=g,
                    skip_update_rule=skip_rule,
                    extra_fetch={"_mean_kl": scalars["_mean_kl"],
                                 "_adv_scale": scalars["_adv_scale"]},
                )
                mean_kl = stats.pop("_mean_kl")
                adv_scale = stats.pop("_adv_scale")
                n_steps += 1
                for key, v in stats.items():
                    agg[key] = agg.get(key, 0.0) + float(v)
                if stats.get("update_applied", 1.0) == 0.0:
                    n = max(stats.get("n_action_tokens", 1.0), 1.0)
                    imp = stats.get("importance_weight_sum", 0.0) / n
                    logger.warning(
                        f"early-stopping PPO minibatches: importance ratio "
                        f"{imp:.2f} > {hp.early_stop_imp_ratio} "
                        "(update skipped)"
                    )
                    break
        else:
            extra = compute_advantages_and_returns(data, hp, self.kl_ctl.value)
            mean_kl = extra.pop("_mean_kl")
            # Raw advantage scale (pre-whitening), mirroring the device
            # prep's _adv_scale: the prompt-mask approximation of the
            # action mask is exact here — doc-first-token advantages are
            # 0 by construction.
            am = (1 - np.asarray(data.data["prompt_mask"])) > 0
            if am.any():
                adv_scale = float(np.abs(extra["advantages"][am]).mean())
            data = attach_keys(data, extra)
            if hp.adv_norm or hp.group_adv_norm:
                normalize_advantages(data, hp)

            # PPO minibatch loop (reference ppo_interface.py:698-760): split
            # the batch into ppo_n_minibatches, one optimizer step each.
            minibatches, _ = data.split(k=min(hp.ppo_n_minibatches, data.bs))
            for mb_sample in minibatches:
                if mb_sample.bs == 0:
                    continue
                # Early-stop semantics (reference ppo_interface.py:735-760):
                # the importance ratio is checked BEFORE the optimizer step —
                # the engine skips the update on device when the ratio
                # exceeds the cap, and we stop the remaining minibatches.
                stats = engine.train_batch(
                    mb_sample, mb_spec, self._loss_fn,
                    _action_token_weight,
                    version_steps=model.version.global_step,
                    skip_update_rule=skip_rule,
                )
                n_steps += 1
                for k, v in stats.items():
                    agg[k] = agg.get(k, 0.0) + float(v)
                if stats.get("update_applied", 1.0) == 0.0:
                    n = max(stats.get("n_action_tokens", 1.0), 1.0)
                    imp = stats.get("importance_weight_sum", 0.0) / n
                    logger.warning(
                        f"early-stopping PPO minibatches: importance ratio "
                        f"{imp:.2f} > {hp.early_stop_imp_ratio} "
                        "(update skipped)"
                    )
                    break
        self.kl_ctl.update(mean_kl, n_steps=1)
        # Version-staleness of the TRAINED batch (how many publishes
        # behind the samples' generation weights are) — measured before
        # this step's version bump, in the same sample units the
        # staleness gate budgets (max_head_offpolicyness).
        staleness = 0.0
        if "version_start" in data.keys:
            staleness = float(
                model.version.global_step
                - np.mean(np.asarray(data.data["version_start"],
                                     np.float64))
            )
        model.inc_version()
        n = max(agg.get("n_action_tokens", 1.0), 1.0)
        moe_stats = {
            k: v / max(n_steps, 1) for k, v in agg.items()
            if k.startswith("moe_")
        }
        rewards_np = np.asarray(data.data["rewards"], np.float32).reshape(-1)
        return {
            **moe_stats,
            "actor_loss": agg.get("loss", 0.0),
            "importance_weight": agg.get("importance_weight_sum", 0.0) / n,
            "clip_ratio": agg.get("clip_ratio_sum", 0.0) / n,
            "dual_clip_ratio": agg.get("dual_clip_ratio_sum", 0.0) / n,
            "mean_kl": mean_kl,
            "kl_coef": self.kl_ctl.value,
            "grad_norm": agg.get("grad_norm", 0.0) / max(n_steps, 1),
            "lr": agg.get("lr", 0.0) / max(n_steps, 1),
            "n_action_tokens": agg.get("n_action_tokens", 0.0),
            "n_ppo_steps": float(n_steps),
            "task_reward": float(rewards_np.mean()),
            # Training-dynamics divergence signatures (first-class
            # telemetry via trainer_worker._export_train_stats; the
            # sentinel's default rule pack keys off these —
            # docs/observability.md §Alerting).
            "approx_kl": agg.get("approx_kl_sum", 0.0) / n,
            "entropy": agg.get("entropy_sum", 0.0) / n,
            "behav_imp_tail": agg.get("behav_tail_sum", 0.0) / n,
            "reward_std": float(rewards_np.std()),
            "adv_scale": float(adv_scale),
            "staleness_lag": staleness,
        }

    def save(self, model: Model, save_dir: str) -> None:
        from areal_tpu.models import hf as hfmod

        engine = model.module
        hfmod.save_hf_checkpoint(
            jax.device_get(engine.params), engine.cfg, save_dir,
            meta={"version": model.version.global_step},
        )

    def state_dict(self):
        return {"kl_ctl": getattr(self.kl_ctl, "_value", self.kl_ctl.value)}

    def load_state_dict(self, d):
        if hasattr(self.kl_ctl, "_value"):
            self.kl_ctl._value = d["kl_ctl"]


def _logprob_hook(logits, batch):
    if logits.ndim == 2:  # engine's chunked-logprob head already did it
        return logits
    return F.token_logprobs_from_logits(
        logits, batch["tokens"], batch["segment_ids"]
    )


_logprob_hook.wants_token_logprobs = True


def _values_hook(values, batch):
    # critic forward output is [B, L] already
    return values * (batch["segment_ids"] > 0)


def _action_token_weight(mb: mbu.MicroBatch) -> float:
    return float(_action_mask(mb.grids).sum())


def attach_keys(data: SequenceSample, extra: Dict[str, np.ndarray]) -> SequenceSample:
    """New sample with full-length per-token keys added (non-mutating)."""
    sls = data.seqlens["packed_input_ids"]
    return SequenceSample(
        ids=list(data.ids),
        keys=set(data.keys) | set(extra.keys()),
        seqlens={**data.seqlens, **{k: [list(s) for s in sls] for k in extra}},
        data={**data.data, **extra},
        metadata=data.metadata,
    )


# ---------------- critic ----------------

class PPOCriticInterface(ModelInterface):
    def __init__(self, hp: Optional[PPOHyperparameters] = None, **kw):
        self.hp = hp or PPOHyperparameters(**kw)
        self.rms = RunningMoments(self.hp.value_norm_beta, self.hp.value_norm_eps)
        hp_ = self.hp

        def critic_loss_fn(values, batch):
            amask = F.action_token_mask(
                batch["segment_ids"], batch["prompt_mask"]
            )
            # Returns at action slot t target the PRE-action value V_{t−1}
            # (reference leave_one_indices pairing, ppo_interface.py:936-948):
            # shift both the fresh forward values and the stored clip
            # baseline right by one inside each doc before the loss.
            seg = batch["segment_ids"]
            loss, st = F.critic_loss(
                F.shift_right_in_doc(values, seg),
                F.shift_right_in_doc(batch["values"], seg),
                batch["_norm_returns"],
                amask,
                value_eps_clip=hp_.value_eps_clip,
                loss_scale=jnp.asarray(1.0),
            )
            return loss, {
                "value_clip_ratio_sum": st["value_clip_ratio"],
                "n_action_tokens": jnp.sum(amask),
            }

        self._loss_fn = critic_loss_fn

    def inference(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        """Critic forward → denormalized per-token values."""
        engine = model.module
        per_sample = engine.forward(data, mb_spec, post_hook=_values_hook)
        vals = np.concatenate(per_sample).astype(np.float32)
        if self.hp.value_norm:
            vals = self.rms.denormalize(vals).astype(np.float32)
        return SequenceSample(
            ids=list(data.ids),
            keys={"values"},
            seqlens={"values": [list(s) for s in data.seqlens["packed_input_ids"]]},
            data={"values": vals},
        )

    def train_step(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        hp = self.hp
        engine = model.module
        extra = compute_advantages_and_returns(data, hp, 0.0)
        extra.pop("_mean_kl")
        returns = extra["returns"]
        pm = np.asarray(data.data["prompt_mask"])
        amask = (1 - pm) > 0
        if hp.value_norm:
            self.rms.update(returns, amask)
            extra["_norm_returns"] = self.rms.normalize(returns).astype(np.float32)
        else:
            extra["_norm_returns"] = returns
        # The critic trains in normalized space; its stored "values" input
        # key must be normalized the same way for the clip baseline.
        if hp.value_norm and "values" in data.keys:
            data = attach_keys(
                data,
                {"values": self.rms.normalize(
                    np.asarray(data.data["values"])).astype(np.float32)},
            )
        data = attach_keys(data, extra)
        minibatches, _ = data.split(k=min(hp.ppo_n_minibatches, data.bs))
        agg: Dict[str, float] = {}
        n_steps = 0
        for mb_sample in minibatches:
            if mb_sample.bs == 0:
                continue
            stats = engine.train_batch(
                mb_sample, mb_spec, self._loss_fn, _action_token_weight,
                version_steps=model.version.global_step,
            )
            n_steps += 1
            for k, v in stats.items():
                agg[k] = agg.get(k, 0.0) + float(v)
        model.inc_version()
        n = max(agg.get("n_action_tokens", 1.0), 1.0)
        return {
            "critic_loss": agg.get("loss", 0.0),
            "value_clip_ratio": agg.get("value_clip_ratio_sum", 0.0) / n,
            "grad_norm": agg.get("grad_norm", 0.0) / max(n_steps, 1),
            "value_mean": float(self.rms.mean),
            "value_var": float(self.rms.var),
        }

    def state_dict(self):
        return {"rms": self.rms.state_dict()}

    def load_state_dict(self, d):
        self.rms.load_state_dict(d["rms"])


register_interface("ppo_critic", PPOCriticInterface)


def trajectories_from_gen_output(
    prompts: SequenceSample,
    gen_out: Dict[str, np.ndarray],
    group_size: int,
    version: int,
    eos_token_id: int = 1,
) -> SequenceSample:
    """Assemble flattened trajectory samples from engine.generate output."""
    offs = prompts.offsets("packed_prompts")
    plens = prompts.total_lens("packed_prompts")
    ids, seqlens = [], []
    toks, pmask, lps = [], [], []
    n_eos = []
    for i in range(prompts.bs):
        prompt = prompts.data["packed_prompts"][offs[i] : offs[i] + plens[i]]
        for j in range(group_size):
            r = i * group_size + j
            gl = int(gen_out["output_lens"][r])
            gl = max(gl, 1)
            g_toks = gen_out["output_ids"][r][:gl]
            g_lps = gen_out["output_logprobs"][r][:gl]
            ids.append(f"{prompts.ids[i]}@{j}")
            seqlens.append(len(prompt) + gl)
            toks.append(np.concatenate([prompt, g_toks]))
            pmask.append(
                np.concatenate([np.ones(len(prompt), np.int32),
                                np.zeros(gl, np.int32)])
            )
            lps.append(
                np.concatenate([np.zeros(len(prompt), np.float32), g_lps])
            )
            # Truncated iff EOS never appeared among the emitted tokens
            # (gen_mask.all() alone misses EOS landing on the final slot).
            n_eos.append(float(eos_token_id not in g_toks))
    md_task = prompts.metadata.get("task", ["math"] * prompts.bs)
    return SequenceSample.from_default(
        ids=ids,
        data={
            "packed_input_ids": np.concatenate(toks).astype(np.int32),
            "prompt_mask": np.concatenate(pmask),
            "packed_logprobs": np.concatenate(lps).astype(np.float32),
            "seq_no_eos_mask": np.asarray(n_eos, np.float32),
            "task_ids": np.repeat(
                np.asarray(
                    prompts.data.get(
                        "task_ids", np.zeros(prompts.bs, np.int32)
                    )
                ).reshape(-1),
                group_size,
            ),
            "version_start": np.full(len(ids), version, np.int32),
            "version_end": np.full(len(ids), version, np.int32),
        },
        seqlens=seqlens,
        metadata={
            "group": [str(prompts.ids[i]) for i in range(prompts.bs)
                      for _ in range(group_size)],
            "task": [md_task[i] for i in range(prompts.bs)
                     for _ in range(group_size)],
        },
    )


class LogprobInterface(ModelInterface):
    """Frozen-model logprob recompute (the reference's ref_inf MFC: actor
    ``inference`` run on the reference policy with an output-key remap)."""

    def __init__(self, output_key: str = "packed_ref_logprobs"):
        self.output_key = output_key

    def inference(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        per_sample = model.module.forward(data, mb_spec, post_hook=_logprob_hook)
        return SequenceSample(
            ids=list(data.ids),
            keys={self.output_key},
            seqlens={self.output_key: [list(s) for s in
                                       data.seqlens["packed_input_ids"]]},
            data={self.output_key: np.concatenate(per_sample).astype(np.float32)},
        )


register_interface("ppo_actor", PPOActorInterface)
register_interface("ref_logprob", LogprobInterface)
