"""Learned reward-model training — Bradley-Terry pairwise loss.

Completes the ``RewardModelingPairedDataset`` path (reference
``realhf/impl/dataset/rw_paired_dataset.py``; the reference ships the
dataset for its legacy RLHF pipeline — the paired-RM *trainer* lives in
earlier RealHF releases, and this interface is its TPU-native equivalent):
a critic-headed model scores each answer at its final token, and pairs
optimize ``-log σ(s_pos − s_neg)``.

Data contract: the paired dataset emits one multi-segment sample per
prompt (segments = pos,neg,pos,neg,...). ``train_step`` flattens each pair
into two independent sequences tagged with per-sequence ``_pair_idx`` /
``_pair_sign`` scalars; the packed grid keeps answers attention-isolated
via segment ids, and the loss re-joins pairs on device with a segment-sum
over ``_pair_idx``. Pairs that FFD packing separates across micro-batches
are skipped for that step (counted in ``orphan_pairs``) — keep
``max_tokens_per_mb`` large enough that this stays 0.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import Model, ModelInterface, register_interface


def _pairwise_loss(values: jnp.ndarray, batch: Dict[str, jnp.ndarray]):
    """values: [R, L] critic outputs. Per-seq score = value at the last
    token; each pos sequence finds its neg partner by _pair_idx equality
    (O(S²) over the tiny per-mb sequence count — no segment-id bounds to
    manage); BT loss over pairs whose BOTH members landed in this
    micro-batch."""
    scores = values[batch["seq_rows"], batch["seq_last_cols"]]
    mask = batch["seq_mask"]
    sign = batch["_pair_sign"]
    idx = batch["_pair_idx"]
    same = (idx[:, None] == idx[None, :]).astype(jnp.float32)
    neg_m = (sign < 0).astype(jnp.float32) * mask
    pos_m = (sign > 0).astype(jnp.float32) * mask
    partner_score = same @ (scores * neg_m)
    partner_present = same @ neg_m
    whole = pos_m * (partner_present == 1.0)
    diff = scores - partner_score  # meaningful where whole == 1
    # -log sigmoid(diff) = softplus(-diff)
    loss = jnp.sum(jax.nn.softplus(-diff) * whole)
    correct = jnp.sum((diff > 0).astype(jnp.float32) * whole)
    n_pairs = jnp.sum(whole)
    orphan = jnp.sum(pos_m) - n_pairs + jnp.sum(
        neg_m * ((pos_m @ same) == 0.0)
    )
    return loss, {
        "n_pairs": n_pairs, "correct_sum": correct, "loss_sum": loss,
        "pos_score_sum": jnp.sum(scores * pos_m),
        "neg_score_sum": jnp.sum(scores * neg_m),
        "orphan_pairs": orphan,
    }


def _loss_weight(mb) -> float:
    # Normalize by pairs, not tokens: every comparison counts equally
    # regardless of answer length.
    sign = mb.scalars["_pair_sign"]
    return float((sign > 0).sum())


def flatten_pairs(data: SequenceSample) -> SequenceSample:
    """Paired multi-segment samples → one sample per ANSWER with
    _pair_idx/_pair_sign scalars (global pair numbering)."""
    out: List[SequenceSample] = []
    pair = 0
    for i in range(data.bs):
        segs = data.seqlens["packed_input_ids"][i]
        assert len(segs) % 2 == 0, "paired data needs pos/neg interleaved"
        off = int(data.offsets("packed_input_ids")[i])
        toks = data.data["packed_input_ids"]
        for j in range(0, len(segs), 2):
            for sign, name in ((1.0, "pos"), (-1.0, "neg")):
                ln = int(segs[j + (sign < 0)])
                out.append(SequenceSample.from_default(
                    ids=[f"{data.ids[i]}@p{j // 2}{name}"],
                    data={
                        "packed_input_ids": toks[off : off + ln],
                        "_pair_idx": np.asarray([pair], np.float32),
                        "_pair_sign": np.asarray([sign], np.float32),
                    },
                    seqlens=[ln],
                ))
                off += ln
            pair += 1
    return SequenceSample.gather(out)


@dataclasses.dataclass
class RewardModelingInterface(ModelInterface):
    n_minibatches: int = 1

    def train_step(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        engine = model.module
        assert engine.cfg.is_critic, "reward model needs a scalar head"
        flat = flatten_pairs(data)
        stats = engine.train_batch(
            flat, mb_spec, _pairwise_loss, _loss_weight,
            token_normalize_scope="global",
            version_steps=model.version.global_step,
        )
        model.inc_version()
        n = max(stats.get("n_pairs", 1.0), 1.0)
        stats["pairwise_accuracy"] = stats.pop("correct_sum", 0.0) / n
        stats["pos_minus_neg"] = (
            stats.pop("pos_score_sum", 0.0) - stats.pop("neg_score_sum", 0.0)
        ) / n
        return stats

    def inference(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        """Per-sequence scores for already-flat (one answer per sample)
        inputs — the serving path of a trained RM."""
        engine = model.module

        def hook(values, batch):
            return values[..., None]  # [R, L, 1] per-token values

        per_sample = engine.forward(data, mb_spec, post_hook=hook)
        scores = np.asarray([float(p[-1, 0]) for p in per_sample], np.float32)
        return SequenceSample.from_default(
            ids=list(data.ids),
            data={"scores": scores},
            seqlens=[1] * data.bs,
        )


register_interface("rw_paired", RewardModelingInterface)
