"""Fused concurrent forward interface.

Parity target: ``realhf/impl/model/interface/fused_interface.py:23``
(FusedThreadingForwardInterface, registered "fused-threading"): one MFC that
runs several child interfaces' ``inference`` concurrently in threads and
merges their output samples. The headline use is fusing ref-logprob
inference (TPU-bound) with rule-based reward verification (CPU/subprocess-
bound) into one DFG node — the two overlap instead of serializing, and the
master schedules one round-trip instead of two.
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional, Tuple

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import (
    Model,
    ModelInterface,
    make_interface,
    register_interface,
)
from areal_tpu.base import logging

logger = logging.getLogger("algorithms.fused")


@dataclasses.dataclass
class FusedForwardInterface(ModelInterface):
    """``interfaces``: {child_name: (registered_interface_name, kwargs)}.

    All children run ``inference`` on the SAME (model, data, mb_spec) in a
    thread pool; their outputs merge via ``SequenceSample.update_`` (key
    sets must be disjoint). Thread safety holds because jax dispatch is
    thread-safe and the reward child only reads the tokenizer.
    """

    interfaces: Dict[str, Tuple[str, Dict[str, Any]]] = dataclasses.field(
        default_factory=dict
    )

    def __post_init__(self):
        self._children: Dict[str, ModelInterface] = {
            key: make_interface(name, **(kwargs or {}))
            for key, (name, kwargs) in self.interfaces.items()
        }
        assert self._children, "fused interface needs at least one child"

    def _run_one(self, key: str, model, data, mb_spec):
        t0 = time.perf_counter()
        out = self._children[key].inference(model, data, mb_spec)
        logger.info(
            f"fused child {key} took {time.perf_counter() - t0:.3f}s"
        )
        return out

    def inference(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Optional[SequenceSample]:
        with ThreadPoolExecutor(max_workers=len(self._children)) as pool:
            futs = {
                key: pool.submit(self._run_one, key, model, data, mb_spec)
                for key in self._children
            }
            final: Optional[SequenceSample] = None
            # Deterministic merge order (dict order), unlike as_completed —
            # update_ asserts disjoint keys so order only affects id checks.
            for key, fut in futs.items():
                res = fut.result()
                if res is None:
                    continue
                if final is None:
                    final = res
                else:
                    final.update_(res)
        return final


register_interface("fused_forward", FusedForwardInterface)
register_interface("fused-threading", FusedForwardInterface)
