"""Rule-based multi-task reward interface (math + code).

Parity target: ``realhf/impl/model/interface/math_rw_interface.py:181``
(``MultiTaskRewardInterface``, registered "rw-math-code"): decode the
generated suffix of each trajectory, dispatch per ``task_ids`` to the math
or code verifier (remote functioncall service or local fallback), and emit a
scalar reward per sequence. No learned reward model is involved — the
"reward model" role is tokenizer-only, exactly as in the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Hashable, Optional

import numpy as np

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import Model, ModelInterface, register_interface
from areal_tpu.base import logging
from areal_tpu.datasets.jsonl import RL_TASKS, load_jsonl
from areal_tpu.rewards.client import batch_reward, task_from_record

logger = logging.getLogger("algorithms.reward")


@dataclasses.dataclass
class MultiTaskRewardInterface(ModelInterface):
    """``id2info`` maps query_id → dataset record ({"task", "solutions",
    "input_output", ...}); built from ``dataset_path`` when given. Sample ids
    are "qid@k" (flattened groups) or bare qids."""

    dataset_path: Optional[str] = None
    id2info: Optional[Dict[Hashable, Dict[str, Any]]] = None
    group_size: int = 1
    check_verifier_status: bool = False

    def __post_init__(self):
        if self.id2info is None and self.dataset_path:
            self.id2info = {
                str(d["query_id"]): d for d in load_jsonl(self.dataset_path)
            }
        self.id2info = self.id2info or {}

    def _lookup(self, sample_id: Hashable) -> Dict[str, Any]:
        # ids carry "@"-separated suffixes (group index, epoch-pass tag);
        # the dataset key is everything before the first "@".
        qid = str(sample_id).split("@", 1)[0]
        return self.id2info.get(qid, {})

    def inference(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        tok = model.tokenizer
        offs = data.offsets("packed_input_ids")
        lens = data.total_lens("packed_input_ids")
        pm = np.asarray(data.data["prompt_mask"])
        task_ids = np.asarray(
            data.data.get("task_ids", np.zeros(data.bs, np.int32))
        ).reshape(-1)
        tasks = []
        for i in range(data.bs):
            span = slice(int(offs[i]), int(offs[i] + lens[i]))
            gen_tokens = data.data["packed_input_ids"][span][pm[span] == 0]
            text = tok.decode(gen_tokens) if tok is not None else ""
            info = self._lookup(data.ids[i])
            # kind falls back to the sample's task_ids when the record is
            # missing; the shared builder handles the per-kind fields
            # (input_output + language for code, solutions otherwise).
            kind = info.get("task") or RL_TASKS[int(task_ids[i])]
            tasks.append(task_from_record({**info, "task": kind}, text))
        scores = np.asarray(batch_reward(tasks), np.float32)
        if self.check_verifier_status and float(np.abs(scores).sum()) == 0:
            logger.warning(
                "all rewards are zero — check the verifier / dataset wiring"
            )
        logger.info(
            f"reward batch: n={data.bs} mean={scores.mean():.3f} "
            f"solve_rate={(scores > 0).mean():.3f}"
        )
        return SequenceSample.from_default(
            ids=list(data.ids),
            data={"rewards": scores},
            seqlens=[1] * data.bs,
        )


register_interface("rw_math_code", MultiTaskRewardInterface)
register_interface("rw-math-code", MultiTaskRewardInterface)
