"""SFT interface — packed cross-entropy over answer tokens.

Parity target: ``realhf/impl/model/interface/sft_interface.py:86`` (packed CE
loss ``:24``). Data contract: ``packed_input_ids`` + ``prompt_mask`` (1 on
prompt tokens, excluded from the loss).
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax.numpy as jnp

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import Model, ModelInterface, register_interface
from areal_tpu.algorithms import ppo_functional as F


def sft_loss(logits: jnp.ndarray, batch: Dict[str, jnp.ndarray]):
    """Sum of -logp over answer tokens. Token t is scored by logits at t-1
    (same doc), so the first token of each doc never contributes. Receives
    precomputed [B, L] logprobs under the engine's chunked-logprob head."""
    lp = logits if logits.ndim == 2 else F.token_logprobs_from_logits(
        logits, batch["tokens"], batch["segment_ids"]
    )
    w = batch["_sft_loss_mask"]
    loss = -jnp.sum(lp * w)
    return loss, {"n_tokens": jnp.sum(w), "nll_sum": loss}


sft_loss.wants_token_logprobs = True


def _loss_weight(mb) -> float:
    return float(mb.grids["_sft_loss_mask"].sum())


@dataclasses.dataclass
class SFTInterface(ModelInterface):
    token_normalize_scope: str = "global"

    def train_step(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        engine = model.module
        data = _attach_loss_mask(data)
        stats = engine.train_batch(
            data, mb_spec, sft_loss, _loss_weight,
            token_normalize_scope=self.token_normalize_scope,
            version_steps=model.version.global_step,
        )
        model.inc_version()
        n = max(stats.pop("n_tokens", 1.0), 1.0)
        stats["ppl"] = float(jnp.exp(jnp.minimum(stats["nll_sum"] / n, 20.0)))
        return stats

    def inference(
        self, model: Model, data: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        """Eval: per-sample NLL (used by eval loops)."""
        engine = model.module
        data = _attach_loss_mask(data)

        def hook(logits, batch):
            lp = logits if logits.ndim == 2 else F.token_logprobs_from_logits(
                logits, batch["tokens"], batch["segment_ids"]
            )
            return -lp * batch["_sft_loss_mask"]

        hook.wants_token_logprobs = True

        per_sample = engine.forward(data, mb_spec, post_hook=_stable(hook))
        import numpy as np

        nll = np.asarray([p.sum() for p in per_sample], np.float32)
        return SequenceSample.from_default(
            ids=data.ids, data={"eval_nll": nll}, seqlens=[1] * data.bs
        )


_HOOKS = {}


def _stable(fn):
    """Keep one hook instance per name so engine jit caches stay warm."""
    return _HOOKS.setdefault(fn.__name__, fn)


def _attach_loss_mask(data: SequenceSample) -> SequenceSample:
    """Answer-token mask as a full-length key (grids ride the layout)."""
    import numpy as np

    pm = data.data["prompt_mask"]
    lm = (1 - np.asarray(pm)).astype(np.float32)
    d = SequenceSample(
        ids=list(data.ids),
        keys=set(data.keys) | {"_sft_loss_mask"},
        seqlens={**data.seqlens, "_sft_loss_mask": data.seqlens["packed_input_ids"]},
        data={**data.data, "_sft_loss_mask": lm},
        metadata=data.metadata,
    )
    return d


register_interface("sft", SFTInterface)
