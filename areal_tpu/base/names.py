"""Name-resolve key schema for distributed discovery.

Parity target: ``realhf/base/names.py:11-108``. All coordination state lives
under ``{root}/{experiment}/{trial}/...`` keys in a name-resolve store.
"""

from __future__ import annotations

ROOT = "areal_tpu"


def _base(experiment: str, trial: str) -> str:
    return f"{ROOT}/{experiment}/{trial}"


def trial_root(experiment: str, trial: str) -> str:
    return _base(experiment, trial)


def worker_status(experiment: str, trial: str, worker: str) -> str:
    return f"{_base(experiment, trial)}/status/{worker}"


def worker_root(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/status/"


def request_reply_stream(experiment: str, trial: str, stream: str) -> str:
    return f"{_base(experiment, trial)}/stream/{stream}"


def push_pull_stream(experiment: str, trial: str, stream: str) -> str:
    return f"{_base(experiment, trial)}/push_pull/{stream}"


def push_pull_stream_root(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/push_pull/"


def gen_servers(experiment: str, trial: str, server_id: str) -> str:
    return f"{_base(experiment, trial)}/gen_servers/{server_id}"


def gen_server_root(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/gen_servers/"


def gen_server_manager(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/gserver_manager"


def reward_worker(experiment: str, trial: str, worker_id: str) -> str:
    """HTTP endpoint of one sandbox reward worker (the sixth worker
    kind, system/reward_worker.py): reward clients discover the fleet
    under the root below and fan grading requests across it
    (rewards/client.py, docs/rewards.md)."""
    return f"{_base(experiment, trial)}/reward_workers/{worker_id}"


def reward_worker_root(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/reward_workers/"


def model_version(experiment: str, trial: str, role: str) -> str:
    return f"{_base(experiment, trial)}/model_version/{role}"


def model_version_time(experiment: str, trial: str, role: str) -> str:
    """Wall-clock publish time of the version above — the start point of
    the trainer→rollout weight-sync latency metric (BASELINE.json)."""
    return f"{_base(experiment, trial)}/model_version_time/{role}"


def weight_stream(experiment: str, trial: str, role: str) -> str:
    """ZMQ endpoint of the trainer's WeightStreamPublisher for ``role`` —
    present iff the trainer publishes weights over the streamed transport
    (system/weight_stream.py); its absence means consumers fall back to
    the disk realloc path."""
    return f"{_base(experiment, trial)}/weight_stream/{role}"


def weight_device(experiment: str, trial: str, role: str) -> str:
    """On-device publication descriptor for ``role`` — present iff the
    trainer publishes over the ``device`` transport (parallel/reshard.py
    registry). Value: JSON {pid, version, digest}; the digest is the
    out-of-band integrity gate the generation server verifies before the
    swap. Absence → stream/disk auto-detection as before."""
    return f"{_base(experiment, trial)}/weight_device/{role}"


def experiment_status(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/exp_status"


def distributed_peer(experiment: str, trial: str, peer: str) -> str:
    return f"{_base(experiment, trial)}/peers/{peer}"


def distributed_root(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/peers/"


def used_data_ids(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/used_data"


def telemetry_aggregator(experiment: str, trial: str) -> str:
    """ZMQ PULL endpoint of the master's TelemetryAggregator — workers'
    TelemetryPushers discover it here (base/telemetry.py)."""
    return f"{_base(experiment, trial)}/telemetry_aggregator"


def profiler_trigger(experiment: str, trial: str) -> str:
    """On-demand profiler request flag: a JSON {dir, secs} written by an
    operator (tools/perf_probe.py) and consumed by the trainer's
    ProfilerTriggerWatcher (base/telemetry.py)."""
    return f"{_base(experiment, trial)}/profiler_trigger"


def profiler_status(experiment: str, trial: str) -> str:
    """Last profiler-capture outcome published by the trainer."""
    return f"{_base(experiment, trial)}/profiler_status"


def telemetry_http(experiment: str, trial: str) -> str:
    """HTTP URL of the aggregator's merged-fleet Prometheus endpoint
    (present iff telemetry.http_port > 0) — lets jax-free tools reach the
    merged scrape without knowing the port (tools/perf_probe.py)."""
    return f"{_base(experiment, trial)}/telemetry_http"


def flight_dump_trigger(experiment: str, trial: str) -> str:
    """On-demand flight-recorder dump request: a JSON {dir, nonce} an
    operator writes (tools/perf_probe.py flight-dump); every worker's
    TelemetryPusher acts on it once per nonce (base/telemetry.py)."""
    return f"{_base(experiment, trial)}/flight_dump_trigger"


def worker_heartbeat(experiment: str, trial: str, worker: str) -> str:
    """Liveness heartbeat of one worker: JSON {ts, incarnation, pid},
    rewritten every heartbeat interval by the worker's HeartbeatThread
    (system/worker_base.py). Observers derive heartbeat AGE from ``ts``;
    the incarnation id distinguishes a respawned worker from its dead
    predecessor's ghost."""
    return f"{_base(experiment, trial)}/heartbeat/{worker}"


def worker_heartbeat_root(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/heartbeat/"


def compile_inflight(experiment: str, trial: str, worker: str) -> str:
    """Compile-in-flight flag of one worker: JSON {ts}, rewritten every
    heartbeat interval by the worker's HeartbeatThread while its
    CompileWatch reports a jit compile in progress, deleted when the
    compile drains (system/worker_base.py, base/compile_watch.py). The
    sentinel's absence rules read this to tell "wedged" apart from
    "legitimately compiling" instead of hiding behind a blanket grace
    (system/sentinel.py trainer_stalled)."""
    return f"{_base(experiment, trial)}/compile_inflight/{worker}"


def compile_inflight_root(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/compile_inflight/"


def autoscale_plan(experiment: str, trial: str) -> str:
    """Fleet-size directive published by the gserver manager's autoscale
    loop (JSON {target, dynamic, ts, reason}): ``dynamic`` is how many
    supervisor-spawned single-server workers the launcher-side
    AutoscaleExecutor should keep alive on top of the baseline gen-fleet
    process (system/autoscaler.py)."""
    return f"{_base(experiment, trial)}/autoscale_plan"


def autoscale_inhibit(experiment: str, trial: str) -> str:
    """Autoscale-inhibit hint published by the training-health sentinel
    on critical alerts (JSON {until, rule, ts}): while live, the gserver
    manager's scaling loop suppresses scale-up — growing the fleet into
    a diverging run only burns capacity (system/sentinel.py,
    system/autoscaler.read_inhibit)."""
    return f"{_base(experiment, trial)}/autoscale_inhibit"


def sentinel_silence(experiment: str, trial: str, rule: str) -> str:
    """Operator silence for one sentinel rule (JSON {until, rule}):
    written by ``tools/perf_probe.py silence <rule> <duration>``; the
    sentinel suppresses the rule's fires until it expires."""
    return f"{_base(experiment, trial)}/sentinel_silence/{rule}"


def sentinel_silence_root(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/sentinel_silence/"


def drain_status(experiment: str, trial: str) -> str:
    """Graceful-drain phase marker written by supervisor.drain_experiment
    (JSON {phase, ts}): pausing -> checkpoint -> exiting -> done. Read by
    tools/perf_probe.py fleet-status."""
    return f"{_base(experiment, trial)}/drain_status"


def metric_server(experiment: str, trial: str, group: str, index: str) -> str:
    return f"{_base(experiment, trial)}/metrics/{group}/{index}"


def metric_server_root(experiment: str, trial: str) -> str:
    return f"{_base(experiment, trial)}/metrics/"
