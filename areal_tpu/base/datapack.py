"""Balanced partitioning of variable-length sequences.

Functional parity target: the reference's ``realhf/base/datapack.py:18-191``
(``min_abs_diff_partition`` + first-fit-decreasing allocation), used for
token-balanced data-parallel dispatch and token-budget micro-batching.

Implementation is original: contiguous k-way partition via binary search on
the bottleneck sum, and FFD bin packing for micro-batch assembly.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = [
    "partition_contiguous_balanced",
    "ffd_allocate",
    "balanced_groups",
]


def _feasible(sizes: np.ndarray, k: int, cap: int) -> bool:
    groups = 1
    cur = 0
    for s in sizes:
        if s > cap:
            return False
        if cur + s > cap:
            groups += 1
            cur = int(s)
            if groups > k:
                return False
        else:
            cur += int(s)
    return True


def partition_contiguous_balanced(sizes: Sequence[int], k: int) -> List[List[int]]:
    """Split ``sizes`` into exactly ``k`` contiguous index groups minimizing the
    maximum group sum. Every group is non-empty (requires ``len(sizes) >= k``).

    Returns a list of k lists of indices (contiguous, in order).
    """
    sizes = np.asarray(sizes, dtype=np.int64)
    n = len(sizes)
    if n < k:
        raise ValueError(f"cannot partition {n} items into {k} non-empty groups")
    if k <= 0:
        raise ValueError("k must be positive")
    lo, hi = int(sizes.max()), int(sizes.sum())
    while lo < hi:
        mid = (lo + hi) // 2
        if _feasible(sizes, k, mid):
            hi = mid
        else:
            lo = mid + 1
    cap = lo
    # Greedy split with the found bottleneck; then fix up to exactly k groups.
    bounds = [0]
    cur = 0
    for i, s in enumerate(sizes):
        if cur + s > cap:
            bounds.append(i)
            cur = int(s)
        else:
            cur += int(s)
    bounds.append(n)
    # We may have fewer than k groups; split the largest groups further.
    while len(bounds) - 1 < k:
        spans = [(bounds[i + 1] - bounds[i], i) for i in range(len(bounds) - 1)]
        spans.sort(reverse=True)
        width, idx = spans[0]
        if width < 2:
            raise RuntimeError("cannot split further")  # unreachable given n >= k
        mid = bounds[idx] + width // 2
        bounds = sorted(set(bounds) | {mid})
    return [list(range(bounds[i], bounds[i + 1])) for i in range(k)]


def _ffd_native(sizes: Sequence[int], capacity: int, force: bool = False):
    """Native first-fit-decreasing (csrc/interval_ops.cpp ffd_assign) —
    bit-identical bin contents to the Python loop (same stable decreasing
    order, same first-fit scan). None → caller runs the Python path.
    ``force`` bypasses the small-input threshold (parity tests)."""
    if len(sizes) < 64 and not force:  # ctypes call overhead: tiny inputs
        return None
    try:
        from areal_tpu.ops import native
    except ImportError:
        return None
    bin_of = native.ffd_assign(sizes, capacity)
    if bin_of is None:
        return None
    n_bins = int(bin_of.max()) + 1 if len(bin_of) else 0
    bins: List[List[int]] = [[] for _ in range(n_bins)]
    # Within-bin order must match the Python loop (items appended in
    # decreasing-size order) — min_groups splitting pops the LAST item.
    order = sorted(range(len(sizes)), key=lambda i: -int(sizes[i]))
    for i in order:
        bins[int(bin_of[i])].append(i)
    return bins


def ffd_allocate(
    sizes: Sequence[int], capacity: int, min_groups: int = 1,
    use_native: Optional[bool] = None,
) -> List[List[int]]:
    """First-fit-decreasing bin packing: group indices so that each group's
    total size is <= capacity (single items larger than capacity get their own
    group), producing at least ``min_groups`` groups when possible.

    ``use_native``: None (default) auto-selects the C fast path for large
    inputs; True forces it (ignoring the size threshold), False forces the
    Python loop — the two must produce bit-identical bins (parity-tested).
    """
    bins: List[List[int]] = []
    loads: List[int] = []
    native_bins = None if use_native is False else _ffd_native(
        sizes, capacity, force=use_native is True
    )
    if native_bins is not None:
        bins = native_bins
        loads = [sum(int(sizes[i]) for i in b) for b in bins]
    else:
        order = sorted(range(len(sizes)), key=lambda i: -sizes[i])
        for i in order:
            s = int(sizes[i])
            placed = False
            for b in range(len(bins)):
                if loads[b] + s <= capacity:
                    bins[b].append(i)
                    loads[b] += s
                    placed = True
                    break
            if not placed:
                bins.append([i])
                loads.append(s)
    while len(bins) < min_groups and any(len(b) > 1 for b in bins):
        # Split the heaviest bin among those that can be split.
        candidates = [j for j in range(len(bins)) if len(bins[j]) > 1]
        b = max(candidates, key=lambda j: loads[j])
        moved = bins[b].pop()
        loads[b] -= int(sizes[moved])
        bins.append([moved])
        loads.append(int(sizes[moved]))
    # Keep deterministic order within groups.
    for b in bins:
        b.sort()
    bins.sort(key=lambda g: g[0])
    return bins


def balanced_groups(sizes: Sequence[int], k: int) -> List[List[int]]:
    """Non-contiguous k-way balanced partition (greedy LPT): assign each item
    (largest first) to the currently lightest group. Groups may be empty only
    when len(sizes) < k.
    """
    order = sorted(range(len(sizes)), key=lambda i: -sizes[i])
    groups: List[List[int]] = [[] for _ in range(k)]
    loads = [0] * k
    for i in order:
        b = int(np.argmin(loads))
        groups[b].append(i)
        loads[b] += int(sizes[i])
    for g in groups:
        g.sort()
    return groups
