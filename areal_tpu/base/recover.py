"""Checkpoint/resume bookkeeping.

Parity target: ``realhf/base/recover.py:19-111`` — ``RecoverInfo`` holds step
counters, frequency-control states, and hashes of already-consumed data so a
restarted run neither repeats trained samples nor skips untrained ones;
``discover_ckpt`` finds the latest usable checkpoint under the run directory.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Set


@dataclasses.dataclass
class StepInfo:
    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0

    def next(self) -> "StepInfo":
        return StepInfo(self.epoch, self.epoch_step + 1, self.global_step + 1)


@dataclasses.dataclass
class RecoverInfo:
    recover_start: StepInfo = dataclasses.field(default_factory=StepInfo)
    last_step_info: StepInfo = dataclasses.field(default_factory=StepInfo)
    save_ctl_states: Dict[str, dict] = dataclasses.field(default_factory=dict)
    ckpt_ctl_states: Dict[str, dict] = dataclasses.field(default_factory=dict)
    eval_ctl_states: Dict[str, dict] = dataclasses.field(default_factory=dict)
    data_loading_dp_idx: int = 0
    hash_vals_to_ignore: List[int] = dataclasses.field(default_factory=list)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "RecoverInfo":
        d = dict(d)
        d["recover_start"] = StepInfo(**d.get("recover_start", {}))
        d["last_step_info"] = StepInfo(**d.get("last_step_info", {}))
        return cls(**d)


def recover_info_path(run_dir: str) -> str:
    return os.path.join(run_dir, "recover_info.json")


def dump(run_dir: str, info: RecoverInfo) -> None:
    os.makedirs(run_dir, exist_ok=True)
    path = recover_info_path(run_dir)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(info.to_json(), f, indent=2)
    os.replace(tmp, path)


def load(run_dir: str) -> Optional[RecoverInfo]:
    path = recover_info_path(run_dir)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return RecoverInfo.from_json(json.load(f))


def ckpt_dirname(epoch: int, epoch_step: int, global_step: int) -> str:
    return f"epoch{epoch}epochstep{epoch_step}globalstep{global_step}"


def parse_ckpt_dirname(name: str) -> Optional[StepInfo]:
    import re

    m = re.fullmatch(r"epoch(\d+)epochstep(\d+)globalstep(\d+)", name)
    if not m:
        return None
    return StepInfo(int(m.group(1)), int(m.group(2)), int(m.group(3)))


# Terminal sentinel written into a checkpoint dir AFTER every file landed.
# A crash mid-save leaves a dir without it; discovery skips such dirs so a
# recovered run never restores from a half-written checkpoint.
CKPT_COMPLETE_MARKER = ".complete"


def mark_ckpt_complete(ckpt_dir: str) -> None:
    tmp = os.path.join(ckpt_dir, CKPT_COMPLETE_MARKER + f".tmp{os.getpid()}")
    with open(tmp, "w") as f:
        f.write("ok\n")
    os.replace(tmp, os.path.join(ckpt_dir, CKPT_COMPLETE_MARKER))


def ckpt_is_complete(ckpt_dir: str) -> bool:
    if os.path.exists(os.path.join(ckpt_dir, CKPT_COMPLETE_MARKER)):
        return True
    # Pre-sentinel compat: those checkpoints end with trainer_state.json
    # (the trainer writes it after every role's train state). It must
    # PARSE — a torn write from a crash mid-dump is exactly the
    # half-written state the sentinel exists to reject.
    try:
        with open(os.path.join(ckpt_dir, "trainer_state.json")) as f:
            json.load(f)
        return True
    except Exception:  # noqa: BLE001 — missing or torn: incomplete
        return False


def discover_ckpt(save_root: str) -> Optional[str]:
    """Latest COMPLETE checkpoint directory (by global step) under
    save_root; dirs missing the terminal sentinel (crash mid-save) are
    skipped."""
    if not os.path.isdir(save_root):
        return None
    best: Optional[str] = None
    best_step = -1
    for name in os.listdir(save_root):
        info = parse_ckpt_dirname(name)
        if info is None or info.global_step <= best_step:
            continue
        path = os.path.join(save_root, name)
        if not ckpt_is_complete(path):
            continue
        best_step = info.global_step
        best = path
    return best
