"""Test fixtures: mock tokenizer + fabricated datasets.

Parity target: ``realhf/base/testing.py`` (tiny fabricated models + random
WordPiece tokenizer) and ``tests/fixtures.py`` (random jsonl datasets).
The tiny model configs live in models/config.py (tiny_config).
"""

from __future__ import annotations

import json
import random
from typing import List, Optional

PAD_TOKEN = 0
EOS_TOKEN = 1


class MockTokenizer:
    """Deterministic char-level tokenizer: byte + 2 (0 = pad, 1 = eos)."""

    def __init__(self, vocab_size: int = 258):
        self.vocab_size = vocab_size
        self.pad_token_id = PAD_TOKEN
        self.eos_token_id = EOS_TOKEN

    def encode(self, text: str) -> List[int]:
        return [(b % (self.vocab_size - 2)) + 2 for b in text.encode()]

    def decode(self, ids) -> str:
        return bytes(
            max(int(i) - 2, 0) for i in ids if int(i) not in (PAD_TOKEN, EOS_TOKEN)
        ).decode(errors="replace")

    def __call__(self, texts, **kw):
        if isinstance(texts, str):
            texts = [texts]
        return {"input_ids": [self.encode(t) for t in texts]}


def make_math_jsonl(path: str, n: int = 32, seed: int = 0) -> List[dict]:
    """Solvable arithmetic prompts with boxed ground truths."""
    rng = random.Random(seed)
    records = []
    for i in range(n):
        a, b = rng.randint(0, 50), rng.randint(0, 50)
        records.append(
            {
                "query_id": f"q{i}",
                "prompt": f"What is {a}+{b}? ",
                "task": "math",
                "solutions": [f"\\boxed{{{a + b}}}"],
            }
        )
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return records


def make_sft_jsonl(path: str, n: int = 32, seed: int = 0) -> List[dict]:
    rng = random.Random(seed)
    records = []
    for i in range(n):
        a, b = rng.randint(0, 50), rng.randint(0, 50)
        records.append(
            {
                "query_id": f"s{i}",
                "prompt": f"What is {a}+{b}? ",
                "answer": f"The answer is {a + b}.",
            }
        )
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return records


def make_code_jsonl(path: str, n: int = 4, seed: int = 0) -> List[dict]:
    rng = random.Random(seed)
    records = []
    for i in range(n):
        k = rng.randint(1, 5)
        io = {
            "inputs": [f"{x}\n" for x in range(3)],
            "outputs": [f"{x + k}\n" for x in range(3)],
        }
        records.append(
            {
                "query_id": f"c{i}",
                "prompt": f"Write a program that reads x and prints x+{k}.",
                "task": "code",
                "solutions": [],
                "input_output": json.dumps(io),
            }
        )
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return records


def make_mixed_jsonl(path: str, n_math: int = 6, n_code: int = 2,
                     seed: int = 0) -> List[dict]:
    """Mixed math+code RL fixture: the code-RL e2e / pass@k eval dataset
    shape (docs/rewards.md). Math records carry boxed solutions; code
    records carry stdin/stdout ``input_output`` cases a one-liner can
    pass — graded by the sandbox, fully solvable in principle."""
    rng = random.Random(seed)
    records = []
    for i in range(n_math):
        a, b = rng.randint(0, 50), rng.randint(0, 50)
        records.append({
            "query_id": f"m{i}",
            "prompt": f"What is {a}+{b}? ",
            "task": "math",
            "solutions": [f"\\boxed{{{a + b}}}"],
        })
    for i in range(n_code):
        k = rng.randint(1, 5)
        io = {
            "inputs": [f"{x}\n" for x in range(2)],
            "outputs": [f"{x + k}\n" for x in range(2)],
        }
        records.append({
            "query_id": f"c{i}",
            "prompt": f"Write a program that reads x and prints x+{k}. ",
            "task": "code",
            "solutions": [],
            "input_output": json.dumps(io),
        })
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return records


def bench_trajectory_dist(seed: int = 0, n_seq: int = 32):
    """The bench.py PPO trajectory length distribution — ~250-token prompts
    + ~640-token generations — as ``(rng, plens, glens)``. The SINGLE
    source of the recipe: bench.py continues drawing tokens/logprobs from
    the returned rng (bit-identical to the historical inline code), while
    ``tools/perf_probe.py packfill`` and tests/test_packing_fill.py build
    packing-only samples from it. Change it here and every fill number,
    probe, and the ≥0.92 gate move together."""
    import numpy as np

    rng = np.random.RandomState(seed)
    plens = rng.randint(200, 257, n_seq)
    glens = rng.randint(512, 769, n_seq)
    return rng, plens, glens


def bench_trajectory_sample(seed: int = 0, n_seq: int = 32,
                            vocab: int = 1000):
    """``(SequenceSample, seqlens)`` carrying only packed_input_ids — what
    packing-fill consumers of :func:`bench_trajectory_dist` need."""
    import numpy as np

    from areal_tpu.api.data import SequenceSample

    rng, plens, glens = bench_trajectory_dist(seed, n_seq)
    seqlens = (plens + glens).astype(int)
    toks = rng.randint(2, vocab, int(seqlens.sum())).astype(np.int32)
    return SequenceSample.from_default(
        ids=[f"b{i}" for i in range(n_seq)],
        data={"packed_input_ids": toks},
        seqlens=seqlens.tolist(),
    ), seqlens
