"""Frequency control for save/eval/ckpt triggers.

Parity target: ``realhf/base/timeutil.py:15`` (``EpochStepTimeFreqCtl``): a
trigger that fires when any of (epochs elapsed, steps elapsed, wall seconds
elapsed) crosses its configured frequency. State is exportable for recovery.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional


@dataclasses.dataclass
class FreqState:
    last_epoch: int = 0
    last_step: int = 0
    last_time: float = dataclasses.field(default_factory=time.monotonic)


class FrequencyControl:
    """check(epoch, step) returns True when a configured frequency elapsed
    since the last True. Frequencies of None never fire on that axis."""

    def __init__(
        self,
        freq_epoch: Optional[int] = None,
        freq_step: Optional[int] = None,
        freq_sec: Optional[float] = None,
        initial_value: bool = False,
    ):
        self.freq_epoch = freq_epoch
        self.freq_step = freq_step
        self.freq_sec = freq_sec
        self._state = FreqState()
        self._first = initial_value

    def check(self, epochs: int, steps: int) -> bool:
        if self._first:
            self._first = False
            self._mark(epochs, steps)
            return True
        fire = False
        if self.freq_epoch is not None and epochs - self._state.last_epoch >= self.freq_epoch:
            fire = True
        if self.freq_step is not None and steps - self._state.last_step >= self.freq_step:
            fire = True
        if (
            self.freq_sec is not None
            and time.monotonic() - self._state.last_time >= self.freq_sec
        ):
            fire = True
        if fire:
            self._mark(epochs, steps)
        return fire

    def _mark(self, epochs: int, steps: int) -> None:
        self._state.last_epoch = epochs
        self._state.last_step = steps
        self._state.last_time = time.monotonic()

    def state_dict(self) -> dict:
        return dataclasses.asdict(self._state)

    def load_state_dict(self, d: dict) -> None:
        self._state = FreqState(**d)
        # last_time is a time.monotonic() from the SAVING process — that
        # clock restarts at boot, so carrying it over can make elapsed time
        # negative and suppress freq_sec firing for arbitrarily long.
        # Restoring re-anchors the time axis at "now" (epoch/step anchors
        # carry over exactly).
        self._state.last_time = time.monotonic()
