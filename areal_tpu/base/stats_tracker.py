"""Scoped distributed statistics tracker.

Parity target: ``realhf/base/stats_tracker.py:20`` (DistributedStatsTracker):
scoped keys, denominator-based reductions (AVG over a bool mask), SUM/MIN/MAX,
moving averages, and scalar stats. In the reference, reductions are
all-reduced over torch process groups; here stats are computed on host numpy
(device arrays are pulled with ``np.asarray``) and — under multi-host JAX —
can be combined with ``jax.experimental.multihost_utils`` by the caller.
"""

from __future__ import annotations

import enum
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional

import numpy as np

__all__ = [
    "ReduceType",
    "StatsTracker",
    "DEFAULT_TRACKER",
    "scope",
    "denominator",
    "stat",
    "scalar",
    "moving_avg",
    "export",
]


class ReduceType(enum.Enum):
    AVG = "avg"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    SCALAR = "scalar"
    MOVING_AVG = "moving_avg"


class StatsTracker:
    """Workers record from async loops AND health-check/flush threads
    concurrently (e.g. a telemetry flush exporting while the serve loop
    appends), so every mutation — scope push/pop included — and the
    export-with-reset run under one re-entrant lock. Scopes are
    per-THREAD (a thread-local stack): a background thread's recording
    must not inherit, or tear, the serve loop's scope nesting."""

    def __init__(self):
        self._local = threading.local()
        self._lock = threading.RLock()
        self._denoms: Dict[str, np.ndarray] = {}
        # key -> (reduce_type, list of (values, denom_key|None))
        self._stats: Dict[str, tuple] = {}
        self._moving: Dict[str, float] = {}

    @property
    def _scopes(self) -> List[str]:
        if not hasattr(self._local, "scopes"):
            self._local.scopes = []
        return self._local.scopes

    # ---- scoping ----
    @contextmanager
    def scope(self, name: str):
        self._scopes.append(name)
        try:
            yield self
        finally:
            self._scopes.pop()

    def _key(self, name: str) -> str:
        return "/".join(self._scopes + [name])

    # ---- recording ----
    def denominator(self, **kwargs) -> None:
        """Register boolean masks usable as denominators for AVG stats."""
        for name, mask in kwargs.items():
            m = np.asarray(mask)
            if m.dtype != np.bool_:
                m = m.astype(bool)
            with self._lock:
                self._denoms[self._key(name)] = m

    def stat(
        self, denominator: str, reduce_type: ReduceType = ReduceType.AVG, **kwargs
    ) -> None:
        """Record vector stats reduced over the elements selected by the named
        denominator mask."""
        with self._lock:
            dkey = self._key(denominator)
            if dkey not in self._denoms:
                raise ValueError(f"unknown denominator {dkey}")
            mask = self._denoms[dkey]
            for name, value in kwargs.items():
                v = np.asarray(value, dtype=np.float64)
                key = self._key(name)
                prev = self._stats.get(key)
                if prev is not None and prev[0] != reduce_type:
                    raise ValueError(f"conflicting reduce types for {key}")
                entries = prev[1] if prev else []
                entries.append((v, mask))
                self._stats[key] = (reduce_type, entries)

    def scalar(self, **kwargs) -> None:
        with self._lock:
            for name, value in kwargs.items():
                key = self._key(name)
                prev = self._stats.get(key)
                entries = prev[1] if prev else []
                entries.append((float(value), None))
                self._stats[key] = (ReduceType.SCALAR, entries)

    def moving_avg(self, decay: float = 0.99, **kwargs) -> None:
        with self._lock:
            for name, value in kwargs.items():
                key = self._key(name)
                old = self._moving.get(key, float(value))
                self._moving[key] = decay * old + (1 - decay) * float(value)

    # ---- export ----
    def export(self, reset: bool = True) -> Dict[str, float]:
        with self._lock:
            return self._export_locked(reset)

    def _export_locked(self, reset: bool) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for key, (rtype, entries) in self._stats.items():
            if rtype == ReduceType.SCALAR:
                vals = [e[0] for e in entries]
                out[key] = float(np.mean(vals))
                continue
            num = 0.0
            den = 0.0
            mn, mx = np.inf, -np.inf
            for v, mask in entries:
                if v.shape != mask.shape:
                    raise ValueError(
                        f"stat {key} shape {v.shape} != denominator shape {mask.shape}"
                    )
                sel = v[mask]
                num += float(sel.sum()) if sel.size else 0.0
                den += float(mask.sum())
                if sel.size:
                    mn = min(mn, float(sel.min()))
                    mx = max(mx, float(sel.max()))
            if rtype == ReduceType.AVG:
                out[key] = num / max(den, 1e-8)
            elif rtype == ReduceType.SUM:
                out[key] = num
            elif rtype == ReduceType.MIN:
                out[key] = mn if np.isfinite(mn) else 0.0
            elif rtype == ReduceType.MAX:
                out[key] = mx if np.isfinite(mx) else 0.0
        for dkey, mask in self._denoms.items():
            out.setdefault(f"{dkey}/count", float(np.asarray(mask).sum()))
        out.update({k: v for k, v in self._moving.items()})
        if reset:
            self._stats.clear()
            self._denoms.clear()
        return out


DEFAULT_TRACKER = StatsTracker()


def scope(name: str):
    return DEFAULT_TRACKER.scope(name)


def denominator(**kwargs):
    return DEFAULT_TRACKER.denominator(**kwargs)


def stat(denominator: str, reduce_type: ReduceType = ReduceType.AVG, **kwargs):
    return DEFAULT_TRACKER.stat(denominator, reduce_type, **kwargs)


def scalar(**kwargs):
    return DEFAULT_TRACKER.scalar(**kwargs)


def moving_avg(decay: float = 0.99, **kwargs):
    return DEFAULT_TRACKER.moving_avg(decay, **kwargs)


def export(reset: bool = True):
    return DEFAULT_TRACKER.export(reset)
