"""Shared retry/backoff policy + fault injection for fleet robustness.

One policy object serves every network hop in the system — the partial
rollout client's chunk failover, the gserver manager's weight fanout, and
the reward client's sandbox calls — so operators tune a single vocabulary
of knobs (attempts, base/max delay, multiplier) instead of per-callsite
magic numbers.

``FaultInjector`` is the test seam: production code calls
``maybe_fail("point")`` at failure-prone boundaries (chunk POST, schedule,
fanout) and tests arm deterministic failures there, so chaos tests run in
milliseconds instead of waiting on real sockets and TTLs.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
from typing import Awaitable, Callable, Dict, Optional, Tuple, Type


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff: delay(n) = min(base * mult^(n-1), max)."""

    max_attempts: int = 4
    base_delay_secs: float = 0.1
    max_delay_secs: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.0  # +/- fraction of the delay, de-synchronizes herds

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based failure count)."""
        d = self.base_delay_secs * self.multiplier ** max(attempt - 1, 0)
        d = min(d, self.max_delay_secs)
        if self.jitter:
            d *= 1.0 + random.uniform(-self.jitter, self.jitter)
        return max(d, 0.0)


async def aretry(
    fn: Callable[[], Awaitable],
    policy: RetryPolicy,
    *,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    timeout: Optional[float] = None,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
):
    """Run ``fn`` up to ``policy.max_attempts`` times with backoff between
    failures. ``timeout`` bounds EACH attempt (asyncio.wait_for), so the
    worst case is max_attempts * (timeout + delay) — a budget the caller can
    compute. The last failure is re-raised unchanged."""
    attempt = 0
    while True:
        attempt += 1
        try:
            if timeout is not None:
                return await asyncio.wait_for(fn(), timeout)
            return await fn()
        except retry_on as e:
            if attempt >= policy.max_attempts:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            await asyncio.sleep(policy.delay(attempt))


# Fleet-wide default for generation chunk failover — referenced by both
# PartialRolloutClient and RolloutWorkerConfig so the two cannot drift.
DEFAULT_GENERATION_RETRY = RetryPolicy(
    max_attempts=6, base_delay_secs=0.05, max_delay_secs=2.0
)


class FaultInjected(RuntimeError):
    """Raised by FaultInjector at an armed fault point."""


class FaultInjector:
    """Deterministic failure injection for chaos tests.

    Production code threads an (optional) injector through and calls
    ``maybe_fail(point, **ctx)`` at each failure boundary; with no injector
    armed this is a dict lookup — effectively free. Tests arm points::

        inj = FaultInjector()
        inj.arm("generate", times=2)            # next 2 calls raise
        inj.arm("fanout", times=-1,             # every call, selectively
                when=lambda ctx: "dead" in ctx.get("url", ""))

    ``times=-1`` means unlimited until :meth:`disarm`. ``fired`` counts
    triggers per point so tests can assert the failure path actually ran.
    """

    def __init__(self):
        self._armed: Dict[str, dict] = {}
        self.fired: Dict[str, int] = {}

    def arm(
        self,
        point: str,
        times: int = 1,
        exc: Optional[Callable[[], BaseException]] = None,
        when: Optional[Callable[[dict], bool]] = None,
    ) -> None:
        self._armed[point] = {"times": times, "exc": exc, "when": when}

    def disarm(self, point: str) -> None:
        self._armed.pop(point, None)

    def maybe_fail(self, point: str, **ctx) -> None:
        spec = self._armed.get(point)
        if spec is None or spec["times"] == 0:
            return
        if spec["when"] is not None and not spec["when"](ctx):
            return
        if spec["times"] > 0:
            spec["times"] -= 1
        self.fired[point] = self.fired.get(point, 0) + 1
        exc = spec["exc"]
        raise exc() if exc is not None else FaultInjected(point)
