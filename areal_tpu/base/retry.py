"""Shared retry/backoff policy + fault injection for fleet robustness.

One policy object serves every network hop in the system — the partial
rollout client's chunk failover, the gserver manager's weight fanout, and
the reward client's sandbox calls — so operators tune a single vocabulary
of knobs (attempts, base/max delay, multiplier) instead of per-callsite
magic numbers.

``FaultInjector`` is the test seam: production code calls
``maybe_fail("point")`` at failure-prone boundaries (chunk POST, schedule,
fanout) and tests arm deterministic failures there, so chaos tests run in
milliseconds instead of waiting on real sockets and TTLs. Points can also
be armed to *delay* instead of raise (``arm_delay`` + ``maybe_delay``) so
chaos tests simulate stragglers and slow networks — the sleep function is
injectable, so fake-clock tests schedule the delays deterministically
without ever sleeping for real.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
from typing import Awaitable, Callable, Dict, Optional, Tuple, Type


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff: delay(n) = min(base * mult^(n-1), max)."""

    max_attempts: int = 4
    base_delay_secs: float = 0.1
    max_delay_secs: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.0  # +/- fraction of the delay, de-synchronizes herds

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based failure count)."""
        d = self.base_delay_secs * self.multiplier ** max(attempt - 1, 0)
        d = min(d, self.max_delay_secs)
        if self.jitter:
            d *= 1.0 + random.uniform(-self.jitter, self.jitter)
        return max(d, 0.0)


async def aretry(
    fn: Callable[[], Awaitable],
    policy: RetryPolicy,
    *,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    timeout: Optional[float] = None,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
):
    """Run ``fn`` up to ``policy.max_attempts`` times with backoff between
    failures. ``timeout`` bounds EACH attempt (asyncio.wait_for), so the
    worst case is max_attempts * (timeout + delay) — a budget the caller can
    compute. The last failure is re-raised unchanged."""
    attempt = 0
    while True:
        attempt += 1
        try:
            if timeout is not None:
                return await asyncio.wait_for(fn(), timeout)
            return await fn()
        except retry_on as e:
            if attempt >= policy.max_attempts:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            await asyncio.sleep(policy.delay(attempt))


# Fleet-wide default for generation chunk failover — referenced by both
# PartialRolloutClient and RolloutWorkerConfig so the two cannot drift.
DEFAULT_GENERATION_RETRY = RetryPolicy(
    max_attempts=6, base_delay_secs=0.05, max_delay_secs=2.0
)


class FaultInjected(RuntimeError):
    """Raised by FaultInjector at an armed fault point."""


class FaultInjector:
    """Deterministic failure injection for chaos tests.

    Production code threads an (optional) injector through and calls
    ``maybe_fail(point, **ctx)`` at each failure boundary; with no injector
    armed this is a dict lookup — effectively free. Tests arm points::

        inj = FaultInjector()
        inj.arm("generate", times=2)            # next 2 calls raise
        inj.arm("fanout", times=-1,             # every call, selectively
                when=lambda ctx: "dead" in ctx.get("url", ""))

    ``times=-1`` means unlimited until :meth:`disarm`. ``fired`` counts
    triggers per point so tests can assert the failure path actually ran.

    Latency injection (straggler / slow-network simulation)::

        inj = FaultInjector(sleeper=fake_sleep)   # default: asyncio.sleep
        inj.arm_delay("decode", 0.8, times=-1,
                      when=lambda ctx: ctx.get("server_id") == "gen1")
        ...
        await inj.maybe_delay("decode", server_id=sid)  # awaits sleeper(0.8)

    ``delay_for`` returns the armed delay without sleeping, for call sites
    that fold it into their own timing (fake servers reporting synthetic
    decode latency). Delay points are independent of failure points: one
    name may be armed for both, in which case ``maybe_delay`` sleeps and
    ``maybe_fail`` raises.
    """

    def __init__(self, sleeper: Optional[Callable] = None):
        self._armed: Dict[str, dict] = {}
        self._delays: Dict[str, dict] = {}
        self.fired: Dict[str, int] = {}
        # Injectable so fake-clock tests advance virtual time instead of
        # blocking the loop; must be an async callable taking seconds.
        self.sleeper = sleeper if sleeper is not None else asyncio.sleep

    def arm(
        self,
        point: str,
        times: int = 1,
        exc: Optional[Callable[[], BaseException]] = None,
        when: Optional[Callable[[dict], bool]] = None,
    ) -> None:
        self._armed[point] = {"times": times, "exc": exc, "when": when}

    def arm_delay(
        self,
        point: str,
        delay_secs: float,
        times: int = 1,
        when: Optional[Callable[[dict], bool]] = None,
    ) -> None:
        self._delays[point] = {
            "delay": float(delay_secs), "times": times, "when": when,
        }

    def disarm(self, point: str) -> None:
        self._armed.pop(point, None)
        self._delays.pop(point, None)

    def delay_for(self, point: str, **ctx) -> float:
        """The armed delay for this call (0.0 when unarmed / filtered /
        exhausted). Consumes one ``times`` charge and counts in ``fired``
        like a failure trigger does."""
        spec = self._delays.get(point)
        if spec is None or spec["times"] == 0:
            return 0.0
        if spec["when"] is not None and not spec["when"](ctx):
            return 0.0
        if spec["times"] > 0:
            spec["times"] -= 1
        self.fired[point] = self.fired.get(point, 0) + 1
        return spec["delay"]

    async def maybe_delay(self, point: str, **ctx) -> float:
        """Await the armed delay through ``self.sleeper`` (deterministic
        under fake clocks); returns the seconds slept (0.0 = unarmed)."""
        d = self.delay_for(point, **ctx)
        if d > 0.0:
            await self.sleeper(d)
        return d

    def maybe_fail(self, point: str, **ctx) -> None:
        spec = self._armed.get(point)
        if spec is None or spec["times"] == 0:
            return
        if spec["when"] is not None and not spec["when"](ctx):
            return
        if spec["times"] > 0:
            spec["times"] -= 1
        self.fired[point] = self.fired.get(point, 0) + 1
        exc = spec["exc"]
        raise exc() if exc is not None else FaultInjected(point)
