"""Per-process global context + canonical filesystem layout.

Parity target: ``realhf/base/constants.py:215``. Two of the reference's three
concerns port: experiment/trial identity (set once per process, used by
logging and the path helpers) and the directory schema every component
shares (``experiments/common.experiment_paths`` delegates here). The third —
``model_scope`` swapping Megatron process groups per model role — has no
TPU equivalent by design: under GSPMD a model role's parallelism is carried
by its ``jax.sharding.Mesh`` object (parallel/mesh.py), passed explicitly,
not by mutable process-global state.
"""

from __future__ import annotations

import getpass
import os
from typing import Dict, Optional

_experiment_name: Optional[str] = None
_trial_name: Optional[str] = None


def set_experiment_trial_names(experiment: str, trial: str) -> None:
    global _experiment_name, _trial_name
    _experiment_name = experiment
    _trial_name = trial


def experiment_name() -> str:
    if _experiment_name is None:
        raise RuntimeError("experiment name unset")
    return _experiment_name


def trial_name() -> str:
    if _trial_name is None:
        raise RuntimeError("trial name unset")
    return _trial_name


# ---- filesystem layout ----
#
# One experiment trial owns one directory tree under a cluster fileroot:
#   <fileroot>/<experiment>/<trial>/{checkpoints,realloc,recover,
#                                    name_resolve,logs}
# ``realloc`` is where the trainer publishes weights for the generation
# fleet (the disk weight-sync path; reference model_worker.py:1053
# REAL_PARAM_REALLOC_IMPL=DISK).


def get_fileroot() -> str:
    return os.environ.get(
        "AREAL_CACHE_ROOT", os.path.join("/tmp", getpass.getuser(), "areal_tpu")
    )


def experiment_paths(
    experiment: Optional[str] = None,
    trial: Optional[str] = None,
    fileroot: Optional[str] = None,
) -> Dict[str, str]:
    root = os.path.join(
        fileroot or get_fileroot(),
        experiment or experiment_name(),
        trial or trial_name(),
    )
    return {
        "root": root,
        "save": os.path.join(root, "checkpoints"),
        "realloc": os.path.join(root, "realloc"),
        "recover": os.path.join(root, "recover"),
        "name_resolve": os.path.join(root, "name_resolve"),
        "log": os.path.join(root, "logs"),
    }


def get_save_root(
    experiment: Optional[str] = None, trial: Optional[str] = None
) -> str:
    return experiment_paths(experiment, trial)["save"]


def get_param_realloc_path(
    experiment: Optional[str] = None, trial: Optional[str] = None
) -> str:
    return experiment_paths(experiment, trial)["realloc"]


def get_log_root(
    experiment: Optional[str] = None, trial: Optional[str] = None
) -> str:
    return experiment_paths(experiment, trial)["log"]
