"""Per-process global context.

Parity target: ``realhf/base/constants.py:215`` — experiment/trial names,
per-model scoped context (the reference swaps Megatron process groups per
model role with ``model_scope``; here the scoped object is the model role's
``jax.sharding.Mesh`` and axis names), and canonical filesystem layout.
"""

from __future__ import annotations

import getpass
import os
from contextlib import contextmanager
from typing import Any, Dict, Optional

_experiment_name: Optional[str] = None
_trial_name: Optional[str] = None
_model_scope: list = []
_model_ctx: Dict[str, Any] = {}


def set_experiment_trial_names(experiment: str, trial: str) -> None:
    global _experiment_name, _trial_name
    _experiment_name = experiment
    _trial_name = trial


def experiment_name() -> str:
    if _experiment_name is None:
        raise RuntimeError("experiment name unset")
    return _experiment_name


def trial_name() -> str:
    if _trial_name is None:
        raise RuntimeError("trial name unset")
    return _trial_name


def has_model_scope() -> bool:
    return bool(_model_scope)


def current_model_name() -> str:
    if not _model_scope:
        raise RuntimeError("not inside model_scope")
    return _model_scope[-1]


@contextmanager
def model_scope(name: str):
    _model_scope.append(name)
    try:
        yield
    finally:
        _model_scope.pop()


def set_model_context(name: str, **ctx) -> None:
    _model_ctx.setdefault(name, {}).update(ctx)


def model_context(name: Optional[str] = None) -> Dict[str, Any]:
    return _model_ctx.get(name or current_model_name(), {})


# ---- filesystem layout ----

def get_cache_root() -> str:
    return os.environ.get(
        "AREAL_CACHE_ROOT", os.path.join("/tmp", getpass.getuser(), "areal_tpu")
    )


def get_log_root(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    return os.path.join(
        get_cache_root(), "logs", experiment or experiment_name(), trial or trial_name()
    )


def get_save_root(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    return os.path.join(
        get_cache_root(), "checkpoints", experiment or experiment_name(), trial or trial_name()
    )


def get_param_realloc_path(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    """Where the trainer publishes weights for the generation fleet (the disk
    weight-sync path; reference: model_worker.py:1053 DISK realloc impl)."""
    return os.path.join(
        get_cache_root(), "param_realloc", experiment or experiment_name(), trial or trial_name()
    )
