"""Deterministic seeding across python/numpy/jax.

Parity target: ``realhf/base/seeding.py`` (global seed + per-component named
seeds). JAX is functional about randomness, so this module hands out
``jax.random.key`` streams derived from (global seed, component name).
"""

from __future__ import annotations

import hashlib
import random

import numpy as np

_SEED: int | None = None
_EXP_NAME = ""
_TRIAL_NAME = ""


def set_random_seed(seed: int, key: str = "") -> None:
    global _SEED
    _SEED = int(seed)
    random.seed(_mix(seed, key))
    np.random.seed(_mix(seed, key) % (2**32))


def get_seed() -> int:
    if _SEED is None:
        raise RuntimeError("set_random_seed was never called")
    return _SEED


def _mix(seed: int, name: str) -> int:
    h = hashlib.blake2b(f"{seed}/{name}".encode(), digest_size=8).digest()
    return int.from_bytes(h, "little")


def component_seed(name: str) -> int:
    """A deterministic per-component integer seed."""
    return _mix(get_seed(), name) % (2**31)


def jax_key(name: str):
    """A fresh jax PRNG key for a named component (lazy jax import so that
    host-only processes never initialize a backend)."""
    import jax

    return jax.random.key(component_seed(name))
