"""Free-port discovery and host identification.

Parity target: ``realhf/base/network.py:25`` (find_free_port w/ lockfiles,
gethostip).
"""

from __future__ import annotations

import fcntl
import os
import socket
from contextlib import closing
from typing import List


def gethostname() -> str:
    return socket.gethostname()


def gethostip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


def bind_addr() -> str:
    """Interface to bind servers on (all interfaces; peers connect via
    gethostip())."""
    return "0.0.0.0"


def advertised_tcp(port: int) -> str:
    """``tcp://<routable-ip>:<port>`` — the address peers should CONNECT to
    for a socket bound on :func:`bind_addr`. Shared by the ZMQ fabric
    (system/streams.py request/push sockets, system/weight_stream.py
    publisher) so every advertisement resolves the host the same way."""
    return f"tcp://{gethostip()}:{port}"


def find_free_port(lockfile_root: str | None = None) -> int:
    """Find a free TCP port. When ``lockfile_root`` is given, takes an flock on
    a per-port lockfile so concurrent processes on one host don't race."""
    for _ in range(100):
        with closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            port = s.getsockname()[1]
        if lockfile_root is None:
            return port
        os.makedirs(lockfile_root, exist_ok=True)
        path = os.path.join(lockfile_root, f"port{port}.lock")
        f = open(path, "w")
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return port
        except OSError:
            f.close()
            continue
    raise RuntimeError("could not find a free port")


def find_multiple_free_ports(n: int, lockfile_root: str | None = None) -> List[int]:
    ports = []
    while len(ports) < n:
        p = find_free_port(lockfile_root)
        if p not in ports:
            ports.append(p)
    return ports
