"""Colored, leveled logging. Parity target: ``realhf/base/logging.py``."""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s.%(msecs)03d %(name)s %(levelname)s: %(message)s"
_DATE = "%Y%m%d-%H:%M:%S"

_COLORS = {
    "DEBUG": "\033[36m",
    "INFO": "\033[32m",
    "WARNING": "\033[33m",
    "ERROR": "\033[31m",
    "CRITICAL": "\033[41m",
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record):
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelname, "")
            return f"{color}{msg}{_RESET}"
        return msg


_configured = False


def getLogger(name: str = "areal", subname: str | None = None) -> logging.Logger:
    global _configured
    if not _configured:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(_ColorFormatter(_FORMAT, _DATE))
        root = logging.getLogger("areal")
        root.addHandler(h)
        root.setLevel(os.environ.get("AREAL_LOG_LEVEL", "INFO").upper())
        root.propagate = False
        _configured = True
    if subname:
        name = f"{name}.{subname}"
    if not name.startswith("areal"):
        name = f"areal.{name}"
    return logging.getLogger(name)
