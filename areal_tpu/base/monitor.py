"""Analytic FLOPs / MFU accounting and experiment metric writers.

Parity target: ``realhf/base/monitor.py:288-330`` (llama-family analytic
FLOPs formulas feeding TFLOPs/GPU master logs) + the master's
wandb/swanlab/tensorboard init (``realhf/system/master_worker.py:291-350``)
+ ``realhf/system/flops_counter.py`` (per-MFC FLOPs sums). TPU differences:
peak-FLOPs table is per TPU generation (bf16), and the writers degrade
gracefully to tensorboard-only (wandb is optional on pods).
"""

from __future__ import annotations

from typing import Dict, Optional

# bf16 peak FLOP/s per chip by TPU generation (public spec sheet numbers).
TPU_PEAK_BF16 = {
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v4": 275e12, "v6e": 918e12, "v6": 918e12, "v5": 459e12,
}


def device_peak_flops(device_kind: Optional[str] = None) -> Optional[float]:
    if device_kind is None:
        import jax

        device_kind = str(jax.devices()[0])
    kind = device_kind.lower()
    return next((v for k, v in TPU_PEAK_BF16.items() if k in kind), None)


def transformer_flops_per_token(
    n_layers: int,
    hidden_dim: int,
    q_dim: int,
    kv_dim: int,
    intermediate_dim: int,
    vocab_size: int,
    avg_seqlen: float,
    backward: bool = True,
    remat: bool = False,
    moe=None,
) -> float:
    """Analytic FLOPs per token (llama formula family, reference
    monitor.py:288-330): matmul terms 2·m·n·k plus the attention-score
    quadratic term; backward ≈ 2× forward, or 3× forward under activation
    rematerialization (the forward is recomputed in the backward pass —
    reference checkpoint_activations_factor=4).

    ``moe`` (a models.config.MoEConfig or anything with its fields)
    switches the MLP term to ACTIVATED compute: each token runs top_k
    routed experts plus the router matmul plus the always-on shared
    expert — not all num_experts — so MoE MFU is measured against the
    FLOPs the token actually buys, matching activated_param_count
    (models/transformer.py)."""
    d, f = hidden_dim, intermediate_dim
    attn_proj = 2 * d * (q_dim + 2 * kv_dim) + 2 * q_dim * d
    attn_score = 2 * 2 * q_dim * avg_seqlen  # QK^T and PV, causal avg ≈ L/2·2
    if moe is not None:
        fr = moe.routed_intermediate_dim or f
        mlp = moe.top_k * 3 * 2 * d * fr + 2 * d * moe.num_experts
        if moe.shared_intermediate_dim:
            mlp += 3 * 2 * d * moe.shared_intermediate_dim
    else:
        mlp = 3 * 2 * d * f
    per_layer = attn_proj + attn_score + mlp
    head = 2 * d * vocab_size
    fwd = n_layers * per_layer + head
    if not backward:
        return fwd
    return fwd * (4.0 if remat else 3.0)


def train_flops_6nt(n_params: float, n_tokens: float) -> float:
    """The classic ``6·N·T`` train-FLOPs estimate (fwd 2·N·T + bwd 4·N·T)
    over parameter count alone — the roofline bench.py reports its MFU
    against. Coarser than :func:`model_flops_per_token` (no attention
    quadratic term, no remat factor) but geometry-free, which is what a
    cross-round trajectory number wants; both live HERE so bench.py and
    the live trainer gauges share one accounting (no duplicated
    formulas to drift apart)."""
    return 6.0 * float(n_params) * float(n_tokens)


def model_flops_per_token(
    cfg, avg_seqlen: float, backward: bool = True, remat: bool = False
) -> float:
    """FLOPs/token from a models.config.TransformerConfig."""
    return transformer_flops_per_token(
        cfg.n_layers, cfg.hidden_dim, cfg.q_dim, cfg.kv_dim,
        cfg.intermediate_dim, 1 if cfg.is_critic else cfg.vocab_size,
        avg_seqlen, backward=backward, remat=remat,
        moe=getattr(cfg, "moe", None),
    )


class FlopsCounter:
    """Per-step FLOPs sum over MFCs (reference flops_counter.py:15)."""

    def __init__(self):
        self.flops = 0.0

    def add_train(
        self, cfg, n_tokens: float, avg_seqlen: float, remat: bool = False
    ) -> None:
        self.flops += (
            model_flops_per_token(cfg, avg_seqlen, True, remat=remat)
            * n_tokens
        )

    def add_inf(self, cfg, n_tokens: float, avg_seqlen: float) -> None:
        self.flops += model_flops_per_token(cfg, avg_seqlen, False) * n_tokens

    def pop(self) -> float:
        f, self.flops = self.flops, 0.0
        return f


class MetricWriter:
    """Tensorboard (+ optional wandb) scalar writer for the master loop."""

    def __init__(self, tensorboard_path: Optional[str] = None,
                 wandb_mode: str = "disabled", wandb_kwargs=None):
        import threading

        # The telemetry aggregator's ingest thread mirrors worker scalars
        # into the same writer the master loop uses — SummaryWriter is not
        # thread-safe, so writes serialize (same fix class as the PR 3
        # evaluator writer lock).
        self._lock = threading.Lock()
        self._tb = None
        self._wandb = None
        if tensorboard_path:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=tensorboard_path)
            except Exception:  # pragma: no cover - tb optional
                pass
        if wandb_mode != "disabled":  # pragma: no cover - wandb optional
            try:
                import wandb

                wandb.init(mode=wandb_mode, **(wandb_kwargs or {}))
                self._wandb = wandb
            except Exception:
                pass

    def write(self, stats: Dict[str, float], step: int) -> None:
        with self._lock:
            if self._tb is not None:
                for k, v in stats.items():
                    self._tb.add_scalar(k, v, step)
                self._tb.flush()
            if self._wandb is not None:  # pragma: no cover
                self._wandb.log(stats, step=step)

    def close(self) -> None:
        with self._lock:
            if self._tb is not None:
                self._tb.close()
                self._tb = None
