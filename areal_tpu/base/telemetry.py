"""Unified telemetry: per-process metric registry, trace spans, cross-worker
aggregation, Prometheus rendering, and on-demand profiler capture.

The paper's core claim — fully-async rollout/training overlap hides
generation latency — is only checkable if queue depth, staleness lag,
weight-sync fanout latency, and the trainer's step-phase breakdown are
visible across the fleet *while it runs*. ``stats_tracker`` covers the
training-loss plane (per-step scoped reductions the master tabulates);
this module covers the *systems* plane on top of it:

 - :class:`TelemetryRegistry` — per-process counters (monotonic), gauges
   (last value), histograms (fixed buckets, Prometheus-style cumulative),
   and lightweight trace spans (id / parent-id / wall-times, nested via a
   contextvar so asyncio tasks and threads each get a correct parent
   chain).
 - :class:`TelemetryPusher` — background thread that snapshots the
   registry every ``flush_interval_secs`` and ZMQ-PUSHes it to the
   master, tagged ``(worker_kind, worker_index)``. Endpoint discovery is
   lazy (the aggregator may start after the worker); until it appears,
   snapshots accumulate spans up to a bounded buffer.
 - :class:`TelemetryAggregator` — master-side PULL endpoint (registered
   under ``names.telemetry_aggregator``) merging per-worker snapshots
   into one state keyed by ``worker_kind:worker_index``, appending every
   snapshot to ``telemetry.jsonl`` and mirroring scalars into a
   :class:`base.monitor.MetricWriter` tensorboard stream. With
   ``http_port > 0`` it also serves the merged fleet state as
   Prometheus text on ``GET /metrics``.
 - :func:`render_prometheus` — registry/plain-dict → Prometheus
   exposition text (the generation server and gserver manager serve it
   on their existing aiohttp apps).
 - Profiler trigger — :func:`request_profiler_capture` writes a
   name-resolve flag (``names.profiler_trigger``) that a trainer-side
   :class:`ProfilerTriggerWatcher` polls between serve iterations; on
   pickup it runs ``jax.profiler.start_trace/stop_trace`` for the
   requested window and reports under ``names.profiler_status``.

Disabled-by-default contract (tier-1 + bench honesty): until
:func:`configure` is called with an enabled config, the module-level API
(:func:`inc`, :func:`set_gauge`, :func:`observe`, :func:`span`) routes to
a shared null object — no locks taken beyond one attribute read, no ZMQ
sockets, no HTTP servers, no span allocation.
"""

from __future__ import annotations

import collections
import contextvars
import dataclasses
import itertools
import json
import os
import pickle
import signal
import sys
import threading
import time
import uuid
import weakref
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple

from areal_tpu.base import logging, name_resolve, names, network

logger = logging.getLogger("base.telemetry")

# Latency-shaped default buckets (seconds): 1ms .. ~2min, Prometheus-style.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

_span_ids = itertools.count(1)
# Current span id of the calling context (asyncio task / thread); copied
# into child tasks by asyncio, fresh (None) in new threads.
_CUR_SPAN: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "areal_tpu_cur_span", default=None
)


# --------------------------------------------------------------------------
# cross-worker trace context (sample-lineage tracing)
# --------------------------------------------------------------------------
#
# Dapper-style propagation: a rollout worker ORIGINATES a trace when a
# prompt is admitted; every RPC that serves that sample carries the
# (trace_id, parent span ref) pair — an HTTP header on /generate and
# /allocate_rollout, an optional ``_trace`` dict on the rollout→trainer
# push stream — and every receiving worker's spans link back to the
# remote parent. Span ids are only unique per process, so a remote
# parent is referenced by its GLOBAL ref ``worker_kind:worker_index/
# span_id`` — exactly the key the aggregator files the span under,
# which is what lets the master-side TraceStitcher join the pieces.


@dataclasses.dataclass
class TraceContext:
    """The portable part of a trace: which trace, and which remote span
    to hang the next child off."""

    trace_id: str
    parent_span: Optional[str] = None  # global ref "kind:idx/span_id"

    def as_dict(self) -> Dict[str, str]:
        d = {"trace_id": self.trace_id}
        if self.parent_span:
            d["parent_span"] = self.parent_span
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> Optional["TraceContext"]:
        tid = d.get("trace_id")
        if not tid:
            return None
        return cls(trace_id=str(tid),
                   parent_span=d.get("parent_span") or None)


_CUR_TRACE: contextvars.ContextVar[Optional[TraceContext]] = (
    contextvars.ContextVar("areal_tpu_cur_trace", default=None)
)

# Single wire header for both directions; value is "<trace_id>;<parent>"
# (the parent half may be empty). One header keeps the disabled-path
# contract trivially checkable: no trace ⇒ the header dict is empty ⇒
# the request bytes are identical to a build without tracing.
TRACE_HEADER = "X-Areal-Trace"
TRACE_FIELD = "_trace"  # optional key on pushed sample dicts (streams.py)


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def current_trace() -> Optional[TraceContext]:
    return _CUR_TRACE.get()


@contextmanager
def trace_scope(ctx: Optional[TraceContext]):
    """Adopt ``ctx`` (e.g. extracted from an incoming request) for the
    calling context; ``None`` is a no-op so call sites never branch."""
    if ctx is None:
        yield None
        return
    token = _CUR_TRACE.set(ctx)
    try:
        yield ctx
    finally:
        _CUR_TRACE.reset(token)


@contextmanager
def start_trace(trace_id: Optional[str] = None):
    """Originate a new trace (rollout worker, at prompt admission). With
    telemetry disabled this allocates nothing and yields None — spans
    stay un-traced and inject() stays empty."""
    if not _GLOBAL.enabled:
        yield None
        return
    ctx = TraceContext(trace_id=trace_id or new_trace_id())
    token = _CUR_TRACE.set(ctx)
    try:
        yield ctx
    finally:
        _CUR_TRACE.reset(token)


def _current_parent_ref(worker_ref: str,
                        ctx: TraceContext) -> Optional[str]:
    """The span ref a downstream child should link to: the caller's open
    span if there is one (qualified by this worker's identity), else
    whatever remote parent the context already carried."""
    sid = _CUR_SPAN.get()
    if sid is not None and worker_ref:
        return f"{worker_ref}/{sid}"
    return ctx.parent_span


def inject_headers() -> Dict[str, str]:
    """Trace context → HTTP headers. Empty dict when telemetry is
    disabled or no trace is active, so request bytes are unchanged."""
    ctx = _CUR_TRACE.get()
    if ctx is None or not _GLOBAL.enabled:
        return {}
    parent = _current_parent_ref(_GLOBAL.worker_ref, ctx) or ""
    return {TRACE_HEADER: f"{ctx.trace_id};{parent}"}


def extract_headers(headers) -> Optional[TraceContext]:
    """HTTP headers → TraceContext (None when absent/malformed)."""
    try:
        raw = headers.get(TRACE_HEADER)
    except Exception:  # noqa: BLE001 — header container without .get
        return None
    if not raw:
        return None
    tid, _, parent = str(raw).partition(";")
    if not tid:
        return None
    return TraceContext(trace_id=tid, parent_span=parent or None)


def inject_payload(obj: Any) -> Any:
    """Attach the active trace context to a ZMQ payload dict under
    ``_trace``. Returns ``obj`` untouched (same object, same bytes on
    the wire) when telemetry is disabled, no trace is active, or the
    payload is not a dict."""
    ctx = _CUR_TRACE.get()
    if ctx is None or not _GLOBAL.enabled or not isinstance(obj, dict):
        return obj
    parent = _current_parent_ref(_GLOBAL.worker_ref, ctx)
    obj[TRACE_FIELD] = TraceContext(ctx.trace_id, parent).as_dict()
    return obj


def extract_payload(obj: Any) -> Optional[TraceContext]:
    """Pop ``_trace`` off a payload dict (backward-compatible: absent
    field → None, payload otherwise untouched)."""
    if not isinstance(obj, dict):
        return None
    d = obj.pop(TRACE_FIELD, None)
    if not isinstance(d, dict):
        return None
    return TraceContext.from_dict(d)


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------


@dataclasses.dataclass
class Span:
    name: str
    span_id: int
    parent_id: Optional[int]
    t_start: float  # wall clock (time.time)
    dur_secs: float
    attrs: Dict[str, Any]
    # Sample-lineage tracing: which trace this span belongs to, and (for
    # a local root adopted from another worker) the remote parent's
    # global ref. None/absent for un-traced spans — the jsonl record
    # stays byte-identical to the pre-tracing format for them.
    trace_id: Optional[str] = None
    remote_parent: Optional[str] = None

    def as_dict(self) -> Dict[str, Any]:
        d = {
            "name": self.name, "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t_start": round(self.t_start, 6),
            "dur_secs": round(self.dur_secs, 6),
            "attrs": self.attrs,
        }
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
        if self.remote_parent is not None:
            d["remote_parent"] = self.remote_parent
        return d


class FlightRecorder:
    """Bounded ring of the most recent span/event records, kept OUTSIDE
    the flush-drained span buffer so the last moments before a crash are
    always reconstructible. Dumped to ``flight_<worker>.jsonl`` on
    SIGTERM/uncaught exception (when ``flight_dir`` is configured), on
    operator request (``names.flight_dump_trigger``, mirroring the
    profiler-trigger pattern), or explicitly (manager eviction path)."""

    def __init__(self, maxlen: int = 512):
        self._lock = threading.Lock()
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=maxlen
        )

    def record(self, kind: str, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append({"kind": kind, **rec})

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def dump(self, path: str, reason: str = "") -> int:
        """Write the ring (oldest first) + a terminal marker record.
        Signal-safe enough: plain buffered writes, no locks held while
        touching the filesystem beyond the snapshot copy."""
        recs = self.snapshot()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
            f.write(json.dumps({
                "kind": "dump", "reason": reason,
                "time": round(time.time(), 6), "n_records": len(recs),
            }) + "\n")
        return len(recs)


class _Histogram:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float]):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # trailing +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.sum += v
        self.count += 1
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def as_dict(self) -> Dict[str, Any]:
        return {
            "buckets": list(self.buckets), "counts": list(self.counts),
            "sum": self.sum, "count": self.count,
        }


class TelemetryRegistry:
    """Thread-safe per-process metric + span store.

    Counters/gauges/histograms are CUMULATIVE — a flush (or a Prometheus
    scrape) never resets them, so scraped counters stay monotonic and
    concurrent exporters cannot race each other's resets. Spans are the
    only drained state: ``snapshot(reset=True)`` hands back the buffered
    spans and clears the buffer (bounded by ``max_spans``; oldest drop
    first so a stalled aggregator cannot OOM a worker).
    """

    def __init__(self, max_spans: int = 4096):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, _Histogram] = {}
        self._spans: List[Span] = []
        self.max_spans = max_spans
        self.dropped_spans = 0
        # Optional crash-evidence ring (set by Telemetry when enabled):
        # finished spans/events are mirrored here, never drained.
        self.flight: Optional[FlightRecorder] = None

    # ---- metrics ----

    def inc(self, name: str, n: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + n

    def set_gauge(self, name: str, v: float) -> None:
        with self._lock:
            self._gauges[name] = float(v)

    def remove_gauge(self, name: str) -> None:
        """Withdraw a gauge from the exposition entirely. For derived
        gauges whose SUBJECT can disappear (a fleet side with no live
        workers): a frozen last value would lie on the scrape, and
        publishing 0.0 instead would read as a real collapse."""
        with self._lock:
            self._gauges.pop(name, None)

    def observe(self, name: str, v: float,
                buckets: Optional[Sequence[float]] = None) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram(buckets or DEFAULT_BUCKETS)
            h.observe(float(v))

    # ---- spans ----

    def _store_span(self, s: Span) -> None:
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self._spans.pop(0)
                self.dropped_spans += 1
                # First-class drop counter (Prometheus:
                # areal_telemetry_spans_dropped_total) so truncated
                # traces are detectable, not silent. Direct dict write:
                # inc() would re-take the held lock.
                self._counters["telemetry/spans_dropped"] = (
                    self._counters.get("telemetry/spans_dropped", 0.0) + 1
                )
            self._spans.append(s)
        if self.flight is not None:
            self.flight.record("span", s.as_dict())
        # Every span doubles as a duration histogram point, so the
        # aggregate view exists even when span volume forces drops.
        self.observe(f"{s.name}/secs", s.dur_secs)

    @contextmanager
    def span(self, name: str, **attrs):
        sid = next(_span_ids)
        parent = _CUR_SPAN.get()
        trace = _CUR_TRACE.get()
        token = _CUR_SPAN.set(sid)
        t_wall = time.time()
        t0 = time.monotonic()
        try:
            yield attrs  # callers may add attrs["key"] = ... mid-span
        finally:
            _CUR_SPAN.reset(token)
            s = Span(name=name, span_id=sid, parent_id=parent,
                     t_start=t_wall, dur_secs=time.monotonic() - t0,
                     attrs=attrs)
            if trace is not None:
                s.trace_id = trace.trace_id
                if parent is None:
                    # Local root of a distributed trace: link to the
                    # remote span that caused this work.
                    s.remote_parent = trace.parent_span
            self._store_span(s)

    def add_span(self, name: str, t_start: float, dur_secs: float,
                 trace: Optional[TraceContext] = None,
                 parent_id: Optional[int] = None, **attrs) -> int:
        """Record a span whose window was measured by the caller (queue
        waits, per-request shares of a batched decode, terminal
        trained-sample marks). ``t_start`` is wall-clock (time.time).
        Parents under the caller's open span when there is one; a local
        root instead links to the trace's remote parent. Returns the
        span id so callers can chain children off it."""
        sid = next(_span_ids)
        if parent_id is None:
            parent_id = _CUR_SPAN.get()
        s = Span(name=name, span_id=sid, parent_id=parent_id,
                 t_start=t_start, dur_secs=float(dur_secs), attrs=attrs)
        if trace is not None:
            s.trace_id = trace.trace_id
            if parent_id is None:
                s.remote_parent = trace.parent_span
        self._store_span(s)
        return sid

    def event(self, name: str, **attrs) -> None:
        """Point-in-time record (failover fired, 429 backoff, eviction):
        a zero-duration span — it rides the same flush/stitch path and
        lands in the flight ring — under the ACTIVE trace context and
        nested below the caller's open span (if any)."""
        self.add_span(name, time.time(), 0.0, trace=_CUR_TRACE.get(),
                      parent_id=_CUR_SPAN.get(), **attrs)

    # ---- export ----

    def snapshot(self, reset: bool = True) -> Dict[str, Any]:
        with self._lock:
            out = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "hists": {k: h.as_dict() for k, h in self._hists.items()},
                "spans": [s.as_dict() for s in self._spans],
                "dropped_spans": self.dropped_spans,
            }
            if reset:
                self._spans = []
        return out


# --------------------------------------------------------------------------
# Prometheus rendering
# --------------------------------------------------------------------------


def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    s = "".join(out)
    return ("_" + s) if s and s[0].isdigit() else (s or "_")


def _metric_key_labels(key: str):
    """Split an optional inline label suffix off a registry metric key:
    ``supervisor/restarts{worker_kind=rollout}`` → (``supervisor/restarts``,
    {"worker_kind": "rollout"}). Lets call sites emit one metric FAMILY
    with several label values (the Prometheus idiom) through the flat
    string-keyed registry; keys without a suffix return (key, None)."""
    if not key.endswith("}"):
        return key, None
    base, brace, rest = key.partition("{")
    if not brace:
        return key, None
    labels: Dict[str, str] = {}
    for part in rest[:-1].split(","):
        k, eq, v = part.partition("=")
        if eq:
            labels[k.strip()] = v.strip().strip('"')
    return base, (labels or None)


def _prom_labels(labels: Optional[Dict[str, str]],
                 extra: Optional[Dict[str, str]] = None) -> str:
    merged = {**(labels or {}), **(extra or {})}
    if not merged:
        return ""

    def esc(v) -> str:
        # Exposition-format escaping for label values: backslash FIRST
        # (or it would double-escape the others), then quote, then
        # newline — an unescaped newline splits the sample line in two
        # and the scraper rejects the whole exposition.
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    inner = ",".join(
        f'{_prom_name(k)}="{esc(v)}"' for k, v in sorted(merged.items())
    )
    return "{" + inner + "}"


def render_prometheus(
    snapshot: Optional[Dict[str, Any]] = None,
    extra_gauges: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    prefix: str = "areal",
) -> str:
    """Registry snapshot (+ ad-hoc gauges) → Prometheus exposition text.

    ``extra_gauges`` lets HTTP workers export live object state (queue
    sizes, versions) without mirroring it into the registry first. Values
    that are None or non-numeric are skipped.
    """
    lines: List[str] = []
    snapshot = snapshot or {}
    lab = _prom_labels(labels)
    typed = set()  # one # TYPE line per family, even with inline labels

    def emit(name: str, kind: str, value: float,
             label_str: Optional[str] = None) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name}{lab if label_str is None else label_str} "
                     f"{float(value):g}")

    emitted = set()
    for k, v in sorted((extra_gauges or {}).items()):
        if isinstance(v, bool):
            v = float(v)
        if not isinstance(v, (int, float)):
            continue  # None / strings have no Prometheus representation
        name = f"{prefix}_{_prom_name(k)}"
        emitted.add(name)
        emit(name, "gauge", float(v))
    for k, v in sorted(snapshot.get("gauges", {}).items()):
        base, kl = _metric_key_labels(k)
        name = f"{prefix}_{_prom_name(base)}"
        if name in emitted and kl is None:
            # extra_gauges win: a registry gauge sanitizing to the same
            # name (e.g. genserver/weight_version vs the live-state
            # gauge) must not produce a duplicate Prometheus sample.
            continue
        emit(name, "gauge", v,
             label_str=_prom_labels(labels, kl) if kl else None)
    for k, v in sorted(snapshot.get("counters", {}).items()):
        base, kl = _metric_key_labels(k)
        emit(f"{prefix}_{_prom_name(base)}_total", "counter", v,
             label_str=_prom_labels(labels, kl) if kl else None)
    for k, h in sorted(snapshot.get("hists", {}).items()):
        kbase, kl = _metric_key_labels(k)
        base = f"{prefix}_{_prom_name(kbase)}"
        if base not in typed:
            typed.add(base)
            lines.append(f"# TYPE {base} histogram")
        merged = {**(labels or {}), **(kl or {})}
        hlab = _prom_labels(merged) if merged else ""
        cum = 0
        for b, c in zip(h["buckets"], h["counts"]):
            cum += c
            lstr = _prom_labels(merged, {"le": f"{float(b):g}"})
            lines.append(f"{base}_bucket{lstr} {cum}")
        cum += h["counts"][-1]
        lines.append(f"{base}_bucket{_prom_labels(merged, {'le': '+Inf'})} "
                     f"{cum}")
        lines.append(f"{base}_sum{hlab} {h['sum']:g}")
        lines.append(f"{base}_count{hlab} {h['count']}")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# pusher (worker side)
# --------------------------------------------------------------------------


class TelemetryPusher:
    """Flush a registry to the master's aggregator on an interval.

    Discovery is lazy and non-fatal: the PUSH socket connects the first
    time ``names.telemetry_aggregator`` resolves; until then flushes are
    skipped (spans stay buffered in the registry, bounded)."""

    def __init__(self, registry: TelemetryRegistry, experiment: str,
                 trial: str, worker_kind: str, worker_index: int = 0,
                 flush_interval_secs: float = 2.0):
        self.registry = registry
        self.worker_kind = worker_kind
        self.worker_index = worker_index
        self.flush_interval_secs = flush_interval_secs
        self._key = names.telemetry_aggregator(experiment, trial)
        self._flight_key = names.flight_dump_trigger(experiment, trial)
        self._flight_nonce: Optional[str] = None  # last handled trigger
        self._t_start_wall = time.time()  # gates stale-trigger replay
        self._sock = None
        self._flush_lock = threading.Lock()  # socket use is single-file
        self._pending: Optional[bytes] = None  # unsent snapshot (backlog)
        self._closing = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"telemetry-push-{worker_kind}{worker_index}",
        )
        self._thread.start()

    def _connect(self) -> bool:
        if self._sock is not None:
            return True
        try:
            addr = name_resolve.get(self._key)
        except Exception:  # noqa: BLE001 — aggregator not up yet
            return False
        import zmq

        self._sock = zmq.Context.instance().socket(zmq.PUSH)
        self._sock.setsockopt(zmq.SNDHWM, 64)
        self._sock.setsockopt(zmq.LINGER, 0)
        self._sock.connect(addr)
        return True

    def flush(self) -> bool:
        """One snapshot push; returns False when no aggregator is known or
        it is backlogged. A snapshot that cannot be sent is kept (and the
        registry is NOT drained again until it goes out), so a stalled
        aggregator loses no spans — exactly the incident window an
        operator will want to see. The registry's bounded span buffer is
        the backstop if the outage outlasts ``max_buffered_spans``."""
        import zmq

        with self._flush_lock:
            if not self._connect():
                return False
            if self._pending is not None:
                try:
                    self._sock.send(self._pending, zmq.NOBLOCK)
                except zmq.Again:
                    return False  # still backlogged; nothing drained
                self._pending = None
            payload = pickle.dumps({
                "worker_kind": self.worker_kind,
                "worker_index": self.worker_index,
                "time": time.time(),
                **self.registry.snapshot(reset=True),
            })
            try:
                self._sock.send(payload, zmq.NOBLOCK)
            except zmq.Again:
                self._pending = payload
                return False
        return True

    def check_flight_trigger(self) -> Optional[str]:
        """On-demand flight dump (profiler-trigger pattern, but fan-out:
        the flag is NOT consumed — every worker acts on it once, keyed by
        its nonce, so one trigger dumps the whole fleet's rings). Returns
        the written path when this call dumped."""
        if self.registry.flight is None:
            return None
        try:
            raw = name_resolve.get(self._flight_key)
        except Exception:  # noqa: BLE001 — no trigger pending
            return None
        try:
            req = json.loads(raw)
            nonce = str(req.get("nonce", ""))
            if not nonce or nonce == self._flight_nonce:
                return None
            self._flight_nonce = nonce
            if float(req.get("time", 0.0)) < self._t_start_wall:
                # The flag predates this worker (it is deliberately not
                # consumed so the whole fleet can act on it) — a freshly
                # (re)started worker must not replay it and overwrite
                # the incident evidence with its near-empty ring.
                return None
            path = os.path.join(
                req["dir"],
                f"flight_{self.worker_kind}{self.worker_index}.jsonl",
            )
            n = self.registry.flight.dump(path, reason=f"trigger:{nonce}")
            logger.info(f"flight dump ({n} records) -> {path}")
            return path
        except Exception as e:  # noqa: BLE001 — telemetry never kills
            logger.warning(f"flight dump trigger failed: {e}")
            return None

    def _loop(self) -> None:
        while not self._closing.wait(self.flush_interval_secs):
            try:
                self.flush()
                self.check_flight_trigger()
            except Exception as e:  # noqa: BLE001 — telemetry never kills
                logger.warning(f"telemetry flush failed: {e}")

    def close(self) -> None:
        # ZMQ sockets are not thread-safe: stop the flush thread BEFORE
        # touching the socket from this thread. If the join times out
        # (thread wedged mid-flush), leak the socket to the daemon thread
        # rather than race it — the process is exiting anyway.
        self._closing.set()
        self._thread.join(timeout=2)
        if self._thread.is_alive():
            return
        try:
            self.flush()  # final snapshot (best-effort)
        except Exception:  # noqa: BLE001
            pass
        if self._sock is not None:
            self._sock.close(linger=0)
            self._sock = None


# --------------------------------------------------------------------------
# trace stitching (master side)
# --------------------------------------------------------------------------

# prompt→trained latencies live on a longer scale than RPCs.
E2E_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
               120.0, 300.0, 600.0)

# Span name → stage of the measured staleness decomposition. The
# "train" stage is the triggering terminal span alone (a group's other
# samples have their own terminals), and "train_wait" is derived
# (terminal start − rollout end), so neither lives in this map.
STAGE_OF_SPAN = {
    "rollout/gate": "gate",
    "rollout/generate": "generate",
    "genserver/queue_wait": "queue",
}
TERMINAL_SPAN = "trainer/train_sample"
TRACE_STAGES = ("generate", "queue", "gate", "train_wait", "train")


@dataclasses.dataclass
class _TraceEntry:
    spans: List[Dict] = dataclasses.field(default_factory=list)
    stitched: bool = False  # at least one terminal already processed


class TraceStitcher:
    """Joins spans by trace_id across workers into end-to-end sample
    timelines.

    Fed from the aggregator's ingest path; spans carrying a ``trace_id``
    are buffered per trace (bounded LRU — a trace whose terminal span
    never arrives, e.g. an abandoned rollout, eventually falls off and
    is counted in ``trace/unstitched_evicted``; traces that already
    stitched age out silently). A TERMINAL span (``trainer/train_sample``)
    schedules a stitch after ``grace_secs`` — sibling workers flush on
    their own ``flush_interval_secs`` cadence, so stitching immediately
    would record a truncated timeline whenever the trainer's snapshot
    outruns the rollout worker's. ``tick()`` (called from the
    aggregator's ingest loop, and with ``force=True`` on close) performs
    the due stitches: one record appended to ``traces.jsonl`` PER
    TRAINED SAMPLE and the derived first-class metrics — prompt→trained
    e2e latency and the per-stage generate/queue/gate/train-wait/train
    breakdown, one observation per trained sample — observed into
    ``registry`` (exported by the aggregator's /metrics).
    ``trace/stitched`` counts unique completed traces (prompts);
    per-sample multiplicity is visible as the e2e histogram count."""

    def __init__(self, traces_path: Optional[str],
                 registry: Optional[TelemetryRegistry] = None,
                 max_traces: int = 1024, grace_secs: float = 5.0):
        self.registry = registry or TelemetryRegistry()
        self.max_traces = max_traces
        self.grace_secs = grace_secs
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, _TraceEntry]" = (
            collections.OrderedDict()
        )
        # (due_monotonic, trace_id, terminal span) awaiting their grace.
        self._deferred: List[Tuple[float, str, Dict]] = []
        self._file = None
        if traces_path:
            os.makedirs(os.path.dirname(traces_path) or ".", exist_ok=True)
            self._file = open(traces_path, "a", buffering=1)

    def feed(self, worker: str, spans: Sequence[Dict[str, Any]]) -> None:
        now = time.monotonic()
        with self._lock:
            for s in spans:
                tid = s.get("trace_id")
                if not tid:
                    continue
                rec = {**s, "worker": worker}
                entry = self._traces.get(tid)
                if entry is None:
                    entry = self._traces[tid] = _TraceEntry()
                self._traces.move_to_end(tid)
                entry.spans.append(rec)
                if s.get("name") == TERMINAL_SPAN:
                    self._deferred.append(
                        (now + self.grace_secs, tid, rec)
                    )
            scanned = 0
            while (len(self._traces) > self.max_traces
                   and scanned <= self.max_traces):
                tid, old = self._traces.popitem(last=False)
                scanned += 1
                if not old.stitched and any(
                    d[1] == tid for d in self._deferred
                ):
                    # Terminal already arrived; its stitch is merely
                    # waiting out the grace window — evicting now would
                    # silently drop a COMPLETED trace. Keep it (at MRU)
                    # until tick() stitches it.
                    self._traces[tid] = old
                    continue
                if not old.stitched:
                    # Only a trace that never saw a terminal span is a
                    # loss signal (abandoned rollout / dropped spans);
                    # completed traces aging out is normal turnover.
                    self.registry.inc("trace/unstitched_evicted")
        self.tick()

    def tick(self, force: bool = False) -> None:
        """Stitch every deferred terminal whose grace elapsed (all of
        them with ``force=True`` — shutdown must not drop stragglers)."""
        now = time.monotonic()
        with self._lock:
            due = [d for d in self._deferred if force or d[0] <= now]
            if not due:
                return
            self._deferred = [d for d in self._deferred
                              if not (force or d[0] <= now)]
        for _, tid, term in due:
            self._stitch(tid, term)

    def _stitch(self, trace_id: str, terminal: Dict[str, Any]) -> None:
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                return  # evicted before its grace elapsed
            first = not entry.stitched
            entry.stitched = True
            spans = sorted(entry.spans, key=lambda s: s["t_start"])
        root_start = min(s["t_start"] for s in spans)
        e2e = max(terminal["t_start"] + terminal["dur_secs"] - root_start,
                  0.0)
        stages = {k: 0.0 for k in TRACE_STAGES}
        # "train" is THIS sample's terminal alone — a group's sibling
        # samples stitch separately with their own terminals.
        stages["train"] = terminal["dur_secs"]
        rollout_end = None
        for s in spans:
            stage = STAGE_OF_SPAN.get(s["name"])
            if stage:
                stages[stage] += s["dur_secs"]
            if s["name"] == "rollout/rollout":
                rollout_end = s["t_start"] + s["dur_secs"]
        if rollout_end is not None:
            # Time between the sample leaving the rollout worker and the
            # trainer step that consumed it: the stream + buffer + MFC
            # gate wait — the part of staleness training speed controls.
            stages["train_wait"] = max(
                terminal["t_start"] - rollout_end, 0.0
            )
        r = self.registry
        if first:
            r.inc("trace/stitched")  # unique completed traces
        r.observe("trace/e2e_secs", e2e, buckets=E2E_BUCKETS)
        for k, v in stages.items():
            r.observe(f"trace/stage_{k}_secs", v, buckets=E2E_BUCKETS)
        if self._file is not None:
            self._file.write(json.dumps({
                "trace_id": trace_id,
                "sample_id": terminal.get("attrs", {}).get("sample_id"),
                "weight_version": terminal.get("attrs", {})
                                          .get("weight_version"),
                "t_start": round(root_start, 6),
                "e2e_secs": round(e2e, 6),
                "stages": {k: round(v, 6) for k, v in stages.items()},
                "workers": sorted({s["worker"] for s in spans}),
                "spans": spans,
            }) + "\n")

    def recent_trace_ids(self, n: int = 8) -> List[str]:
        """The most recently touched trace ids (newest last) — the
        sentinel pins these into alert evidence bundles so the operator
        can replay the samples that were in flight when an anomaly
        fired (tools/perf_probe.py trace <traces.jsonl> <id>)."""
        with self._lock:
            return list(self._traces)[-max(int(n), 0):]

    def close(self) -> None:
        self.tick(force=True)
        if self._file is not None:
            self._file.close()
            self._file = None


# --------------------------------------------------------------------------
# aggregator (master side)
# --------------------------------------------------------------------------


class TelemetryAggregator:
    """PULL-side merge of per-worker snapshots keyed by
    ``worker_kind:worker_index``; every received snapshot is appended to
    ``telemetry.jsonl`` and its scalars mirrored into ``metric_writer``
    (tensorboard) as ``telemetry/{worker}/{metric}``."""

    def __init__(self, experiment: str, trial: str,
                 jsonl_path: Optional[str] = None,
                 metric_writer=None, http_port: int = 0,
                 traces_path: Optional[str] = None,
                 stitch_grace_secs: float = 5.0,
                 sentinel=None, goodput=None):
        import zmq

        self.jsonl_path = jsonl_path
        # Optional training-health sentinel (system/sentinel.Sentinel):
        # fed every ingested snapshot's gauges/counters and ticked from
        # the ingest loop — it owns no thread of its own. None (the
        # default) leaves ingest and the merged scrape bit-identical.
        self.sentinel = sentinel
        # Optional fleet-goodput stitcher (system/goodput.FleetGoodput):
        # fed every ingested snapshot's ledger counters; its derived
        # gauges join the merged scrape as the "fleet" pseudo-worker and
        # land in telemetry.jsonl on a slow cadence. None (the default)
        # leaves ingest and the scrape bit-identical.
        self.goodput = goodput
        self._last_fleet_rec = 0.0
        self._writer = metric_writer
        self._seq = 0
        self.state: Dict[str, Dict[str, Any]] = {}
        self._state_lock = threading.Lock()
        self._experiment, self._trial = experiment, trial
        # Sample-lineage stitching: spans with a trace_id are joined into
        # traces.jsonl (default: next to telemetry.jsonl) and the derived
        # e2e/stage histograms live in the aggregator's OWN registry,
        # exported under worker_kind="aggregator" on /metrics.
        if traces_path is None and jsonl_path:
            traces_path = os.path.join(
                os.path.dirname(jsonl_path) or ".", "traces.jsonl"
            )
        self.traces_path = traces_path
        self.stitcher = TraceStitcher(traces_path,
                                      grace_secs=stitch_grace_secs)
        if self.sentinel is not None \
                and getattr(self.sentinel, "stitcher", None) is None:
            # Evidence bundles pin recent stitched trace ids.
            self.sentinel.stitcher = self.stitcher
        self._sock = zmq.Context.instance().socket(zmq.PULL)
        self._sock.setsockopt(zmq.RCVHWM, 4096)
        port = self._sock.bind_to_random_port(f"tcp://{network.bind_addr()}")
        self._key = names.telemetry_aggregator(experiment, trial)
        name_resolve.add(self._key, network.advertised_tcp(port),
                         replace=True)
        self._jsonl_file = None
        if jsonl_path:
            os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
            self._jsonl_file = open(jsonl_path, "a", buffering=1)
        self._closing = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="telemetry-aggregate"
        )
        self._thread.start()
        self._http = None
        if http_port:
            self._start_http(http_port)
        logger.info(f"telemetry aggregator up (jsonl={jsonl_path})")

    # ---- ingest ----

    def _ingest(self, payload: Dict[str, Any]) -> None:
        worker = f"{payload.get('worker_kind', '?')}:" \
                 f"{payload.get('worker_index', 0)}"
        self._derive_hbm_utilization(payload)
        with self._state_lock:
            prev = self.state.get(worker)
            spans = payload.get("spans", [])
            merged = {
                "time": payload.get("time"),
                "counters": payload.get("counters", {}),
                "gauges": payload.get("gauges", {}),
                "hists": payload.get("hists", {}),
                "n_spans": (prev["n_spans"] if prev else 0) + len(spans),
                "last_spans": spans or (prev["last_spans"] if prev else []),
            }
            self.state[worker] = merged
            self._seq += 1
            seq = self._seq
        self.stitcher.feed(worker, spans)
        if self.sentinel is not None:
            try:
                # Full "kind:index" identity: same-kind workers must be
                # DISTINCT sources or cross-worker agg (max/mean/sum)
                # collapses to whichever worker pushed last.
                self.sentinel.feed(
                    worker,
                    payload.get("gauges", {}),
                    payload.get("counters", {}),
                )
            except Exception as e:  # noqa: BLE001 — watcher never kills
                logger.warning(f"sentinel feed failed: {e}")
        if self.goodput is not None:
            try:
                fg = self.goodput.update(worker,
                                         payload.get("counters", {}))
                if fg:
                    if self.sentinel is not None:
                        # Fleet goodput is derived HERE, not flushed by
                        # any worker — feed it to the sentinel under its
                        # own source identity so goodput_collapse-style
                        # rules see the series. UNLABELED keys only: the
                        # sentinel folds {side=...} variants into the
                        # same family, and averaging the overall with
                        # the per-side splits would mis-weight the sides
                        # (and step-change when a side appears/expires).
                        self.sentinel.feed("fleet:0", {
                            k: v for k, v in fg.items() if "{" not in k
                        })
                    now = time.monotonic()
                    if self._jsonl_file is not None \
                            and now - self._last_fleet_rec > 5.0:
                        # Slow-cadence fleet record so telemetry.jsonl
                        # carries the stitched number without doubling
                        # the per-snapshot volume.
                        self._last_fleet_rec = now
                        # Same record shape as the per-worker snapshots
                        # so jsonl consumers never special-case the
                        # fleet row.
                        self._jsonl_file.write(json.dumps({
                            "worker": "fleet:0", "time": time.time(),
                            "counters": {}, "gauges": fg, "spans": [],
                            "dropped_spans": 0, "hists": {},
                        }) + "\n")
            except Exception as e:  # noqa: BLE001 — derived, never kills
                logger.warning(f"fleet goodput update failed: {e}")
        if self._jsonl_file is not None:
            rec = {"worker": worker, **{
                k: payload.get(k) for k in
                ("time", "counters", "gauges", "spans", "dropped_spans")
            }, "hists": payload.get("hists", {})}
            self._jsonl_file.write(json.dumps(rec) + "\n")
        if self._writer is not None:
            flat = {
                **{f"telemetry/{worker}/{k}": v
                   for k, v in merged["counters"].items()},
                **{f"telemetry/{worker}/{k}": v
                   for k, v in merged["gauges"].items()},
            }
            if flat:
                try:
                    self._writer.write(flat, seq)
                except Exception:  # noqa: BLE001 — TB is best-effort
                    pass

    @staticmethod
    def _derive_hbm_utilization(payload: Dict[str, Any]) -> None:
        """Inject per-device ``hbm/utilization{device=i}`` =
        bytes_in_use / limit_bytes into a snapshot that carries both
        memwatch gauges (system/memwatch.py) — derived HERE because only
        the aggregator-side series feeds the ``hbm_pressure`` sentinel
        rule as a ready-made ratio. No hbm gauges in the payload ⇒ no
        mutation at all: with the observatory disabled the merged scrape
        stays bit-identical."""
        gauges = payload.get("gauges")
        if not gauges:
            return
        limits = {}
        for k, v in gauges.items():
            base, labels = _metric_key_labels(k)
            if base == "hbm/limit_bytes" and labels \
                    and isinstance(v, (int, float)) and v > 0:
                limits[labels.get("device")] = float(v)
        if not limits:
            return
        derived = {}
        for k, v in gauges.items():
            base, labels = _metric_key_labels(k)
            dev = labels.get("device") if labels else None
            if base == "hbm/bytes_in_use" and dev in limits \
                    and isinstance(v, (int, float)):
                derived[f"hbm/utilization{{device={dev}}}"] = \
                    float(v) / limits[dev]
        gauges.update(derived)

    def _loop(self) -> None:
        while not self._closing.is_set():
            try:
                if self._sock.poll(100):
                    self._ingest(pickle.loads(self._sock.recv()))
                # Deferred stitches come due on wall time, not on new
                # snapshots — run them on idle poll timeouts too. Same
                # for the sentinel: absence-of-signal rules and `for:`
                # windows elapse without any snapshot arriving.
                self.stitcher.tick()
                if self.sentinel is not None:
                    self.sentinel.tick()
            except Exception as e:  # noqa: BLE001 — aggregator must survive
                if not self._closing.is_set():
                    logger.warning(f"telemetry ingest failed: {e}")

    def set_metric_writer(self, writer) -> None:
        """Attach (or swap) the tensorboard mirror after construction —
        the master builds its MetricWriter later in setup."""
        self._writer = writer

    # ---- views ----

    def merged(self) -> Dict[str, Dict[str, Any]]:
        with self._state_lock:
            return {k: dict(v) for k, v in self.state.items()}

    def render_prometheus(self) -> str:
        """Merged fleet state as ONE valid exposition: samples of the same
        metric family (e.g. two rollout workers' gauges) are grouped under
        a single ``# TYPE`` line — concatenating per-worker renderings
        would emit duplicate TYPE lines, which expfmt-based consumers
        (promtool etc.) reject wholesale."""
        fams: Dict[str, Dict[str, Any]] = {}

        def add(name: str, kind: str, line: str) -> None:
            fams.setdefault(name, {"kind": kind, "lines": []})["lines"] \
                .append(line)

        rows = dict(self.merged())
        # Derived trace metrics (prompt→trained e2e + stage breakdown)
        # join the fleet exposition as their own pseudo-worker.
        stitched = self.stitcher.registry.snapshot(reset=False)
        if stitched["counters"] or stitched["hists"]:
            rows["aggregator:0"] = stitched
        if self.sentinel is not None:
            # areal_alerts_total{rule,severity} + areal_alert_active join
            # the merged exposition as the sentinel pseudo-worker.
            sn = self.sentinel.registry.snapshot(reset=False)
            if sn["counters"] or sn["gauges"]:
                rows["sentinel:0"] = sn
        goodput = getattr(self, "goodput", None)  # duck-typed in tests
        if goodput is not None:
            # areal_fleet_goodput{side=...} joins the merged exposition
            # as the fleet pseudo-worker (system/goodput.FleetGoodput).
            fg = goodput.registry.snapshot(reset=False)
            if fg["gauges"]:
                rows["fleet:0"] = fg
        # Fleet rollups for the compile & HBM observatory: the total
        # compile seconds burned across every worker, and the worst HBM
        # utilization per worker kind (the capacity-planning numbers an
        # operator wants without a PromQL layer). Appended ONLY when the
        # source series exist — with compile_watch disabled nothing is
        # added and the scrape stays bit-identical.
        compile_secs = 0.0
        any_compile = False
        hbm_util: Dict[str, float] = {}
        for worker, st in rows.items():
            kind = worker.partition(":")[0]
            for k, v in st.get("counters", {}).items():
                if _metric_key_labels(k)[0] == "compile/secs":
                    compile_secs += float(v)
                    any_compile = True
            for k, v in st.get("gauges", {}).items():
                if _metric_key_labels(k)[0] == "hbm/utilization":
                    hbm_util[kind] = max(hbm_util.get(kind, 0.0), float(v))
        if any_compile:
            ls = _prom_labels({"worker_kind": "fleet", "worker_index": "0"})
            add("areal_compile_secs_total", "counter",
                f"areal_compile_secs_total{ls} {compile_secs:g}")
        for kind in sorted(hbm_util):
            ls = _prom_labels({"worker_kind": kind, "worker_index": "fleet"})
            add("areal_hbm_utilization", "gauge",
                f"areal_hbm_utilization{ls} {hbm_util[kind]:g}")
        for worker, st in sorted(rows.items()):
            kind, _, idx = worker.partition(":")
            labels = {"worker_kind": kind, "worker_index": idx}
            lab = _prom_labels(labels)
            for k, v in sorted(st["gauges"].items()):
                kb, kl = _metric_key_labels(k)
                n = f"areal_{_prom_name(kb)}"
                ls = _prom_labels(labels, kl) if kl else lab
                add(n, "gauge", f"{n}{ls} {float(v):g}")
            for k, v in sorted(st["counters"].items()):
                kb, kl = _metric_key_labels(k)
                n = f"areal_{_prom_name(kb)}_total"
                ls = _prom_labels(labels, kl) if kl else lab
                add(n, "counter", f"{n}{ls} {float(v):g}")
            for k, h in sorted(st["hists"].items()):
                kb, kl = _metric_key_labels(k)
                base = f"areal_{_prom_name(kb)}"
                hlabels = {**labels, **(kl or {})}
                hlab = _prom_labels(hlabels)
                cum = 0
                for b, c in zip(h["buckets"], h["counts"]):
                    cum += c
                    ls = _prom_labels(hlabels, {"le": f"{float(b):g}"})
                    add(base, "histogram", f"{base}_bucket{ls} {cum}")
                cum += h["counts"][-1]
                ls = _prom_labels(hlabels, {"le": "+Inf"})
                add(base, "histogram", f"{base}_bucket{ls} {cum}")
                add(base, "histogram", f"{base}_sum{hlab} {h['sum']:g}")
                add(base, "histogram", f"{base}_count{hlab} {h['count']}")
        if not fams:
            return "# no telemetry received yet\n"
        out: List[str] = []
        for name in sorted(fams):
            out.append(f"# TYPE {name} {fams[name]['kind']}")
            out.extend(fams[name]["lines"])
        return "\n".join(out) + "\n"

    # ---- optional unified /metrics over plain http ----

    def _start_http(self, port: int) -> None:
        import http.server

        agg = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = agg.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # noqa: D102 — silence stdlib logs
                pass

        self._http = http.server.ThreadingHTTPServer(
            (network.bind_addr(), port), Handler
        )
        threading.Thread(target=self._http.serve_forever, daemon=True,
                         name="telemetry-http").start()
        # Advertise the merged endpoint so jax-free tools (perf_probe
        # scrape <exp> <trial>) can find it without knowing the port.
        self._http_key = names.telemetry_http(self._experiment, self._trial)
        name_resolve.add(
            self._http_key,
            f"http://{network.gethostip()}:{port}", replace=True,
        )

    def close(self) -> None:
        # ZMQ sockets are not thread-safe: stop the ingest thread BEFORE
        # this thread touches the socket for the final drain. A wedged
        # ingest thread (slow tensorboard/NFS write) keeps the socket —
        # skip the drain rather than race a live poll/recv.
        self._closing.set()
        self._thread.join(timeout=2)
        try:
            name_resolve.delete(self._key)
        except Exception:  # noqa: BLE001 — already gone / repo reset
            pass
        if not self._thread.is_alive():
            # One last drain so snapshots pushed during shutdown land.
            try:
                while self._sock.poll(50):
                    self._ingest(pickle.loads(self._sock.recv()))
            except Exception:  # noqa: BLE001
                pass
            self._sock.close(linger=0)
        if self._http is not None:
            try:
                name_resolve.delete(self._http_key)
            except Exception:  # noqa: BLE001 — already gone / repo reset
                pass
            self._http.shutdown()
            self._http.server_close()
        if self._jsonl_file is not None:
            self._jsonl_file.close()
        self.stitcher.close()
        if self.sentinel is not None:
            self.sentinel.close()


# --------------------------------------------------------------------------
# process-global facade
# --------------------------------------------------------------------------


class _NullSpanCtx:
    """Reusable no-op span context (allocation-free disabled path)."""

    _attrs: Dict[str, Any] = {}

    def __enter__(self):
        return {}

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpanCtx()

# Live enabled Telemetry instances in this process (the gen-fleet process
# hosts several) — the crash hooks dump every ring at once.
_LIVE: "weakref.WeakSet" = weakref.WeakSet()
_EXCEPTHOOK_INSTALLED = False
_SIGTERM_INSTALLED = False


def _dump_all_flight(reason: str) -> List[str]:
    paths = []
    for t in list(_LIVE):
        p = t.flight_dump(reason=reason)
        if p:
            paths.append(p)
    return paths


def _install_crash_hooks() -> None:
    """Chain a SIGTERM handler + sys.excepthook that dump every live
    flight ring before the process dies. Installed only when a
    ``flight_dir`` is configured — test processes and disabled runs never
    have their signal disposition touched. The two halves latch
    separately: a first install off the main thread (where
    ``signal.signal`` raises) still gets excepthook coverage, and a later
    main-thread install retries the signal half."""
    global _EXCEPTHOOK_INSTALLED, _SIGTERM_INSTALLED
    if not _EXCEPTHOOK_INSTALLED:
        _EXCEPTHOOK_INSTALLED = True
        prev_hook = sys.excepthook

        def hook(tp, value, tb):
            try:
                _dump_all_flight(f"uncaught:{tp.__name__}: {value}")
            except Exception:  # noqa: BLE001 — never mask the real crash
                pass
            prev_hook(tp, value, tb)

        sys.excepthook = hook
    if not _SIGTERM_INSTALLED:
        try:
            prev_term = signal.getsignal(signal.SIGTERM)

            def on_term(signum, frame):
                try:
                    _dump_all_flight("sigterm")
                except Exception:  # noqa: BLE001
                    pass
                if callable(prev_term):
                    prev_term(signum, frame)
                elif prev_term == signal.SIG_IGN:
                    # The process deliberately ignored SIGTERM before;
                    # dumping must not turn an ignored signal fatal.
                    return
                else:
                    # Restore the default disposition and re-deliver so
                    # the exit status still says "killed by SIGTERM".
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, on_term)
            _SIGTERM_INSTALLED = True
        except ValueError:
            # Off the main thread: excepthook coverage only; a later
            # main-thread Telemetry construction retries this half.
            pass


class Telemetry:
    """A (registry, pusher) bundle — the unit each worker owns.

    The gen-fleet process hosts generation servers AND the manager in one
    process, so they each construct their own instance (distinct
    ``worker_kind`` keys at the aggregator) rather than sharing the
    process-global one."""

    def __init__(self, experiment: str, trial: str, worker_kind: str,
                 worker_index: int = 0, cfg: Optional["TelemetryConfig"] = None,
                 push: bool = True):
        from areal_tpu.api.train_config import TelemetryConfig

        cfg = cfg or TelemetryConfig(enabled=True)
        self.cfg = cfg
        self.worker_kind = worker_kind
        self.worker_index = worker_index
        # Global span-ref prefix for cross-worker parent links: matches
        # the key the aggregator files this worker's spans under.
        self.worker_ref = f"{worker_kind}:{worker_index}"
        self.registry = TelemetryRegistry(max_spans=cfg.max_buffered_spans)
        if getattr(cfg, "flight_recorder_len", 0) > 0:
            self.registry.flight = FlightRecorder(cfg.flight_recorder_len)
        self.flight_dir = getattr(cfg, "flight_dir", None)
        _LIVE.add(self)
        if self.flight_dir and self.registry.flight is not None:
            _install_crash_hooks()
        self.pusher = (
            TelemetryPusher(
                self.registry, experiment, trial, worker_kind, worker_index,
                flush_interval_secs=cfg.flush_interval_secs,
            ) if push else None
        )

    enabled = True

    def inc(self, name: str, n: float = 1.0) -> None:
        self.registry.inc(name, n)

    def set_gauge(self, name: str, v: float) -> None:
        self.registry.set_gauge(name, v)

    def observe(self, name: str, v: float, buckets=None) -> None:
        self.registry.observe(name, v, buckets)

    def span(self, name: str, **attrs):
        return self.registry.span(name, **attrs)

    def add_span(self, name: str, t_start: float, dur_secs: float,
                 trace: Optional[TraceContext] = None, **attrs) -> int:
        return self.registry.add_span(name, t_start, dur_secs,
                                      trace=trace, **attrs)

    def event(self, name: str, **attrs) -> None:
        self.registry.event(name, **attrs)

    def flight_dump(self, out_dir: Optional[str] = None,
                    reason: str = "") -> Optional[str]:
        """Dump this worker's flight ring to
        ``<dir>/flight_<kind><index>.jsonl``; None when no ring or no
        directory is configured (never raises — crash-path safe)."""
        d = out_dir or self.flight_dir
        if d is None or self.registry.flight is None:
            return None
        path = os.path.join(
            d, f"flight_{self.worker_kind}{self.worker_index}.jsonl"
        )
        try:
            self.registry.flight.dump(path, reason=reason)
        except Exception as e:  # noqa: BLE001 — evidence is best-effort
            logger.warning(f"flight dump failed: {e}")
            return None
        return path

    def snapshot(self, reset: bool = False) -> Dict[str, Any]:
        return self.registry.snapshot(reset=reset)

    def close(self) -> None:
        if self.pusher is not None:
            self.pusher.close()
            self.pusher = None
        _LIVE.discard(self)


class _NullTelemetry:
    """Shared disabled sink: no sockets, no threads, no span objects."""

    enabled = False
    registry = None
    pusher = None
    worker_ref = ""
    flight_dir = None

    def inc(self, name: str, n: float = 1.0) -> None:
        pass

    def set_gauge(self, name: str, v: float) -> None:
        pass

    def observe(self, name: str, v: float, buckets=None) -> None:
        pass

    def span(self, name: str, **attrs):
        return _NULL_SPAN

    def add_span(self, name: str, t_start: float, dur_secs: float,
                 trace=None, **attrs) -> int:
        return 0

    def event(self, name: str, **attrs) -> None:
        pass

    def flight_dump(self, out_dir=None, reason: str = "") -> Optional[str]:
        return None

    def snapshot(self, reset: bool = False) -> Dict[str, Any]:
        return {"counters": {}, "gauges": {}, "hists": {}, "spans": [],
                "dropped_spans": 0}

    def close(self) -> None:
        pass


NULL = _NullTelemetry()
_GLOBAL: Any = NULL


def configure(experiment: str, trial: str, worker_kind: str,
              worker_index: int = 0, cfg=None, push: bool = True):
    """Install the process-global telemetry sink. A disabled (or absent)
    config keeps the null sink — callers never need to re-check."""
    global _GLOBAL
    if cfg is not None and not cfg.enabled:
        return NULL
    if _GLOBAL is not NULL:
        _GLOBAL.close()
    _GLOBAL = Telemetry(experiment, trial, worker_kind, worker_index,
                        cfg=cfg, push=push)
    return _GLOBAL


def get():
    return _GLOBAL


def enabled() -> bool:
    return _GLOBAL.enabled


def shutdown() -> None:
    global _GLOBAL
    if _GLOBAL is not NULL:
        _GLOBAL.close()
        _GLOBAL = NULL


def inc(name: str, n: float = 1.0) -> None:
    _GLOBAL.inc(name, n)


def set_gauge(name: str, v: float) -> None:
    _GLOBAL.set_gauge(name, v)


def observe(name: str, v: float, buckets=None) -> None:
    _GLOBAL.observe(name, v, buckets)


def span(name: str, **attrs):
    return _GLOBAL.span(name, **attrs)


def add_span(name: str, t_start: float, dur_secs: float,
             trace: Optional[TraceContext] = None, **attrs) -> int:
    return _GLOBAL.add_span(name, t_start, dur_secs, trace=trace, **attrs)


def event(name: str, **attrs) -> None:
    _GLOBAL.event(name, **attrs)


def request_flight_dump(experiment: str, trial: str, out_dir: str) -> str:
    """Operator entry (tools/perf_probe.py flight-dump): ask EVERY worker
    to dump its flight ring into ``out_dir``. Unlike the profiler trigger
    the flag is not consumed — each worker's pusher acts once per nonce —
    so one request snapshots the whole fleet. Returns the nonce."""
    nonce = uuid.uuid4().hex[:12]
    name_resolve.add(
        names.flight_dump_trigger(experiment, trial),
        json.dumps({"dir": out_dir, "nonce": nonce, "time": time.time()}),
        replace=True,
    )
    return nonce


# --------------------------------------------------------------------------
# on-demand profiler capture
# --------------------------------------------------------------------------


def request_profiler_capture(experiment: str, trial: str, out_dir: str,
                             secs: float = 5.0) -> None:
    """Operator entry (tools/perf_probe.py): ask the trainer for one
    ``jax.profiler`` trace of ~``secs`` seconds into ``out_dir``."""
    name_resolve.add(
        names.profiler_trigger(experiment, trial),
        json.dumps({"dir": out_dir, "secs": float(secs)}),
        replace=True,
    )


def read_profiler_status(experiment: str, trial: str) -> Optional[Dict]:
    try:
        return json.loads(name_resolve.get(
            names.profiler_status(experiment, trial)
        ))
    except Exception:  # noqa: BLE001 — never captured yet
        return None


class ProfilerTriggerWatcher:
    """Trainer-side poller for the profiler-trigger flag.

    ``poll()`` is called once per serve-loop iteration; it rate-limits
    the name-resolve read to ``poll_secs`` so the hot loop never pays a
    filesystem stat per iteration. On pickup: consume the flag, start a
    ``jax.profiler`` trace, and stop it once the requested window has
    elapsed (checked on subsequent polls), publishing the outcome under
    ``names.profiler_status``. ``start_fn``/``stop_fn`` are injectable
    for tests (and guard environments where the profiler is unavailable).
    """

    def __init__(self, experiment: str, trial: str, poll_secs: float = 1.0,
                 start_fn=None, stop_fn=None):
        self.experiment = experiment
        self.trial = trial
        self.poll_secs = poll_secs
        self._trigger_key = names.profiler_trigger(experiment, trial)
        self._status_key = names.profiler_status(experiment, trial)
        self._next_check = 0.0
        self._deadline: Optional[float] = None
        self._out_dir: Optional[str] = None
        self._start_fn = start_fn
        self._stop_fn = stop_fn

    def _start(self, out_dir: str) -> None:
        if self._start_fn is not None:
            self._start_fn(out_dir)
            return
        import jax

        jax.profiler.start_trace(out_dir)

    def _stop(self) -> None:
        if self._stop_fn is not None:
            self._stop_fn()
            return
        import jax

        jax.profiler.stop_trace()

    def _set_status(self, state: str, **extra) -> None:
        name_resolve.add(
            self._status_key,
            json.dumps({"state": state, "dir": self._out_dir,
                        "time": time.time(), **extra}),
            replace=True,
        )

    @property
    def capturing(self) -> bool:
        return self._deadline is not None

    def poll(self) -> None:
        now = time.monotonic()
        if self.capturing:
            if now >= self._deadline:
                self._deadline = None
                try:
                    self._stop()
                    self._set_status("done")
                    logger.info(f"profiler capture done -> {self._out_dir}")
                except Exception as e:  # noqa: BLE001 — never kill serving
                    self._set_status("failed", error=str(e))
                    logger.warning(f"profiler stop failed: {e}")
            return
        if now < self._next_check:
            return
        self._next_check = now + self.poll_secs
        try:
            raw = name_resolve.get(self._trigger_key)
        except Exception:  # noqa: BLE001 — no trigger pending
            return
        try:
            name_resolve.delete(self._trigger_key)  # consume exactly once
        except Exception:  # noqa: BLE001 — raced another consumer
            return
        try:
            req = json.loads(raw)
            self._out_dir = req["dir"]
            secs = float(req.get("secs", 5.0))
            self._start(self._out_dir)
            self._deadline = now + secs
            self._set_status("capturing", secs=secs)
            logger.info(
                f"profiler capture started ({secs}s) -> {self._out_dir}"
            )
        except Exception as e:  # noqa: BLE001 — bad request / no profiler
            self._deadline = None
            self._set_status("failed", error=str(e))
            logger.warning(f"profiler trigger failed: {e}")
