"""Unified telemetry: per-process metric registry, trace spans, cross-worker
aggregation, Prometheus rendering, and on-demand profiler capture.

The paper's core claim — fully-async rollout/training overlap hides
generation latency — is only checkable if queue depth, staleness lag,
weight-sync fanout latency, and the trainer's step-phase breakdown are
visible across the fleet *while it runs*. ``stats_tracker`` covers the
training-loss plane (per-step scoped reductions the master tabulates);
this module covers the *systems* plane on top of it:

 - :class:`TelemetryRegistry` — per-process counters (monotonic), gauges
   (last value), histograms (fixed buckets, Prometheus-style cumulative),
   and lightweight trace spans (id / parent-id / wall-times, nested via a
   contextvar so asyncio tasks and threads each get a correct parent
   chain).
 - :class:`TelemetryPusher` — background thread that snapshots the
   registry every ``flush_interval_secs`` and ZMQ-PUSHes it to the
   master, tagged ``(worker_kind, worker_index)``. Endpoint discovery is
   lazy (the aggregator may start after the worker); until it appears,
   snapshots accumulate spans up to a bounded buffer.
 - :class:`TelemetryAggregator` — master-side PULL endpoint (registered
   under ``names.telemetry_aggregator``) merging per-worker snapshots
   into one state keyed by ``worker_kind:worker_index``, appending every
   snapshot to ``telemetry.jsonl`` and mirroring scalars into a
   :class:`base.monitor.MetricWriter` tensorboard stream. With
   ``http_port > 0`` it also serves the merged fleet state as
   Prometheus text on ``GET /metrics``.
 - :func:`render_prometheus` — registry/plain-dict → Prometheus
   exposition text (the generation server and gserver manager serve it
   on their existing aiohttp apps).
 - Profiler trigger — :func:`request_profiler_capture` writes a
   name-resolve flag (``names.profiler_trigger``) that a trainer-side
   :class:`ProfilerTriggerWatcher` polls between serve iterations; on
   pickup it runs ``jax.profiler.start_trace/stop_trace`` for the
   requested window and reports under ``names.profiler_status``.

Disabled-by-default contract (tier-1 + bench honesty): until
:func:`configure` is called with an enabled config, the module-level API
(:func:`inc`, :func:`set_gauge`, :func:`observe`, :func:`span`) routes to
a shared null object — no locks taken beyond one attribute read, no ZMQ
sockets, no HTTP servers, no span allocation.
"""

from __future__ import annotations

import contextvars
import dataclasses
import itertools
import json
import os
import pickle
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple

from areal_tpu.base import logging, name_resolve, names, network

logger = logging.getLogger("base.telemetry")

# Latency-shaped default buckets (seconds): 1ms .. ~2min, Prometheus-style.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

_span_ids = itertools.count(1)
# Current span id of the calling context (asyncio task / thread); copied
# into child tasks by asyncio, fresh (None) in new threads.
_CUR_SPAN: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "areal_tpu_cur_span", default=None
)


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------


@dataclasses.dataclass
class Span:
    name: str
    span_id: int
    parent_id: Optional[int]
    t_start: float  # wall clock (time.time)
    dur_secs: float
    attrs: Dict[str, Any]

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t_start": round(self.t_start, 6),
            "dur_secs": round(self.dur_secs, 6),
            "attrs": self.attrs,
        }


class _Histogram:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float]):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # trailing +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.sum += v
        self.count += 1
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def as_dict(self) -> Dict[str, Any]:
        return {
            "buckets": list(self.buckets), "counts": list(self.counts),
            "sum": self.sum, "count": self.count,
        }


class TelemetryRegistry:
    """Thread-safe per-process metric + span store.

    Counters/gauges/histograms are CUMULATIVE — a flush (or a Prometheus
    scrape) never resets them, so scraped counters stay monotonic and
    concurrent exporters cannot race each other's resets. Spans are the
    only drained state: ``snapshot(reset=True)`` hands back the buffered
    spans and clears the buffer (bounded by ``max_spans``; oldest drop
    first so a stalled aggregator cannot OOM a worker).
    """

    def __init__(self, max_spans: int = 4096):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, _Histogram] = {}
        self._spans: List[Span] = []
        self.max_spans = max_spans
        self.dropped_spans = 0

    # ---- metrics ----

    def inc(self, name: str, n: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + n

    def set_gauge(self, name: str, v: float) -> None:
        with self._lock:
            self._gauges[name] = float(v)

    def observe(self, name: str, v: float,
                buckets: Optional[Sequence[float]] = None) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram(buckets or DEFAULT_BUCKETS)
            h.observe(float(v))

    # ---- spans ----

    @contextmanager
    def span(self, name: str, **attrs):
        sid = next(_span_ids)
        parent = _CUR_SPAN.get()
        token = _CUR_SPAN.set(sid)
        t_wall = time.time()
        t0 = time.monotonic()
        try:
            yield attrs  # callers may add attrs["key"] = ... mid-span
        finally:
            _CUR_SPAN.reset(token)
            s = Span(name=name, span_id=sid, parent_id=parent,
                     t_start=t_wall, dur_secs=time.monotonic() - t0,
                     attrs=attrs)
            with self._lock:
                if len(self._spans) >= self.max_spans:
                    self._spans.pop(0)
                    self.dropped_spans += 1
                self._spans.append(s)
            # Every span doubles as a duration histogram point, so the
            # aggregate view exists even when span volume forces drops.
            self.observe(f"{name}/secs", s.dur_secs)

    # ---- export ----

    def snapshot(self, reset: bool = True) -> Dict[str, Any]:
        with self._lock:
            out = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "hists": {k: h.as_dict() for k, h in self._hists.items()},
                "spans": [s.as_dict() for s in self._spans],
                "dropped_spans": self.dropped_spans,
            }
            if reset:
                self._spans = []
        return out


# --------------------------------------------------------------------------
# Prometheus rendering
# --------------------------------------------------------------------------


def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    s = "".join(out)
    return ("_" + s) if s and s[0].isdigit() else (s or "_")


def _prom_labels(labels: Optional[Dict[str, str]],
                 extra: Optional[Dict[str, str]] = None) -> str:
    merged = {**(labels or {}), **(extra or {})}
    if not merged:
        return ""

    def esc(v) -> str:
        return str(v).replace("\\", "\\\\").replace('"', '\\"')

    inner = ",".join(
        f'{_prom_name(k)}="{esc(v)}"' for k, v in sorted(merged.items())
    )
    return "{" + inner + "}"


def render_prometheus(
    snapshot: Optional[Dict[str, Any]] = None,
    extra_gauges: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    prefix: str = "areal",
) -> str:
    """Registry snapshot (+ ad-hoc gauges) → Prometheus exposition text.

    ``extra_gauges`` lets HTTP workers export live object state (queue
    sizes, versions) without mirroring it into the registry first. Values
    that are None or non-numeric are skipped.
    """
    lines: List[str] = []
    snapshot = snapshot or {}
    lab = _prom_labels(labels)

    def emit(name: str, kind: str, value: float,
             label_str: Optional[str] = None) -> None:
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name}{lab if label_str is None else label_str} "
                     f"{float(value):g}")

    emitted = set()
    for k, v in sorted((extra_gauges or {}).items()):
        if isinstance(v, bool):
            v = float(v)
        if not isinstance(v, (int, float)):
            continue  # None / strings have no Prometheus representation
        name = f"{prefix}_{_prom_name(k)}"
        emitted.add(name)
        emit(name, "gauge", float(v))
    for k, v in sorted(snapshot.get("gauges", {}).items()):
        name = f"{prefix}_{_prom_name(k)}"
        if name in emitted:
            # extra_gauges win: a registry gauge sanitizing to the same
            # name (e.g. genserver/weight_version vs the live-state
            # gauge) must not produce a duplicate Prometheus sample.
            continue
        emit(name, "gauge", v)
    for k, v in sorted(snapshot.get("counters", {}).items()):
        emit(f"{prefix}_{_prom_name(k)}_total", "counter", v)
    for k, h in sorted(snapshot.get("hists", {}).items()):
        base = f"{prefix}_{_prom_name(k)}"
        lines.append(f"# TYPE {base} histogram")
        cum = 0
        for b, c in zip(h["buckets"], h["counts"]):
            cum += c
            lstr = _prom_labels(labels, {"le": f"{float(b):g}"})
            lines.append(f"{base}_bucket{lstr} {cum}")
        cum += h["counts"][-1]
        lines.append(f"{base}_bucket{_prom_labels(labels, {'le': '+Inf'})} "
                     f"{cum}")
        lines.append(f"{base}_sum{lab} {h['sum']:g}")
        lines.append(f"{base}_count{lab} {h['count']}")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# pusher (worker side)
# --------------------------------------------------------------------------


class TelemetryPusher:
    """Flush a registry to the master's aggregator on an interval.

    Discovery is lazy and non-fatal: the PUSH socket connects the first
    time ``names.telemetry_aggregator`` resolves; until then flushes are
    skipped (spans stay buffered in the registry, bounded)."""

    def __init__(self, registry: TelemetryRegistry, experiment: str,
                 trial: str, worker_kind: str, worker_index: int = 0,
                 flush_interval_secs: float = 2.0):
        self.registry = registry
        self.worker_kind = worker_kind
        self.worker_index = worker_index
        self.flush_interval_secs = flush_interval_secs
        self._key = names.telemetry_aggregator(experiment, trial)
        self._sock = None
        self._flush_lock = threading.Lock()  # socket use is single-file
        self._pending: Optional[bytes] = None  # unsent snapshot (backlog)
        self._closing = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"telemetry-push-{worker_kind}{worker_index}",
        )
        self._thread.start()

    def _connect(self) -> bool:
        if self._sock is not None:
            return True
        try:
            addr = name_resolve.get(self._key)
        except Exception:  # noqa: BLE001 — aggregator not up yet
            return False
        import zmq

        self._sock = zmq.Context.instance().socket(zmq.PUSH)
        self._sock.setsockopt(zmq.SNDHWM, 64)
        self._sock.setsockopt(zmq.LINGER, 0)
        self._sock.connect(addr)
        return True

    def flush(self) -> bool:
        """One snapshot push; returns False when no aggregator is known or
        it is backlogged. A snapshot that cannot be sent is kept (and the
        registry is NOT drained again until it goes out), so a stalled
        aggregator loses no spans — exactly the incident window an
        operator will want to see. The registry's bounded span buffer is
        the backstop if the outage outlasts ``max_buffered_spans``."""
        import zmq

        with self._flush_lock:
            if not self._connect():
                return False
            if self._pending is not None:
                try:
                    self._sock.send(self._pending, zmq.NOBLOCK)
                except zmq.Again:
                    return False  # still backlogged; nothing drained
                self._pending = None
            payload = pickle.dumps({
                "worker_kind": self.worker_kind,
                "worker_index": self.worker_index,
                "time": time.time(),
                **self.registry.snapshot(reset=True),
            })
            try:
                self._sock.send(payload, zmq.NOBLOCK)
            except zmq.Again:
                self._pending = payload
                return False
        return True

    def _loop(self) -> None:
        while not self._closing.wait(self.flush_interval_secs):
            try:
                self.flush()
            except Exception as e:  # noqa: BLE001 — telemetry never kills
                logger.warning(f"telemetry flush failed: {e}")

    def close(self) -> None:
        # ZMQ sockets are not thread-safe: stop the flush thread BEFORE
        # touching the socket from this thread. If the join times out
        # (thread wedged mid-flush), leak the socket to the daemon thread
        # rather than race it — the process is exiting anyway.
        self._closing.set()
        self._thread.join(timeout=2)
        if self._thread.is_alive():
            return
        try:
            self.flush()  # final snapshot (best-effort)
        except Exception:  # noqa: BLE001
            pass
        if self._sock is not None:
            self._sock.close(linger=0)
            self._sock = None


# --------------------------------------------------------------------------
# aggregator (master side)
# --------------------------------------------------------------------------


class TelemetryAggregator:
    """PULL-side merge of per-worker snapshots keyed by
    ``worker_kind:worker_index``; every received snapshot is appended to
    ``telemetry.jsonl`` and its scalars mirrored into ``metric_writer``
    (tensorboard) as ``telemetry/{worker}/{metric}``."""

    def __init__(self, experiment: str, trial: str,
                 jsonl_path: Optional[str] = None,
                 metric_writer=None, http_port: int = 0):
        import zmq

        self.jsonl_path = jsonl_path
        self._writer = metric_writer
        self._seq = 0
        self.state: Dict[str, Dict[str, Any]] = {}
        self._state_lock = threading.Lock()
        self._sock = zmq.Context.instance().socket(zmq.PULL)
        self._sock.setsockopt(zmq.RCVHWM, 4096)
        port = self._sock.bind_to_random_port(f"tcp://{network.bind_addr()}")
        self._key = names.telemetry_aggregator(experiment, trial)
        name_resolve.add(self._key, network.advertised_tcp(port),
                         replace=True)
        self._jsonl_file = None
        if jsonl_path:
            os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
            self._jsonl_file = open(jsonl_path, "a", buffering=1)
        self._closing = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="telemetry-aggregate"
        )
        self._thread.start()
        self._http = None
        if http_port:
            self._start_http(http_port)
        logger.info(f"telemetry aggregator up (jsonl={jsonl_path})")

    # ---- ingest ----

    def _ingest(self, payload: Dict[str, Any]) -> None:
        worker = f"{payload.get('worker_kind', '?')}:" \
                 f"{payload.get('worker_index', 0)}"
        with self._state_lock:
            prev = self.state.get(worker)
            spans = payload.get("spans", [])
            merged = {
                "time": payload.get("time"),
                "counters": payload.get("counters", {}),
                "gauges": payload.get("gauges", {}),
                "hists": payload.get("hists", {}),
                "n_spans": (prev["n_spans"] if prev else 0) + len(spans),
                "last_spans": spans or (prev["last_spans"] if prev else []),
            }
            self.state[worker] = merged
            self._seq += 1
            seq = self._seq
        if self._jsonl_file is not None:
            rec = {"worker": worker, **{
                k: payload.get(k) for k in
                ("time", "counters", "gauges", "spans", "dropped_spans")
            }, "hists": payload.get("hists", {})}
            self._jsonl_file.write(json.dumps(rec) + "\n")
        if self._writer is not None:
            flat = {
                **{f"telemetry/{worker}/{k}": v
                   for k, v in merged["counters"].items()},
                **{f"telemetry/{worker}/{k}": v
                   for k, v in merged["gauges"].items()},
            }
            if flat:
                try:
                    self._writer.write(flat, seq)
                except Exception:  # noqa: BLE001 — TB is best-effort
                    pass

    def _loop(self) -> None:
        while not self._closing.is_set():
            try:
                if not self._sock.poll(100):
                    continue
                self._ingest(pickle.loads(self._sock.recv()))
            except Exception as e:  # noqa: BLE001 — aggregator must survive
                if not self._closing.is_set():
                    logger.warning(f"telemetry ingest failed: {e}")

    def set_metric_writer(self, writer) -> None:
        """Attach (or swap) the tensorboard mirror after construction —
        the master builds its MetricWriter later in setup."""
        self._writer = writer

    # ---- views ----

    def merged(self) -> Dict[str, Dict[str, Any]]:
        with self._state_lock:
            return {k: dict(v) for k, v in self.state.items()}

    def render_prometheus(self) -> str:
        """Merged fleet state as ONE valid exposition: samples of the same
        metric family (e.g. two rollout workers' gauges) are grouped under
        a single ``# TYPE`` line — concatenating per-worker renderings
        would emit duplicate TYPE lines, which expfmt-based consumers
        (promtool etc.) reject wholesale."""
        fams: Dict[str, Dict[str, Any]] = {}

        def add(name: str, kind: str, line: str) -> None:
            fams.setdefault(name, {"kind": kind, "lines": []})["lines"] \
                .append(line)

        for worker, st in sorted(self.merged().items()):
            kind, _, idx = worker.partition(":")
            labels = {"worker_kind": kind, "worker_index": idx}
            lab = _prom_labels(labels)
            for k, v in sorted(st["gauges"].items()):
                n = f"areal_{_prom_name(k)}"
                add(n, "gauge", f"{n}{lab} {float(v):g}")
            for k, v in sorted(st["counters"].items()):
                n = f"areal_{_prom_name(k)}_total"
                add(n, "counter", f"{n}{lab} {float(v):g}")
            for k, h in sorted(st["hists"].items()):
                base = f"areal_{_prom_name(k)}"
                cum = 0
                for b, c in zip(h["buckets"], h["counts"]):
                    cum += c
                    ls = _prom_labels(labels, {"le": f"{float(b):g}"})
                    add(base, "histogram", f"{base}_bucket{ls} {cum}")
                cum += h["counts"][-1]
                ls = _prom_labels(labels, {"le": "+Inf"})
                add(base, "histogram", f"{base}_bucket{ls} {cum}")
                add(base, "histogram", f"{base}_sum{lab} {h['sum']:g}")
                add(base, "histogram", f"{base}_count{lab} {h['count']}")
        if not fams:
            return "# no telemetry received yet\n"
        out: List[str] = []
        for name in sorted(fams):
            out.append(f"# TYPE {name} {fams[name]['kind']}")
            out.extend(fams[name]["lines"])
        return "\n".join(out) + "\n"

    # ---- optional unified /metrics over plain http ----

    def _start_http(self, port: int) -> None:
        import http.server

        agg = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = agg.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # noqa: D102 — silence stdlib logs
                pass

        self._http = http.server.ThreadingHTTPServer(
            (network.bind_addr(), port), Handler
        )
        threading.Thread(target=self._http.serve_forever, daemon=True,
                         name="telemetry-http").start()

    def close(self) -> None:
        # ZMQ sockets are not thread-safe: stop the ingest thread BEFORE
        # this thread touches the socket for the final drain. A wedged
        # ingest thread (slow tensorboard/NFS write) keeps the socket —
        # skip the drain rather than race a live poll/recv.
        self._closing.set()
        self._thread.join(timeout=2)
        try:
            name_resolve.delete(self._key)
        except Exception:  # noqa: BLE001 — already gone / repo reset
            pass
        if not self._thread.is_alive():
            # One last drain so snapshots pushed during shutdown land.
            try:
                while self._sock.poll(50):
                    self._ingest(pickle.loads(self._sock.recv()))
            except Exception:  # noqa: BLE001
                pass
            self._sock.close(linger=0)
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
        if self._jsonl_file is not None:
            self._jsonl_file.close()


# --------------------------------------------------------------------------
# process-global facade
# --------------------------------------------------------------------------


class _NullSpanCtx:
    """Reusable no-op span context (allocation-free disabled path)."""

    _attrs: Dict[str, Any] = {}

    def __enter__(self):
        return {}

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpanCtx()


class Telemetry:
    """A (registry, pusher) bundle — the unit each worker owns.

    The gen-fleet process hosts generation servers AND the manager in one
    process, so they each construct their own instance (distinct
    ``worker_kind`` keys at the aggregator) rather than sharing the
    process-global one."""

    def __init__(self, experiment: str, trial: str, worker_kind: str,
                 worker_index: int = 0, cfg: Optional["TelemetryConfig"] = None,
                 push: bool = True):
        from areal_tpu.api.train_config import TelemetryConfig

        cfg = cfg or TelemetryConfig(enabled=True)
        self.cfg = cfg
        self.registry = TelemetryRegistry(max_spans=cfg.max_buffered_spans)
        self.pusher = (
            TelemetryPusher(
                self.registry, experiment, trial, worker_kind, worker_index,
                flush_interval_secs=cfg.flush_interval_secs,
            ) if push else None
        )

    enabled = True

    def inc(self, name: str, n: float = 1.0) -> None:
        self.registry.inc(name, n)

    def set_gauge(self, name: str, v: float) -> None:
        self.registry.set_gauge(name, v)

    def observe(self, name: str, v: float, buckets=None) -> None:
        self.registry.observe(name, v, buckets)

    def span(self, name: str, **attrs):
        return self.registry.span(name, **attrs)

    def snapshot(self, reset: bool = False) -> Dict[str, Any]:
        return self.registry.snapshot(reset=reset)

    def close(self) -> None:
        if self.pusher is not None:
            self.pusher.close()
            self.pusher = None


class _NullTelemetry:
    """Shared disabled sink: no sockets, no threads, no span objects."""

    enabled = False
    registry = None
    pusher = None

    def inc(self, name: str, n: float = 1.0) -> None:
        pass

    def set_gauge(self, name: str, v: float) -> None:
        pass

    def observe(self, name: str, v: float, buckets=None) -> None:
        pass

    def span(self, name: str, **attrs):
        return _NULL_SPAN

    def snapshot(self, reset: bool = False) -> Dict[str, Any]:
        return {"counters": {}, "gauges": {}, "hists": {}, "spans": [],
                "dropped_spans": 0}

    def close(self) -> None:
        pass


NULL = _NullTelemetry()
_GLOBAL: Any = NULL


def configure(experiment: str, trial: str, worker_kind: str,
              worker_index: int = 0, cfg=None, push: bool = True):
    """Install the process-global telemetry sink. A disabled (or absent)
    config keeps the null sink — callers never need to re-check."""
    global _GLOBAL
    if cfg is not None and not cfg.enabled:
        return NULL
    if _GLOBAL is not NULL:
        _GLOBAL.close()
    _GLOBAL = Telemetry(experiment, trial, worker_kind, worker_index,
                        cfg=cfg, push=push)
    return _GLOBAL


def get():
    return _GLOBAL


def enabled() -> bool:
    return _GLOBAL.enabled


def shutdown() -> None:
    global _GLOBAL
    if _GLOBAL is not NULL:
        _GLOBAL.close()
        _GLOBAL = NULL


def inc(name: str, n: float = 1.0) -> None:
    _GLOBAL.inc(name, n)


def set_gauge(name: str, v: float) -> None:
    _GLOBAL.set_gauge(name, v)


def observe(name: str, v: float, buckets=None) -> None:
    _GLOBAL.observe(name, v, buckets)


def span(name: str, **attrs):
    return _GLOBAL.span(name, **attrs)


# --------------------------------------------------------------------------
# on-demand profiler capture
# --------------------------------------------------------------------------


def request_profiler_capture(experiment: str, trial: str, out_dir: str,
                             secs: float = 5.0) -> None:
    """Operator entry (tools/perf_probe.py): ask the trainer for one
    ``jax.profiler`` trace of ~``secs`` seconds into ``out_dir``."""
    name_resolve.add(
        names.profiler_trigger(experiment, trial),
        json.dumps({"dir": out_dir, "secs": float(secs)}),
        replace=True,
    )


def read_profiler_status(experiment: str, trial: str) -> Optional[Dict]:
    try:
        return json.loads(name_resolve.get(
            names.profiler_status(experiment, trial)
        ))
    except Exception:  # noqa: BLE001 — never captured yet
        return None


class ProfilerTriggerWatcher:
    """Trainer-side poller for the profiler-trigger flag.

    ``poll()`` is called once per serve-loop iteration; it rate-limits
    the name-resolve read to ``poll_secs`` so the hot loop never pays a
    filesystem stat per iteration. On pickup: consume the flag, start a
    ``jax.profiler`` trace, and stop it once the requested window has
    elapsed (checked on subsequent polls), publishing the outcome under
    ``names.profiler_status``. ``start_fn``/``stop_fn`` are injectable
    for tests (and guard environments where the profiler is unavailable).
    """

    def __init__(self, experiment: str, trial: str, poll_secs: float = 1.0,
                 start_fn=None, stop_fn=None):
        self.experiment = experiment
        self.trial = trial
        self.poll_secs = poll_secs
        self._trigger_key = names.profiler_trigger(experiment, trial)
        self._status_key = names.profiler_status(experiment, trial)
        self._next_check = 0.0
        self._deadline: Optional[float] = None
        self._out_dir: Optional[str] = None
        self._start_fn = start_fn
        self._stop_fn = stop_fn

    def _start(self, out_dir: str) -> None:
        if self._start_fn is not None:
            self._start_fn(out_dir)
            return
        import jax

        jax.profiler.start_trace(out_dir)

    def _stop(self) -> None:
        if self._stop_fn is not None:
            self._stop_fn()
            return
        import jax

        jax.profiler.stop_trace()

    def _set_status(self, state: str, **extra) -> None:
        name_resolve.add(
            self._status_key,
            json.dumps({"state": state, "dir": self._out_dir,
                        "time": time.time(), **extra}),
            replace=True,
        )

    @property
    def capturing(self) -> bool:
        return self._deadline is not None

    def poll(self) -> None:
        now = time.monotonic()
        if self.capturing:
            if now >= self._deadline:
                self._deadline = None
                try:
                    self._stop()
                    self._set_status("done")
                    logger.info(f"profiler capture done -> {self._out_dir}")
                except Exception as e:  # noqa: BLE001 — never kill serving
                    self._set_status("failed", error=str(e))
                    logger.warning(f"profiler stop failed: {e}")
            return
        if now < self._next_check:
            return
        self._next_check = now + self.poll_secs
        try:
            raw = name_resolve.get(self._trigger_key)
        except Exception:  # noqa: BLE001 — no trigger pending
            return
        try:
            name_resolve.delete(self._trigger_key)  # consume exactly once
        except Exception:  # noqa: BLE001 — raced another consumer
            return
        try:
            req = json.loads(raw)
            self._out_dir = req["dir"]
            secs = float(req.get("secs", 5.0))
            self._start(self._out_dir)
            self._deadline = now + secs
            self._set_status("capturing", secs=secs)
            logger.info(
                f"profiler capture started ({secs}s) -> {self._out_dir}"
            )
        except Exception as e:  # noqa: BLE001 — bad request / no profiler
            self._deadline = None
            self._set_status("failed", error=str(e))
            logger.warning(f"profiler trigger failed: {e}")
