"""Compile-event observatory: jit entry-point tracing, recompile-storm
detection, and persistent-cache accounting.

The observability stack can say where wall-clock goes (telemetry spans,
goodput states) but was blind to the failure mode that actually dominates
TPU-native JAX operation: XLA compilation. BENCH_r08 died inside a warmup
compile no metric could see, and the sentinel papered over the hole with a
blanket 30-minute ``trainer_stalled`` grace. This module makes compilation
a first-class, alertable signal:

 - :func:`watched_jit` / :meth:`CompileWatch.wrap` shim an ALREADY-JITTED
   callable. Each call's abstract signature (shape/dtype of array leaves,
   values of static args) is computed host-side; a signature this wrapper
   has not seen is exactly the condition under which ``jax.jit`` traces
   and compiles, so the wall time of that first call is recorded as a
   compile event (first-execution-inclusive — XLA holds the caller through
   compile + the initial dispatch). Signature sets are PER WRAPPER, not
   per name: a fresh ``jax.jit`` object (new grad-fn cache entry, a
   reshard identity built per group) recompiles even for a shape some
   other wrapper saw, and the ledger must say so.
 - Per-function families on the PR-4 telemetry registry:
   ``compile/events{fn=...}`` / ``compile/secs{fn=...}`` counters, a
   ``compile/inflight`` gauge (nonzero while any wrapped call is tracing)
   and ``compile/distinct_shapes{fn=...}`` — the same family the serving
   ShapeBucketPolicy feeds, so trainer ``[R, L]`` packed grids and decode
   bucket shapes are audited with one ruler.
 - A recompile-storm detector: a NEW signature for a function that had
   been shape-stable for ``storm_warmup_calls`` calls increments
   ``compile/storm_events`` and logs the offending signature once — the
   signal the sentinel's ``recompile_storm`` rate rule watches.
 - Persistent-cache accounting: when the launcher's compilation cache is
   configured (``AREAL_COMPILATION_CACHE``), the cache directory's entry
   count is probed around each observed compile — an entry appearing
   means XLA really compiled (``compile/cache_misses``); none appearing
   means the compile was served from the persistent cache
   (``compile/cache_hits``).

Disabled contract (mirrors telemetry/goodput): until :func:`configure`
installs an enabled watch, :func:`watched_jit` returns the raw function
object unchanged — zero wrappers, zero per-call work, and the Prometheus
scrape is bit-identical to a build without this module.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Set

from areal_tpu.base import logging, telemetry

logger = logging.getLogger("base.compile_watch")

# Single source of truth for the persistent-cache location (apps/launcher
# re-exports it): the watch and the launcher must agree on the directory
# or hit/miss accounting probes an empty dir forever.
DEFAULT_COMPILATION_CACHE = os.path.expanduser(
    "~/.cache/areal_tpu/jax_compilation_cache"
)


def compilation_cache_dir() -> Optional[str]:
    """The persistent-cache directory the launcher configures, or None
    when caching is disabled (``AREAL_COMPILATION_CACHE=""``)."""
    path = os.environ.get("AREAL_COMPILATION_CACHE",
                          DEFAULT_COMPILATION_CACHE)
    return path or None


def abstract_signature(args: tuple, kwargs: dict) -> str:
    """The host-side stand-in for jax.jit's cache key: array-like leaves
    (anything with ``.shape`` and ``.dtype``) collapse to ``dtype[shape]``,
    containers recurse, and everything else — the static args whose VALUES
    key the jit cache (``S``, ``n_tokens``, config objects) — contributes
    a bounded repr. Pure string math, no jax import: jax-free tests feed
    lightweight fakes through the same path the fleet runs."""
    parts: list = []

    def walk(x: Any) -> None:
        if isinstance(x, (list, tuple)):
            parts.append("(" if isinstance(x, tuple) else "[")
            for v in x:
                walk(v)
            parts.append(")" if isinstance(x, tuple) else "]")
        elif isinstance(x, dict):
            parts.append("{")
            for k in sorted(x, key=str):
                parts.append(f"{k}:")
                walk(x[k])
            parts.append("}")
        else:
            shape = getattr(x, "shape", None)
            dtype = getattr(x, "dtype", None)
            if shape is not None and dtype is not None:
                try:
                    dims = ",".join(str(int(d)) for d in shape)
                except (TypeError, ValueError):
                    dims = str(shape)
                parts.append(f"{dtype}[{dims}]")
            elif x is None or isinstance(x, (bool, int, float, str, bytes)):
                parts.append(repr(x))
            else:
                # Hashable static arg (model config, mesh): identity by a
                # bounded repr — enough to tell bucket ladders apart
                # without serializing a whole config tree per call.
                parts.append(f"{type(x).__name__}:{repr(x)[:160]}")

    walk(args)
    parts.append("|")
    walk(kwargs)
    return "".join(parts)


class _FnRecord:
    """Per-NAME aggregate: the union of signatures any wrapper observed
    (the distinct-shapes gauge) and the shape-stability counter the storm
    detector runs on."""

    __slots__ = ("signatures", "calls", "calls_since_new_sig")

    def __init__(self) -> None:
        self.signatures: Set[str] = set()
        self.calls = 0
        self.calls_since_new_sig = 0


class _WatchedFn:
    """The wrapper :meth:`CompileWatch.wrap` returns. Owns its own
    seen-signature set (fresh jit objects recompile known shapes); the
    shared watch owns the per-name aggregates and metric export."""

    __slots__ = ("_watch", "_name", "_fn", "_seen")

    def __init__(self, watch: "CompileWatch", name: str, fn: Callable):
        self._watch = watch
        self._name = name
        self._fn = fn
        self._seen: Set[str] = set()

    @property
    def __wrapped__(self) -> Callable:
        return self._fn

    def __call__(self, *args, **kwargs):
        sig = abstract_signature(args, kwargs)
        if sig in self._seen:
            self._watch._note_call(self._name)
            return self._fn(*args, **kwargs)
        self._seen.add(sig)
        self._watch._compile_begin()
        t0 = self._watch._clock()
        try:
            return self._fn(*args, **kwargs)
        finally:
            self._watch._compile_end(
                self._name, sig, self._watch._clock() - t0
            )


class CompileWatch:
    """Process-wide (or per-server) compile-event registry.

    ``telemetry_sink`` is any Telemetry-like object (``inc`` /
    ``set_gauge`` / ``event``); ``clock`` is injectable for fake-clock
    tests. ``cache_dir=None`` disables persistent-cache accounting."""

    enabled = True

    def __init__(self, telemetry_sink=None, *,
                 storm_warmup_calls: int = 16,
                 cache_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.tel = telemetry_sink if telemetry_sink is not None \
            else telemetry.get()
        self.storm_warmup_calls = max(int(storm_warmup_calls), 1)
        self.cache_dir = cache_dir
        self._clock = clock
        self._lock = threading.Lock()
        self._fns: Dict[str, _FnRecord] = {}
        self._inflight = 0
        self._warned_storms: Set[str] = set()
        self._cache_entries = self._count_cache_entries()

    # ---- wrapping ----

    def wrap(self, name: str, fn: Callable) -> Callable:
        return _WatchedFn(self, name, fn)

    def inflight(self) -> bool:
        """True while any wrapped call is inside its first-signature
        (trace + compile) execution — the HeartbeatThread publishes this
        so sentinel absence rules can tell "wedged" from "compiling"."""
        return self._inflight > 0

    # ---- internals (called by _WatchedFn) ----

    def _note_call(self, name: str) -> None:
        with self._lock:
            rec = self._fns.get(name)
            if rec is None:
                rec = self._fns[name] = _FnRecord()
            rec.calls += 1
            rec.calls_since_new_sig += 1

    def _compile_begin(self) -> None:
        with self._lock:
            self._inflight += 1
            self.tel.set_gauge("compile/inflight", float(self._inflight))

    def _compile_end(self, name: str, sig: str, secs: float) -> None:
        storm = False
        with self._lock:
            self._inflight -= 1
            self.tel.set_gauge("compile/inflight", float(self._inflight))
            rec = self._fns.get(name)
            if rec is None:
                rec = self._fns[name] = _FnRecord()
            rec.calls += 1
            if sig not in rec.signatures:
                # A new shape after the fn had been stable through the
                # warmup window is the storm signature: something churns
                # past the bucket policy (length distribution drift, a
                # mis-rounded batch dim) and every occurrence costs a
                # full XLA compile on the hot path.
                storm = (rec.calls_since_new_sig >= self.storm_warmup_calls
                         and bool(rec.signatures))
                rec.signatures.add(sig)
                rec.calls_since_new_sig = 0
            n_shapes = len(rec.signatures)
        self.tel.inc(f"compile/events{{fn={name}}}")
        self.tel.inc(f"compile/secs{{fn={name}}}", max(secs, 0.0))
        self.tel.set_gauge(f"compile/distinct_shapes{{fn={name}}}",
                           float(n_shapes))
        if storm:
            self.tel.inc("compile/storm_events")
            key = f"{name}|{sig}"
            if key not in self._warned_storms:
                self._warned_storms.add(key)
                logger.warning(
                    f"recompile storm: {name} compiled a NEW shape after "
                    f"being stable for >= {self.storm_warmup_calls} calls "
                    f"— offending signature: {sig[:512]}"
                )
            self.tel.event("compile/storm", fn=name, sig=sig[:512])
        self._probe_cache()

    # ---- persistent-cache accounting ----

    def _count_cache_entries(self) -> Optional[int]:
        if not self.cache_dir:
            return None
        try:
            return len(os.listdir(self.cache_dir))
        except OSError:
            return None

    def _probe_cache(self) -> None:
        """Around each observed compile: a new entry in the persistent
        cache dir means XLA really compiled (miss — it wrote the result);
        no new entry means the compile was served from cache (hit)."""
        if self.cache_dir is None:
            return
        count = self._count_cache_entries()
        if count is None:
            return
        prev, self._cache_entries = self._cache_entries, count
        if prev is not None and count > prev:
            self.tel.inc("compile/cache_misses", float(count - prev))
        else:
            self.tel.inc("compile/cache_hits")

    # ---- views ----

    def stats(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "calls": float(rec.calls),
                    "distinct_shapes": float(len(rec.signatures)),
                }
                for name, rec in self._fns.items()
            }

    def close(self) -> None:
        pass


class _NullCompileWatch:
    """Shared disabled sink: wrap() hands the raw fn back — the call path
    is bit-identical to a build without this module."""

    enabled = False

    def wrap(self, name: str, fn: Callable) -> Callable:
        return fn

    def inflight(self) -> bool:
        return False

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {}

    def close(self) -> None:
        pass


NULL = _NullCompileWatch()
_GLOBAL: Any = NULL


def configure(cfg=None, telemetry_sink=None,
              cache_dir: Optional[str] = "auto",
              clock: Callable[[], float] = time.monotonic):
    """Install the process-global compile watch. A disabled (or absent)
    config keeps the null sink — jit sites never re-check.

    ``cache_dir="auto"`` resolves the launcher's persistent-cache dir
    from the environment; pass None to disable cache accounting."""
    global _GLOBAL
    if cfg is None or not getattr(cfg, "enabled", False):
        _GLOBAL = NULL
        return NULL
    if cache_dir == "auto":
        cache_dir = compilation_cache_dir()
    _GLOBAL = CompileWatch(
        telemetry_sink,
        storm_warmup_calls=getattr(cfg, "storm_warmup_calls", 16),
        cache_dir=cache_dir,
        clock=clock,
    )
    return _GLOBAL


def get():
    return _GLOBAL


def enabled() -> bool:
    return _GLOBAL.enabled


def watched_jit(name: str, fn: Callable) -> Callable:
    """Wrap an already-jitted callable under the process-global watch
    (the raw fn comes straight back while disabled). Call at jit-creation
    sites: ``fn = compile_watch.watched_jit("train/grad", jax.jit(f))``."""
    return _GLOBAL.wrap(name, fn)


def inflight() -> bool:
    return _GLOBAL.inflight()


def shutdown() -> None:
    global _GLOBAL
    if _GLOBAL is not NULL:
        _GLOBAL.close()
        _GLOBAL = NULL
