"""Distributed KV / service-discovery store with interchangeable backends.

Parity target: ``realhf/base/name_resolve.py:43`` — the reference ships
memory/NFS/redis/etcd3/ray stores behind one interface; workers use it for
rendezvous, liveness (keepalive TTL), and small control state (model version,
server URLs, experiment status).

This implementation provides:
 - ``MemoryNameRecordRepo``   — in-process dict (single-process tests/local).
 - ``NfsNameRecordRepo``      — files under a shared directory (multi-process
   on one host or over NFS; the default for tests and local launches).
 - ``Etcd3NameRecordRepo``    — optional, only if etcd3 is importable.

Keys are slash-separated; values are short strings. ``add(..., replace=...)``,
``get``, ``wait``, ``delete``, ``get_subtree``, ``find_subtree``, and
``watch_names`` mirror the reference semantics.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from areal_tpu.base import logging

logger = logging.getLogger("name_resolve")


class NameEntryExistsError(Exception):
    pass


class NameEntryNotFoundError(Exception):
    pass


class NameRecordRepository:
    def add(
        self,
        name: str,
        value: str,
        delete_on_exit: bool = True,
        keepalive_ttl: Optional[float] = None,
        replace: bool = False,
    ) -> None:
        raise NotImplementedError()

    def add_subentry(self, name: str, value: str, **kwargs) -> str:
        sub = str(uuid.uuid4())[:8]
        self.add(f"{name}/{sub}", value, **kwargs)
        return f"{name}/{sub}"

    def get(self, name: str) -> str:
        raise NotImplementedError()

    def delete(self, name: str) -> None:
        raise NotImplementedError()

    def clear_subtree(self, root: str) -> None:
        raise NotImplementedError()

    def get_subtree(self, root: str) -> List[str]:
        """Values of all keys under root."""
        raise NotImplementedError()

    def find_subtree(self, root: str) -> List[str]:
        """Keys under root, sorted."""
        raise NotImplementedError()

    def wait(
        self, name: str, timeout: Optional[float] = None, poll_frequency: float = 0.1
    ) -> str:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"timed out waiting for key: {name}")
                time.sleep(poll_frequency)

    def watch_names(
        self,
        names: List[str],
        call_back: Callable[[], None],
        poll_frequency: float = 5.0,
    ) -> threading.Thread:
        """Fire call_back once when any of the names disappears."""

        def _watch():
            while True:
                for n in names:
                    try:
                        self.get(n)
                    except NameEntryNotFoundError:
                        call_back()
                        return
                time.sleep(poll_frequency)

        t = threading.Thread(target=_watch, daemon=True)
        t.start()
        return t

    def reset(self) -> None:
        pass


class MemoryNameRecordRepo(NameRecordRepository):
    def __init__(self):
        self._store: Dict[str, str] = {}
        self._lock = threading.Lock()

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        name = name.rstrip("/")
        with self._lock:
            if name in self._store and not replace:
                raise NameEntryExistsError(name)
            self._store[name] = str(value)

    def get(self, name):
        name = name.rstrip("/")
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            return self._store[name]

    def delete(self, name):
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            del self._store[name]

    @staticmethod
    def _under(key: str, root: str) -> bool:
        root = root.rstrip("/")
        return key == root or key.startswith(root + "/")

    def clear_subtree(self, root):
        with self._lock:
            for k in [k for k in self._store if self._under(k, root)]:
                del self._store[k]

    def get_subtree(self, root):
        with self._lock:
            return [
                v for k, v in sorted(self._store.items()) if self._under(k, root)
            ]

    def find_subtree(self, root):
        with self._lock:
            return sorted(k for k in self._store if self._under(k, root))

    def reset(self):
        with self._lock:
            self._store.clear()


class NfsNameRecordRepo(NameRecordRepository):
    """One file per key under a shared root directory."""

    def __init__(self, record_root: Optional[str] = None):
        self._root = record_root or os.environ.get(
            "AREAL_NAME_RESOLVE_ROOT",
            os.path.join(tempfile.gettempdir(), "areal_tpu", "name_resolve"),
        )
        self._to_delete: List[str] = []

    def _path(self, name: str) -> str:
        name = name.strip("/")
        return os.path.join(self._root, name, "ENTRY")

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        path = self._path(name)
        if os.path.exists(path) and not replace:
            raise NameEntryExistsError(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(value))
        os.replace(tmp, path)
        if delete_on_exit:
            self._to_delete.append(name)

    def get(self, name):
        path = self._path(name)
        try:
            with open(path) as f:
                return f.read()
        except FileNotFoundError:
            raise NameEntryNotFoundError(name) from None

    def delete(self, name):
        path = self._path(name)
        if not os.path.exists(path):
            raise NameEntryNotFoundError(name)
        os.remove(path)
        # Prune empty dirs up to root.
        d = os.path.dirname(path)
        while d != self._root and not os.listdir(d):
            os.rmdir(d)
            d = os.path.dirname(d)

    def clear_subtree(self, root):
        d = os.path.join(self._root, root.strip("/"))
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)

    def find_subtree(self, root):
        base = os.path.join(self._root, root.strip("/"))
        out = []
        for dirpath, _dirnames, filenames in os.walk(base):
            if "ENTRY" in filenames:
                rel = os.path.relpath(dirpath, self._root)
                out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    def get_subtree(self, root):
        return [self.get(k) for k in self.find_subtree(root)]

    def reset(self):
        for name in self._to_delete:
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self._to_delete.clear()


@dataclasses.dataclass
class NameResolveConfig:
    """Mirrors the reference's NameResolveConfig (realhf/api/cli_args.py:872)."""

    type: str = "nfs"  # memory | nfs | etcd3
    nfs_record_root: Optional[str] = None
    etcd3_addr: Optional[str] = None


DEFAULT_REPO: NameRecordRepository = NfsNameRecordRepo()


def reconfigure(config: NameResolveConfig) -> None:
    global DEFAULT_REPO
    if config.type == "memory":
        DEFAULT_REPO = MemoryNameRecordRepo()
    elif config.type == "nfs":
        DEFAULT_REPO = NfsNameRecordRepo(config.nfs_record_root)
    elif config.type == "etcd3":  # pragma: no cover - optional dependency
        raise NotImplementedError(
            "etcd3 backend requires the etcd3 package, not available in this image"
        )
    else:
        raise ValueError(f"unknown name_resolve type {config.type}")


def add(name, value, **kwargs):
    return DEFAULT_REPO.add(name, value, **kwargs)


def add_subentry(name, value, **kwargs):
    return DEFAULT_REPO.add_subentry(name, value, **kwargs)


def get(name):
    return DEFAULT_REPO.get(name)


def delete(name):
    return DEFAULT_REPO.delete(name)


def clear_subtree(root):
    return DEFAULT_REPO.clear_subtree(root)


def get_subtree(root):
    return DEFAULT_REPO.get_subtree(root)


def find_subtree(root):
    return DEFAULT_REPO.find_subtree(root)


def wait(name, timeout=None, poll_frequency=0.1):
    return DEFAULT_REPO.wait(name, timeout, poll_frequency)


def watch_names(names, call_back, poll_frequency=5.0):
    return DEFAULT_REPO.watch_names(names, call_back, poll_frequency)


def reset():
    return DEFAULT_REPO.reset()
