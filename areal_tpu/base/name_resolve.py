"""Distributed KV / service-discovery store with interchangeable backends.

Parity target: ``realhf/base/name_resolve.py:43`` — the reference ships
memory/NFS/redis/etcd3/ray stores behind one interface; workers use it for
rendezvous, liveness (keepalive TTL), and small control state (model version,
server URLs, experiment status).

This implementation provides:
 - ``MemoryNameRecordRepo``   — in-process dict (single-process tests/local).
 - ``NfsNameRecordRepo``      — files under a shared directory (multi-process
   on one host or over NFS; the default for tests and local launches).

An etcd3-backed repository is deliberately NOT implemented (the etcd3
client package is not in the TPU image): ``NameResolveConfig.type="etcd3"``
is rejected at config-parse time by ``api.cli_args.validate_config`` with
guidance, and :func:`reconfigure` raises as a backstop for programmatic
callers. A real backend would slot in at :func:`reconfigure`.

Keys are slash-separated; values are short strings. ``add(..., replace=...)``,
``get``, ``wait``, ``delete``, ``get_subtree``, ``find_subtree``, and
``watch_names`` mirror the reference semantics.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from areal_tpu.base import logging

logger = logging.getLogger("name_resolve")


class NameEntryExistsError(Exception):
    pass


class NameEntryNotFoundError(Exception):
    pass


class NameRecordRepository:
    def add(
        self,
        name: str,
        value: str,
        delete_on_exit: bool = True,
        keepalive_ttl: Optional[float] = None,
        replace: bool = False,
    ) -> None:
        raise NotImplementedError()

    def add_subentry(self, name: str, value: str, **kwargs) -> str:
        sub = str(uuid.uuid4())[:8]
        self.add(f"{name}/{sub}", value, **kwargs)
        return f"{name}/{sub}"

    def get(self, name: str) -> str:
        raise NotImplementedError()

    def touch(self, name: str) -> None:
        """Refresh a key's keepalive lease (no-op for keys registered
        without ``keepalive_ttl``). Raises NameEntryNotFoundError when the
        key is absent or its lease already expired — the caller's
        registration is gone and must be re-added, not refreshed."""
        raise NotImplementedError()

    def delete(self, name: str) -> None:
        raise NotImplementedError()

    def clear_subtree(self, root: str) -> None:
        raise NotImplementedError()

    def get_subtree(self, root: str) -> List[str]:
        """Values of all keys under root."""
        raise NotImplementedError()

    def find_subtree(self, root: str) -> List[str]:
        """Keys under root, sorted."""
        raise NotImplementedError()

    def wait(
        self, name: str, timeout: Optional[float] = None, poll_frequency: float = 0.1
    ) -> str:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"timed out waiting for key: {name}")
                time.sleep(poll_frequency)

    def watch_names(
        self,
        names: List[str],
        call_back: Callable[[], None],
        poll_frequency: float = 5.0,
    ) -> threading.Thread:
        """Fire call_back once when any of the names disappears."""

        def _watch():
            while True:
                for n in names:
                    try:
                        self.get(n)
                    except NameEntryNotFoundError:
                        call_back()
                        return
                time.sleep(poll_frequency)

        t = threading.Thread(target=_watch, daemon=True)
        t.start()
        return t

    def reset(self) -> None:
        pass


class MemoryNameRecordRepo(NameRecordRepository):
    def __init__(self):
        # name -> (value, expiry_monotonic_or_None, ttl_or_None)
        self._store: Dict[str, tuple] = {}
        self._lock = threading.Lock()

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        name = name.rstrip("/")
        with self._lock:
            self._purge_expired_locked(name)
            if name in self._store and not replace:
                raise NameEntryExistsError(name)
            expiry = (
                time.monotonic() + keepalive_ttl if keepalive_ttl else None
            )
            self._store[name] = (str(value), expiry, keepalive_ttl)

    def _purge_expired_locked(self, name) -> bool:
        """True iff the key existed but its lease had expired (purged)."""
        rec = self._store.get(name)
        if rec is None:
            return False
        if rec[1] is not None and time.monotonic() > rec[1]:
            del self._store[name]
            return True
        return False

    def get(self, name):
        name = name.rstrip("/")
        with self._lock:
            if self._purge_expired_locked(name) or name not in self._store:
                raise NameEntryNotFoundError(name)
            return self._store[name][0]

    def touch(self, name):
        name = name.rstrip("/")
        with self._lock:
            if self._purge_expired_locked(name) or name not in self._store:
                raise NameEntryNotFoundError(name)
            value, _, ttl = self._store[name]
            if ttl:
                self._store[name] = (value, time.monotonic() + ttl, ttl)

    def delete(self, name):
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            del self._store[name]

    @staticmethod
    def _under(key: str, root: str) -> bool:
        root = root.rstrip("/")
        return key == root or key.startswith(root + "/")

    def clear_subtree(self, root):
        with self._lock:
            for k in [k for k in self._store if self._under(k, root)]:
                del self._store[k]

    def get_subtree(self, root):
        with self._lock:
            return [
                self._store[k][0] for k in sorted(self._store)
                if self._under(k, root)
                and not self._purge_expired_locked(k)
            ]

    def find_subtree(self, root):
        with self._lock:
            return sorted(
                k for k in list(self._store)
                if self._under(k, root)
                and not self._purge_expired_locked(k)
            )

    def reset(self):
        with self._lock:
            self._store.clear()


class NfsNameRecordRepo(NameRecordRepository):
    """One file per key under a shared root directory."""

    def __init__(self, record_root: Optional[str] = None):
        self._root = record_root or os.environ.get(
            "AREAL_NAME_RESOLVE_ROOT",
            os.path.join(tempfile.gettempdir(), "areal_tpu", "name_resolve"),
        )
        self._to_delete: List[str] = []

    def _path(self, name: str) -> str:
        name = name.strip("/")
        return os.path.join(self._root, name, "ENTRY")

    @staticmethod
    def _ttl_path(entry_path: str) -> str:
        # Keepalive sidecar: the lease TTL in seconds; the ENTRY file's
        # mtime is the heartbeat timestamp (touch() refreshes it).
        return os.path.join(os.path.dirname(entry_path), "TTL")

    def _lease_expired(self, path: str) -> bool:
        ttl_path = self._ttl_path(path)
        try:
            with open(ttl_path) as f:
                ttl = float(f.read().strip())
            age = time.time() - os.path.getmtime(path)
        except (OSError, ValueError):
            return False  # no lease on this key (or racing deletion)
        return ttl > 0 and age > ttl

    def _purge_expired(self, name: str) -> None:
        logger.warning(f"name_resolve lease expired: {name}")
        try:
            self.delete(name)
        except (NameEntryNotFoundError, OSError):
            pass  # another observer purged it first

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        path = self._path(name)
        if os.path.exists(path) and not (replace or self._lease_expired(path)):
            raise NameEntryExistsError(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(value))
        # ENTRY first, TTL sidecar second. The other order opens a purge
        # race: a concurrent reader sees the NEW ttl against the STALE
        # entry's old mtime, judges the lease expired, and deletes the
        # just-written sidecar — leaving the re-registration permanently
        # lease-less (its ghost would never expire after a later kill).
        # This order's transient states are safe: fresh ENTRY + old TTL
        # is unexpired (fresh mtime), and ENTRY with no TTL yet is just
        # momentarily lease-less.
        os.replace(tmp, path)
        ttl_path = self._ttl_path(path)
        if keepalive_ttl:
            with open(ttl_path + f".tmp{os.getpid()}", "w") as f:
                f.write(repr(float(keepalive_ttl)))
            os.replace(ttl_path + f".tmp{os.getpid()}", ttl_path)
        elif os.path.exists(ttl_path):
            # Re-registration WITHOUT a lease must not inherit the dead
            # predecessor's TTL and expire out from under the new owner.
            try:
                os.remove(ttl_path)
            except OSError:
                pass
        if delete_on_exit:
            self._to_delete.append(name)

    def get(self, name):
        path = self._path(name)
        try:
            if self._lease_expired(path):
                self._purge_expired(name)
                raise NameEntryNotFoundError(name)
            with open(path) as f:
                return f.read()
        except FileNotFoundError:
            raise NameEntryNotFoundError(name) from None

    def touch(self, name):
        path = self._path(name)
        if not os.path.exists(path) or self._lease_expired(path):
            raise NameEntryNotFoundError(name)
        os.utime(path, None)

    def delete(self, name):
        path = self._path(name)
        if not os.path.exists(path):
            raise NameEntryNotFoundError(name)
        os.remove(path)
        ttl_path = self._ttl_path(path)
        if os.path.exists(ttl_path):
            try:
                os.remove(ttl_path)
            except OSError:
                pass
        # Prune empty dirs up to root.
        d = os.path.dirname(path)
        while d != self._root and not os.listdir(d):
            os.rmdir(d)
            d = os.path.dirname(d)

    def clear_subtree(self, root):
        d = os.path.join(self._root, root.strip("/"))
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)

    def find_subtree(self, root):
        base = os.path.join(self._root, root.strip("/"))
        out = []
        for dirpath, _dirnames, filenames in os.walk(base):
            if "ENTRY" in filenames:
                rel = os.path.relpath(dirpath, self._root)
                key = rel.replace(os.sep, "/")
                path = os.path.join(dirpath, "ENTRY")
                if self._lease_expired(path):
                    self._purge_expired(key)
                    continue
                out.append(key)
        return sorted(out)

    def get_subtree(self, root):
        out = []
        for k in self.find_subtree(root):
            try:
                out.append(self.get(k))
            except NameEntryNotFoundError:
                pass  # purged between the walk and the read
        return out

    def reset(self):
        for name in self._to_delete:
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self._to_delete.clear()


@dataclasses.dataclass
class NameResolveConfig:
    """Mirrors the reference's NameResolveConfig (realhf/api/cli_args.py:872)."""

    type: str = "nfs"  # memory | nfs ("etcd3" is rejected at config parse)
    nfs_record_root: Optional[str] = None
    etcd3_addr: Optional[str] = None  # kept for CLI parity; unused


DEFAULT_REPO: NameRecordRepository = NfsNameRecordRepo()


def reconfigure(config: NameResolveConfig) -> None:
    global DEFAULT_REPO
    if config.type == "memory":
        DEFAULT_REPO = MemoryNameRecordRepo()
    elif config.type == "nfs":
        DEFAULT_REPO = NfsNameRecordRepo(config.nfs_record_root)
    elif config.type == "etcd3":
        # Backstop for programmatic callers; the CLI path rejects this
        # earlier (and with the same guidance) in cli_args.validate_config.
        raise NotImplementedError(
            "name_resolve type='etcd3' is descoped: no etcd3 repository is "
            "implemented and the etcd3 package is not in this image — use "
            "type='nfs' (multi-host) or type='memory' (single-process)"
        )
    else:
        raise ValueError(f"unknown name_resolve type {config.type}")


def add(name, value, **kwargs):
    return DEFAULT_REPO.add(name, value, **kwargs)


def add_subentry(name, value, **kwargs):
    return DEFAULT_REPO.add_subentry(name, value, **kwargs)


def get(name):
    return DEFAULT_REPO.get(name)


def touch(name):
    return DEFAULT_REPO.touch(name)


def delete(name):
    return DEFAULT_REPO.delete(name)


def clear_subtree(root):
    return DEFAULT_REPO.clear_subtree(root)


def get_subtree(root):
    return DEFAULT_REPO.get_subtree(root)


def find_subtree(root):
    return DEFAULT_REPO.find_subtree(root)


def wait(name, timeout=None, poll_frequency=0.1):
    return DEFAULT_REPO.wait(name, timeout, poll_frequency)


def watch_names(names, call_back, poll_frequency=5.0):
    return DEFAULT_REPO.watch_names(names, call_back, poll_frequency)


def reset():
    return DEFAULT_REPO.reset()
