"""Serving engine for the generation fleet — admission control, priority
batch formation, cross-request prefix-reuse KV, bounded compile shapes,
per-class latency SLOs.

ROADMAP item 2: "millions of users" means the fleet must behave like a real
inference stack, not a rollout-only decode loop. The reference leans on
SGLang's radix cache and interruptible scheduler (SURVEY §2.12); the
serving literature (vLLM's PagedAttention block-level KV sharing, SGLang's
RadixAttention prefix cache) shows cross-request prefix reuse plus
admission-controlled continuous batching is what turns a decode loop into
a serving engine. This module owns those decisions; the generation server
(system/generation_server.py) delegates to it:

 - **Request classes** — ``interactive`` > ``eval`` > ``rollout`` in
   priority order (:data:`REQUEST_CLASSES`). Each class has a bounded
   admission queue; a full queue rejects with a 429-style
   :class:`AdmissionReject` carrying a retry-after hint, so backpressure
   reaches clients instead of growing an unbounded pending list.
 - **Priority batch formation** — :class:`ServingQueue` drains
   interactive requests into a batch before eval before rollout (FIFO
   within a class), so one fleet serves latency-sensitive traffic and
   bulk rollout traffic concurrently.
 - **Cross-request prefix-reuse KV** — :class:`KVStateStore` keeps the
   per-request decode states behind a token :class:`PrefixTrie`; a new
   request whose prompt shares a prefix with a retained state clones the
   donor's KV up to the shared length and prefills only the suffix
   (models/generate.py ``clone_prefix`` + ``extend_state``). Refcounted
   pinning guarantees LRU eviction never drops a state another request is
   cloning from.
 - **Bounded compile shapes** (VERDICT #9) — :class:`ShapeBucketPolicy`
   owns the (rows, capacity, chunk) shape set: capacities are geometric
   buckets up to a ceiling, chunk lengths and batch rows round up to
   configured buckets, and every compiled shape is recorded so the
   distinct-compiled-shapes gauge is a real number an alert can watch.
 - **Per-class SLOs** — queue-wait, time-to-first-chunk, and per-token
   latency histograms per request class through the PR 4 telemetry
   registry, served on the existing Prometheus ``/metrics``.

Everything here is event-loop-side bookkeeping (plain Python, no jax);
the decode math stays in models/generate.py.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from areal_tpu.api.train_config import ServingConfig
from areal_tpu.base import logging

logger = logging.getLogger("system.serving")

# Priority order: interactive traffic has the tightest latency SLO, eval
# is operator-interactive, rollout is bulk throughput work that tolerates
# queue-wait (the staleness gate upstream already paces it).
REQUEST_CLASSES = ("interactive", "eval", "rollout")


def normalize_class(cls: Any) -> str:
    """Unknown/absent classes serve as rollout (never reject on a typo —
    the bulk class has the loosest SLO and the deepest queue)."""
    return cls if cls in REQUEST_CLASSES else "rollout"


def round_up(n: int, bucket: int) -> int:
    """Round ``n`` up to a multiple of ``bucket``. The ONE copy of the
    bucket arithmetic: admission feasibility, prefill padding, and the
    decode thread's capacity math must all agree on it."""
    return ((n + bucket - 1) // bucket) * bucket


class AdmissionReject(Exception):
    """Queue for ``cls`` is at its admission limit; retry after a bit."""

    def __init__(self, cls: str, depth: int, limit: int, retry_after: float):
        super().__init__(
            f"{cls} queue full ({depth}/{limit}); retry after "
            f"{retry_after:g}s"
        )
        self.cls = cls
        self.depth = depth
        self.limit = limit
        self.retry_after = retry_after


class PromptTooLong(Exception):
    """Prompt (+ one decode chunk) exceeds the largest KV capacity bucket
    — permanent for this request (413), not a backpressure condition."""

    def __init__(self, needed: int, cap: int):
        super().__init__(
            f"prompt needs {needed} KV slots > max capacity {cap}"
        )
        self.needed = needed
        self.cap = cap


# --------------------------------------------------------------------------
# bounded compile-shape bucketing (VERDICT #9)
# --------------------------------------------------------------------------

# Shape-policy inputs of GenerationServerConfig, hoisted here (this module
# is jax-free) so config-parse-time validation can use the very same
# numbers: GenerationServerConfig's dataclass defaults alias these
# constants, and :func:`experiment_policy_kwargs` below is the ONE mapping
# from experiment-level knobs to the policy inputs — used by the async
# experiment wiring AND cli_args.validate_config, so the parse-time check
# and the spawned servers' real construction cannot drift.
GEN_KV_BUCKET_DEFAULT = 256
GEN_CHUNK_TOKENS_DEFAULT = 128
GEN_MAX_BATCH_SIZE_DEFAULT = 64
GEN_PROMPT_BUCKET_DEFAULT = 128


def experiment_policy_kwargs(cfg: Any) -> Dict[str, int]:
    """The exact ``policy_from_config`` inputs the generation servers
    spawned for ``cfg`` will construct their :class:`ShapeBucketPolicy`
    with. ``cfg`` is an experiment config; non-async experiments (no
    generation-server knobs) fall back to the server dataclass defaults,
    which alias the ``GEN_*_DEFAULT`` constants above."""
    return dict(
        # The servers' KV quantum is not an experiment-level knob.
        kv_bucket=GEN_KV_BUCKET_DEFAULT,
        chunk_tokens=int(getattr(
            cfg, "new_tokens_per_chunk", GEN_CHUNK_TOKENS_DEFAULT
        )),
        max_batch_size=int(getattr(
            cfg, "gen_max_batch_size", GEN_MAX_BATCH_SIZE_DEFAULT
        )),
        prompt_bucket=int(getattr(
            cfg, "gen_prompt_bucket", GEN_PROMPT_BUCKET_DEFAULT
        )),
    )


class ShapeBucketPolicy:
    """Owns the compiled-shape set of the decode engine.

    ``capacity_buckets=None`` is the legacy policy: capacities round to
    multiples of ``quantum`` without bound and chunk/rows pass through
    (exactly the pre-serving server behavior); shapes are still recorded
    so the gauge exists either way. With bucket lists, every dimension
    rounds UP to a configured bucket, which caps the shape set by
    construction — and ``width_buckets`` extends that to the prefill and
    suffix-extend widths, so the WORST-CASE total over all three shape
    kinds (decode: rows x capacities x chunks; prefill: rows x widths x
    chunks; extend: widths x capacities) is what the constructor checks
    against ``max_shapes`` — the gauge can never exceed the cap.
    """

    def __init__(
        self,
        quantum: int,
        capacity_buckets: Optional[Sequence[int]] = None,
        chunk_buckets: Optional[Sequence[int]] = None,
        row_buckets: Optional[Sequence[int]] = None,
        width_buckets: Optional[Sequence[int]] = None,
        max_shapes: int = 0,
    ):
        self.quantum = max(int(quantum), 1)
        self.capacity_buckets = (
            sorted(set(int(b) for b in capacity_buckets))
            if capacity_buckets else None
        )
        self.chunk_buckets = (
            sorted(set(int(b) for b in chunk_buckets))
            if chunk_buckets else None
        )
        self.row_buckets = (
            sorted(set(int(b) for b in row_buckets)) if row_buckets else None
        )
        self.width_buckets = (
            sorted(set(int(b) for b in width_buckets))
            if width_buckets else None
        )
        self.max_shapes = int(max_shapes)
        self._shapes: set = set()
        if self.max_shapes > 0 and self.capacity_buckets is not None:
            n_caps = len(self.capacity_buckets)
            n_chunks = len(self.chunk_buckets or [1])
            n_rows = len(self.row_buckets or [1])
            worst = n_caps * n_chunks * n_rows  # decode
            if self.width_buckets is not None:
                n_widths = len(self.width_buckets)
                # prefill (rows, width, S): S is a function of width+chunk
                worst += n_rows * n_widths * n_chunks
                # extend (1, width, S)
                worst += n_widths * n_caps
            if worst > self.max_shapes:
                raise ValueError(
                    f"shape-bucket config allows {worst} compiled shapes "
                    f"worst-case (decode + prefill + extend) > "
                    f"max_compiled_shapes={self.max_shapes}; coarsen the "
                    f"bucket lists or raise the cap (serving.* in "
                    f"api/train_config.py)"
                )

    # ---- rounding ----

    @staticmethod
    def _round_up(n: int, buckets: List[int]) -> int:
        for b in buckets:
            if n <= b:
                return b
        raise PromptTooLong(n, buckets[-1])

    def round_capacity(self, n: int) -> int:
        if self.capacity_buckets is None:
            return round_up(n, self.quantum)
        return self._round_up(n, self.capacity_buckets)

    def round_width(self, n: int) -> int:
        """Prefill/extend TOKEN width bucket for ``n`` (pass-through when
        unbounded). Prompt widths otherwise take one distinct value per
        ``prompt_bucket`` multiple — an unbounded prefill-shape family the
        decode-side buckets can't cap."""
        if self.width_buckets is None:
            return n
        return self._round_up(n, self.width_buckets)

    def round_chunk(self, n: int) -> int:
        if self.chunk_buckets is None:
            return n
        # Beyond the largest bucket: clamp (the row budget stops each row
        # at its own allowance, so a short chunk is a latency choice, not
        # a correctness one).
        if n >= self.chunk_buckets[-1]:
            return self.chunk_buckets[-1]
        return self._round_up(n, self.chunk_buckets)

    def round_chunk_down(self, n: int) -> int:
        """Largest chunk bucket ≤ n (n itself when none fits) — used when
        a capacity ceiling clamps the chunk: snapping DOWN keeps the
        emitted chunk a bucketed shape instead of minting one compiled
        shape per distinct remaining-room value."""
        if self.chunk_buckets is None:
            return n
        for b in reversed(self.chunk_buckets):
            if b <= n:
                return b
        return n

    def round_rows(self, n: int) -> int:
        if self.row_buckets is None:
            return n
        if n >= self.row_buckets[-1]:
            return self.row_buckets[-1]
        return self._round_up(n, self.row_buckets)

    def fits(self, n_slots: int) -> bool:
        """Can a sequence of ``n_slots`` ever sit in a KV capacity bucket?"""
        return (
            self.capacity_buckets is None
            or n_slots <= self.capacity_buckets[-1]
        )

    # ---- accounting ----

    def observe(self, kind: str, *dims: int) -> None:
        self._shapes.add((kind,) + tuple(int(d) for d in dims))

    @property
    def distinct_shapes(self) -> int:
        return len(self._shapes)

    def shapes(self) -> List[Tuple]:
        return sorted(self._shapes)


def policy_from_config(
    cfg: ServingConfig, *, kv_bucket: int, chunk_tokens: int,
    max_batch_size: int, prompt_bucket: int,
) -> ShapeBucketPolicy:
    """Build the server's shape policy: legacy pass-through when serving
    is disabled, bounded buckets (with derived defaults) when enabled."""
    if not cfg.enabled:
        return ShapeBucketPolicy(quantum=kv_bucket)
    caps = []
    c = max(kv_bucket, 1)
    while c < cfg.max_kv_capacity:
        caps.append(c)
        c *= 2
    caps.append(cfg.max_kv_capacity)
    chunks = list(cfg.chunk_buckets)
    if not chunks:
        # Geometric ladder (factor 4) down from chunk_tokens: a
        # small-budget batch (interactive TTFC) scans a small chunk
        # instead of the full chunk_tokens — round_chunk would otherwise
        # round a 4-token budget up to a 1024-step lax.scan. The ladder
        # multiplies the worst-case shape count by its length (≤ 4 at
        # the default chunk_tokens), which the constructor still checks.
        c = chunk_tokens
        while c > 16:
            chunks.append(c)
            c //= 4
        chunks.append(max(c, 1))
    rows = list(cfg.row_buckets)
    if not rows:
        r = 1
        while r < max_batch_size:
            rows.append(r)
            r *= 2
        rows.append(max_batch_size)
    elif max(rows) < max_batch_size:
        # round_rows would clamp a bigger drain DOWN and the decode batch
        # would run at its raw (unbucketed) size — one compiled shape per
        # distinct batch size, the exact churn the policy exists to stop.
        raise ValueError(
            f"serving.row_buckets max ({max(rows)}) < max_batch_size "
            f"({max_batch_size}): batches above the largest bucket would "
            f"compile per exact size; add {max_batch_size} to row_buckets "
            f"or lower max_batch_size"
        )
    # Prefill/extend widths: geometric doubling from prompt_bucket, with a
    # final bucket at the widest prefill that still leaves room for one
    # minimum decode chunk under the capacity ceiling — so the admissible
    # prompt range matches linear prompt_bucket padding while the width
    # set stays O(log(capacity)).
    top = cfg.max_kv_capacity - min(chunks)
    if top < max(prompt_bucket, 1):
        # A degenerate width ladder ([1]-ish) would pass construction and
        # then 413 EVERY request at admission — the widest admissible
        # prompt must cover at least one prompt_bucket-wide prefill.
        raise ValueError(
            f"serving.max_kv_capacity ({cfg.max_kv_capacity}) minus the "
            f"minimum chunk bucket ({min(chunks)}) leaves {top} KV slots "
            f"for prompts — less than one {prompt_bucket}-wide prompt "
            f"bucket, so every request would be rejected at admission; "
            f"raise max_kv_capacity or shrink chunk_buckets"
        )
    widths = []
    w = max(prompt_bucket, 1)
    while w < top:
        widths.append(w)
        w *= 2
    widths.append(max(top, 1))
    return ShapeBucketPolicy(
        quantum=kv_bucket, capacity_buckets=caps, chunk_buckets=chunks,
        row_buckets=rows, width_buckets=widths,
        max_shapes=cfg.max_compiled_shapes,
    )


# --------------------------------------------------------------------------
# token trie over retained prefixes
# --------------------------------------------------------------------------


class _TrieNode:
    __slots__ = ("children", "rids")

    def __init__(self):
        self.children: Dict[int, _TrieNode] = {}
        self.rids: set = set()


class PrefixTrie:
    """Token trie over the full token sequences backing retained KV
    states. ``longest(tokens)`` finds the deepest node on ``tokens``'s
    path that some retained sequence passes through — i.e. the longest
    shared prefix between the query and ANY retained state, plus a donor
    rid whose KV covers it (compact layout: slot j of a state holds token
    j, so any prefix of a donor's sequence is directly cloneable).

    One node per token, no path compression: insert/remove/match are all
    O(sequence length) pure-Python walks — fine at test scale and
    acceptable at kv_slots=256; a radix (edge-label-compressed) trie,
    SGLang's RadixAttention structure, is the follow-up if retained
    sequences reach tens of thousands of tokens."""

    def __init__(self):
        self._root = _TrieNode()
        # rid -> deepest node on its inserted path. Lets the per-chunk
        # replace in KVStateStore.put extend a retained sequence by the
        # new chunk's tokens (O(chunk)) instead of re-walking the full
        # sequence twice (O(seq) remove + O(seq) insert) on the decode
        # thread every chunk.
        self._tails: Dict[str, _TrieNode] = {}

    def insert(self, rid: str, tokens: np.ndarray) -> None:
        node = self._root
        node.rids.add(rid)
        for t in tokens:
            node = node.children.setdefault(int(t), _TrieNode())
            node.rids.add(rid)
        self._tails[rid] = node

    def extend(self, rid: str, suffix: np.ndarray) -> bool:
        """Grow ``rid``'s path by ``suffix`` from its cached tail node.
        The caller guarantees ``rid``'s inserted sequence is a prefix of
        (inserted + suffix) — i.e. the trie already covers everything up
        to the tail. Returns False (no-op) when ``rid`` has no cached
        tail, and the caller falls back to remove + insert."""
        node = self._tails.get(rid)
        if node is None:
            return False
        for t in suffix:
            node = node.children.setdefault(int(t), _TrieNode())
            node.rids.add(rid)
        self._tails[rid] = node
        return True

    def remove(self, rid: str, tokens: np.ndarray) -> None:
        self._tails.pop(rid, None)
        node = self._root
        node.rids.discard(rid)
        path = []
        for t in tokens:
            child = node.children.get(int(t))
            if child is None:
                return  # partially-removed / never inserted
            path.append((node, int(t), child))
            child.rids.discard(rid)
            node = child
        # Prune now-empty branches so the trie's size tracks live states.
        for parent, tok, child in reversed(path):
            if not child.rids and not child.children:
                del parent.children[tok]

    def longest(self, tokens: Sequence[int]) -> Tuple[Optional[str], int]:
        node = self._root
        best: Tuple[Optional[str], int] = (None, 0)
        depth = 0
        for t in tokens:
            node = node.children.get(int(t))
            if node is None or not node.rids:
                break
            depth += 1
            best = (next(iter(node.rids)), depth)
        return best


# --------------------------------------------------------------------------
# retained decode states: LRU + bytes budget + refcounted pins
# --------------------------------------------------------------------------


class ReqState:
    """Server-resident decode state of one in-flight chunked request.

    ``tokens`` is the full token sequence the KV covers (prompt +
    generated), backing the prefix trie; ``pins`` is the refcount held by
    requests currently cloning from this state — eviction skips pinned
    states unconditionally."""

    __slots__ = ("state", "cur_len", "version", "last_used", "nbytes",
                 "tokens", "pins")

    def __init__(self, state, cur_len: int, version: int,
                 tokens: Optional[np.ndarray] = None):
        self.state = state  # single-row decode state (models.generate)
        self.cur_len = cur_len
        self.version = version
        self.last_used = time.monotonic()
        self.nbytes = state["kv_k"].nbytes + state["kv_v"].nbytes
        self.tokens = tokens
        self.pins = 0


class KVStateStore:
    """Retained per-request decode states with LRU + KV-bytes eviction,
    indexed by a prefix trie for cross-request seeding.

    Thread-safe: the decode thread mutates the store (put/pop/evict and
    trie walks) while ``/update_weights`` clears it from the event loop —
    every method holds one RLock so dict/trie iteration never races a
    concurrent clear. The jax arrays inside a state are immutable, so a
    clone captured before a clear stays valid; the lock only protects the
    (dict, trie, pins) bookkeeping."""

    def __init__(self, slots: int, bytes_budget: int,
                 prefix_reuse: bool = False):
        import threading

        self.slots = slots
        self.bytes_budget = bytes_budget
        self.prefix_reuse = prefix_reuse
        self._states: Dict[str, ReqState] = {}
        self._trie = PrefixTrie()
        self._lock = threading.RLock()

    # ---- dict-ish surface ----

    def get(self, rid: str) -> Optional[ReqState]:
        with self._lock:
            return self._states.get(rid)

    def put(self, rid: str, st: ReqState) -> None:
        with self._lock:
            old = self._states.get(rid)
            if (
                self.prefix_reuse
                and st.tokens is not None
                and old is not None
                and old.tokens is not None
                and len(old.tokens) <= len(st.tokens)
                # Vectorized prefix check (memcmp-speed), vs. the two
                # O(seq) pure-Python trie walks it replaces: each chunk's
                # retained sequence strictly extends the previous one, so
                # the trie path only needs to grow by the new chunk.
                and np.array_equal(
                    st.tokens[: len(old.tokens)], old.tokens
                )
                and self._trie.extend(rid, st.tokens[len(old.tokens):])
            ):
                self._states[rid] = st
                return
            # replace: old trie entry must not outlive the state
            self.pop(rid)
            self._states[rid] = st
            if self.prefix_reuse and st.tokens is not None:
                self._trie.insert(rid, st.tokens)

    def pop(self, rid: str) -> Optional[ReqState]:
        with self._lock:
            st = self._states.pop(rid, None)
            if st is not None and self.prefix_reuse \
                    and st.tokens is not None:
                self._trie.remove(rid, st.tokens)
            return st

    def clear(self) -> None:
        with self._lock:
            self._states.clear()
            self._trie = PrefixTrie()

    @property
    def count(self) -> int:
        with self._lock:
            return len(self._states)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return sum(s.nbytes for s in self._states.values())

    # ---- prefix seeding ----

    def acquire_prefix(self, tokens: Sequence[int], version: int,
                       min_len: int = 1) -> Optional[Tuple[str, int]]:
        """Longest retained prefix of ``tokens`` at the given weight
        version. Returns ``(rid, shared_len)`` with the donor PINNED —
        the caller must :meth:`release` after cloning. The shared length
        is clamped to ``len(tokens) - 1`` unless the donor's whole state
        ends exactly at ``len(tokens)`` (a full match carries usable
        last-step logits; a partial one must leave ≥ 1 suffix token to
        recompute them)."""
        if not self.prefix_reuse:
            return None
        with self._lock:
            rid, depth = self._trie.longest(tokens)
            if rid is None:
                return None
            st = self._states.get(rid)
            if st is None or st.version != version:
                return None
            shared = min(depth, st.cur_len, len(tokens))
            if shared == len(tokens) and st.cur_len != shared:
                shared -= 1
            if shared < max(min_len, 1):
                return None
            st.pins += 1
            st.last_used = time.monotonic()
            return rid, shared

    def release(self, rid: str) -> None:
        with self._lock:
            st = self._states.get(rid)
            if st is not None and st.pins > 0:
                st.pins -= 1

    # ---- eviction ----

    def evict(self) -> int:
        """LRU-evict down to the slot/bytes budgets; pinned states are
        never dropped (a clone in flight would read freed KV). Returns
        the number of evicted states."""
        with self._lock:
            if self.slots <= 0:
                n = self.count
                self.clear()
                return n
            n_evicted = 0
            total = self.nbytes
            while True:
                over = len(self._states) > self.slots or (
                    total > self.bytes_budget and self._states
                )
                if not over:
                    break
                victims = [
                    (st.last_used, rid)
                    for rid, st in self._states.items()
                    if st.pins == 0
                ]
                if not victims:
                    break  # everything pinned: budgets yield to correctness
                _, rid = min(victims)
                total -= self._states[rid].nbytes
                self.pop(rid)
                n_evicted += 1
            return n_evicted


# --------------------------------------------------------------------------
# admission + priority batch formation
# --------------------------------------------------------------------------


class ServingQueue:
    """Per-class bounded queues with priority drain.

    Disabled mode reproduces the legacy server exactly: one unbounded
    FIFO across classes. Enabled mode admits per class up to its limit
    (else :class:`AdmissionReject`) and pops in :data:`REQUEST_CLASSES`
    priority order, FIFO within a class."""

    def __init__(self, cfg: ServingConfig):
        self.cfg = cfg
        self._queues: Dict[str, deque] = {c: deque() for c in REQUEST_CLASSES}
        self._fifo: deque = deque()  # disabled-mode arrival order
        import asyncio

        self._nonempty = asyncio.Event()

    def _limit(self, cls: str) -> int:
        return int(getattr(self.cfg, f"queue_limit_{cls}", 0))

    def depth(self, cls: str) -> int:
        return len(self._queues[cls]) if self.cfg.enabled else len(self._fifo)

    def qsize(self) -> int:
        if not self.cfg.enabled:
            return len(self._fifo)
        return sum(len(q) for q in self._queues.values())

    def empty(self) -> bool:
        return self.qsize() == 0

    def put(self, pending, cls: str = "rollout") -> None:
        """Admit or raise. Synchronous on purpose: the admission check
        and the append are atomic on the event loop (no await between)."""
        if not self.cfg.enabled:
            self._fifo.append(pending)
        else:
            limit = self._limit(cls)
            q = self._queues[cls]
            if limit > 0 and len(q) >= limit:
                raise AdmissionReject(
                    cls, len(q), limit, self.cfg.retry_after_secs
                )
            q.append(pending)
        self._nonempty.set()

    def _pop(self):
        if not self.cfg.enabled:
            return self._fifo.popleft() if self._fifo else None
        for cls in REQUEST_CLASSES:
            if self._queues[cls]:
                return self._queues[cls].popleft()
        return None

    async def get(self):
        while True:
            p = self._pop()
            if p is not None:
                return p
            self._nonempty.clear()
            await self._nonempty.wait()

    def get_nowait(self):
        p = self._pop()
        if p is None:
            raise IndexError("serving queue empty")
        return p

    def drain(self, max_n: int) -> list:
        """Up to ``max_n`` more requests, priority order, non-blocking.

        ``min_rollout_share`` of the batch is reserved for the rollout
        class while it has waiters: strict priority alone would let
        sustained interactive/eval load starve rollouts indefinitely —
        429s escalating to abandoned generations fleet-wide — while
        every serving SLO still looked healthy."""
        out = []
        reserve = 0
        if self.cfg.enabled and max_n > 0:
            share = min(max(float(self.cfg.min_rollout_share), 0.0), 1.0)
            if share > 0 and self._queues["rollout"]:
                reserve = min(
                    len(self._queues["rollout"]),
                    max(1, int(max_n * share)),
                )
        while len(out) < max_n - reserve:
            p = self._pop()
            if p is None:
                break
            out.append(p)
        # The priority loop may already have drained rollout (higher
        # classes ran dry); popleft only what is still waiting.
        while reserve > 0 and self._queues["rollout"]:
            out.append(self._queues["rollout"].popleft())
            reserve -= 1
        return out


# --------------------------------------------------------------------------
# engine facade
# --------------------------------------------------------------------------


class ServingEngine:
    """The (queue, kv store, shape policy, SLO metrics) bundle the
    generation server delegates its scheduling decisions to."""

    def __init__(self, cfg: ServingConfig, *, kv_slots: int,
                 kv_bytes_budget: int, kv_bucket: int, chunk_tokens: int,
                 max_batch_size: int, prompt_bucket: int = 1,
                 telemetry=None):
        from areal_tpu.base import telemetry as telemetry_mod

        self.cfg = cfg
        self.prompt_bucket = max(int(prompt_bucket), 1)
        self.telemetry = telemetry if telemetry is not None \
            else telemetry_mod.NULL
        self.queue = ServingQueue(cfg)
        self.kv = KVStateStore(
            kv_slots, kv_bytes_budget,
            prefix_reuse=cfg.enabled and cfg.prefix_reuse,
        )
        self.shapes = policy_from_config(
            cfg, kv_bucket=kv_bucket, chunk_tokens=chunk_tokens,
            max_batch_size=max_batch_size, prompt_bucket=self.prompt_bucket,
        )

    # ---- admission ----

    def admit(self, pending, cls: str, prompt_len: int,
              planned_len: Optional[int] = None) -> None:
        """Admission decision for one request: capacity feasibility first
        (413-style, permanent), then the class queue bound (429-style,
        backpressure), then enqueue. Raises or succeeds atomically.

        ``planned_len`` is the generation's eventual total sequence
        length (prompt + the client's FULL remaining token budget, not
        just this chunk). When given, infeasibility is rejected up front
        — vLLM's prompt+max_tokens admission check — instead of decoding
        up to the capacity ceiling and 413-abandoning mid-flight with
        every accumulated token discarded."""
        # Feasibility is judged on the BUCKETED prompt width the decode
        # thread will actually pad to — prompt_bucket multiple, then the
        # policy's width bucket: admitting on the raw length would let a
        # near-ceiling prompt pass here and then blow past the largest
        # capacity bucket inside the decode thread, failing the whole
        # co-scheduled batch.
        if self.cfg.enabled:
            try:
                # The widest admission a chunked generation can reach is
                # its LAST chunk's: prompt+accumulated = planned - 1 in
                # the worst (no-EOS) case. Checking that width now makes
                # the mid-flight 413 a chunk-1 reject.
                check_len = max(prompt_len, (planned_len or 0) - 1)
                w = self.shapes.round_width(
                    round_up(check_len, self.prompt_bucket)
                )
                # Derived width buckets top out at capacity - min_chunk,
                # so round_width succeeding implies the prompt fits; the
                # explicit check only covers directly-constructed
                # policies without width buckets (pass-through).
                if self.shapes.width_buckets is None \
                        and not self.shapes.fits(w + 1):
                    raise PromptTooLong(
                        w + 1, self.shapes.capacity_buckets[-1]
                    )
            except PromptTooLong:
                self.telemetry.inc(f"serving/{cls}/too_long")
                raise
        try:
            self.queue.put(pending, cls)
        except AdmissionReject:
            self.telemetry.inc(f"serving/{cls}/rejected")
            raise
        self.telemetry.inc(f"serving/{cls}/admitted")

    # ---- SLO recording ----

    def record_queue_wait(self, cls: str, secs: float,
                          trace=None, t_start_wall: float = 0.0) -> None:
        self.telemetry.observe(f"serving/{cls}/queue_wait_secs", secs)
        if trace is not None:
            # Sample-lineage tracing (docs/observability.md): the same
            # dwell as a per-request span under the caller's trace — the
            # "queue" stage of the stitched staleness decomposition.
            self.telemetry.add_span(
                "genserver/queue_wait", t_start_wall, secs,
                trace=trace, cls=cls,
            )

    def record_first_chunk(self, cls: str, secs: float) -> None:
        self.telemetry.observe(f"serving/{cls}/ttfc_secs", secs)

    def record_token_latency(self, cls: str, secs: float) -> None:
        self.telemetry.observe(
            f"serving/{cls}/token_secs", secs,
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5),
        )

    def export_gauges(self) -> None:
        t = self.telemetry
        t.set_gauge("serving/compiled_shapes", self.shapes.distinct_shapes)
        t.set_gauge("genserver/kv_states", self.kv.count)
        t.set_gauge("genserver/kv_bytes", self.kv.nbytes)
        if self.cfg.enabled:
            for cls in REQUEST_CLASSES:
                t.set_gauge(f"serving/{cls}/queue_depth",
                            self.queue.depth(cls))
