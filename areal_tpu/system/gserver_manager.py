"""Generation-server manager — routing, staleness gate, weight fanout,
fleet health.

Parity target: ``realhf/system/gserver_manager.py:32`` — the singleton
rollout controller: HTTP router over the generation-server fleet
(round-robin / least-requests), the **staleness gate** that blocks new
rollouts when they would be too off-policy, ``/finish_rollout`` accounting,
and the weight-update fanout (watch ``names.model_version``, POST
``/update_weights`` to every server, GC old realloc dirs). The fanout
payload is transport-aware: when the trainer publishes over the streamed
transport (system/weight_stream.py, discovered via names.weight_stream)
servers pull per-tensor chunks from the trainer's host cache; otherwise
they read the realloc-dir checkpoint (disk fallback).

Staleness rule (reference ``is_staled`` :351):
    expected_version = (trained_samples + running) // train_batch_size
    allowed  iff  expected_version <= max_head_offpolicyness + current_version

Fleet health (docs/fault_tolerance.md): a background loop polls every
known server's ``GET /health``; ``health_failure_threshold`` consecutive
failures evict a server from routing (its leases drain, its inflight slots
free), a passing check re-admits it after its weights are reconciled to the
current version, and newly registered servers join through the same gate.
The weight fanout has a per-server timeout + bounded retry; a server that
never acks is evicted rather than left silently serving stale weights.

Fleet elasticity (docs/fault_tolerance.md §Autoscaling): with
``autoscale.enabled`` the manager additionally hosts the slow scaling
controller (system/autoscaler.py) — target size from telemetry signals
with hysteresis/cooldown/bounds, scale-up via a published plan the
launcher-side executor satisfies by spawning supervised single-server
workers (joining through this manager's discovery + streamed-weight
admission path), and scale-down / straggler defense / preemption notices
through the **cordon** state: the server leaves the routing set, its
inflight rollouts drain on their sticky leases (or fail over), then a
drained dynamic server gets a WorkerControl-commanded exit. Pinned at
``max_servers`` under sustained saturation, ``/allocate_rollout`` denials
carry a Retry-After hint so rollout workers slow prompt admission
(overload backpressure).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import shutil
import time
from typing import Dict, List, Optional

from areal_tpu.api.train_config import AutoscaleConfig, TelemetryConfig
from areal_tpu.base import logging, name_resolve, names, network, telemetry
from areal_tpu.base.retry import FaultInjector, RetryPolicy, aretry
from areal_tpu.system import autoscaler as autoscale_mod
from areal_tpu.system.serving import REQUEST_CLASSES, normalize_class

logger = logging.getLogger("system.gserver_mgr")


@dataclasses.dataclass
class GserverManagerConfig:
    experiment: str = "exp"
    trial: str = "trial"
    model_role: str = "actor"
    n_servers: int = 1
    train_batch_size: int = 8
    max_head_offpolicyness: int = 0
    max_concurrent_rollouts: int = 64
    schedule_policy: str = "round_robin"  # or least_requests
    realloc_dir: str = "/tmp/areal_tpu/realloc"
    weight_poll_secs: float = 1.0
    port: Optional[int] = None
    keep_last_versions: int = 2
    # Routing leases expire if the client neither renews (per chunk) nor
    # releases — a crashed client must not pin inflight counts forever.
    lease_ttl_secs: float = 120.0
    # ---- fleet health / failure recovery (docs/fault_tolerance.md) ----
    health_check_interval_secs: float = 2.0
    health_check_timeout_secs: float = 2.0
    # Consecutive /health failures before a server is evicted from routing.
    health_failure_threshold: int = 3
    # Per-server /update_weights budget: each attempt is bounded by
    # fanout_timeout_secs and retried per fanout_retry before eviction.
    fanout_timeout_secs: float = 60.0
    fanout_retry: RetryPolicy = dataclasses.field(
        default_factory=lambda: RetryPolicy(
            max_attempts=2, base_delay_secs=0.2, max_delay_secs=2.0
        )
    )
    # Unified telemetry (base/telemetry.py): fleet gauges, probe-outcome
    # counters, fanout ack-latency histograms. Off by default.
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )
    # Liveness lease on the manager's name_resolve registration
    # (docs/fault_tolerance.md): >0 registers the URL with this
    # keepalive TTL and heartbeats it from a dedicated thread, so a
    # SIGKILLed manager's ghost endpoint expires instead of wedging
    # every client resolve. 0 falls back to the supervisor-set
    # AREAL_WORKER_KEEPALIVE_TTL env (absent → no lease).
    keepalive_ttl_secs: float = 0.0
    # Elastic fleet autoscaling + straggler defense + overload
    # backpressure (system/autoscaler.py, docs/fault_tolerance.md
    # §Autoscaling). The cordon API works even when disabled.
    autoscale: AutoscaleConfig = dataclasses.field(
        default_factory=AutoscaleConfig
    )


@dataclasses.dataclass
class _ServerHealth:
    """Per-server fleet-membership state (keyed by url)."""

    routable: bool = True  # in the routing set
    consecutive_failures: int = 0
    acked_version: int = 0  # last weight version this server confirmed
    evicted_reason: str = ""
    # Most recent probe/push failure detail — kept even after the counter
    # resets so an eviction can say WHY, not just which url.
    last_failure: str = ""
    reconciling: bool = False  # re-admission weight push in flight
    # ---- cordon-and-drain (docs/fault_tolerance.md §Autoscaling) ----
    # Cordoned: out of the routing set but NOT forgotten — existing
    # leases stay valid so inflight rollouts drain on their sticky
    # routes, and the health loop keeps probing but never re-admits
    # until uncordon. Powers scale-down, straggler defense, and
    # operator preemption notices alike.
    cordoned: bool = False
    cordon_reason: str = ""
    cordon_deadline: float = 0.0  # monotonic; 0 = no drain in progress
    exit_commanded: bool = False  # dynamic server already told to exit
    # Uncordoned but not yet re-admitted by the health gate: counts as
    # pending capacity so the plan doesn't spawn a spurious replacement
    # in the one-sweep gap.
    uncordon_pending: bool = False
    # ---- per-server stats captured from /health probe bodies ----
    server_id: str = ""
    queue_depth: int = 0
    ttfc_ewma_secs: float = 0.0
    # Straggler defense: routed only when no faster server is available.
    deprioritized: bool = False


# Read-only fallback for lookups on urls that raced out of self.health.
_DEFAULT_HEALTH = _ServerHealth()


class GserverManager:
    def __init__(self, cfg: GserverManagerConfig,
                 fault_injector: Optional[FaultInjector] = None):
        self.cfg = cfg
        self.servers: List[str] = []  # healthy, routable urls
        self.health: Dict[str, _ServerHealth] = {}  # every known url
        self.version = 0
        self._rr = 0
        self._inflight: Dict[str, int] = {}  # url -> outstanding requests
        self._leases: Dict[str, tuple] = {}  # lease_id -> (url, expires_at)
        # Class-aware routing (docs/serving.md): leases carry a request
        # class so one fleet serves rollout AND interactive/eval traffic
        # with per-class load accounting. Kept out of the lease tuple so
        # existing (url, expires) consumers stay untouched.
        self._lease_class: Dict[str, str] = {}  # lease_id -> class
        self._inflight_cls: Dict[str, Dict[str, int]] = {}  # url -> cls -> n
        self._lease_seq = 0
        # Both staleness terms are counted in SAMPLE units (the reference's
        # is_staled compares against train_batch_size samples): a rollout
        # allocation of group_size samples adds group_size to running.
        self.running_rollouts = 0
        self.accepted_rollouts = 0  # trained samples submitted
        self._watcher_task = None
        self._health_task = None
        self._autoscale_task = None
        self._reconcile_tasks: set = set()
        self._url: Optional[str] = None
        self.faults = fault_injector
        # Elastic autoscaling (system/autoscaler.py): the slow scaling
        # controller riding next to this reactive router. The straggler
        # tracker runs whenever straggler_defense is on — it only needs
        # the health loop, not the scaling loop.
        ac = cfg.autoscale
        self.autoscaler = (
            autoscale_mod.AutoscalerCore(ac) if ac.enabled else None
        )
        self.straggler = (
            autoscale_mod.StragglerTracker(
                factor=ac.straggler_factor,
                min_probes=ac.straggler_min_probes,
                slow_sweeps=ac.straggler_slow_sweeps,
                cordon_sweeps=ac.straggler_cordon_sweeps,
                floor_secs=ac.straggler_floor_secs,
            ) if ac.enabled and ac.straggler_defense else None
        )
        self._overloaded = False  # pinned at max_servers AND saturated
        # Weight-sync latency bookkeeping (north-star metric #2).
        self.last_sync_fanout_secs: Optional[float] = None
        self.last_sync_e2e_secs: Optional[float] = None
        self.sync_history: List[tuple] = []
        self.telemetry = (
            telemetry.Telemetry(
                cfg.experiment, cfg.trial, "gserver_manager", 0,
                cfg=cfg.telemetry,
            ) if cfg.telemetry.enabled else telemetry.NULL
        )

    # ---------------- discovery ----------------

    async def wait_for_servers(self, timeout: float = 300.0):
        deadline = time.monotonic() + timeout
        root = names.gen_server_root(self.cfg.experiment, self.cfg.trial)
        while time.monotonic() < deadline:
            urls = sorted(name_resolve.get_subtree(root))
            if len(urls) >= self.cfg.n_servers:
                self.servers = urls
                self._inflight = {u: 0 for u in urls}
                self.health = {u: _ServerHealth() for u in urls}
                logger.info(f"found {len(urls)} generation servers")
                return
            await asyncio.sleep(0.2)
        raise TimeoutError("generation servers did not register")

    # ---------------- fleet health ----------------

    def _drop_server_leases(self, url: str) -> int:
        """Retire every lease on ``url`` and forget its inflight slots.
        Returns the number of leases dropped."""
        dropped = [lid for lid, (u, _) in self._leases.items() if u == url]
        for lid in dropped:
            del self._leases[lid]
            self._lease_class.pop(lid, None)
        self._inflight.pop(url, None)
        self._inflight_cls.pop(url, None)
        return len(dropped)

    def _evict(self, url: str, reason: str) -> None:
        """Remove a server from routing: drain its leases, free its
        inflight slots. The url stays in ``self.health`` so the health loop
        keeps probing it for re-admission."""
        st = self.health.setdefault(url, _ServerHealth())
        if (
            not st.routable
            and url not in self.servers
            and url not in self._inflight
            and not any(u == url for u, _ in self._leases.values())
        ):
            # Already fully out (a CORDONED server keeps its inflight
            # bookkeeping until it drains — evicting one, e.g. on
            # deregistration, must still drop those leases above).
            return
        st.routable = False
        st.evicted_reason = reason
        if url in self.servers:
            self.servers.remove(url)
        dropped = self._drop_server_leases(url)
        self.telemetry.inc("gsmgr/evictions")
        # The last probe/push failure is the actionable detail (connection
        # refused vs timeout vs bad status) — the reason alone often only
        # says "N consecutive health failures".
        why = (f"; last failure: {st.last_failure}"
               if st.last_failure and st.last_failure not in reason else "")
        # Leave post-mortem evidence when a fault-tolerance path fires:
        # the eviction lands in the flight ring as an event, and the
        # manager's recent span/event window is dumped to
        # flight_gserver_manager0.jsonl (no-op without flight_dir).
        self.telemetry.event(
            "gsmgr/evict", url=url, reason=reason,
            last_failure=st.last_failure, dropped_leases=dropped,
        )
        self.telemetry.flight_dump(reason=f"evict {url}: {reason}")
        logger.warning(
            f"evicted {url} ({reason}{why}); dropped {dropped} leases, "
            f"{len(self.servers)} servers remain"
        )

    def _admit(self, url: str) -> None:
        st = self.health.get(url)
        if st is None:
            # Deregistered while a reconcile was in flight: stay forgotten
            # rather than resurrecting a permanently-dead url into routing.
            return
        if st.cordoned:
            # Cordon survives health recoveries by design — only an
            # explicit uncordon (operator or autoscaler reclaim) lets the
            # health loop route this server again.
            return
        st.routable = True
        st.consecutive_failures = 0
        st.evicted_reason = ""
        st.uncordon_pending = False
        if url not in self.servers:
            self.servers.append(url)
            self.servers.sort()
        self._inflight.setdefault(url, 0)

    # ---------------- cordon-and-drain ----------------

    def cordon(self, url: str, reason: str, source: str = "operator") -> bool:
        """Take ``url`` out of the routing set WITHOUT dropping its
        leases: new requests stop landing, inflight rollouts drain on
        their sticky routes (or fail over when the server dies), and the
        health loop keeps probing but never re-admits. Scale-down,
        straggler defense, and preemption notices all converge here."""
        st = self.health.get(url)
        if st is None or st.cordoned:
            return False
        st.cordoned = True
        st.cordon_reason = reason
        st.routable = False
        st.deprioritized = False
        st.exit_commanded = False
        st.uncordon_pending = False
        st.cordon_deadline = (
            time.monotonic() + self.cfg.autoscale.drain_timeout_secs
        )
        if url in self.servers:
            self.servers.remove(url)
        if self.straggler is not None:
            self.straggler.forget(url)
        inflight = self._inflight.get(url, 0)
        self.telemetry.inc("autoscale/cordons")
        self.telemetry.inc(f"autoscale/cordons_{source}")
        self.telemetry.event(
            "autoscale/cordon", url=url, reason=reason, source=source,
            inflight=inflight,
        )
        logger.warning(
            f"cordoned {url} ({reason}, source={source}); {inflight} "
            f"inflight requests draining, {len(self.servers)} servers "
            f"remain routable"
        )
        self._update_fleet_gauges()
        return True

    def uncordon(self, url: str) -> bool:
        """Lift a cordon. The server does NOT route immediately: it goes
        back through the health gate (probe + weight reconcile), exactly
        like a newly discovered server — its weights may be several
        versions stale by now."""
        st = self.health.get(url)
        if st is None or not st.cordoned:
            return False
        st.cordoned = False
        st.cordon_reason = ""
        st.cordon_deadline = 0.0
        st.exit_commanded = False
        st.consecutive_failures = 0
        st.uncordon_pending = True
        self.telemetry.inc("autoscale/uncordons")
        self.telemetry.event("autoscale/uncordon", url=url)
        logger.info(f"uncordoned {url}; re-admission via the health gate")
        return True

    def _server_draining_load(self, url: str) -> int:
        """Outstanding work pinning a cordoned server: live leases plus
        any inflight slots they hold."""
        leases = sum(1 for u, _ in self._leases.values() if u == url)
        return max(leases, self._inflight.get(url, 0))

    def _current_weight_path(self) -> str:
        return os.path.join(
            self.cfg.realloc_dir, self.cfg.model_role, str(self.version)
        )

    def _update_payload(self, v: int, path: str) -> Dict:
        """The /update_weights request body for version ``v``. Transport is
        auto-detected per push, most-direct first: a trainer publishing
        over the DEVICE transport registers a publication descriptor under
        names.weight_device — servers swap the on-device publication in
        (parallel/reshard.py), with the descriptor's digest as the
        integrity gate; a STREAM trainer registers its
        WeightStreamPublisher endpoint under names.weight_stream — servers
        pull chunks from the trainer's host cache; otherwise the legacy
        disk payload points at the realloc checkpoint
        (docs/weight_sync.md)."""
        try:
            desc = json.loads(name_resolve.get(names.weight_device(
                self.cfg.experiment, self.cfg.trial, self.cfg.model_role
            )))
        except Exception:  # noqa: BLE001 — no device publication
            desc = None
        if desc and int(desc.get("version", -1)) == v:
            # A version-skewed descriptor (descriptor written, version key
            # not yet bumped — or vice versa after a crash) falls through
            # to stream/disk rather than steering the fleet at a
            # publication whose digest gate is guaranteed to fail.
            return {"device": True, "role": self.cfg.model_role,
                    "digest": desc.get("digest", ""), "version": v}
        try:
            endpoint = name_resolve.get(names.weight_stream(
                self.cfg.experiment, self.cfg.trial, self.cfg.model_role
            ))
        except Exception:  # noqa: BLE001 — no stream publisher: disk mode
            endpoint = None
        if endpoint:
            return {"endpoint": endpoint, "version": v}
        return {"path": path, "version": v}

    async def _reconcile_weights(self, sess, url: str,
                                 server_version: int) -> bool:
        """Bring a (re)joining server to the current weight version before
        it serves traffic — a stale server would tag rollouts with old
        version numbers AND old logprobs (silently off-policy)."""
        if self.version == 0 or server_version >= self.version:
            st = self.health.get(url)
            if st is not None:  # entry may have been pruned mid-reconcile
                st.acked_version = self.version
            return True
        ok = await self._push_weights_one(
            sess, url, self.version, self._current_weight_path()
        )
        if not ok:
            logger.warning(f"{url} failed weight reconcile to "
                           f"v{self.version}; not re-admitting yet")
        return ok

    async def _check_one(self, sess, url: str) -> None:
        import aiohttp

        st = self.health.setdefault(url, _ServerHealth())
        # Compare the probed version against the fleet version AT PROBE
        # TIME: a fanout completing while the GET is in flight would
        # otherwise make a just-updated server's (older) snapshot look
        # stale and falsely evict it on every weight update.
        version_at_probe = self.version
        try:
            if self.faults is not None:
                self.faults.maybe_fail("health", url=url)
            async with sess.get(
                f"{url}/health",
                timeout=aiohttp.ClientTimeout(
                    total=self.cfg.health_check_timeout_secs
                ),
            ) as r:
                if r.status != 200:
                    raise RuntimeError(f"/health status {r.status}")
                body = await r.json()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — any probe failure counts
            st.consecutive_failures += 1
            st.last_failure = f"health probe: {e!r}"
            self.telemetry.inc("gsmgr/health_probe_failures")
            if (
                st.routable
                and st.consecutive_failures
                >= self.cfg.health_failure_threshold
            ):
                self._evict(url, f"{st.consecutive_failures} consecutive "
                                 f"health failures ({e})")
            elif (
                st.cordoned
                and st.consecutive_failures
                >= self.cfg.health_failure_threshold
            ):
                # A cordoned server died mid-drain: its clients fail over
                # via chunk replay; retire its leases now so the quota
                # accounting doesn't wait out the lease TTL.
                self._drop_server_leases(url)
            if st.consecutive_failures >= self.cfg.health_failure_threshold:
                st.uncordon_pending = False  # dead, not pending capacity
            return
        st.consecutive_failures = 0
        # Per-server load/latency stats ride the probe body — the
        # autoscale signals and the straggler EWMAs come for free with
        # the sweep the health loop already pays for.
        st.server_id = str(body.get("server_id", st.server_id) or "")
        st.queue_depth = int(body.get("queue_depth", 0) or 0)
        st.ttfc_ewma_secs = float(body.get("ttfc_ewma_secs", 0.0) or 0.0)
        decode_ewma = body.get("decode_ewma_secs")
        if (
            self.straggler is not None and st.routable
            and decode_ewma is not None
        ):
            self.straggler.observe(url, float(decode_ewma))
        # A passing probe clears the failure detail — otherwise a later
        # eviction via a NON-probe path (version regression, fanout no-ack)
        # would attach an hours-stale probe error as its explanation.
        st.last_failure = ""
        self.telemetry.inc("gsmgr/health_probe_ok")
        if st.routable and int(body.get("version", 0)) < version_at_probe:
            # A routable server reporting an old version was restarted in
            # place (pinned port: same url, fresh process at base weights).
            # Demote it — the reconcile path below brings it back at the
            # current version instead of letting it serve stale weights.
            self._evict(
                url, f"reports v{body.get('version')} < fleet "
                     f"v{version_at_probe} (in-place restart?)"
            )
        if not st.routable and not st.reconciling and not st.cordoned:
            # Re-admission reconcile runs DETACHED: a slow weight load on
            # one rejoining server must not stall the sweep (and eviction
            # of other dead servers) for the whole fanout budget. A
            # CORDONED server never re-admits here — uncordon first.
            st.reconciling = True
            server_version = int(body.get("version", 0))

            async def _readmit():
                try:
                    if not await self._reconcile_weights(
                        sess, url, server_version
                    ):
                        return
                    cur = self.health.get(url)
                    if cur is None:
                        return  # deregistered mid-reconcile: stay forgotten
                    if cur.acked_version < self.version:
                        # A fanout advanced the fleet past the version we
                        # just reconciled to — admitting now would route to
                        # stale weights; the next sweep reconciles again.
                        return
                    self._admit(url)
                    logger.info(
                        f"re-admitted {url} at weight v{self.version}"
                    )
                finally:
                    st.reconciling = False

            t = asyncio.ensure_future(_readmit())
            self._reconcile_tasks.add(t)
            t.add_done_callback(self._reconcile_tasks.discard)

    async def check_fleet(self, sess) -> None:
        """One health sweep: pick up new registrations from name_resolve,
        drop deregistered urls, probe every known server, evict/re-admit
        accordingly."""
        root = names.gen_server_root(self.cfg.experiment, self.cfg.trial)
        try:
            registered = set(name_resolve.get_subtree(root))
        except Exception:  # noqa: BLE001 — name-resolve hiccups are benign
            registered = None
        if registered is not None:
            for url in registered:
                if url not in self.health:
                    # New registration joins through the health gate —
                    # routed only after a passing probe + weight reconcile.
                    self.health[url] = _ServerHealth(routable=False)
                    logger.info(f"discovered new server {url}")
            for url in list(self.health):
                if url not in registered:
                    # Deregistered: a restarted server binds a fresh port,
                    # so the old url never comes back — forget it instead
                    # of probing it (and growing /metrics) forever.
                    self._evict(url, "deregistered from name_resolve")
                    del self.health[url]
        await asyncio.gather(*[
            self._check_one(sess, u) for u in list(self.health)
        ])
        self._straggler_sweep()
        self._update_fleet_gauges()

    def _straggler_sweep(self) -> None:
        """Score every routable server's decode-latency EWMA against its
        peers (system/autoscaler.py StragglerTracker): persistently slow
        servers are deprioritized in routing, then cordoned before they
        wedge the staleness gate by pinning the oldest inflight rollouts
        on the slowest decode path."""
        if self.straggler is None or len(self.servers) < 2:
            return
        verdicts = self.straggler.sweep(list(self.servers))
        for url, verdict in verdicts.items():
            st = self.health.get(url)
            if st is None or st.cordoned:
                continue
            if verdict == "cordon":
                self.telemetry.inc("autoscale/straggler_cordons")
                self.cordon(
                    url,
                    f"straggler: decode EWMA "
                    f"{(self.straggler.ewma(url) or 0.0) * 1e3:.1f}ms vs "
                    f"peers",
                    source="straggler",
                )
            elif verdict == "slow" and not st.deprioritized:
                st.deprioritized = True
                self.telemetry.inc("autoscale/straggler_deprioritized")
                self.telemetry.event(
                    "autoscale/deprioritize", url=url,
                    ewma_secs=self.straggler.ewma(url),
                )
                logger.warning(
                    f"{url} deprioritized: decode EWMA "
                    f"{(self.straggler.ewma(url) or 0.0) * 1e3:.1f}ms is "
                    f"{self.cfg.autoscale.straggler_factor:.0f}x over the "
                    f"peer median"
                )
            elif verdict == "ok" and st.deprioritized:
                st.deprioritized = False
                logger.info(f"{url} back within peer latency; "
                            f"restored to full routing priority")

    def _cordoned_count(self) -> int:
        return sum(1 for st in self.health.values() if st.cordoned)

    def _update_fleet_gauges(self) -> None:
        t = self.telemetry
        t.set_gauge("gsmgr/healthy_servers", len(self.servers))
        t.set_gauge("gsmgr/known_servers", len(self.health))
        t.set_gauge("autoscale/cordoned_servers", self._cordoned_count())
        t.set_gauge("autoscale/current_size", len(self.servers))
        if self.autoscaler is not None:
            t.set_gauge("autoscale/target_size", self.autoscaler.target
                        if self.autoscaler.target is not None
                        else len(self.servers))
            t.set_gauge("autoscale/overloaded", float(self._overloaded))
        t.set_gauge("gsmgr/lease_depth", len(self._leases))
        t.set_gauge("gsmgr/running_rollouts", self.running_rollouts)
        t.set_gauge("gsmgr/accepted_rollouts", self.accepted_rollouts)
        t.set_gauge("gsmgr/weight_version", self.version)
        for c in REQUEST_CLASSES:
            t.set_gauge(
                f"gsmgr/inflight_{c}",
                sum(by.get(c, 0) for by in self._inflight_cls.values()),
            )
        if self.last_sync_fanout_secs is not None:
            t.set_gauge("gsmgr/weight_sync_fanout_secs",
                        self.last_sync_fanout_secs)
        if self.last_sync_e2e_secs is not None:
            t.set_gauge("gsmgr/weight_sync_e2e_secs",
                        self.last_sync_e2e_secs)

    async def _health_loop(self):
        import aiohttp

        # No session-level timeout: /health probes carry their own
        # per-request budget, while re-admission weight reconciles are
        # bounded by the (much larger) fanout timeout in aretry.
        async with aiohttp.ClientSession() as sess:
            while True:
                try:
                    await self.check_fleet(sess)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — loop must survive
                    logger.warning(f"health sweep error: {e}")
                await asyncio.sleep(self.cfg.health_check_interval_secs)

    # ---------------- elastic autoscaling ----------------

    def _stale_heartbeat_urls(self, routable) -> set:
        """Routable servers whose liveness heartbeat has gone stale (the
        process is alive per the OS but wedged per the lease) — they
        don't count as capacity, so the plan replaces them at constant
        target."""
        ttl = self.cfg.keepalive_ttl_secs
        if not ttl:
            from areal_tpu.system.worker_base import env_keepalive_ttl

            ttl = env_keepalive_ttl() or 0.0
        if ttl <= 0 or not routable:
            return set()
        try:
            from areal_tpu.system.worker_base import read_heartbeats

            hbs = read_heartbeats(self.cfg.experiment, self.cfg.trial)
        except Exception:  # noqa: BLE001 — name-resolve hiccup
            return set()
        stale_ids = set()
        for worker, d in hbs.items():
            if not worker.startswith("genserver_"):
                continue
            age = d.get("age_secs")
            if age is not None and age > 3 * ttl:
                stale_ids.add(worker[len("genserver_"):])
        return {
            u for u in routable
            if self.health.get(u, _DEFAULT_HEALTH).server_id in stale_ids
        }

    def _autoscale_signals(self, stale_urls: set
                           ) -> "autoscale_mod.FleetSignals":
        ac = self.cfg.autoscale
        routable = list(self.servers)
        qd = 0.0
        slo_frac = 0.0
        if routable:
            qd = sum(
                self.health.get(u, _DEFAULT_HEALTH).queue_depth
                for u in routable
            ) / len(routable)
            if ac.slo_ttfc_secs > 0:
                slo_frac = sum(
                    1 for u in routable
                    if self.health.get(u, _DEFAULT_HEALTH).ttfc_ewma_secs
                    > ac.slo_ttfc_secs
                ) / len(routable)
        return autoscale_mod.FleetSignals(
            current_size=len(routable),
            cordoned=self._cordoned_count(),
            utilization=(
                self.running_rollouts
                / max(self.cfg.max_concurrent_rollouts, 1)
            ),
            queue_depth=qd,
            staled=self.is_staled(),
            slo_miss_frac=slo_frac,
            fanout_ack_secs=self.last_sync_fanout_secs or 0.0,
            stale_heartbeats=len(stale_urls),
        )

    def _pick_scale_down_victim(self) -> Optional[str]:
        if len(self.servers) <= 1:
            return None  # never cordon the last routable server

        def key(u):
            st = self.health.get(u, _DEFAULT_HEALTH)
            return (
                0 if st.deprioritized else 1,  # shed slow servers first
                # Dynamic spawns before baseline: baseline servers share
                # the gen-fleet process and can only idle, never exit.
                0 if st.server_id.startswith("dyn") else 1,
                self._inflight.get(u, 0),  # least work left to drain
            )

        return min(self.servers, key=key)

    def _autoscale_tick(self) -> None:
        """One decision interval of the slow scaling controller: feed the
        core a signals snapshot, act on its verdict (cordon a victim /
        reclaim a cordoned server), and publish the dynamic-spawn plan
        the launcher-side executor reconciles against."""
        ac = self.cfg.autoscale
        stale_urls = self._stale_heartbeat_urls(list(self.servers))
        sig = self._autoscale_signals(stale_urls)
        # Sentinel autoscale-inhibit hint (critical training-health alert
        # live): suppress scale-up for its duration — more decode
        # capacity cannot fix a diverging trainer, it only deepens
        # off-policyness (system/sentinel.py, docs/observability.md).
        inhibit = autoscale_mod.read_inhibit(
            self.cfg.experiment, self.cfg.trial
        )
        sig.inhibited = inhibit is not None
        self.telemetry.set_gauge("autoscale/inhibited",
                                 1.0 if inhibit else 0.0)
        if inhibit:
            logger.debug(
                f"autoscale: scale-up inhibited by sentinel rule "
                f"{inhibit.get('rule')!r}"
            )
        action = self.autoscaler.observe(sig)
        self._overloaded = self.autoscaler.overloaded
        if action is not None:
            if action["action"] == "up":
                self.telemetry.inc("autoscale/scale_up")
            else:
                self.telemetry.inc("autoscale/target_down")
            self.telemetry.event("autoscale/retarget", **action)
            logger.info(
                f"autoscale: target -> {action['target']} "
                f"({action['action']}: {action['reason']})"
            )
        target = (
            self.autoscaler.target
            if self.autoscaler.target is not None else len(self.servers)
        )
        if len(self.servers) > target:
            victim = self._pick_scale_down_victim()
            if victim is not None:
                self.cordon(victim, f"scale-down to {target}",
                            source="autoscaler")
        elif len(self.servers) < target:
            # Reclaim the cheapest capacity first: a healthy server this
            # loop cordoned for scale-down still holds near-current
            # weights — uncordon beats spawning a cold process.
            for url, st in self.health.items():
                if (
                    st.cordoned
                    and st.cordon_reason.startswith("scale-down")
                    and st.consecutive_failures == 0
                    # Never reclaim a server already told to exit — its
                    # process is shutting down and a passing probe could
                    # route leases onto a corpse.
                    and not st.exit_commanded
                ):
                    self.uncordon(url)
                    break
        # Wedged (stale-heartbeat) servers stay routable — eviction is
        # the health loop's call — but don't count as capacity here, so
        # the plan spawns a replacement WITHOUT moving the target.
        baseline_alive = sum(
            1 for url, st in self.health.items()
            if not st.cordoned
            and url not in stale_urls
            and (st.routable or st.reconciling or st.uncordon_pending)
            and not st.server_id.startswith("dyn")
        )
        dynamic = max(0, min(target, ac.max_servers) - baseline_alive)
        autoscale_mod.publish_plan(self.cfg.experiment, self.cfg.trial, {
            "target": target,
            "dynamic": dynamic,
            "overloaded": self._overloaded,
            "ts": time.time(),
        })
        self._update_fleet_gauges()

    def _command_server_exit(self, server_id: str) -> bool:
        """WorkerControl-commanded exit of a drained dynamic server (runs
        in a thread: the panel is sync ZMQ). The supervisor sees the
        clean exit of a non-required worker — expected, never respawned."""
        from areal_tpu.system.worker_base import WorkerControlPanel

        panel = WorkerControlPanel(self.cfg.experiment, self.cfg.trial,
                                   timeout=5.0)
        try:
            res = panel.try_command(f"genserver_{server_id}", "exit")
            return bool(res.get("ok"))
        except Exception as e:  # noqa: BLE001 — endpoint gone / resolving
            logger.warning(f"exit command to genserver_{server_id} "
                           f"failed: {e}")
            return False
        finally:
            panel.close()

    async def _drain_cordoned(self) -> None:
        """Walk cordoned servers: once one has no outstanding leases (or
        its drain deadline passed — clients fail over via chunk replay),
        count the scale-down and, for dynamic servers, command the
        process exit over WorkerControl."""
        now = time.monotonic()
        for url in list(self.health):
            st = self.health.get(url)
            if st is None or not st.cordoned or st.exit_commanded:
                continue
            load = self._server_draining_load(url)
            if load > 0 and now < st.cordon_deadline:
                continue
            if load > 0:
                logger.warning(
                    f"{url} drain deadline passed with {load} leases "
                    f"outstanding; proceeding (clients fail over via "
                    f"chunk replay)"
                )
                self._drop_server_leases(url)
            sid = st.server_id
            if sid.startswith("dyn"):
                ok = await asyncio.to_thread(self._command_server_exit, sid)
                if not ok:
                    continue  # retried next interval
            st.exit_commanded = True
            self.telemetry.inc("autoscale/scale_down")
            self.telemetry.event(
                "autoscale/drained", url=url, reason=st.cordon_reason,
                forced=load > 0,
            )
            logger.info(
                f"cordoned server {url} drained ({st.cordon_reason}); "
                + ("exit commanded" if sid.startswith("dyn")
                   else "idling in the baseline gen-fleet process")
            )

    async def _autoscale_loop(self):
        while True:
            try:
                self._autoscale_tick()
                await self._drain_cordoned()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — loop must survive
                logger.warning(f"autoscale tick error: {e}")
            await asyncio.sleep(self.cfg.autoscale.interval_secs)

    # ---------------- scheduling ----------------

    def _drop_lease_class(self, lid: str, url: str) -> None:
        cls = self._lease_class.pop(lid, "rollout")
        by = self._inflight_cls.get(url)
        if by and by.get(cls, 0) > 0:
            by[cls] -= 1

    def _expire_leases(self) -> None:
        now = time.monotonic()
        dead = [lid for lid, (_, exp) in self._leases.items() if exp < now]
        for lid in dead:
            url, _ = self._leases.pop(lid)
            if self._inflight.get(url, 0) > 0:
                self._inflight[url] -= 1
            self._drop_lease_class(lid, url)
            logger.warning(f"lease {lid} on {url} expired (client gone?)")

    def _cls_inflight(self, url: str, classes) -> int:
        by = self._inflight_cls.get(url, {})
        return sum(by.get(c, 0) for c in classes)

    def _pick_server(self, cls: str = "rollout") -> Optional[str]:
        self._expire_leases()
        if not self.servers:
            return None
        # Straggler defense: a deprioritized (persistently slow) server
        # is routed only when every faster peer is gone — its inflight
        # work finishes, but new work prefers the healthy set.
        pool = [
            u for u in self.servers
            if not self.health.get(u, _DEFAULT_HEALTH).deprioritized
        ] or self.servers
        if cls != "rollout":
            # Latency-sensitive classes route to the server carrying the
            # least interactive+eval load (total inflight tie-breaks) —
            # bulk rollout traffic keeps its configured policy, so one
            # fleet serves both without the bulk queue burying the SLOs.
            return min(
                pool,
                key=lambda u: (
                    self._cls_inflight(u, ("interactive", "eval")),
                    self._inflight.get(u, 0),
                ),
            )
        if self.cfg.schedule_policy == "least_requests":
            return min(pool, key=lambda u: self._inflight.get(u, 0))
        url = pool[self._rr % len(pool)]
        self._rr += 1
        return url

    def is_staled(self) -> bool:
        expected = (
            self.accepted_rollouts + self.running_rollouts
        ) // max(self.cfg.train_batch_size, 1)
        return expected > self.cfg.max_head_offpolicyness + self.version

    # ---------------- http handlers ----------------

    async def handle_schedule_request(self, request):
        from aiohttp import web

        try:
            d = await request.json()
        except Exception:  # noqa: BLE001 — empty body = legacy client
            d = {}
        cls = normalize_class(d.get("class"))
        url = self._pick_server(cls)
        if url is None:
            # Whole fleet evicted/dead: clients back off and retry — the
            # health loop re-admits servers as they recover.
            return web.json_response(
                {"url": None, "reason": "no_healthy_servers"}, status=503
            )
        self._inflight[url] += 1
        self._lease_seq += 1
        lease_id = f"l{self._lease_seq}"
        self._leases[lease_id] = (
            url, time.monotonic() + self.cfg.lease_ttl_secs
        )
        self._lease_class[lease_id] = cls
        self._inflight_cls.setdefault(url, {})
        self._inflight_cls[url][cls] = \
            self._inflight_cls[url].get(cls, 0) + 1
        self.telemetry.inc(f"gsmgr/scheduled_{cls}")
        return web.json_response({
            "url": url, "version": self.version, "lease_id": lease_id,
            "class": cls,
        })

    async def handle_renew(self, request):
        from aiohttp import web

        d = await request.json()
        lid = d.get("lease_id")
        if lid in self._leases:
            url, _ = self._leases[lid]
            self._leases[lid] = (
                url, time.monotonic() + self.cfg.lease_ttl_secs
            )
            return web.json_response({"ok": True})
        return web.json_response({"ok": False, "reason": "unknown lease"})

    async def handle_release(self, request):
        from aiohttp import web

        d = await request.json()
        lid = d.get("lease_id")
        if lid is not None:
            if lid in self._leases:
                u, _ = self._leases.pop(lid)
                if self._inflight.get(u, 0) > 0:
                    self._inflight[u] -= 1
                self._drop_lease_class(lid, u)
            return web.json_response({"ok": True})
        # Legacy: release by url. Must ALSO retire the lease pointing at
        # that url — otherwise the orphaned lease's TTL expiry later
        # decrements the same inflight slot a second time. Without a client
        # identity on leases the match is only safe when UNAMBIGUOUS
        # (exactly one lease on the url); with concurrent leases we must
        # not guess and delete another client's lease.
        u = d.get("url")
        matches = [lid for lid, (lu, _) in self._leases.items() if lu == u]
        if len(matches) == 1:
            del self._leases[matches[0]]
            self._drop_lease_class(matches[0], u)
        elif matches:
            # Ambiguous: no lease is retired (guessing could delete
            # another client's), but the per-class gauge must move with
            # the _inflight decrement below or the two drift apart until
            # TTL expiry. Legacy by-url clients predate request classes,
            # so prefer a rollout lease's class; the lease's class record
            # stays (the lease is still alive), giving the class count
            # the same guarded double-decrement-at-expiry semantics as
            # _inflight itself.
            lid2 = next(
                (l for l in matches
                 if self._lease_class.get(l, "rollout") == "rollout"),
                matches[0],
            )
            cls = self._lease_class.get(lid2, "rollout")
            by = self._inflight_cls.get(u)
            if by and by.get(cls, 0) > 0:
                by[cls] -= 1
        if u in self._inflight and self._inflight[u] > 0:
            self._inflight[u] -= 1
        return web.json_response({"ok": True})

    async def handle_allocate_rollout(self, request):
        from aiohttp import web

        d = await request.json()
        n = int(d.get("n_samples", 1))
        if self.running_rollouts >= self.cfg.max_concurrent_rollouts:
            resp = {"allowed": False, "reason": "capacity"}
            if self._overloaded:
                # Overload backpressure (docs/fault_tolerance.md
                # §Autoscaling): the fleet is pinned at max_servers and
                # still saturated — no amount of 0.5s polling will open
                # the gate sooner, so tell the workers to slow prompt
                # admission instead of hammering it.
                resp["retry_after"] = (
                    self.cfg.autoscale.backpressure_retry_secs
                )
                self.telemetry.inc("autoscale/backpressure_denials")
            return web.json_response(resp)
        if self.is_staled():
            return web.json_response({"allowed": False, "reason": "staleness"})
        self.running_rollouts += n
        # Adopt the caller's sample trace: the gate's ADMIT decision
        # joins the stitched timeline (denials stay counters only — a
        # closed gate produces ~2 retries/s per pending prompt and would
        # flood the span buffers).
        if self.telemetry.enabled:
            ctx = telemetry.extract_headers(request.headers)
            if ctx is not None:
                self.telemetry.add_span(
                    "gsmgr/alloc", time.time(), 0.0, trace=ctx,
                    n_samples=n, version=self.version,
                )
        return web.json_response({"allowed": True, "version": self.version})

    async def handle_finish_rollout(self, request):
        from aiohttp import web

        d = await request.json()
        # n_samples must mirror what /allocate_rollout booked for this
        # rollout (group_size), independent of acceptance; n_accepted is how
        # many of those samples were actually pushed to the trainer.
        n = int(d.get("n_samples", 1))
        self.running_rollouts = max(0, self.running_rollouts - n)
        n_accepted = int(
            d.get("n_accepted", n if d.get("accepted") else 0)
        )
        self.accepted_rollouts += n_accepted
        if self.telemetry.enabled:
            ctx = telemetry.extract_headers(request.headers)
            if ctx is not None:
                self.telemetry.add_span(
                    "gsmgr/finish", time.time(), 0.0, trace=ctx,
                    n_samples=n, n_accepted=n_accepted,
                )
        return web.json_response({"ok": True})

    async def handle_get_model_version(self, request):
        from aiohttp import web

        return web.json_response({"version": self.version})

    def _resolve_server(self, d: Dict) -> Optional[str]:
        """Map a {url} or {server_id} request body onto a known url."""
        url = d.get("url")
        if url:
            return url if url in self.health else None
        sid = str(d.get("server_id") or "")
        if sid:
            return next(
                (u for u, st in self.health.items()
                 if st.server_id == sid), None,
            )
        return None

    async def handle_cordon(self, request):
        """Operator/preemption cordon: POST {url | server_id, reason}.
        The server stops receiving leases; inflight rollouts drain (the
        autoscale loop reaps dynamic servers once drained). This is the
        preemption-notice hook — `perf_probe cordon` calls it."""
        from aiohttp import web

        d = await request.json()
        url = self._resolve_server(d)
        if url is None:
            return web.json_response(
                {"ok": False, "reason": "unknown server"}, status=404
            )
        ok = self.cordon(
            url, str(d.get("reason") or "operator request"),
            source="operator",
        )
        return web.json_response({
            "ok": ok, "url": url,
            "draining": self._server_draining_load(url),
            "already_cordoned": not ok,
        })

    async def handle_uncordon(self, request):
        from aiohttp import web

        d = await request.json()
        url = self._resolve_server(d)
        if url is None:
            return web.json_response(
                {"ok": False, "reason": "unknown server"}, status=404
            )
        ok = self.uncordon(url)
        return web.json_response({"ok": ok, "url": url})

    async def handle_metrics(self, request):
        """Prometheus exposition text: fleet gauges (healthy servers,
        lease depth, staleness counters, weight version, sync latency)
        plus the manager's telemetry registry (probe/fanout counters and
        histograms). The structured JSON body — including the per-server
        fleet map — moved to ``/metrics.json``."""
        from aiohttp import web

        gauges = {
            "gsmgr_weight_version": self.version,
            "gsmgr_running_rollouts": self.running_rollouts,
            "gsmgr_accepted_rollouts": self.accepted_rollouts,
            "gsmgr_healthy_servers": len(self.servers),
            "gsmgr_known_servers": len(self.health),
            "gsmgr_lease_depth": len(self._leases),
            "gsmgr_inflight_requests": sum(self._inflight.values()),
            # Per-class lease load (docs/serving.md): one fleet carrying
            # rollout + interactive/eval traffic concurrently.
            **{
                f"gsmgr_inflight_{c}": sum(
                    by.get(c, 0) for by in self._inflight_cls.values()
                )
                for c in REQUEST_CLASSES
            },
            "gsmgr_staled": float(self.is_staled()),
            "gsmgr_weight_sync_fanout_secs": self.last_sync_fanout_secs,
            "gsmgr_weight_sync_e2e_secs": self.last_sync_e2e_secs,
            # Fleet elasticity (docs/fault_tolerance.md §Autoscaling) —
            # present even with telemetry disabled, so a bare scrape of
            # this endpoint can follow a drain.
            "autoscale_cordoned_servers": self._cordoned_count(),
            "autoscale_current_size": len(self.servers),
        }
        if self.autoscaler is not None:
            gauges["autoscale_target_size"] = (
                self.autoscaler.target
                if self.autoscaler.target is not None else len(self.servers)
            )
            gauges["autoscale_overloaded"] = float(self._overloaded)
        body = telemetry.render_prometheus(
            self.telemetry.snapshot(reset=False), extra_gauges=gauges,
        )
        return web.Response(text=body, content_type="text/plain",
                            charset="utf-8")

    async def handle_metrics_json(self, request):
        from aiohttp import web

        hist = self.sync_history[-20:]
        return web.json_response({
            "version": self.version,
            "running_rollouts": self.running_rollouts,
            "accepted_rollouts": self.accepted_rollouts,
            "healthy_servers": len(self.servers),
            "known_servers": len(self.health),
            "inflight_by_class": {
                c: sum(by.get(c, 0) for by in self._inflight_cls.values())
                for c in REQUEST_CLASSES
            },
            "autoscale": {
                "enabled": self.cfg.autoscale.enabled,
                "target_size": (
                    self.autoscaler.target if self.autoscaler is not None
                    else None
                ),
                "current_size": len(self.servers),
                "cordoned": self._cordoned_count(),
                "overloaded": self._overloaded,
            },
            "fleet": {
                u: {
                    "routable": st.routable,
                    "consecutive_failures": st.consecutive_failures,
                    "acked_version": st.acked_version,
                    "evicted_reason": st.evicted_reason,
                    "last_failure": st.last_failure,
                    "server_id": st.server_id,
                    "cordoned": st.cordoned,
                    "cordon_reason": st.cordon_reason,
                    "deprioritized": st.deprioritized,
                    "queue_depth": st.queue_depth,
                    "draining": (
                        self._server_draining_load(u) if st.cordoned else 0
                    ),
                }
                for u, st in self.health.items()
            },
            "weight_sync_fanout_secs": self.last_sync_fanout_secs,
            "weight_sync_e2e_secs": self.last_sync_e2e_secs,
            "weight_sync_history": [
                {"version": v, "fanout_secs": f, "e2e_secs": e}
                for v, f, e in hist
            ],
        })

    async def handle_metrics_discovery(self, request):
        """Scrape-target discovery (reference controller.py:41-74 exposes
        the same for its Prometheus scraper): every metrics endpoint of
        this experiment — the generation servers' and this manager's —
        in http_sd format ([{"targets": [...], "labels": {...}}])."""
        from aiohttp import web

        def _host(u: str) -> str:
            return u.split("//", 1)[-1]

        groups = [{
            "targets": [_host(u) for u in self.servers],
            "labels": {"experiment": self.cfg.experiment,
                       "trial": self.cfg.trial, "role": "generation_server"},
        }]
        if self._url:
            groups.append({
                "targets": [_host(self._url)],
                "labels": {"experiment": self.cfg.experiment,
                           "trial": self.cfg.trial,
                           "role": "gserver_manager"},
            })
        return web.json_response(groups)

    # ---------------- weight-update fanout ----------------

    async def _push_weights_one(self, sess, url: str, v: int,
                                path: str,
                                payload: Optional[Dict] = None) -> bool:
        """POST /update_weights to one server, bounded by the per-server
        timeout and retried per ``fanout_retry``. Returns ack success."""
        if payload is None:
            payload = self._update_payload(v, path)

        async def _post():
            if self.faults is not None:
                self.faults.maybe_fail("fanout", url=url, version=v)
            async with sess.post(
                f"{url}/update_weights", json=payload
            ) as r:
                if r.status != 200:
                    raise RuntimeError(f"/update_weights status {r.status}")
                await r.read()
            return True

        t0 = time.monotonic()
        try:
            await aretry(
                _post, self.cfg.fanout_retry,
                timeout=self.cfg.fanout_timeout_secs,
                on_retry=lambda n, e: logger.warning(
                    f"weight push v{v} -> {url} attempt {n} failed: {e}"
                ),
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — ack failure, not fatal
            st = self.health.get(url)
            if st is not None:
                st.last_failure = f"weight push v{v}: {e!r}"
            self.telemetry.inc("gsmgr/fanout_failures")
            logger.warning(f"weight push v{v} -> {url} gave up: {e}")
            return False
        self.telemetry.observe("gsmgr/fanout_ack_secs",
                               time.monotonic() - t0)
        st = self.health.get(url)
        if st is not None:  # entry may have been pruned mid-push
            st.acked_version = v
        return True

    async def fanout_weights(self, sess, v: int, path: str) -> List[str]:
        """Push version ``v`` to every routable server concurrently. Bumps
        ``self.version`` only when at least one server acked; a server that
        exhausts its retry budget is EVICTED (never silently left serving
        stale weights behind a bumped version). Returns the acked urls."""
        targets = list(self.servers)
        # One payload for the whole fanout: every server must receive the
        # SAME transport for version v (a mid-fanout trainer restart could
        # otherwise hand half the fleet a stream endpoint and half a path).
        payload = self._update_payload(v, path)
        results = await asyncio.gather(*[
            self._push_weights_one(sess, u, v, path, payload=payload)
            for u in targets
        ])
        acked = [u for u, ok in zip(targets, results) if ok]
        if not acked:
            # SYSTEMIC failure (bad/late weight path, shared-FS lag): no
            # server acked, so the fault is almost certainly not per-server.
            # Evicting the whole fleet here would drop every lease and flap
            # (health re-admits, next poll evicts again) — hold the version
            # and let the watcher retry; genuinely dead servers are the
            # health loop's job.
            logger.error(f"weight v{v}: no server acked; version held at "
                         f"{self.version} for retry next poll")
            return []
        for u, ok in zip(targets, results):
            if not ok:
                self._evict(u, f"no ack for weight v{v}")
        self.version = v
        # Close the re-admission race: a server admitted WHILE this fanout
        # was in flight reconciled against the old version and is not in
        # ``targets`` — demote it so the health loop reconciles it to v
        # before it routes again (never stale).
        for u in list(self.servers):
            st = self.health.get(u)
            if u not in targets and st and st.acked_version < v:
                self._evict(
                    u, f"admitted mid-fanout at stale "
                       f"v{st.acked_version} (< v{v})"
                )
        return acked

    async def _watch_weights(self):
        import aiohttp

        key = names.model_version(
            self.cfg.experiment, self.cfg.trial, self.cfg.model_role
        )
        while True:
            try:
                v = int(name_resolve.get(key))
            except Exception:  # noqa: BLE001 — key not yet published
                v = self.version
            if v > self.version and self.servers:
                path = os.path.join(
                    self.cfg.realloc_dir, self.cfg.model_role, str(v)
                )
                t0 = time.monotonic()
                async with aiohttp.ClientSession() as sess:
                    acked = await self.fanout_weights(sess, v, path)
                if not acked:
                    await asyncio.sleep(self.cfg.weight_poll_secs)
                    continue
                fanout_secs = time.monotonic() - t0
                # End-to-end weight-sync latency (north-star metric #2,
                # BASELINE.json): trainer save START → every server swapped.
                # Requires loosely-synchronized host clocks across machines
                # (same-host in local mode, NTP otherwise).
                e2e_secs = None
                try:
                    pub_ts = float(name_resolve.get(
                        names.model_version_time(
                            self.cfg.experiment, self.cfg.trial,
                            self.cfg.model_role,
                        )
                    ))
                    e2e_secs = max(time.time() - pub_ts, fanout_secs)
                except Exception:  # noqa: BLE001 — older trainers don't publish it
                    pass
                self.last_sync_fanout_secs = fanout_secs
                self.last_sync_e2e_secs = e2e_secs
                self.sync_history.append((v, fanout_secs, e2e_secs))
                self._update_fleet_gauges()
                logger.info(
                    f"weight sync v{v}: fanout {fanout_secs:.2f}s over "
                    f"{len(self.servers)} servers"
                    + (f", publish->swap {e2e_secs:.2f}s"
                       if e2e_secs is not None else "")
                )
                self._gc_old_versions(v)
            await asyncio.sleep(self.cfg.weight_poll_secs)

    def _gc_old_versions(self, current: int):
        root = os.path.join(self.cfg.realloc_dir, self.cfg.model_role)
        if not os.path.isdir(root):
            return
        for d in os.listdir(root):
            try:
                v = int(d)
            except ValueError:
                continue
            if v <= current - self.cfg.keep_last_versions:
                shutil.rmtree(os.path.join(root, d), ignore_errors=True)

    # ---------------- lifecycle ----------------

    def build_app(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_post("/schedule_request", self.handle_schedule_request)
        app.router.add_post("/renew", self.handle_renew)
        app.router.add_post("/release", self.handle_release)
        app.router.add_post("/allocate_rollout", self.handle_allocate_rollout)
        app.router.add_post("/finish_rollout", self.handle_finish_rollout)
        app.router.add_get("/get_model_version", self.handle_get_model_version)
        app.router.add_post("/cordon", self.handle_cordon)
        app.router.add_post("/uncordon", self.handle_uncordon)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/metrics.json", self.handle_metrics_json)
        app.router.add_get("/metrics_discovery", self.handle_metrics_discovery)
        return app

    async def start(self) -> str:
        from aiohttp import web

        await self.wait_for_servers()
        self._watcher_task = asyncio.create_task(self._watch_weights())
        self._health_task = asyncio.create_task(self._health_loop())
        if self.autoscaler is not None:
            self._autoscale_task = asyncio.create_task(
                self._autoscale_loop()
            )
        runner = web.AppRunner(self.build_app())
        await runner.setup()
        port = self.cfg.port or network.find_free_port()
        site = web.TCPSite(runner, network.bind_addr(), port)
        await site.start()
        url = f"http://{network.gethostip()}:{port}"
        self._url = url
        from areal_tpu.system.worker_base import (
            HeartbeatThread,
            default_heartbeat_interval,
            env_keepalive_ttl,
        )

        ttl = self.cfg.keepalive_ttl_secs or env_keepalive_ttl() or 0.0
        key = names.gen_server_manager(self.cfg.experiment, self.cfg.trial)
        name_resolve.add(key, url, replace=True,
                         keepalive_ttl=ttl or None)
        self._hb = None
        if ttl:
            self._hb = HeartbeatThread(
                self.cfg.experiment, self.cfg.trial, "gserver_manager",
                interval=default_heartbeat_interval(ttl),
            )
            self._hb.lease(key, url, ttl)
        logger.info(f"gserver manager at {url}"
                    + (f" (keepalive {ttl:.0f}s)" if ttl else ""))
        self._runner_obj = runner
        return url

    async def stop(self):
        tasks = [t for t in
                 [self._watcher_task, self._health_task,
                  self._autoscale_task, *self._reconcile_tasks] if t]
        for t in tasks:
            t.cancel()
        # Let cancellations unwind before tearing down the HTTP runner —
        # otherwise a mid-POST reconcile races the session close and logs
        # destroyed-pending-task noise.
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        if getattr(self, "_hb", None) is not None:
            self._hb.close()
        self.telemetry.close()
        await self._runner_obj.cleanup()
