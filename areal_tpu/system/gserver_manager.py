"""Generation-server manager — routing, staleness gate, weight fanout.

Parity target: ``realhf/system/gserver_manager.py:32`` — the singleton
rollout controller: HTTP router over the generation-server fleet
(round-robin / least-requests), the **staleness gate** that blocks new
rollouts when they would be too off-policy, ``/finish_rollout`` accounting,
and the weight-update fanout (watch ``names.model_version``, POST
``/update_weights`` to every server, GC old realloc dirs).

Staleness rule (reference ``is_staled`` :351):
    expected_version = (trained_samples + running) // train_batch_size
    allowed  iff  expected_version <= max_head_offpolicyness + current_version
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import shutil
import time
from typing import Dict, List, Optional

from areal_tpu.base import logging, name_resolve, names, network

logger = logging.getLogger("system.gserver_mgr")


@dataclasses.dataclass
class GserverManagerConfig:
    experiment: str = "exp"
    trial: str = "trial"
    model_role: str = "actor"
    n_servers: int = 1
    train_batch_size: int = 8
    max_head_offpolicyness: int = 0
    max_concurrent_rollouts: int = 64
    schedule_policy: str = "round_robin"  # or least_requests
    realloc_dir: str = "/tmp/areal_tpu/realloc"
    weight_poll_secs: float = 1.0
    port: Optional[int] = None
    keep_last_versions: int = 2
    # Routing leases expire if the client neither renews (per chunk) nor
    # releases — a crashed client must not pin inflight counts forever.
    lease_ttl_secs: float = 120.0


class GserverManager:
    def __init__(self, cfg: GserverManagerConfig):
        self.cfg = cfg
        self.servers: List[str] = []
        self.version = 0
        self._rr = 0
        self._inflight: Dict[str, int] = {}  # url -> outstanding requests
        self._leases: Dict[str, tuple] = {}  # lease_id -> (url, expires_at)
        self._lease_seq = 0
        # Both staleness terms are counted in SAMPLE units (the reference's
        # is_staled compares against train_batch_size samples): a rollout
        # allocation of group_size samples adds group_size to running.
        self.running_rollouts = 0
        self.accepted_rollouts = 0  # trained samples submitted
        self._watcher_task = None
        self._url: Optional[str] = None
        # Weight-sync latency bookkeeping (north-star metric #2).
        self.last_sync_fanout_secs: Optional[float] = None
        self.last_sync_e2e_secs: Optional[float] = None
        self.sync_history: List[tuple] = []

    # ---------------- discovery ----------------

    async def wait_for_servers(self, timeout: float = 300.0):
        deadline = time.monotonic() + timeout
        root = names.gen_server_root(self.cfg.experiment, self.cfg.trial)
        while time.monotonic() < deadline:
            urls = sorted(name_resolve.get_subtree(root))
            if len(urls) >= self.cfg.n_servers:
                self.servers = urls
                self._inflight = {u: 0 for u in urls}
                logger.info(f"found {len(urls)} generation servers")
                return
            await asyncio.sleep(0.2)
        raise TimeoutError("generation servers did not register")

    # ---------------- scheduling ----------------

    def _expire_leases(self) -> None:
        now = time.monotonic()
        dead = [lid for lid, (_, exp) in self._leases.items() if exp < now]
        for lid in dead:
            url, _ = self._leases.pop(lid)
            if self._inflight.get(url, 0) > 0:
                self._inflight[url] -= 1
            logger.warning(f"lease {lid} on {url} expired (client gone?)")

    def _pick_server(self) -> str:
        self._expire_leases()
        if self.cfg.schedule_policy == "least_requests":
            return min(self.servers, key=lambda u: self._inflight[u])
        url = self.servers[self._rr % len(self.servers)]
        self._rr += 1
        return url

    def is_staled(self) -> bool:
        expected = (
            self.accepted_rollouts + self.running_rollouts
        ) // max(self.cfg.train_batch_size, 1)
        return expected > self.cfg.max_head_offpolicyness + self.version

    # ---------------- http handlers ----------------

    async def handle_schedule_request(self, request):
        from aiohttp import web

        url = self._pick_server()
        self._inflight[url] += 1
        self._lease_seq += 1
        lease_id = f"l{self._lease_seq}"
        self._leases[lease_id] = (
            url, time.monotonic() + self.cfg.lease_ttl_secs
        )
        return web.json_response({
            "url": url, "version": self.version, "lease_id": lease_id,
        })

    async def handle_renew(self, request):
        from aiohttp import web

        d = await request.json()
        lid = d.get("lease_id")
        if lid in self._leases:
            url, _ = self._leases[lid]
            self._leases[lid] = (
                url, time.monotonic() + self.cfg.lease_ttl_secs
            )
            return web.json_response({"ok": True})
        return web.json_response({"ok": False, "reason": "unknown lease"})

    async def handle_release(self, request):
        from aiohttp import web

        d = await request.json()
        lid = d.get("lease_id")
        if lid is not None:
            if lid in self._leases:
                u, _ = self._leases.pop(lid)
                if self._inflight.get(u, 0) > 0:
                    self._inflight[u] -= 1
            return web.json_response({"ok": True})
        # legacy: release by url (no lease bookkeeping)
        u = d.get("url")
        if u in self._inflight and self._inflight[u] > 0:
            self._inflight[u] -= 1
        return web.json_response({"ok": True})

    async def handle_allocate_rollout(self, request):
        from aiohttp import web

        d = await request.json()
        n = int(d.get("n_samples", 1))
        if self.running_rollouts >= self.cfg.max_concurrent_rollouts:
            return web.json_response({"allowed": False, "reason": "capacity"})
        if self.is_staled():
            return web.json_response({"allowed": False, "reason": "staleness"})
        self.running_rollouts += n
        return web.json_response({"allowed": True, "version": self.version})

    async def handle_finish_rollout(self, request):
        from aiohttp import web

        d = await request.json()
        # n_samples must mirror what /allocate_rollout booked for this
        # rollout (group_size), independent of acceptance; n_accepted is how
        # many of those samples were actually pushed to the trainer.
        n = int(d.get("n_samples", 1))
        self.running_rollouts = max(0, self.running_rollouts - n)
        n_accepted = int(
            d.get("n_accepted", n if d.get("accepted") else 0)
        )
        self.accepted_rollouts += n_accepted
        return web.json_response({"ok": True})

    async def handle_get_model_version(self, request):
        from aiohttp import web

        return web.json_response({"version": self.version})

    async def handle_metrics(self, request):
        from aiohttp import web

        hist = self.sync_history[-20:]
        return web.json_response({
            "version": self.version,
            "running_rollouts": self.running_rollouts,
            "accepted_rollouts": self.accepted_rollouts,
            "weight_sync_fanout_secs": self.last_sync_fanout_secs,
            "weight_sync_e2e_secs": self.last_sync_e2e_secs,
            "weight_sync_history": [
                {"version": v, "fanout_secs": f, "e2e_secs": e}
                for v, f, e in hist
            ],
        })

    async def handle_metrics_discovery(self, request):
        """Scrape-target discovery (reference controller.py:41-74 exposes
        the same for its Prometheus scraper): every metrics endpoint of
        this experiment — the generation servers' and this manager's —
        in http_sd format ([{"targets": [...], "labels": {...}}])."""
        from aiohttp import web

        def _host(u: str) -> str:
            return u.split("//", 1)[-1]

        groups = [{
            "targets": [_host(u) for u in self.servers],
            "labels": {"experiment": self.cfg.experiment,
                       "trial": self.cfg.trial, "role": "generation_server"},
        }]
        if self._url:
            groups.append({
                "targets": [_host(self._url)],
                "labels": {"experiment": self.cfg.experiment,
                           "trial": self.cfg.trial,
                           "role": "gserver_manager"},
            })
        return web.json_response(groups)

    # ---------------- weight-update fanout ----------------

    async def _watch_weights(self):
        import aiohttp

        key = names.model_version(
            self.cfg.experiment, self.cfg.trial, self.cfg.model_role
        )
        while True:
            try:
                v = int(name_resolve.get(key))
            except Exception:  # noqa: BLE001 — key not yet published
                v = self.version
            if v > self.version:
                path = os.path.join(
                    self.cfg.realloc_dir, self.cfg.model_role, str(v)
                )
                t0 = time.monotonic()
                async with aiohttp.ClientSession() as sess:
                    await asyncio.gather(*[
                        sess.post(f"{u}/update_weights",
                                  json={"path": path, "version": v})
                        for u in self.servers
                    ])
                self.version = v
                fanout_secs = time.monotonic() - t0
                # End-to-end weight-sync latency (north-star metric #2,
                # BASELINE.json): trainer save START → every server swapped.
                # Requires loosely-synchronized host clocks across machines
                # (same-host in local mode, NTP otherwise).
                e2e_secs = None
                try:
                    pub_ts = float(name_resolve.get(
                        names.model_version_time(
                            self.cfg.experiment, self.cfg.trial,
                            self.cfg.model_role,
                        )
                    ))
                    e2e_secs = max(time.time() - pub_ts, fanout_secs)
                except Exception:  # noqa: BLE001 — older trainers don't publish it
                    pass
                self.last_sync_fanout_secs = fanout_secs
                self.last_sync_e2e_secs = e2e_secs
                self.sync_history.append((v, fanout_secs, e2e_secs))
                logger.info(
                    f"weight sync v{v}: fanout {fanout_secs:.2f}s over "
                    f"{len(self.servers)} servers"
                    + (f", publish->swap {e2e_secs:.2f}s"
                       if e2e_secs is not None else "")
                )
                self._gc_old_versions(v)
            await asyncio.sleep(self.cfg.weight_poll_secs)

    def _gc_old_versions(self, current: int):
        root = os.path.join(self.cfg.realloc_dir, self.cfg.model_role)
        if not os.path.isdir(root):
            return
        for d in os.listdir(root):
            try:
                v = int(d)
            except ValueError:
                continue
            if v <= current - self.cfg.keep_last_versions:
                shutil.rmtree(os.path.join(root, d), ignore_errors=True)

    # ---------------- lifecycle ----------------

    def build_app(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_post("/schedule_request", self.handle_schedule_request)
        app.router.add_post("/renew", self.handle_renew)
        app.router.add_post("/release", self.handle_release)
        app.router.add_post("/allocate_rollout", self.handle_allocate_rollout)
        app.router.add_post("/finish_rollout", self.handle_finish_rollout)
        app.router.add_get("/get_model_version", self.handle_get_model_version)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/metrics_discovery", self.handle_metrics_discovery)
        return app

    async def start(self) -> str:
        from aiohttp import web

        await self.wait_for_servers()
        self._watcher_task = asyncio.create_task(self._watch_weights())
        runner = web.AppRunner(self.build_app())
        await runner.setup()
        port = self.cfg.port or network.find_free_port()
        site = web.TCPSite(runner, network.bind_addr(), port)
        await site.start()
        url = f"http://{network.gethostip()}:{port}"
        self._url = url
        name_resolve.add(
            names.gen_server_manager(self.cfg.experiment, self.cfg.trial),
            url, replace=True,
        )
        logger.info(f"gserver manager at {url}")
        self._runner_obj = runner
        return url

    async def stop(self):
        if self._watcher_task:
            self._watcher_task.cancel()
        await self._runner_obj.cleanup()
