"""Training-health sentinel: streaming anomaly detection, declarative
alerting, and automatic evidence capture.

PR 4 gave the fleet metrics, PR 7 stitched traces + flight recorders, and
PR 11 autoscale signals — but nothing *watched* any of it: a KL blowup,
entropy collapse, staleness-gate wedge, or throughput regression was only
discovered by a human reading tensorboard after the run was dead. This
module is the watcher. It is hosted inside the master's
:class:`~areal_tpu.base.telemetry.TelemetryAggregator` (the one process
that already sees every worker's snapshots) and evaluates a declarative
rule set over two streams:

 - the merged fleet telemetry flowing into ``telemetry.jsonl`` (gauges and
   counters from all six worker kinds), and
 - the per-step RL training-dynamics series the trainer exports as
   ``train/*`` gauges (approx-KL, token entropy, clip fraction,
   importance-weight tail, grad norm, reward mean/std, advantage scale,
   staleness lag — the divergence signatures that actually kill RL runs;
   see ``system/trainer_worker._export_train_stats``).

Rule grammar (docs/observability.md §Alerting): each rule is a dict with
an ``id``, a ``metric`` from :data:`METRIC_CATALOG`, a predicate ``kind``

 - ``threshold``  latest aggregated value ``op`` ``value``
 - ``rate``       per-second rate of change over ``window`` ``op`` ``value``
                  (counters differentiate naturally)
 - ``baseline``   |latest − rolling median(window)| exceeds ``value`` ×
                  max(1.4826·MAD, 5% of |median|) — self-calibrating
                  robust deviation for series with no sane absolute
                  threshold (median/MAD so a live anomaly cannot poison
                  its own baseline and self-clear)
 - ``absence``    no sample for the metric within ``for`` seconds
                  (dead producer / wedged pipeline detection)

plus a ``for`` duration the predicate must hold before the alert fires, a
``severity`` (``info|warn|critical``), and a per-rule ``cooldown``
bounding re-fires. Firing alerts are appended to ``alerts.jsonl``,
exported as ``areal_alerts_total{rule,severity}`` and
``areal_alert_active{rule}`` on the merged Prometheus endpoint, and —
the part that makes this more than a threshold checker — trigger
automatic evidence capture while the anomaly is still live:

 - a fan-out flight-recorder dump (``names.flight_dump_trigger``; every
   worker's ring lands in the bundle within one telemetry flush),
 - optionally an on-demand ``jax.profiler`` capture on the trainer,
 - a pinned sample of recent stitched trace ids,
 - the triggering metric's recent window,

bundled into a per-alert ``evidence/<rule>-<ts>/`` directory. Critical
alerts additionally publish an **autoscale-inhibit** hint
(``names.autoscale_inhibit``) so the fleet does not scale into a
diverging run, and rules with ``action: pause`` may (when
``allow_pause``) command a master pause at the next step boundary through
the PR 9 WorkerControl panel instead of letting the run burn.

Disabled contract: the sentinel creates **no threads, sockets, or files**
of its own — it is driven entirely by the aggregator's existing ingest
loop — and with ``sentinel.enabled=false`` nothing here is constructed at
all, so behavior and scrape output are bit-identical to a build without
this module.
"""

from __future__ import annotations

import collections
import dataclasses
import difflib
import json
import math
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from areal_tpu.base import logging, name_resolve, names, telemetry

logger = logging.getLogger("system.sentinel")

RULE_KINDS = ("threshold", "rate", "baseline", "absence")
SEVERITIES = ("info", "warn", "critical")
OPS = {
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
}
AGGS = ("max", "min", "mean", "sum")
ACTIONS = ("evidence", "pause")

# Metric names a rule may reference — the union of every gauge/counter
# series the workers export (base names; inline ``{label=...}`` suffixes
# are stripped at feed time, so one rule watches a family across all its
# label values and workers). validate_config rejects rules referencing
# names outside this catalog at parse time, while the operator is still
# at the command line (docs/observability.md carries the same table).
METRIC_CATALOG = frozenset({
    # trainer training-dynamics series (trainer_worker._export_train_stats
    # republishes every train_step stat as train/<name>{mfc=...})
    "train/actor_loss", "train/critic_loss", "train/importance_weight",
    "train/clip_ratio", "train/dual_clip_ratio", "train/value_clip_ratio",
    "train/mean_kl", "train/approx_kl", "train/entropy",
    "train/behav_imp_tail", "train/kl_coef", "train/grad_norm", "train/lr",
    "train/n_action_tokens", "train/n_ppo_steps", "train/task_reward",
    "train/reward_std", "train/adv_scale", "train/staleness_lag",
    "train/value_mean", "train/value_var", "train/update_applied",
    "train/loss_weight", "train/total_tokens",
    # train engine counters/gauges (backend/jax_train.py)
    "train/tokens", "train/optimizer_steps", "train/pack_fill",
    # parallelism engagement (parallel/pipeline.py gates, exported per
    # batch by backend/jax_train.py): 0/1 gauges for whether the pipeline
    # schedule and ring attention actually engaged, plus the per-reason
    # GSPMD-fallback counter.
    "train/pp_engaged", "train/ring_engaged", "parallel/pp_fallback",
    "train/moe_ep_engaged",
    # MoE routing health (backend/jax_train.py publishes per train step):
    # fraction of routed assignments dropped at the capacity boundary, the
    # per-expert load share histogram, and its max/mean ratio (1 = balanced,
    # num_experts = full collapse onto one expert).
    "train/moe_dropped_frac", "train/moe_expert_load_dist",
    "train/moe_expert_load_ratio",
    # goodput ledger + live MFU (system/goodput.py): per-worker
    # time-in-state counters, the trainer's achieved-FLOP/s gauges, the
    # generation servers' analytic decode/prefill FLOP/s, and the
    # aggregator-derived fleet goodput (fed as source "fleet:0").
    "goodput/secs", "train/achieved_tflops", "train/mfu",
    "genserver/decode_tflops", "genserver/decode_mfu",
    "genserver/prefill_tflops", "fleet/goodput", "fleet/goodput_workers",
    # trainer worker
    "trainer/store_size", "trainer/pull_queue_depth",
    "trainer/weight_publish_secs", "trainer/weight_publishes",
    # master (fed directly from the step loop — no flush latency)
    "master/step_secs", "master/step",
    # rollout workers
    "rollout/inflight", "rollout/done", "rollout/failovers",
    "rollout/alloc_denied", "rollout/backpressure_throttled",
    "rollout/trajectories_pushed", "rollout/staleness_current",
    # generation fleet + manager
    "gsmgr/healthy_servers", "gsmgr/known_servers", "gsmgr/lease_depth",
    "gsmgr/running_rollouts", "gsmgr/accepted_rollouts", "gsmgr/evictions",
    "gsmgr/health_probe_failures", "gsmgr/fanout_failures",
    "gsmgr/weight_version", "genserver/weight_version",
    "genserver/generated_tokens", "genserver/decode_chunks",
    "genserver/inflight_requests", "genserver/weight_update_failures",
    # autoscaler wedge/cordon counters (the sentinel consumes these; on
    # critical alerts it publishes the inhibit hint back — see
    # system/autoscaler.read_inhibit)
    "autoscale/cordoned_servers", "autoscale/current_size",
    "autoscale/target_size", "autoscale/overloaded", "autoscale/cordons",
    "autoscale/straggler_cordons", "autoscale/straggler_deprioritized",
    "autoscale/backpressure_denials", "autoscale/inhibited",
    # supervision + reward fleet + telemetry health
    "supervisor/restarts", "supervisor/deaths", "supervisor/draining",
    "reward/requests", "reward/timeouts", "reward/errors",
    "telemetry/spans_dropped",
    # durable sample spool (system/sample_spool.py): per-rollout-worker
    # depth/bytes/age gauges + delivery counters, the trainer's
    # dedup/stale-drop counters, and the stream/buffer degradation
    # counters the at-least-once path leans on.
    "spool/depth", "spool/bytes", "spool/oldest_unacked_age_secs",
    "spool/appended", "spool/acked", "spool/resent", "spool/replayed",
    "spool/backpressure_waits", "spool/replay_stale_dropped",
    "spool/duplicate_dropped", "buffer/duplicate_dropped",
    "stream/push_blocked",
    # compile & HBM observatory (base/compile_watch.py,
    # system/memwatch.py; docs/observability.md §Compile & memory):
    # per-fn compile events/seconds/shape counts, the process-wide
    # in-flight gauge the compile-aware absence rules read, persistent
    # cache hit/miss counters, and per-device HBM gauges plus the
    # aggregator-derived utilization series.
    "compile/events", "compile/secs", "compile/storm_events",
    "compile/cache_hits", "compile/cache_misses", "compile/inflight",
    "compile/distinct_shapes",
    "hbm/bytes_in_use", "hbm/peak_bytes", "hbm/limit_bytes",
    "hbm/watermark_bytes", "hbm/utilization",
    "hbm/memory_stats_unavailable",
})

_DUR_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*(ms|s|m|h)?\s*$")
_DUR_UNITS = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, None: 1.0}


def parse_duration(v) -> float:
    """``30``, ``"30"``, ``"30s"``, ``"5m"``, ``"1.5h"`` → seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    m = _DUR_RE.match(str(v))
    if not m:
        raise ValueError(f"cannot parse duration {v!r} "
                         f"(use seconds, or '30s'/'5m'/'1h')")
    return float(m.group(1)) * _DUR_UNITS[m.group(2)]


class SentinelConfigError(ValueError):
    """Raised at parse time for an invalid rule pack; api.cli_args wraps
    it into its ConfigError so a bad pack fails at the command line."""


@dataclasses.dataclass
class Rule:
    """One parsed, validated sentinel rule."""

    id: str
    metric: str
    kind: str = "threshold"
    op: str = "gt"
    value: float = 0.0  # threshold / rate-per-sec / baseline sigmas
    for_secs: float = 10.0
    cooldown_secs: float = 300.0
    severity: str = "warn"
    window_secs: float = 120.0  # rate + baseline lookback
    agg: str = "max"  # across workers/labels reporting the metric
    action: str = "evidence"  # "pause" additionally pauses the master
    description: str = ""
    # Absence-rule suppressor: while this metric family has a recent
    # nonzero reading (or the matching names.compile_inflight flag is
    # fresh), the absence predicate reports healthy instead of counting
    # toward 'for'. The compile-aware liveness story: trainer_stalled
    # sets it to compile/inflight so a long warmup XLA compile doesn't
    # need a blanket 30-minute grace.
    unless_metric: Optional[str] = None


# The default rule pack — the divergence signatures that actually kill RL
# runs (AReaL's decoupled-PPO staleness control; long-horizon runs where
# silent divergence wastes days of compute) plus fleet-wedge detection.
# Thresholds are deliberately conservative: a healthy run fires nothing.
# docs/operations.md maps each id to its first diagnostic step.
DEFAULT_RULES: Tuple[Dict[str, Any], ...] = (
    {"id": "kl_blowup", "metric": "train/approx_kl", "kind": "threshold",
     "op": "gt", "value": 1.0, "for": 10, "cooldown": 300,
     "severity": "critical",
     "description": "policy ran away from the behavior policy "
                    "(approx-KL > 1 nat sustained)"},
    {"id": "ref_kl_runaway", "metric": "train/mean_kl", "kind": "threshold",
     "op": "gt", "value": 10.0, "for": 30, "cooldown": 600,
     "severity": "warn",
     "description": "behavior policy far from the reference policy"},
    {"id": "entropy_collapse", "metric": "train/entropy",
     "kind": "threshold", "op": "lt", "value": 0.05, "for": 30,
     "cooldown": 600, "severity": "critical",
     "description": "token entropy near zero: the policy went "
                    "deterministic and exploration is dead"},
    {"id": "clip_saturation", "metric": "train/clip_ratio",
     "kind": "threshold", "op": "gt", "value": 0.5, "for": 30,
     "cooldown": 600, "severity": "warn",
     "description": "most action tokens are clipping: updates are "
                    "dominated by the trust region"},
    {"id": "imp_weight_tail", "metric": "train/behav_imp_tail",
     "kind": "threshold", "op": "gt", "value": 0.2, "for": 30,
     "cooldown": 600, "severity": "warn",
     "description": "importance-weight cap is dropping a heavy token "
                    "tail: off-policyness beyond what the loss corrects"},
    {"id": "grad_norm_spike", "metric": "train/grad_norm",
     "kind": "baseline", "value": 8.0, "for": 5, "window": 600,
     "cooldown": 300, "severity": "warn",
     "description": "grad norm jumped far off its rolling baseline"},
    {"id": "reward_drift", "metric": "train/task_reward",
     "kind": "baseline", "value": 8.0, "for": 30, "window": 1200,
     "cooldown": 900, "severity": "warn",
     "description": "task reward moved far off its rolling baseline "
                    "(reward hacking or a broken grader)"},
    {"id": "staleness_runaway", "metric": "train/staleness_lag",
     "kind": "threshold", "op": "gt", "value": 16.0, "for": 60,
     "cooldown": 600, "severity": "warn",
     "description": "trained samples lag many weight versions behind: "
                    "the staleness gate is not holding"},
    # Short grace + compile-aware suppression, not a blanket 30 minutes:
    # the FIRST optimizer step on TPU sits behind the warmup XLA compile,
    # and the old fix was a fixed 1800s grace that also hid every
    # genuinely-wedged trainer for half an hour. With the compile
    # observatory the rule is suppressed only while compile/inflight (or
    # the worker's names.compile_inflight flag) says a compile is
    # actually in progress — a cold start stays quiet, a wedged trainer
    # alerts in minutes.
    {"id": "trainer_stalled", "metric": "train/optimizer_steps",
     "kind": "absence", "for": 300, "cooldown": 1800,
     "severity": "critical", "unless": "compile/inflight",
     "description": "no optimizer step in 5 minutes and no compile in "
                    "flight: the training pipeline is wedged"},
    {"id": "fleet_down", "metric": "gsmgr/healthy_servers",
     "kind": "threshold", "op": "lt", "value": 1.0, "for": 60,
     "cooldown": 300, "severity": "critical",
     "description": "no routable generation server"},
    {"id": "step_time_regression", "metric": "master/step_secs",
     "kind": "baseline", "value": 10.0, "for": 30, "window": 1800,
     "cooldown": 900, "severity": "warn",
     "description": "step wall time far off its rolling baseline "
                    "(throughput regression)"},
    # Only has data on MoE runs: dense models never export the series,
    # so the rule stays silent (baseline rules need samples to fire).
    {"id": "expert_collapse", "metric": "train/moe_expert_load_ratio",
     "kind": "baseline", "value": 8.0, "for": 30, "window": 1200,
     "cooldown": 900, "severity": "warn",
     "description": "expert load max/mean ratio jumped far off its "
                    "rolling baseline: routing is collapsing onto a few "
                    "experts — check train/moe_expert_load_dist and the "
                    "load-balance loss coefficient"},
    # Needs goodput.enabled (the fleet/goodput series only exists when
    # the ledger runs); with goodput off the rule simply never has data,
    # like every rule on a disabled subsystem's metrics.
    {"id": "goodput_collapse", "metric": "fleet/goodput",
     "kind": "baseline", "value": 8.0, "for": 60, "window": 1200,
     "cooldown": 900, "severity": "warn", "agg": "mean",
     "description": "fleet goodput (useful chip-seconds / total) fell "
                    "far off its rolling baseline: chips went idle — "
                    "check the per-state split (perf_probe goodput) for "
                    "which side starved"},
)


# Armed only when durability.enabled (rules_from_config): an absence
# rule fires even for a never-seen metric, so shipping this in the
# always-on pack would false-fire on every non-durable run.
DURABILITY_RULES: Tuple[Dict[str, Any], ...] = (
    {"id": "sample_loss", "metric": "spool/acked", "kind": "absence",
     "for": 1800, "cooldown": 1800, "severity": "critical",
     "description": "no spool ack in 30 minutes: trajectories are being "
                    "generated but never settle at the trainer — the "
                    "at-least-once loop is broken somewhere between push, "
                    "train, and ack (perf_probe spool-status; "
                    "docs/operations.md §Did we lose samples?)"},
)


# Armed only when compile_watch.enabled (rules_from_config): the series
# these watch exist only with the observatory on, and compile_stall is a
# threshold on a gauge a disabled fleet never exports. Thresholds follow
# the default-pack philosophy — a healthy warmup fires nothing.
COMPILE_RULES: Tuple[Dict[str, Any], ...] = (
    # ~2 storms/100s sustained: one stray shape after warmup is a blip
    # (logged + counted, no alert); a steady drip means something feeds
    # the jit unbucketed shapes every step (docs/operations.md §my step
    # got slow).
    {"id": "recompile_storm", "metric": "compile/storm_events",
     "kind": "rate", "op": "gt", "value": 0.02, "for": 10, "window": 120,
     "cooldown": 600, "severity": "warn",
     "description": "recompiles of previously-stable jit functions keep "
                    "arriving after warmup: shape churn is defeating the "
                    "bucketing (perf_probe compile-status names the fn "
                    "and offending shape)"},
    {"id": "hbm_pressure", "metric": "hbm/utilization",
     "kind": "threshold", "op": "gt", "value": 0.92, "for": 60,
     "cooldown": 600, "severity": "warn", "agg": "max",
     "description": "a device sits above 92% HBM for a minute: the next "
                    "weight publish or shape spike OOMs — check "
                    "hbm/watermark_bytes for which allocator owns the "
                    "peak (docs/weight_sync.md §HBM headroom)"},
    # 20 min inside ONE compile: even pathological warmup compiles
    # finish in minutes — a compile/inflight gauge stuck >= 1 this long
    # means the compile itself hung (or the end-hook never ran).
    {"id": "compile_stall", "metric": "compile/inflight",
     "kind": "threshold", "op": "ge", "value": 1.0, "for": 1200,
     "cooldown": 1800, "severity": "critical",
     "description": "a jit compile has been in flight for 20+ minutes: "
                    "the run is wedged inside XLA, not between steps"},
)


def _dur_field(raw: Dict[str, Any], rule_id: str, *keys,
               default: Optional[float] = None) -> Optional[float]:
    for k in keys:
        if k in raw:
            try:
                return parse_duration(raw[k])
            except ValueError as e:
                raise SentinelConfigError(
                    f"rule {rule_id!r}: bad {keys[0]!r} duration: {e}"
                ) from None
    return default


def parse_rule(raw: Dict[str, Any],
               catalog: Optional[frozenset] = None) -> Rule:
    if not isinstance(raw, dict):
        raise SentinelConfigError(
            f"each sentinel rule must be a mapping, got {type(raw).__name__}"
        )
    rid = str(raw.get("id") or "").strip()
    if not rid:
        raise SentinelConfigError(
            f"sentinel rule without an 'id': {raw!r}"
        )
    metric = str(raw.get("metric") or "").strip()
    catalog = catalog if catalog is not None else METRIC_CATALOG
    if metric not in catalog:
        close = difflib.get_close_matches(metric, sorted(catalog), n=3)
        hint = f" (did you mean: {', '.join(close)}?)" if close else ""
        raise SentinelConfigError(
            f"rule {rid!r}: unknown metric {metric!r}{hint}; the sentinel "
            f"only evaluates names in system/sentinel.METRIC_CATALOG "
            f"(docs/observability.md)"
        )
    kind = str(raw.get("kind", "threshold"))
    if kind not in RULE_KINDS:
        raise SentinelConfigError(
            f"rule {rid!r}: unknown kind {kind!r} "
            f"(valid: {', '.join(RULE_KINDS)})"
        )
    severity = str(raw.get("severity", "warn"))
    if severity not in SEVERITIES:
        raise SentinelConfigError(
            f"rule {rid!r}: unknown severity {severity!r} "
            f"(valid: {', '.join(SEVERITIES)})"
        )
    op = str(raw.get("op", "gt"))
    if op not in OPS:
        raise SentinelConfigError(
            f"rule {rid!r}: unknown op {op!r} (valid: {', '.join(OPS)})"
        )
    agg = str(raw.get("agg", "max"))
    if agg not in AGGS:
        raise SentinelConfigError(
            f"rule {rid!r}: unknown agg {agg!r} (valid: {', '.join(AGGS)})"
        )
    action = str(raw.get("action", "evidence"))
    if action not in ACTIONS:
        raise SentinelConfigError(
            f"rule {rid!r}: unknown action {action!r} "
            f"(valid: {', '.join(ACTIONS)})"
        )
    for_secs = _dur_field(raw, rid, "for", "for_secs", default=10.0)
    cooldown = _dur_field(raw, rid, "cooldown", "cooldown_secs",
                          default=300.0)
    window = _dur_field(raw, rid, "window", "window_secs", default=120.0)
    if for_secs is None or for_secs <= 0:
        raise SentinelConfigError(
            f"rule {rid!r}: 'for' must be a positive duration "
            f"(got {for_secs})"
        )
    if cooldown is None or cooldown <= 0:
        raise SentinelConfigError(
            f"rule {rid!r}: 'cooldown' must be a positive duration "
            f"(got {cooldown})"
        )
    if window is None or window <= 0:
        raise SentinelConfigError(
            f"rule {rid!r}: 'window' must be a positive duration "
            f"(got {window})"
        )
    try:
        value = float(raw.get("value", 0.0))
    except (TypeError, ValueError):
        raise SentinelConfigError(
            f"rule {rid!r}: 'value' must be a number, "
            f"got {raw.get('value')!r}"
        ) from None
    if kind == "baseline" and value <= 0:
        raise SentinelConfigError(
            f"rule {rid!r}: baseline rules need value > 0 "
            f"(the deviation multiplier)"
        )
    unless = raw.get("unless")
    if unless is not None:
        unless = str(unless).strip()
        if kind != "absence":
            raise SentinelConfigError(
                f"rule {rid!r}: 'unless' only applies to absence rules "
                f"(it suppresses the missing-progress predicate while "
                f"the named metric is live)"
            )
        if unless not in catalog:
            close = difflib.get_close_matches(unless, sorted(catalog), n=3)
            hint = f" (did you mean: {', '.join(close)}?)" if close else ""
            raise SentinelConfigError(
                f"rule {rid!r}: unknown 'unless' metric {unless!r}{hint}"
            )
    return Rule(
        id=rid, metric=metric, kind=kind, op=op, value=value,
        for_secs=for_secs, cooldown_secs=cooldown, severity=severity,
        window_secs=window, agg=agg, action=action,
        description=str(raw.get("description", "")),
        unless_metric=unless,
    )


def parse_rules(raw_rules: Sequence[Dict[str, Any]],
                catalog: Optional[frozenset] = None) -> List[Rule]:
    rules = [parse_rule(r, catalog=catalog) for r in raw_rules]
    seen: Dict[str, int] = {}
    for r in rules:
        seen[r.id] = seen.get(r.id, 0) + 1
    dups = sorted(k for k, n in seen.items() if n > 1)
    if dups:
        raise SentinelConfigError(
            f"duplicate sentinel rule id(s): {', '.join(dups)} — every "
            f"rule needs a unique id (alert records, silences, and the "
            f"areal_alerts_total label key on it)"
        )
    return rules


def rules_from_config(cfg, durability_enabled: bool = False,
                      compile_watch_enabled: bool = False) -> List[Rule]:
    """``SentinelConfig`` → parsed rule list: the default pack (unless
    ``default_rules=false``), the durability pack when the durable
    sample spool is armed, the compile/HBM pack when the compile
    observatory is armed, plus the operator's ``rules`` entries. This
    is the function ``validate_config`` front-runs at parse time."""
    raw: List[Dict[str, Any]] = []
    if getattr(cfg, "default_rules", True):
        raw.extend(dict(r) for r in DEFAULT_RULES)
        if durability_enabled:
            raw.extend(dict(r) for r in DURABILITY_RULES)
        if compile_watch_enabled:
            raw.extend(dict(r) for r in COMPILE_RULES)
    raw.extend(getattr(cfg, "rules", []) or [])
    return parse_rules(raw)


# --------------------------------------------------------------------------
# the engine
# --------------------------------------------------------------------------


class _Series:
    """Per-source ``(value, t)`` readings (source = ``worker|metric-key``)
    + when any source last reported a NEW value. Rings of the aggregated
    value live per RULE (two rules may aggregate the same metric
    differently).

    ``last_seen`` refreshes only when a value CHANGES (or a source first
    appears): workers flush their full cumulative registry every
    interval, so mere sample arrival proves the worker process is alive,
    not that the activity the metric counts is still happening — an
    absence rule on ``train/optimizer_steps`` must catch a trainer that
    is wedged-but-flushing, not just a dead one. (Absence rules are
    therefore meant for counters/activity series, not for gauges that
    legitimately sit constant.)"""

    __slots__ = ("latest", "last_seen")

    def __init__(self):
        self.latest: Dict[str, Tuple[float, float]] = {}  # src -> (v, t)
        self.last_seen: Optional[float] = None


class _RuleState:
    __slots__ = ("rule", "state", "pending_since", "last_fired",
                 "fire_count", "ring", "last_value")

    def __init__(self, rule: Rule, eval_interval_secs: float = 1.0):
        self.rule = rule
        self.state = "ok"  # ok | pending | firing
        self.pending_since: Optional[float] = None
        self.last_fired: Optional[float] = None
        self.fire_count = 0
        # (monotonic t, aggregated value) appended once per eval tick —
        # sized so the rule's OWN window fits (a fixed length would
        # silently truncate long baseline windows), bounded for memory.
        points = int(rule.window_secs / max(eval_interval_secs, 1e-3)) + 8
        self.ring: "collections.deque[Tuple[float, float]]" = (
            collections.deque(maxlen=max(64, min(points, 7200)))
        )
        self.last_value: Optional[float] = None


def _agg(values: Sequence[float], how: str) -> float:
    if how == "max":
        return max(values)
    if how == "min":
        return min(values)
    if how == "sum":
        return sum(values)
    return sum(values) / len(values)


class Sentinel:
    """The rule-driven health engine. Thread-safe; creates no threads of
    its own — ``feed()`` is called by the aggregator's ingest path (and
    directly by the master's step loop), ``tick()`` by the aggregator's
    poll loop. Every clock/side-effect is injectable for fake-clock
    tests; the defaults wire the real fleet hooks:

    - ``flight_fn(dir)``   → :func:`telemetry.request_flight_dump`
    - ``profile_fn(dir,s)``→ :func:`telemetry.request_profiler_capture`
    - ``inhibit_fn(rec)``  → write ``names.autoscale_inhibit``
    - ``pause_fn()``       → WorkerControlPanel.pause("master") in a
      one-shot thread (spawned only at that moment)
    """

    def __init__(self, cfg, experiment: str, trial: str, *,
                 rules: Optional[List[Rule]] = None,
                 registry: Optional["telemetry.TelemetryRegistry"] = None,
                 stitcher=None,
                 alerts_path: Optional[str] = None,
                 evidence_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 flight_fn: Optional[Callable[[str], Any]] = None,
                 profile_fn: Optional[Callable[[str, float], Any]] = None,
                 inhibit_fn: Optional[Callable[[Dict], Any]] = None,
                 pause_fn: Optional[Callable[[], Any]] = None):
        self.cfg = cfg
        self.experiment = experiment
        self.trial = trial
        self.registry = registry or telemetry.TelemetryRegistry()
        self.stitcher = stitcher
        self.clock = clock
        self.wall = wall
        self.alerts_path = alerts_path or getattr(cfg, "alerts_path", None)
        self.evidence_dir = (evidence_dir
                             or getattr(cfg, "evidence_dir", None))
        self._flight_fn = flight_fn or self._default_flight
        self._profile_fn = profile_fn or self._default_profile
        self._inhibit_fn = inhibit_fn or self._default_inhibit
        self._pause_fn = pause_fn or self._default_pause
        self._lock = threading.Lock()
        self._emit_lock = threading.Lock()
        self._series: Dict[str, _Series] = {}
        # rule id -> cached silence expiry (wall clock): lets the eval
        # loop suppress a silenced alert without per-tick name-resolve
        # reads; refreshed by _silenced() at real fire attempts.
        self._silence_until: Dict[str, float] = {}
        interval = getattr(cfg, "eval_interval_secs", 1.0)
        self._states = [
            _RuleState(r, eval_interval_secs=interval)
            for r in (rules if rules is not None else rules_from_config(cfg))
        ]
        self._alerts_file = None
        self._last_eval: Optional[float] = None
        self._bundles = 0
        self._t_start = clock()
        self.registry.set_gauge("sentinel/rules", float(len(self._states)))

    # ---- ingest ----

    def feed(self, worker: str, gauges: Optional[Dict[str, float]] = None,
             counters: Optional[Dict[str, float]] = None,
             now: Optional[float] = None) -> None:
        """Record one worker's latest gauge/counter values. Inline label
        suffixes (``train/grad_norm{mfc=actor_train}``) are folded into
        the base metric's source set, so one rule watches the whole
        family across workers AND label values."""
        now = self.clock() if now is None else now
        with self._lock:
            for src in (gauges, counters):
                for key, v in (src or {}).items():
                    if not isinstance(v, (int, float)) \
                            or not math.isfinite(v):
                        continue
                    base, _labels = telemetry._metric_key_labels(key)
                    s = self._series.get(base)
                    if s is None:
                        s = self._series[base] = _Series()
                    sk = f"{worker}|{key}"
                    prev = s.latest.get(sk)
                    s.latest[sk] = (float(v), now)
                    if prev is None or prev[0] != float(v):
                        s.last_seen = now  # NEW value, not mere arrival

    # ---- evaluation ----

    def tick(self, now: Optional[float] = None) -> None:
        """Evaluate every rule (rate-limited to ``eval_interval_secs``).
        Called from the aggregator's poll loop; safe from any thread."""
        now = self.clock() if now is None else now
        interval = getattr(self.cfg, "eval_interval_secs", 1.0)
        fired: List[Tuple[_RuleState, Dict]] = []
        resolved: List[Tuple[_RuleState, Dict]] = []
        wall_now = self.wall()
        with self._lock:
            if self._last_eval is not None \
                    and now - self._last_eval < interval:
                return
            self._last_eval = now
            # Expire sources that stopped reporting (scaled-down /
            # evicted workers): a departed worker's last gauge must not
            # pin a max/sum aggregate — and a false alert — forever.
            expiry = getattr(self.cfg, "source_expiry_secs", 120.0)
            for s in self._series.values():
                stale = [k for k, (_, t) in s.latest.items()
                         if now - t > expiry]
                for k in stale:
                    del s.latest[k]
            for st in self._states:
                self._eval_rule(st, now, wall_now, fired, resolved)
        # Side effects (file appends, evidence, inhibit, pause) run
        # OUTSIDE the lock: none of them may stall feed().
        for st, rec in resolved:
            self._emit(rec)
        for st, rec in fired:
            self._on_fire(st, rec)

    def _eval_rule(self, st: _RuleState, now: float, wall_now: float,
                   fired: List, resolved: List) -> None:
        r = st.rule
        s = self._series.get(r.metric)
        cur: Optional[float] = None
        if s is not None and s.latest:
            cur = _agg([v for v, _ in s.latest.values()], r.agg)
            st.ring.append((now, cur))
            st.last_value = cur
        active = self._predicate(st, s, cur, now)
        if active and st.state == "ok":
            st.state = "pending"
            st.pending_since = now
        elif not active:
            if st.state == "firing":
                since = (st.pending_since
                         if st.pending_since is not None else now)
                resolved.append((st, {
                    "event": "resolved", "rule": r.id,
                    "severity": r.severity, "metric": r.metric,
                    "value": cur, "ts": round(self.wall(), 3),
                    "active_secs": round(now - since, 3),
                }))
                self.registry.set_gauge(
                    f"alert_active{{rule={r.id}}}", 0.0)
            st.state = "ok"
            st.pending_since = None
            return
        # Absence rules carry their own duration in the predicate (the
        # silence IS the `for:` window) — they fire the tick they trip.
        since = st.pending_since if st.pending_since is not None else now
        held = now - since >= r.for_secs or r.kind == "absence"
        if st.state == "pending" and held:
            if st.last_fired is not None \
                    and now - st.last_fired < r.cooldown_secs:
                return  # cooling down: stay pending
            if self._silence_until.get(r.id, 0.0) > wall_now:
                # Cached operator silence: stay pending with zero I/O —
                # an active alert under a long silence must not hit
                # name-resolve (or bump counters) every tick.
                return
            # The fresh silence lookup (name-resolve I/O) happens in
            # _on_fire, OUTSIDE the engine lock — a slow NFS mount must
            # never stall feed() from the ingest path. A silenced fire
            # is rolled back to pending there and its expiry cached.
            st.state = "firing"
            st.last_fired = now
            st.fire_count += 1
            fired.append((st, {
                "event": "firing", "rule": r.id, "severity": r.severity,
                "kind": r.kind, "metric": r.metric, "value": cur,
                "threshold": r.value, "for_secs": r.for_secs,
                "ts": round(self.wall(), 3),
                "description": r.description,
            }))

    def _predicate(self, st: _RuleState, s: Optional[_Series],
                   cur: Optional[float], now: float) -> bool:
        r = st.rule
        if r.kind == "absence":
            if r.unless_metric is not None:
                # Compile-aware suppression: a live nonzero reading on
                # the unless-metric family (any worker, any label) means
                # the absence is EXPLAINED — the worker is inside a jit
                # compile, not wedged. Source expiry already dropped
                # stale readings, so a SIGKILLed worker's last gauge
                # stops suppressing within source_expiry_secs.
                u = self._series.get(r.unless_metric)
                if u is not None and any(
                    v > 0 for v, _ in u.latest.values()
                ):
                    return False
            # Grace from sentinel start: a metric never seen only counts
            # as absent once the run is older than the rule's window.
            last = s.last_seen if (s and s.last_seen is not None) \
                else self._t_start
            return now - last > r.for_secs
        if cur is None:
            return False
        if r.kind == "threshold":
            return OPS[r.op](cur, r.value)
        pts = [(t, v) for t, v in st.ring if t >= now - r.window_secs]
        if r.kind == "rate":
            if len(pts) < 2:
                return False
            t0, v0 = pts[0]
            t1, v1 = pts[-1]
            if t1 - t0 <= 0:
                return False
            return OPS[r.op]((v1 - v0) / (t1 - t0), r.value)
        # baseline: robust z-score of the latest point against the
        # window — median/MAD, not mean/std, so an anomaly that persists
        # for a few ticks cannot poison its own baseline and self-clear
        # (the classic self-referential threshold bug). The relative
        # floor (5% of |median|) keeps a near-constant series from
        # firing on jitter.
        base = sorted(v for _, v in pts[:-1])
        if len(base) < 8:
            return False
        med = base[len(base) // 2]
        mad = sorted(abs(v - med) for v in base)[len(base) // 2]
        scale = max(1.4826 * mad, 0.05 * abs(med), 1e-12)
        return abs(cur - med) > r.value * scale

    # ---- silences (tools/perf_probe.py silence <rule> <duration>) ----

    def _silenced(self, rule: Rule) -> bool:
        """Fresh name-resolve read of the rule's silence (called only at
        an actual fire attempt, never under the engine lock); a live
        silence is cached so subsequent ticks suppress in memory."""
        try:
            raw = name_resolve.get(names.sentinel_silence(
                self.experiment, self.trial, rule.id))
        except Exception:  # noqa: BLE001 — no silence registered
            return False
        try:
            until = float(json.loads(raw).get("until", 0.0))
        except Exception:  # noqa: BLE001 — torn write
            return False
        if self.wall() < until:
            with self._lock:
                self._silence_until[rule.id] = until
            return True
        return False

    # ---- compile-aware suppression (base/compile_watch.py) ----

    def _compile_inflight_fresh(self, max_age_secs: float = 60.0) -> bool:
        """Fresh name-resolve read of every worker's
        ``names.compile_inflight`` flag (called only at an actual fire
        attempt of an unless-guarded absence rule, never under the
        engine lock — same discipline as :meth:`_silenced`). The metric
        path above already suppresses in-memory; this catches the gap
        where a worker is wedged INSIDE a compile and its telemetry
        flush (but not its heartbeat thread) stopped. Flags are
        rewritten every heartbeat, so anything older than
        ``max_age_secs`` is a dead worker's ghost and does not
        suppress."""
        try:
            vals = name_resolve.get_subtree(
                names.compile_inflight_root(self.experiment, self.trial))
        except Exception:  # noqa: BLE001 — no flags registered
            return False
        now = self.wall()
        for raw in vals:
            try:
                ts = float(json.loads(raw).get("ts", 0.0))
            except Exception:  # noqa: BLE001 — torn write
                continue
            if now - ts < max_age_secs:
                return True
        return False

    # ---- firing side effects ----

    def _on_fire(self, st: _RuleState, rec: Dict) -> None:
        r = st.rule
        if r.kind == "absence" and r.unless_metric is not None \
                and self._compile_inflight_fresh():
            # Roll back to pending exactly like a silence: the compile
            # drains, the flag disappears, and the next tick re-attempts
            # with the `for:` hold still satisfied.
            with self._lock:
                if st.state == "firing":
                    st.state = "pending"
                st.last_fired = None
                st.fire_count -= 1
            self.registry.inc(
                f"sentinel/compile_suppressed{{rule={r.id}}}")
            return
        if self._silenced(r):
            # Operator silence: roll the transition back to pending (the
            # `for:` hold stays satisfied; the next tick re-attempts) and
            # burn neither the cooldown nor an evidence bundle.
            with self._lock:
                if st.state == "firing":
                    st.state = "pending"
                st.last_fired = None
                st.fire_count -= 1
            self.registry.inc(f"sentinel/silenced{{rule={r.id}}}")
            return
        self.registry.inc(f"alerts{{rule={r.id},severity={r.severity}}}")
        self.registry.set_gauge(f"alert_active{{rule={r.id}}}", 1.0)
        logger.warning(
            f"ALERT {r.severity} {r.id}: {r.metric}={rec.get('value')} "
            f"({r.description or r.kind})"
        )
        evidence = None
        if r.severity in ("warn", "critical"):
            evidence = self._capture_evidence(st, rec)
            if evidence:
                rec["evidence_dir"] = evidence
        if r.severity == "critical" \
                and getattr(self.cfg, "autoscale_inhibit", True):
            try:
                self._inhibit_fn(rec)
                rec["autoscale_inhibited"] = True
            except Exception as e:  # noqa: BLE001 — hint is best-effort
                logger.warning(f"autoscale inhibit publish failed: {e}")
        if r.action == "pause":
            if getattr(self.cfg, "allow_pause", False):
                rec["pause_requested"] = True
                try:
                    self._pause_fn()
                except Exception as e:  # noqa: BLE001
                    logger.warning(f"sentinel pause request failed: {e}")
            else:
                rec["pause_requested"] = False
        self._emit(rec)

    def _capture_evidence(self, st: _RuleState,
                          rec: Dict) -> Optional[str]:
        """Bundle the anomaly's context while it is still live:
        ``evidence/<rule>-<ts>/`` with the alert + triggering metric
        window, a fleet-wide flight-dump trigger, pinned recent stitched
        trace ids, and (optionally, critical only) a trainer profiler
        capture. Never raises — evidence is best-effort."""
        if not self.evidence_dir:
            return None
        cap = getattr(self.cfg, "max_evidence_bundles", 8)
        if self._bundles >= cap:
            self.registry.inc("sentinel/evidence_skipped")
            return None
        try:
            d = os.path.join(
                self.evidence_dir,
                f"{st.rule.id}-{int(self.wall() * 1000)}",
            )
            os.makedirs(d, exist_ok=True)
            with self._lock:
                window = [
                    {"t": round(t, 3), "value": v} for t, v in st.ring
                ]
                series = self._series.get(st.rule.metric)
                sources = (
                    {k: v for k, (v, _) in series.latest.items()}
                    if series else {}
                )
            with open(os.path.join(d, "alert.json"), "w") as f:
                json.dump({
                    **rec,
                    "metric_window": window[-240:],
                    "sources": sources,
                }, f, indent=1, sort_keys=True)
            self._flight_fn(d)
            pinned = []
            if self.stitcher is not None:
                try:
                    pinned = self.stitcher.recent_trace_ids(
                        getattr(self.cfg, "pinned_traces", 8))
                except Exception:  # noqa: BLE001
                    pinned = []
            with open(os.path.join(d, "traces.json"), "w") as f:
                json.dump({"pinned_trace_ids": pinned}, f)
            if st.rule.severity == "critical" \
                    and getattr(self.cfg, "profile_on_critical", False):
                self._profile_fn(
                    os.path.join(d, "profile"),
                    getattr(self.cfg, "profile_secs", 5.0),
                )
            self._bundles += 1
            self.registry.inc("sentinel/evidence_bundles")
            return d
        except Exception as e:  # noqa: BLE001 — never kill the aggregator
            logger.warning(f"evidence capture for {st.rule.id} failed: {e}")
            return None

    # ---- default fleet hooks ----

    def _default_flight(self, out_dir: str) -> None:
        telemetry.request_flight_dump(self.experiment, self.trial, out_dir)

    def _default_profile(self, out_dir: str, secs: float) -> None:
        telemetry.request_profiler_capture(
            self.experiment, self.trial, out_dir, secs)

    def _default_inhibit(self, rec: Dict) -> None:
        """Publish the autoscale-inhibit hint: while it is live the
        manager's scaling loop suppresses scale-up (growing the fleet
        into a diverging run only burns capacity and deepens
        off-policyness) — system/autoscaler.read_inhibit."""
        name_resolve.add(
            names.autoscale_inhibit(self.experiment, self.trial),
            json.dumps({
                "until": self.wall() + getattr(
                    self.cfg, "inhibit_secs", 300.0),
                "rule": rec.get("rule"), "ts": rec.get("ts"),
            }),
            replace=True, delete_on_exit=False,
        )

    def _default_pause(self) -> None:
        """Command a master pause at the next step boundary (PR 9 panel
        machinery) from a one-shot thread — the panel is sync ZMQ and
        must never block the aggregator's ingest loop."""
        exp, trial = self.experiment, self.trial

        def run():
            from areal_tpu.system.worker_base import WorkerControlPanel

            panel = WorkerControlPanel(exp, trial, timeout=30.0)
            try:
                st = panel.pause("master")
                logger.warning(f"sentinel paused the master: {st}")
            except Exception as e:  # noqa: BLE001 — master busy/gone
                logger.warning(f"sentinel master pause failed: {e}")
            finally:
                panel.close()

        threading.Thread(target=run, daemon=True,
                         name="sentinel-pause").start()

    # ---- output ----

    def _emit(self, rec: Dict) -> None:
        # Both the master's step loop and the aggregator's ingest loop
        # may tick concurrently; one lock keeps alert lines whole.
        if not self.alerts_path:
            return
        try:
            with self._emit_lock:
                if self._alerts_file is None:
                    os.makedirs(os.path.dirname(self.alerts_path) or ".",
                                exist_ok=True)
                    self._alerts_file = open(self.alerts_path, "a",
                                             buffering=1)
                self._alerts_file.write(json.dumps(rec) + "\n")
        except Exception as e:  # noqa: BLE001 — alerting must not kill
            logger.warning(f"alert append failed: {e}")

    # ---- views ----

    def states(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                st.rule.id: {
                    "state": st.state, "severity": st.rule.severity,
                    "metric": st.rule.metric, "value": st.last_value,
                    "fires": st.fire_count,
                }
                for st in self._states
            }

    def close(self) -> None:
        with self._emit_lock:
            if self._alerts_file is not None:
                self._alerts_file.close()
                self._alerts_file = None
