"""Master-side metadata replay buffer.

Parity target: ``realhf/system/buffer.py:117`` (AsyncIOSequenceBuffer) —
per-slot state machine (empty → put → amend* → read* → free), asyncio
condition signalling, per-MFC readiness from input keys, oldest-first batch
selection, slots freed after all consuming MFCs have read them.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Dict, Hashable, List, Optional, Sequence, Set

from areal_tpu.api.data import SequenceSample
from areal_tpu.base import logging, telemetry

logger = logging.getLogger("system.buffer")


@dataclasses.dataclass
class _Slot:
    sample: SequenceSample  # metadata-only (data=None)
    # Monotonic for LOCAL oldest-first ordering (immune to clock steps)…
    birth_time: float
    reads_left: int
    read_by: Set[str] = dataclasses.field(default_factory=set)
    # …and wall-clock alongside, so cross-process stitched timelines
    # (base/telemetry.TraceStitcher) can line the buffer dwell up against
    # spans from other workers — monotonic values are meaningless across
    # process boundaries.
    birth_wall: float = 0.0


class AsyncSequenceBuffer:
    """Holds SequenceSample METADATA only; tensors live in the trainer's
    data store (the master-sees-metadata invariant, SURVEY §1)."""

    def __init__(self, n_rpcs_reading: int, max_size: int = 65536):
        self.max_size = max_size
        self._n_reads = n_rpcs_reading
        self._slots: Dict[Hashable, _Slot] = {}
        self._lock = asyncio.Lock()
        self._changed = asyncio.Condition(self._lock)
        # ids whose slots were fully consumed/dropped since the last
        # pop_freed() — the master forwards these to the trainer's "clear"
        # handler so its tensor store can GC (it otherwise grows unbounded).
        self._freed: List[Hashable] = []

    def __len__(self) -> int:
        return len(self._slots)

    async def put_batch(self, samples: Sequence[SequenceSample]) -> None:
        async with self._lock:
            for s in samples:
                if s.bs != 1:
                    raise ValueError("buffer slots hold single samples")
                sid = s.ids[0]
                if sid in self._slots:
                    # At-least-once delivery (docs/fault_tolerance.md
                    # §Data durability) makes duplicates a normal event,
                    # not corruption: a resent trajectory that slipped
                    # past the trainer's dedup must be skipped
                    # idempotently — the live slot keeps its read state
                    # untouched and the id does NOT re-enter _freed.
                    telemetry.inc("buffer/duplicate_dropped")
                    continue
                if len(self._slots) >= self.max_size:
                    raise RuntimeError("buffer overflow")
                self._slots[sid] = _Slot(
                    sample=s.meta(), birth_time=time.monotonic(),
                    reads_left=self._n_reads, birth_wall=time.time(),
                )
            self._changed.notify_all()

    async def amend_batch(self, sample: SequenceSample) -> None:
        """Merge new keys into existing slots (an MFC's outputs)."""
        async with self._lock:
            for i, sid in enumerate(sample.ids):
                slot = self._slots.get(sid)
                if slot is None:
                    continue  # slot already consumed (late amend is benign)
                slot.sample.update_(sample.select_idx([i]).meta())
            self._changed.notify_all()

    async def get_batch_for_rpc(
        self,
        rpc_name: str,
        input_keys: Set[str],
        n_seqs: int,
        timeout: Optional[float] = None,
    ) -> List[SequenceSample]:
        """Block until ≥ n_seqs samples hold all ``input_keys`` and were not
        yet read by ``rpc_name``; return the n_seqs oldest (metadata)."""

        def ready() -> List[Hashable]:
            cand = [
                (slot.birth_time, sid)
                for sid, slot in self._slots.items()
                if rpc_name not in slot.read_by
                and input_keys <= slot.sample.keys
            ]
            cand.sort()
            return [sid for _, sid in cand]

        deadline = time.monotonic() + timeout if timeout else None
        async with self._lock:
            while True:
                ids = ready()
                if len(ids) >= n_seqs:
                    out = []
                    now_wall = time.time()
                    for sid in ids[:n_seqs]:
                        slot = self._slots[sid]
                        slot.read_by.add(rpc_name)
                        slot.reads_left -= 1
                        # Buffer dwell at selection (wall clock, so it
                        # composes with the stitched cross-worker
                        # timeline). No-op when telemetry is off.
                        telemetry.observe(
                            f"buffer/{rpc_name}_sample_age_secs",
                            max(now_wall - slot.birth_wall, 0.0),
                        )
                        out.append(slot.sample.meta())
                        if slot.reads_left <= 0:
                            del self._slots[sid]
                            self._freed.append(sid)
                    return out
                wait = None
                if deadline is not None:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        raise asyncio.TimeoutError(
                            f"rpc {rpc_name}: {len(ids)}/{n_seqs} ready"
                        )
                try:
                    await asyncio.wait_for(self._changed.wait(), wait)
                except asyncio.TimeoutError:
                    raise asyncio.TimeoutError(
                        f"rpc {rpc_name}: {len(ids)}/{n_seqs} ready"
                    ) from None

    async def mark_read(self, ids: Sequence[Hashable], rpc_name: str) -> None:
        """Mark slots as already consumed by ``rpc_name`` (used when a
        generate MFC replaces prompt slots with trajectory slots — the
        producing MFC must not re-read its own outputs)."""
        async with self._lock:
            for sid in ids:
                slot = self._slots.get(sid)
                if slot is None or rpc_name in slot.read_by:
                    continue
                slot.read_by.add(rpc_name)
                slot.reads_left -= 1
                if slot.reads_left <= 0:
                    del self._slots[sid]
                    self._freed.append(sid)
            self._changed.notify_all()

    async def drop_ids(self, ids: Sequence[Hashable]) -> None:
        async with self._lock:
            for sid in ids:
                if self._slots.pop(sid, None) is not None:
                    self._freed.append(sid)
            self._changed.notify_all()

    async def pop_freed(self) -> List[Hashable]:
        """Fully-consumed sample ids since the last call (for trainer GC)."""
        async with self._lock:
            out, self._freed = self._freed, []
            return out
