"""Device-memory observatory: HBM gauges + per-site high-water marks.

ROADMAP items 1 and 3 (device-reshard HBM headroom, multi-version weight
residency) budget HBM by hand-arithmetic in docs/weight_sync.md, and the
serving KVStateStore bounds its bytes against the same paper math — but
nothing in the tree ever read ``device.memory_stats()``. This module is
the measurement side of those budgets:

 - :meth:`MemWatch.sample` polls ``jax.local_devices()[i].memory_stats()``
   (rate-limited to ``sample_interval_secs``; piggybacked on existing
   worker cadences — the trainer step loop, the generation server's
   metrics endpoint — so no thread is spawned) and exports per-device
   ``hbm/bytes_in_use{device=i}``, ``hbm/peak_bytes{device=i}``, and
   ``hbm/limit_bytes{device=i}`` gauges.
 - :meth:`MemWatch.watermark` brackets the big allocators (weight
   publish/consume in weight_stream/reshard, the shadow-pytree swap in
   the generation server, the trainer's fwd/bwd) and records the max
   ``bytes_in_use`` observed at block exit as
   ``hbm/watermark_bytes{site=...}`` — the measured number the reshard
   ``transfer_group_mb`` headroom math checks against.

Degradation contract (mirrors MfuEmitter's unknown-device path): where
the backend has no ``memory_stats`` (CPU, some TPU runtime versions) the
watch logs ONE warning, bumps the ``hbm/memory_stats_unavailable``
counter once, and goes quiet — it never exports fake zero gauges that
would read as an empty chip on the merged scrape.

Disabled contract: until :func:`configure` installs an enabled watch the
module-level API routes to a shared null object — no device polls, no
gauges, scrape bit-identical.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

from areal_tpu.base import logging, telemetry

logger = logging.getLogger("system.memwatch")


def _default_devices() -> List[Any]:
    import jax

    return list(jax.local_devices())


class MemWatch:
    """Per-worker HBM sampler over injectable devices.

    ``devices_fn`` returns device-like objects exposing
    ``memory_stats() -> dict | None`` (the jax device API); tests inject
    fakes. ``telemetry_sink`` is any Telemetry-like object."""

    enabled = True

    def __init__(self, telemetry_sink=None, *,
                 sample_interval_secs: float = 10.0,
                 devices_fn: Callable[[], List[Any]] = _default_devices,
                 clock: Callable[[], float] = time.monotonic):
        self.tel = telemetry_sink if telemetry_sink is not None \
            else telemetry.get()
        self.sample_interval_secs = max(float(sample_interval_secs), 0.0)
        self._devices_fn = devices_fn
        self._clock = clock
        self._lock = threading.Lock()
        self._last_sample: Optional[float] = None
        self._unavailable = False
        self._peak_bytes = 0.0
        self._site_peaks: Dict[str, float] = {}

    # ---- polling ----

    def _poll(self) -> Optional[List[Dict[str, float]]]:
        """One reading per device: {bytes_in_use, peak_bytes, limit}.
        None once the backend proved it has no memory_stats."""
        if self._unavailable:
            return None
        try:
            devices = self._devices_fn()
        except Exception as e:  # noqa: BLE001 — no backend at all
            self._degrade(f"device enumeration failed: {e}")
            return None
        out: List[Dict[str, float]] = []
        for d in devices:
            stats_fn = getattr(d, "memory_stats", None)
            if stats_fn is None:
                continue
            try:
                stats = stats_fn()
            except Exception:  # noqa: BLE001 — backend stub raised
                continue
            if not stats:
                continue
            out.append({
                "bytes_in_use": float(stats.get("bytes_in_use", 0.0)),
                "peak_bytes": float(
                    stats.get("peak_bytes_in_use",
                              stats.get("bytes_in_use", 0.0))
                ),
                "limit": float(stats.get("bytes_limit", 0.0)),
            })
        if not out:
            self._degrade(
                "no local device reports memory_stats() (CPU backend?)"
            )
            return None
        return out

    def _degrade(self, why: str) -> None:
        """One-time: warn, bump the degradation counter, go quiet —
        mirrors MfuEmitter's unknown-device path. Never exports zero
        gauges that would read as an empty chip."""
        if self._unavailable:
            return
        self._unavailable = True
        logger.warning(
            f"HBM gauges degraded to unavailable: {why} — "
            f"hbm/* gauges will not be exported by this worker"
        )
        self.tel.inc("hbm/memory_stats_unavailable")

    def sample(self, force: bool = False) -> Optional[float]:
        """Export per-device HBM gauges (rate-limited unless ``force``).
        Returns the max bytes_in_use across devices, or None when the
        backend has no stats / the interval has not elapsed."""
        now = self._clock()
        with self._lock:
            if (not force and self._last_sample is not None
                    and now - self._last_sample < self.sample_interval_secs):
                return None
            self._last_sample = now
        readings = self._poll()
        if readings is None:
            return None
        top = 0.0
        for i, r in enumerate(readings):
            self.tel.set_gauge(f"hbm/bytes_in_use{{device={i}}}",
                               r["bytes_in_use"])
            self.tel.set_gauge(f"hbm/peak_bytes{{device={i}}}",
                               r["peak_bytes"])
            if r["limit"] > 0:
                self.tel.set_gauge(f"hbm/limit_bytes{{device={i}}}",
                                   r["limit"])
            top = max(top, r["bytes_in_use"])
            with self._lock:
                self._peak_bytes = max(self._peak_bytes, r["peak_bytes"],
                                       r["bytes_in_use"])
        return top

    # ---- high-water marks ----

    @contextmanager
    def watermark(self, site: str):
        """Bracket a big allocator: the max ``bytes_in_use`` observed at
        block exit becomes the (monotonic) ``hbm/watermark_bytes{site=}``
        gauge. Cheap no-op on degraded backends."""
        try:
            yield
        finally:
            top = self.sample(force=True)
            if top is not None:
                with self._lock:
                    peak = max(self._site_peaks.get(site, 0.0), top)
                    self._site_peaks[site] = peak
                self.tel.set_gauge(f"hbm/watermark_bytes{{site={site}}}",
                                   peak)

    # ---- views ----

    def peak_gb(self) -> float:
        """Highest HBM occupancy seen by any sample (bench.py field)."""
        with self._lock:
            return self._peak_bytes / (1 << 30)

    def site_peaks(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._site_peaks)

    def close(self) -> None:
        pass


@contextmanager
def _null_ctx():
    yield


class _NullMemWatch:
    """Shared disabled sink: no device polls, no gauges."""

    enabled = False

    def sample(self, force: bool = False) -> Optional[float]:
        return None

    def watermark(self, site: str):
        return _null_ctx()

    def peak_gb(self) -> float:
        return 0.0

    def site_peaks(self) -> Dict[str, float]:
        return {}

    def close(self) -> None:
        pass


NULL = _NullMemWatch()
_GLOBAL: Any = NULL


def configure(cfg=None, telemetry_sink=None,
              devices_fn: Callable[[], List[Any]] = _default_devices,
              clock: Callable[[], float] = time.monotonic):
    """Install the process-global HBM watch (gated on the same
    ``compile_watch`` config group — one knob arms the whole
    compile-and-memory observatory). Disabled keeps the null sink."""
    global _GLOBAL
    if cfg is None or not getattr(cfg, "enabled", False):
        _GLOBAL = NULL
        return NULL
    _GLOBAL = MemWatch(
        telemetry_sink,
        sample_interval_secs=getattr(cfg, "mem_sample_interval_secs", 10.0),
        devices_fn=devices_fn,
        clock=clock,
    )
    return _GLOBAL


def get():
    return _GLOBAL


def enabled() -> bool:
    return _GLOBAL.enabled


def sample(force: bool = False) -> Optional[float]:
    return _GLOBAL.sample(force=force)


def watermark(site: str):
    """Module-level watermark context manager — jit sites call
    ``with memwatch.watermark("trainer/weight_publish"): ...`` without
    re-checking whether the watch is armed."""
    return _GLOBAL.watermark(site)


def peak_gb() -> float:
    return _GLOBAL.peak_gb()


def shutdown() -> None:
    global _GLOBAL
    if _GLOBAL is not NULL:
        _GLOBAL.close()
        _GLOBAL = NULL
