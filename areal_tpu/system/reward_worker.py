"""Reward worker — the sixth worker kind: a sandbox fleet member.

Parity target: the reference's standalone functioncall reward service
(SURVEY §2.13), recast as a first-class worker in this system's lifecycle
vocabulary: it registers through ``name_resolve``
(``names.reward_worker``), serves ``/health`` + Prometheus ``/metrics``,
pushes per-task-kind latency/verdict/timeout telemetry to the master's
aggregator, heartbeats a liveness lease, answers WorkerControl
(pause/resume/exit/status), and rides launcher supervision as a
restartable stateless domain — a crashed reward worker respawns in place
while clients retry on the surviving replicas (rewards/client.py).

The grading core (HTTP endpoints, sandbox subprocess pools, language
dispatch) lives in rewards/service.py; this module is the process glue.
CPU-only by design: a reward worker must never initialize an accelerator
— untrusted code runs on whatever host has spare cores, not on the chips
that train (docs/rewards.md).
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Optional

from areal_tpu.api.train_config import RewardServiceConfig, TelemetryConfig
from areal_tpu.base import logging, name_resolve, names, network, telemetry
from areal_tpu.rewards.service import RewardService

logger = logging.getLogger("system.reward_worker")


@dataclasses.dataclass
class RewardWorkerConfig:
    experiment: str = "exp"
    trial: str = "trial"
    worker_index: int = 0
    # Fixed port (0 = random); discovery goes through name_resolve either
    # way, so fixed ports only matter for firewalled deployments.
    port: int = 0
    reward: RewardServiceConfig = dataclasses.field(
        default_factory=RewardServiceConfig
    )
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )
    # Liveness lease on the reward_workers/ registration: a SIGKILLed
    # worker's ghost URL expires from discovery instead of being fanned
    # out to forever. 0 falls back to the supervisor-set env TTL.
    keepalive_ttl_secs: float = 0.0


class RewardWorker:
    """Owns one RewardService + its fleet registration and control."""

    def __init__(self, cfg: RewardWorkerConfig, grade_fn=None):
        self.cfg = cfg
        self.worker_id = f"rw{cfg.worker_index}"
        # Own instance (not the process global): tests host several
        # workers in one process, and each must be a distinct
        # (worker_kind, worker_index) at the aggregator.
        self.telemetry = (
            telemetry.Telemetry(
                cfg.experiment, cfg.trial, "reward", cfg.worker_index,
                cfg=cfg.telemetry,
            ) if cfg.telemetry.enabled else telemetry.NULL
        )
        self.service = RewardService(
            cfg.reward, telemetry_sink=self.telemetry, grade_fn=grade_fn
        )
        self.url = ""
        self._t_start = time.monotonic()
        self._runner_obj = None
        self._hb = None

    async def start(self) -> str:
        """Serve + register under names.reward_worker; returns the URL."""
        from aiohttp import web

        from areal_tpu.system.worker_base import (
            HeartbeatThread,
            default_heartbeat_interval,
            env_keepalive_ttl,
        )

        app = self.service.build_app(
            extra_metrics=lambda: {
                "reward_worker_uptime_secs":
                    time.monotonic() - self._t_start,
            },
            labels={"worker_id": self.worker_id},
        )
        runner = web.AppRunner(app)
        await runner.setup()
        port = (self.cfg.port + self.cfg.worker_index) if self.cfg.port \
            else network.find_free_port()
        site = web.TCPSite(runner, network.bind_addr(), port)
        await site.start()
        self._runner_obj = runner
        self.url = f"http://{network.gethostip()}:{port}"
        ttl = self.cfg.keepalive_ttl_secs or env_keepalive_ttl() or 0.0
        key = names.reward_worker(self.cfg.experiment, self.cfg.trial,
                                  self.worker_id)
        name_resolve.add(key, self.url, replace=True,
                         keepalive_ttl=ttl or None)
        if ttl:
            # Dedicated thread, same contract as the generation server: a
            # worker wedged in a long grade must still look alive; only a
            # SIGKILL (which takes the thread too) lapses the lease. The
            # heartbeat name matches the launcher's WorkerSpec name
            # (f"reward{i}") so the supervisor's respawn purge finds the
            # dead incarnation's record.
            self._hb = HeartbeatThread(
                self.cfg.experiment, self.cfg.trial,
                f"reward{self.cfg.worker_index}",
                interval=default_heartbeat_interval(ttl),
            )
            self._hb.lease(key, self.url, ttl)
        logger.info(f"reward worker {self.worker_id} at {self.url} "
                    f"(pool={self.cfg.reward.pool_size}, "
                    f"languages={list(self.cfg.reward.languages)})"
                    + (f" (keepalive {ttl:.0f}s)" if ttl else ""))
        return self.url

    async def stop(self) -> None:
        if self._hb is not None:
            self._hb.close()
        # Withdraw discovery NOW so client fanout forgets this URL
        # instead of burning a retry against a closing socket.
        try:
            name_resolve.delete(names.reward_worker(
                self.cfg.experiment, self.cfg.trial, self.worker_id
            ))
        except Exception:  # noqa: BLE001 — already gone / repo reset
            pass
        if self._runner_obj is not None:
            await self._runner_obj.cleanup()
        self.service.close()
        self.telemetry.close()

    async def run_async(self) -> None:
        """Serve until WorkerControl commands exit (the launcher-spawned
        entry; tests drive start/stop directly)."""
        from areal_tpu.system.worker_base import WorkerControl

        await self.start()
        ctrl = WorkerControl(
            self.cfg.experiment, self.cfg.trial,
            f"reward{self.cfg.worker_index}",
        )
        try:
            while True:
                # Control served between sleeps; pause blocks inside step
                # (grading already in flight still completes — the HTTP
                # server keeps serving; pause gates nothing here because
                # a reward worker holds no training state to freeze).
                await asyncio.to_thread(
                    ctrl.step,
                    lambda: {
                        "url": self.url,
                        "graded": self.service._graded,
                        "inflight": self.service._inflight,
                        "timeouts": self.service._timeouts,
                    },
                    200,
                )
                if ctrl.should_exit:
                    break
        finally:
            ctrl.close()
            await self.stop()
        logger.info(
            f"reward worker {self.worker_id} done: "
            f"{self.service._graded} graded, "
            f"{self.service._timeouts} timeouts"
        )

    def run(self) -> None:
        asyncio.run(self.run_async())


def resolve_fleet(experiment: str, trial: str) -> list:
    """Live reward-worker URLs from name_resolve (sorted for stable
    round-robin). The ONE discovery helper clients and tools share."""
    root = names.reward_worker_root(experiment, trial)
    try:
        return sorted(name_resolve.get_subtree(root))
    except Exception:  # noqa: BLE001 — repo unreachable counts as empty
        return []
