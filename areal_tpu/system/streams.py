"""ZMQ control/data streams.

Parity targets:
 - ``realhf/system/request_reply_stream.py`` (master↔trainer RPC with named
   handlers, request batching, async gather) — here ROUTER/DEALER instead of
   PUB/SUB+syn/ack: ZMQ's ROUTER gives per-peer addressing and queueing for
   free, so the handshake layer disappears;
 - ``realhf/system/push_pull_stream.py`` (bounded PUSH/PULL rollout→trainer
   trajectory stream with name-resolve discovery) — msgpack on the wire
   (numpy arrays as raw bytes) instead of JSON.

Control-plane payloads are pickled (trusted intra-cluster traffic, same
trust model as the reference); the data plane (tensors) never crosses these
sockets — the trainer's data store keeps them process-local.
"""

from __future__ import annotations

import dataclasses
import pickle
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import zmq

from areal_tpu.base import logging, name_resolve, network, telemetry

logger = logging.getLogger("system.streams")


def req_reply_addr_key(experiment: str, trial: str, handler: str) -> str:
    return f"areal_tpu/{experiment}/{trial}/req_reply/{handler}"


def push_pull_addr_key(experiment: str, trial: str, puller: str) -> str:
    return f"areal_tpu/{experiment}/{trial}/push_pull/{puller}"


@dataclasses.dataclass
class Payload:
    handler: str  # target worker name
    handle_name: str  # e.g. "generate"/"inference"/"train_step"/"fetch"
    request_id: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex)
    data: Any = None  # SequenceSample metadata / small control values
    mb_spec: Any = None
    # pre/post hooks executed by the worker around the MFC
    # (param realloc / save / eval / offload; reference request_reply:47)
    pre_hooks: List[Dict] = dataclasses.field(default_factory=list)
    post_hooks: List[Dict] = dataclasses.field(default_factory=list)
    output: Any = None
    exception: Optional[str] = None
    # Reply-completion flag, set by WorkerRequestServer.reply: a reply is
    # done because the worker SAID so, not because output happens to be
    # non-None — a legitimate None-output reply must not wedge gather.
    done: bool = False


class MasterRequestStream:
    """Master-side: one DEALER per handler, addresses from name_resolve.

    Thread-safety: the master's asyncio loop runs ``call``/``gather`` from
    several ``asyncio.to_thread`` workers at once (the data-loading task
    and every concurrent MFC task share this stream), but ZMQ sockets are
    not thread-safe. All socket I/O therefore goes through ``_io_lock``
    with NON-blocking recvs: without it, two threads can both wake from
    ``poll()`` for the same reply, the loser blocks in ``recv()`` forever
    while the winner files its reply in ``_pending`` — a whole-step wedge
    (the long-standing "fabric test hang", finally pinned down by the
    stitched sample-lineage traces: the trainer's mfc span closed, the
    master's exec span never did). The lock is held only across a bounded
    poll+drain (≤ the poll timeout), never across a gather wait."""

    def __init__(self, experiment: str, trial: str, handlers: Sequence[str],
                 timeout: float = 300.0):
        self._ctx = zmq.Context.instance()
        self._socks: Dict[str, zmq.Socket] = {}
        self._pending: Dict[str, Payload] = {}
        self._io_lock = threading.Lock()
        for h in handlers:
            addr = name_resolve.wait(
                req_reply_addr_key(experiment, trial, h), timeout=timeout
            )
            s = self._ctx.socket(zmq.DEALER)
            s.connect(addr)
            self._socks[h] = s
        self._poller = zmq.Poller()
        for s in self._socks.values():
            self._poller.register(s, zmq.POLLIN)

    def post(self, p: Payload) -> str:
        raw = pickle.dumps(p)
        with self._io_lock:
            self._socks[p.handler].send(raw)
            self._pending[p.request_id] = p
        return p.request_id

    def _drain(self, timeout_ms: int) -> None:
        with self._io_lock:
            for sock, _ in self._poller.poll(timeout_ms):
                try:
                    # Non-blocking even under the lock: poll() readiness
                    # is advisory, and a blocking recv on a spurious
                    # wakeup would hold the lock indefinitely.
                    reply: Payload = pickle.loads(sock.recv(zmq.NOBLOCK))
                except zmq.Again:
                    continue
                self._pending[reply.request_id] = reply

    def gather(self, request_ids: Sequence[str],
               timeout: float = 3600.0) -> List[Payload]:
        """Blocking gather; raises on worker-side exception."""
        deadline = time.monotonic() + timeout
        out: Dict[str, Payload] = {}
        while len(out) < len(request_ids):
            for rid in request_ids:
                p = self._pending.get(rid)
                # getattr + output-sniffing fallback: tolerate a reply
                # pickled by a pre-``done``-flag worker during a rolling
                # restart (the request Payload parked here by post() has
                # done=False and never false-completes).
                if p is not None and (getattr(p, "done", False)
                                      or p.output is not None
                                      or p.exception):
                    out[rid] = self._pending.pop(rid)
            if len(out) >= len(request_ids):
                break
            left = deadline - time.monotonic()
            if left <= 0:
                raise TimeoutError(f"gather timed out; got {len(out)}")
            self._drain(int(min(left, 0.2) * 1000))
        for p in out.values():
            if p.exception:
                raise RuntimeError(
                    f"worker {p.handler} failed on {p.handle_name}: {p.exception}"
                )
        return [out[rid] for rid in request_ids]

    def call(self, handler: str, handle_name: str, data: Any = None,
             **kw) -> Any:
        rid = self.post(Payload(handler=handler, handle_name=handle_name,
                                data=data, **kw))
        return self.gather([rid])[0].output

    def close(self):
        for s in self._socks.values():
            s.close(linger=0)


class WorkerRequestServer:
    """Worker-side ROUTER bound on a free port, registered in name_resolve.

    Under a supervisor the advertisement carries a liveness lease
    (AREAL_WORKER_KEEPALIVE_TTL): the owning worker must keep it alive
    via its control heartbeat (``WorkerControl.lease(server._key)``) so a
    SIGKILLed worker's stale address expires instead of silently
    swallowing every request a recovered master sends it."""

    def __init__(self, experiment: str, trial: str, handler: str):
        from areal_tpu.system.worker_base import env_keepalive_ttl

        self.handler = handler
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.ROUTER)
        port = self._sock.bind_to_random_port(f"tcp://{network.bind_addr()}")
        self._key = req_reply_addr_key(experiment, trial, handler)
        self._addr = network.advertised_tcp(port)
        name_resolve.add(self._key, self._addr,
                         replace=True, keepalive_ttl=env_keepalive_ttl())
        self._peer_of: Dict[str, bytes] = {}

    def poll(self, timeout_ms: int = 0) -> Optional[Payload]:
        if not self._sock.poll(timeout_ms):
            return None
        ident, raw = self._sock.recv_multipart()
        p: Payload = pickle.loads(raw)
        self._peer_of[p.request_id] = ident
        return p

    def reply(self, p: Payload) -> None:
        ident = self._peer_of.pop(p.request_id)
        p.done = True
        self._sock.send_multipart([ident, pickle.dumps(p)])

    def close(self):
        # Withdraw the advertisement FIRST: a restarted experiment's
        # master must not resolve this (about-to-die) address — connecting
        # to a stale ROUTER port silently drops every request (the
        # recover-test run-2 hang).
        try:
            name_resolve.delete(self._key)
        except Exception:  # noqa: BLE001 — already gone / repo reset
            pass
        self._sock.close(linger=0)


# ---------------- push/pull (rollout → trainer) ----------------


def _pack(obj: Any) -> bytes:
    import msgpack

    def default(o):
        if isinstance(o, np.ndarray):
            return {
                "__nd__": True, "dtype": str(o.dtype), "shape": o.shape,
                "data": o.tobytes(),
            }
        if isinstance(o, (np.integer,)):
            return int(o)
        if isinstance(o, (np.floating,)):
            return float(o)
        raise TypeError(f"cannot pack {type(o)}")

    return msgpack.packb(obj, default=default)


def _unpack(raw: bytes) -> Any:
    import msgpack

    def hook(o):
        if o.get("__nd__"):
            return np.frombuffer(o["data"], dtype=o["dtype"]).reshape(o["shape"])
        return o

    return msgpack.unpackb(raw, object_hook=hook, strict_map_key=False)


class ZmqPuller:
    def __init__(self, experiment: str, trial: str, name: str,
                 capacity: int = 16384):
        from areal_tpu.system.worker_base import env_keepalive_ttl

        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PULL)
        self._sock.setsockopt(zmq.RCVHWM, capacity)
        port = self._sock.bind_to_random_port(f"tcp://{network.bind_addr()}")
        self._key = push_pull_addr_key(experiment, trial, name)
        self._addr = network.advertised_tcp(port)
        name_resolve.add(
            self._key, self._addr, replace=True,
            keepalive_ttl=env_keepalive_ttl(),
        )

    def pull(self, timeout_ms: int = 0) -> Optional[Any]:
        if not self._sock.poll(timeout_ms):
            return None
        return _unpack(self._sock.recv())

    def close(self):
        # Withdraw the advertisement (same contract as
        # WorkerRequestServer.close): a drained run's successor resolves
        # this key within seconds — a pusher that binds the dead address
        # sends every trajectory into the void, starving the new master
        # until the staleness gate wedges the whole resume.
        try:
            name_resolve.delete(self._key)
        except Exception:  # noqa: BLE001 — already gone / repo reset
            pass
        self._sock.close(linger=0)


class ZmqPusher:
    """Discovers the puller via name_resolve (reference
    NameResolvingZmqPusher:141).

    Sends are NON-wedging: a slow/dead puller used to freeze the
    caller's thread forever inside a blocking ``send`` at the HWM — on a
    rollout worker that wedged the whole asyncio loop. Every send now
    uses ``zmq.NOBLOCK`` with a bounded retry budget (``block_secs``)
    and counts each blocked attempt in ``stream/push_blocked``, so
    backpressure degrades visibly (a climbing counter, then a loud
    ``zmq.Again``) instead of silently."""

    def __init__(self, experiment: str, trial: str, puller: str,
                 capacity: int = 16384, timeout: float = 300.0,
                 block_secs: float = 120.0):
        addr = name_resolve.wait(
            push_pull_addr_key(experiment, trial, puller), timeout=timeout
        )
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PUSH)
        self._sock.setsockopt(zmq.SNDHWM, capacity)
        self._sock.connect(addr)
        self.block_secs = block_secs

    def push(self, obj: Any) -> None:
        # Sample-lineage tracing (docs/observability.md): dict payloads
        # pushed while a trace is active gain an OPTIONAL ``_trace`` key
        # ({trace_id, parent_span}) the puller side may pop — the
        # trainer re-attaches it to the sample's metadata so the trace
        # survives buffer/store hops. With telemetry disabled (or no
        # active trace) inject_payload returns the object untouched:
        # the wire bytes are identical to the pre-tracing format.
        self.push_packed(_pack(telemetry.inject_payload(obj)))

    def push_packed(self, raw: bytes,
                    block_secs: Optional[float] = None) -> None:
        """Send pre-packed bytes (the durable spool sender re-sends the
        exact bytes it spooled). Raises ``zmq.Again`` once the retry
        budget is exhausted."""
        budget = self.block_secs if block_secs is None else block_secs
        deadline = time.monotonic() + budget
        while True:
            try:
                self._sock.send(raw, zmq.NOBLOCK)
                return
            except zmq.Again:
                telemetry.inc("stream/push_blocked")
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)

    def close(self):
        self._sock.close(linger=0)
