"""Elastic generation-fleet autoscaling: telemetry-driven scale up/down,
straggler scoring, and the launcher-side scale executor.

The production inference stacks this repo tracks (the SGLang/vLLM fleet
schedulers in PAPERS.md) converge on the same split this module encodes:
a **reactive router** (the gserver manager: millisecond lease routing,
health eviction, cordon) kept separate from a **slow scaling controller**
(seconds-cadence, hysteresis + cooldown) that only ever changes the
fleet's *size*. Three pieces, each testable with an injected clock and no
I/O:

 - :class:`AutoscalerCore` — the pure decision engine. Feed it one
   :class:`FleetSignals` snapshot per interval; it votes up/down with
   hysteresis (``up_consecutive``/``down_consecutive``), enforces
   per-direction cooldowns and the [min, max] bounds, and moves the
   target one server at a time. ``overloaded`` latches while the fleet
   is pinned at max under sustained up-pressure — the manager turns that
   into admission backpressure on the rollout workers.
 - :class:`StragglerTracker` — per-server decode-latency EWMAs scored
   against the *median of the peers* (self excluded, so one slow server
   cannot drag the baseline toward itself). A server persistently over
   ``factor`` x the peer median is first deprioritized in routing, then
   cordoned — before it wedges the staleness gate by holding the oldest
   inflight rollouts.
 - :class:`AutoscaleExecutor` — the launcher-side actuator. The manager
   publishes a plan (``names.autoscale_plan``: how many *dynamic*
   single-server workers should exist beyond the baseline gen-fleet
   process); the executor reconciles the supervisor's live ``gen_server``
   children against it, spawning fresh specs that join through the
   existing discovery + streamed-weight admission path (no checkpoint
   round-trip). Scale-DOWN never goes through the executor: the manager
   cordons a victim, lets it drain, and commands the exit over
   WorkerControl — the supervisor sees an expected clean exit.

The wire between the two halves is a single name-resolve key, so the
manager (gen-fleet process) and the executor (launcher process) need no
new channel, and ``tools/perf_probe.py fleet-status`` can show the plan
from outside the run.
"""

from __future__ import annotations

import dataclasses
import json
import statistics
import time
from typing import Callable, Dict, List, Optional

from areal_tpu.api.train_config import AutoscaleConfig  # noqa: F401 (re-export)
from areal_tpu.base import logging, name_resolve, names, telemetry

logger = logging.getLogger("system.autoscaler")


# --------------------------------------------------------------------------
# decision engine
# --------------------------------------------------------------------------


@dataclasses.dataclass
class FleetSignals:
    """One interval's view of the fleet, as the gserver manager sees it.

    Every field is derivable without extra RPCs: utilization and the
    staleness gate are manager-local quota state, queue depth and the
    TTFC SLO come from the ``/health`` bodies the health loop already
    polls, fanout ack latency from the last weight sync, and stale
    heartbeats from the liveness leases (docs/fault_tolerance.md)."""

    current_size: int  # routable servers
    cordoned: int = 0
    utilization: float = 0.0  # running_rollouts / max_concurrent_rollouts
    queue_depth: float = 0.0  # mean decode queue depth per routable server
    staled: bool = False  # the staleness gate is closed (trainer behind)
    slo_miss_frac: float = 0.0  # fraction of servers over the TTFC SLO
    fanout_ack_secs: float = 0.0  # last weight-fanout ack latency
    stale_heartbeats: int = 0  # servers alive-but-wedged per liveness lease
    # The training-health sentinel published an autoscale-inhibit hint
    # (critical alert live — system/sentinel.py): scale-up is suppressed,
    # since growing the fleet into a diverging run only burns capacity
    # and deepens off-policyness. Scale-down stays allowed.
    inhibited: bool = False


class AutoscalerCore:
    """Hysteresis + cooldown + bounds around a target fleet size.

    ``observe`` is called once per autoscale interval and never sleeps —
    tests drive the whole state machine with an injected clock. Scale-up
    pressure is ANY saturation signal while the staleness gate is open
    (a closed gate means the *trainer* is the bottleneck; more servers
    would only deepen off-policyness). Scale-down needs EVERY idleness
    signal at once. A wedged server (stale heartbeat) does not count as
    capacity — but it is replaced through the manager's plan at constant
    target, never by ratcheting the target itself."""

    def __init__(self, cfg: AutoscaleConfig,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self.clock = clock
        self.target: Optional[int] = None  # set from the first observation
        self.overloaded = False
        self._up_votes = 0
        self._down_votes = 0
        self._last_action: Optional[float] = None

    def _up_reasons(self, s: FleetSignals) -> List[str]:
        c = self.cfg
        if s.staled or s.inhibited:
            return []
        reasons = []
        if s.utilization >= c.up_utilization:
            reasons.append(f"utilization {s.utilization:.2f}")
        if s.queue_depth >= c.queue_high:
            reasons.append(f"queue depth {s.queue_depth:.1f}")
        if c.slo_ttfc_secs > 0 and s.slo_miss_frac >= c.slo_miss_fraction:
            reasons.append(f"SLO miss fraction {s.slo_miss_frac:.2f}")
        if (c.fanout_ack_high_secs > 0
                and s.fanout_ack_secs >= c.fanout_ack_high_secs):
            reasons.append(f"fanout ack {s.fanout_ack_secs:.1f}s")
        # Wedged heartbeats are deliberately NOT up-pressure: spawning
        # more servers never clears a stale lease, so the signal would
        # ratchet the target to max (and latch overload backpressure) on
        # an idle fleet. They subtract from counted capacity instead —
        # the manager's plan replaces the wedged server at constant
        # target (see _autoscale_tick's baseline accounting).
        return reasons

    def _down_ok(self, s: FleetSignals) -> bool:
        c = self.cfg
        if s.utilization > c.down_utilization or s.queue_depth > c.queue_low:
            return False
        if c.slo_ttfc_secs > 0 and s.slo_miss_frac > 0:
            return False
        return True

    def observe(self, s: FleetSignals) -> Optional[Dict]:
        """Record one interval; returns an action record
        ({action, target, reason}) when the target moved, else None."""
        c = self.cfg
        now = self.clock()
        # Wedged servers are not capacity: the effective size drives both
        # the bounds check and the published plan's replacement math.
        effective = max(s.current_size - s.stale_heartbeats, 0)
        if self.target is None:
            self.target = min(max(effective, c.min_servers), c.max_servers)
        up = self._up_reasons(s)
        down = self._down_ok(s)
        self.overloaded = bool(up) and self.target >= c.max_servers
        if up:
            self._up_votes += 1
            self._down_votes = 0
        elif down:
            self._down_votes += 1
            self._up_votes = 0
        else:
            self._up_votes = 0
            self._down_votes = 0
        if (
            up
            and self._up_votes >= c.up_consecutive
            and self.target < c.max_servers
            and self._cooled(now, c.scale_up_cooldown_secs)
        ):
            self.target += 1
            self._last_action = now
            self._up_votes = 0
            return {"action": "up", "target": self.target,
                    "reason": "; ".join(up)}
        if (
            down
            and self._down_votes >= c.down_consecutive
            and self.target > c.min_servers
            and self._cooled(now, c.scale_down_cooldown_secs)
        ):
            self.target -= 1
            self._last_action = now
            self._down_votes = 0
            return {"action": "down", "target": self.target,
                    "reason": "fleet idle"}
        return None

    def _cooled(self, now: float, cooldown: float) -> bool:
        return self._last_action is None or now - self._last_action >= cooldown


# --------------------------------------------------------------------------
# straggler scoring
# --------------------------------------------------------------------------


class _StragglerState:
    __slots__ = ("ewma", "n", "slow_sweeps")

    def __init__(self):
        self.ewma: Optional[float] = None
        self.n = 0
        self.slow_sweeps = 0


class StragglerTracker:
    """Per-server decode-latency EWMAs + peer-relative slowness streaks.

    ``observe(url, secs)`` folds one /health-reported decode-latency
    sample into the url's EWMA; ``sweep(urls)`` scores every url against
    the median of its PEERS (self excluded — a single straggler must not
    drag the baseline toward itself) and returns
    ``{url: "ok" | "slow" | "cordon"}``. "slow" after
    ``slow_sweeps`` consecutive over-factor sweeps (deprioritize in
    routing), "cordon" after ``cordon_sweeps``. Samples below
    ``floor_secs`` are jitter at timescales routing cannot exploit."""

    def __init__(self, factor: float = 3.0, min_probes: int = 5,
                 slow_sweeps: int = 2, cordon_sweeps: int = 6,
                 floor_secs: float = 0.002, alpha: float = 0.3):
        self.factor = factor
        self.min_probes = min_probes
        self.slow_sweeps = slow_sweeps
        self.cordon_sweeps = cordon_sweeps
        self.floor_secs = floor_secs
        self.alpha = alpha
        self._state: Dict[str, _StragglerState] = {}

    def observe(self, url: str, secs: float) -> None:
        st = self._state.setdefault(url, _StragglerState())
        st.n += 1
        st.ewma = (
            secs if st.ewma is None
            else (1 - self.alpha) * st.ewma + self.alpha * secs
        )

    def forget(self, url: str) -> None:
        self._state.pop(url, None)

    def ewma(self, url: str) -> Optional[float]:
        st = self._state.get(url)
        return st.ewma if st is not None else None

    def sweep(self, urls: List[str]) -> Dict[str, str]:
        out: Dict[str, str] = {}
        mature = {
            u: self._state[u] for u in urls
            if u in self._state and self._state[u].n >= self.min_probes
            and self._state[u].ewma is not None
        }
        for url in urls:
            st = mature.get(url)
            if st is None:
                out[url] = "ok"
                continue
            peers = [s.ewma for u, s in mature.items() if u != url]
            if not peers:
                out[url] = "ok"  # no peer baseline: cannot judge
                continue
            med = statistics.median(peers)
            slow = (
                st.ewma >= self.floor_secs
                and st.ewma >= self.factor * max(med, self.floor_secs / 10)
            )
            st.slow_sweeps = st.slow_sweeps + 1 if slow else 0
            if st.slow_sweeps >= self.cordon_sweeps:
                out[url] = "cordon"
            elif st.slow_sweeps >= self.slow_sweeps:
                out[url] = "slow"
            else:
                out[url] = "ok"
        return out


# --------------------------------------------------------------------------
# plan wire (manager -> launcher executor, via name_resolve)
# --------------------------------------------------------------------------


def publish_plan(experiment: str, trial: str, plan: Dict) -> None:
    try:
        name_resolve.add(
            names.autoscale_plan(experiment, trial),
            json.dumps(plan), replace=True, delete_on_exit=False,
        )
    except Exception as e:  # noqa: BLE001 — retried next interval
        logger.warning(f"autoscale plan publish failed: {e}")


def read_plan(experiment: str, trial: str) -> Optional[Dict]:
    try:
        return json.loads(name_resolve.get(
            names.autoscale_plan(experiment, trial)
        ))
    except Exception:  # noqa: BLE001 — no plan yet / torn write
        return None


def read_inhibit(experiment: str, trial: str,
                 wall: Callable[[], float] = time.time) -> Optional[Dict]:
    """The sentinel's autoscale-inhibit hint ({until, rule, ts}), or None
    when absent/expired. Consumed by the manager's scaling loop each
    interval; expiry means a stale hint from a resolved incident can
    never pin the fleet forever."""
    try:
        d = json.loads(name_resolve.get(
            names.autoscale_inhibit(experiment, trial)
        ))
    except Exception:  # noqa: BLE001 — no hint published
        return None
    try:
        return d if wall() < float(d.get("until", 0.0)) else None
    except (TypeError, ValueError):
        return None


# --------------------------------------------------------------------------
# launcher-side executor
# --------------------------------------------------------------------------


class AutoscaleExecutor:
    """Reconcile the supervisor's dynamic gen-server children against the
    manager's published plan.

    Called from the launcher's monitor loop (~1 Hz) next to
    ``supervisor.check()``. It only ever spawns — scale-down is the
    manager's cordon → drain → WorkerControl-exit sequence, which the
    supervisor observes as an expected clean exit (``required=False``).
    A crash-looped dynamic server the supervisor permanently removed
    (``WorkerSpec.expendable``) simply drops the live count, so the next
    step spawns a *fresh* spec within the plan's bounds. One spawn per
    step with a cooldown keeps a hard-failing spec from machine-gunning
    processes faster than the circuit breaker can count them."""

    def __init__(self, experiment: str, trial: str, supervisor,
                 spawn_fn: Callable[[str], None], kind: str = "gen_server",
                 spawn_cooldown_secs: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        self.experiment = experiment
        self.trial = trial
        self.supervisor = supervisor
        self.spawn_fn = spawn_fn
        self.kind = kind
        self.spawn_cooldown_secs = spawn_cooldown_secs
        self.clock = clock
        self.spawned: List[str] = []
        self._seq = 0
        self._last_spawn: Optional[float] = None

    def step(self) -> Optional[str]:
        """One reconcile pass; returns the spawned server_id, if any."""
        if getattr(self.supervisor, "_draining", False):
            return None
        plan = read_plan(self.experiment, self.trial)
        if not plan:
            return None
        want = int(plan.get("dynamic", 0))
        have = self.supervisor.alive_count(self.kind)
        if have >= want:
            return None
        now = self.clock()
        if (self._last_spawn is not None
                and now - self._last_spawn < self.spawn_cooldown_secs):
            return None
        self._seq += 1
        server_id = f"dyn{self._seq}"
        self.spawn_fn(server_id)
        self._last_spawn = now
        self.spawned.append(server_id)
        telemetry.inc("autoscale/spawns")
        logger.info(
            f"autoscale: spawned dynamic generation server {server_id} "
            f"({have + 1}/{want} dynamic, plan target {plan.get('target')})"
        )
        return server_id
