"""TPU generation server — the SGLang/JetStream role, in-house.

Parity target: ``realhf/system/generation_server.py`` + the sglang patch
(``patch/sglang/v0.4.6.post4.patch``: interruptible generation, weight
update from disk). TPU-first design differences:

 - **Chunked decoding replaces interruption.** The reference patches SGLang
   to abort in-flight requests when weights update. Here every ``/generate``
   call decodes AT MOST ``chunk_tokens`` new tokens as one static-shape
   ``lax.scan`` and returns a partial result tagged with the weight version
   that produced it; the client (PartialRolloutManager) re-submits with the
   accumulated prefix. Weight updates therefore wait at most one chunk —
   the same bound the reference achieves by aborting, with zero lost work
   and no recompilation (chunk length is static).
 - **Micro-batched continuous batching**: concurrent requests are drained
   from a queue every ``batch_window_ms`` and decoded together, padded to
   bucketed prompt lengths (prefix re-prefill per chunk; a paged KV cache
   across chunks is a later optimization).
 - ``/update_weights`` hot-swaps params in place (device_put over the old
   sharding) from the trainer's published checkpoint (§3.5 disk path).

Endpoints: POST /generate, POST /update_weights, GET /health, GET /metrics.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np

from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.base import logging, name_resolve, names, network
from areal_tpu.models import generate as genmod
from areal_tpu.models import transformer  # noqa: F401 (engine deps)

logger = logging.getLogger("system.genserver")


@dataclasses.dataclass
class GenerationServerConfig:
    experiment: str = "exp"
    trial: str = "trial"
    server_id: str = "gen0"
    chunk_tokens: int = 128  # static decode length per /generate call
    batch_window_ms: int = 5
    max_batch_size: int = 64
    prompt_bucket: int = 128
    eos_token_id: int = 1
    pad_token_id: int = 0
    port: Optional[int] = None


class _Pending:
    __slots__ = ("prompt", "gconfig", "future", "max_tokens")

    def __init__(self, prompt, gconfig, max_tokens, future):
        self.prompt = prompt
        self.gconfig = gconfig
        self.max_tokens = max_tokens
        self.future = future


class GenerationServer:
    """Owns (cfg, params) of the serving model; hot-swappable."""

    def __init__(self, cfg: GenerationServerConfig, model_cfg, params,
                 mesh=None):
        self.cfg = cfg
        self.model_cfg = model_cfg
        import jax

        if mesh is not None:
            from areal_tpu.parallel import sharding as psh

            params = psh.shard_params(params, mesh, model_cfg)
        else:
            params = jax.tree.map(jax.numpy.asarray, params)
        self.params = params
        self.mesh = mesh
        self.version = 0
        self._queue: asyncio.Queue = None  # created on loop start
        self._key = jax.random.PRNGKey(0)
        self._tokens_out = 0
        self._t_start = time.monotonic()
        self._runner_task = None

    # ---------------- decode core ----------------

    def _decode_batch(self, batch: List[_Pending]) -> List[Dict[str, Any]]:
        import jax

        cfg = self.cfg
        # Capture (params, version) atomically: handle_update_weights swaps
        # both on the event loop while we run in a thread, and tokens
        # sampled under the old weights must be tagged with the version
        # that actually produced them (decoupled-loss bookkeeping).
        params, version = self.params, self.version
        chunk = min(cfg.chunk_tokens, max(p.max_tokens for p in batch))
        prompts = [p.prompt for p in batch]
        padded, plens = genmod.pad_prompts(
            prompts, cfg.pad_token_id, bucket=cfg.prompt_bucket
        )
        self._key, sub = jax.random.split(self._key)
        # _runner groups the batch by identical sampling params.
        gconfig = batch[0].gconfig
        out = genmod.generate_batch(
            params, self.model_cfg, padded, plens, sub,
            gconfig, max_new_tokens=chunk,
            eos_token_id=cfg.eos_token_id, pad_token_id=cfg.pad_token_id,
        )
        res = []
        for i, p in enumerate(batch):
            # Never hand back more than the request's remaining budget —
            # the client appends every token we return.
            n = min(int(out["output_lens"][i]), p.max_tokens)
            toks = np.asarray(out["output_ids"][i][:n])
            lps = np.asarray(out["output_logprobs"][i][:n])
            # "finished" = the MODEL ended the sequence (EOS). Budget
            # exhaustion is the client's call — it knows the total budget
            # across chunks, we only see this chunk's slice.
            emitted_eos = bool((toks == cfg.eos_token_id).any())
            res.append({
                "output_ids": toks.tolist(),
                "output_logprobs": lps.tolist(),
                "finished": emitted_eos,
                "version": version,
            })
            self._tokens_out += n
        return res

    async def _runner(self):
        cfg = self.cfg
        while True:
            first: _Pending = await self._queue.get()
            batch = [first]
            await asyncio.sleep(cfg.batch_window_ms / 1000)
            # Drain only requests with the SAME sampling params as the
            # head of the batch — one generate_batch call applies one
            # gconfig, and mixed-temperature clients must not silently get
            # the first request's params. Mismatches go back in the queue.
            deferred = []
            while len(batch) < cfg.max_batch_size and not self._queue.empty():
                p = self._queue.get_nowait()
                if p.gconfig == first.gconfig:
                    batch.append(p)
                else:
                    deferred.append(p)
            for p in deferred:
                self._queue.put_nowait(p)
            try:
                results = await asyncio.to_thread(self._decode_batch, batch)
                for p, r in zip(batch, results):
                    p.future.set_result(r)
            except Exception as e:  # noqa: BLE001 — propagate per-request
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(e)

    # ---------------- http ----------------

    async def handle_generate(self, request):
        from aiohttp import web

        d = await request.json()
        gconfig = GenerationHyperparameters(**d.get("gconfig", {}))
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put(_Pending(
            prompt=np.asarray(d["prompt_ids"], np.int32),
            gconfig=gconfig,
            max_tokens=int(d.get("max_tokens", gconfig.max_new_tokens)),
            future=fut,
        ))
        return web.json_response(await fut)

    async def handle_update_weights(self, request):
        import jax

        from areal_tpu.models import hf as hfmod

        d = await request.json()
        t0 = time.monotonic()
        cfg2, params = hfmod.load_hf_checkpoint(d["path"])
        # Preserve the existing per-leaf device placement/sharding.
        new = jax.tree.map(
            lambda old, npv: jax.device_put(
                np.asarray(npv, dtype=old.dtype), old.sharding
            ),
            self.params,
            params,
        )
        self.params = new
        self.version = int(d.get("version", self.version + 1))
        dt = time.monotonic() - t0
        logger.info(f"weights updated to v{self.version} in {dt:.2f}s")
        from aiohttp import web

        return web.json_response({"ok": True, "version": self.version,
                                  "latency_s": dt})

    async def handle_health(self, request):
        from aiohttp import web

        return web.json_response({"ok": True, "version": self.version})

    async def handle_metrics(self, request):
        from aiohttp import web

        dt = max(time.monotonic() - self._t_start, 1e-6)
        return web.json_response({
            "generated_tokens": self._tokens_out,
            "tokens_per_sec": self._tokens_out / dt,
            "version": self.version,
        })

    def build_app(self):
        from aiohttp import web

        app = web.Application(client_max_size=256 * 1024 * 1024)
        app.router.add_post("/generate", self.handle_generate)
        app.router.add_post("/update_weights", self.handle_update_weights)
        app.router.add_get("/health", self.handle_health)
        app.router.add_get("/metrics", self.handle_metrics)
        return app

    async def start(self) -> str:
        """Start serving; registers the URL under names.gen_servers."""
        from aiohttp import web

        self._queue = asyncio.Queue()
        self._runner_task = asyncio.create_task(self._runner())
        app = self.build_app()
        runner = web.AppRunner(app)
        await runner.setup()
        port = self.cfg.port or network.find_free_port()
        site = web.TCPSite(runner, network.bind_addr(), port)
        await site.start()
        url = f"http://{network.gethostip()}:{port}"
        name_resolve.add(
            names.gen_servers(self.cfg.experiment, self.cfg.trial,
                              self.cfg.server_id),
            url, replace=True,
        )
        logger.info(f"generation server {self.cfg.server_id} at {url}")
        self._runner_obj = runner
        return url

    async def stop(self):
        if self._runner_task:
            self._runner_task.cancel()
        await self._runner_obj.cleanup()
