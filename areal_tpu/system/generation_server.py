"""TPU generation server — the SGLang/JetStream role, in-house.

Parity target: ``realhf/system/generation_server.py`` + the sglang patch
(``patch/sglang/v0.4.6.post4.patch``: interruptible generation, weight
update from disk). TPU-first design differences:

 - **Chunked decoding replaces interruption.** The reference patches SGLang
   to abort in-flight requests when weights update. Here every ``/generate``
   call decodes AT MOST ``chunk_tokens`` new tokens as one static-shape
   ``lax.scan`` and returns a partial result tagged with the weight version
   that produced it; the client (PartialRolloutManager) re-submits with the
   accumulated prefix. Weight updates therefore wait at most one chunk —
   the same bound the reference achieves by aborting, with zero lost work
   and no recompilation (chunk length is static).
 - **Micro-batched continuous batching**: concurrent requests are drained
   from a queue every ``batch_window_ms`` and decoded together, padded to
   bucketed prompt lengths (prefix re-prefill per chunk; a paged KV cache
   across chunks is a later optimization).
 - **Scheduling is delegated to the serving engine**
   (system/serving.py, docs/serving.md): request-class admission control
   with bounded queues and 429 backpressure, priority batch formation,
   cross-request prefix-reuse KV behind a token trie, bounded
   compile-shape bucketing, and per-class latency SLO histograms. With
   ``serving.enabled=false`` (default) the engine reproduces the legacy
   rollout-only behavior exactly.
 - ``/update_weights`` hot-swaps params in place (device_put over the old
   sharding) from the trainer's publish — either streamed per-tensor over
   ZMQ (§3.5 low-latency path, system/weight_stream.py) or read from the
   published checkpoint (disk fallback).

Endpoints: POST /generate, POST /update_weights, GET /health,
GET /metrics (Prometheus text), GET /metrics.json (structured).
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np

from areal_tpu.api.model import GenerationHyperparameters
from areal_tpu.api.train_config import (
    CompileWatchConfig,
    GoodputConfig,
    ServingConfig,
    TelemetryConfig,
)
from areal_tpu.base import compile_watch as compile_watch_mod
from areal_tpu.base import logging, name_resolve, names, network, telemetry
from areal_tpu.models import generate as genmod
from areal_tpu.models import transformer  # noqa: F401 (engine deps)
from areal_tpu.system import goodput as goodput_mod
from areal_tpu.system import memwatch as memwatch_mod
from areal_tpu.system import serving as serving_mod

logger = logging.getLogger("system.genserver")


@dataclasses.dataclass
class GenerationServerConfig:
    experiment: str = "exp"
    trial: str = "trial"
    server_id: str = "gen0"
    # Shape-policy inputs default to the serving module's GEN_*_DEFAULT
    # constants: cli_args.validate_config front-runs the ShapeBucketPolicy
    # construction at config-parse time (jax-free) with the same numbers.
    chunk_tokens: int = (  # static decode length per /generate call
        serving_mod.GEN_CHUNK_TOKENS_DEFAULT
    )
    batch_window_ms: int = 5
    max_batch_size: int = serving_mod.GEN_MAX_BATCH_SIZE_DEFAULT
    prompt_bucket: int = serving_mod.GEN_PROMPT_BUCKET_DEFAULT
    eos_token_id: int = 1
    pad_token_id: int = 0
    port: Optional[int] = None
    # Persistent-KV continuous batching: keep per-request decode state so a
    # chunk continuation decodes from its cache instead of re-prefilling the
    # whole prefix (the reference's SGLang radix-cache role). 0 disables.
    kv_slots: int = 256
    # KV capacity granularity (slots)
    kv_bucket: int = serving_mod.GEN_KV_BUCKET_DEFAULT
    # Hard budget on retained KV BYTES (not just state count): per-request
    # KV grows with sequence length, so count alone can exhaust HBM long
    # before kv_slots states (advisor r2, medium). LRU-evicted states simply
    # re-prefill on their next chunk.
    kv_bytes_budget: int = 4 << 30
    # In-flight chunk requests when consuming a streamed weight update
    # (weight_sync.pipeline_depth threaded through the experiment config).
    weight_stream_pipeline_depth: int = 4
    # Serving engine (system/serving.py): request-class admission control,
    # cross-request prefix-reuse KV, bounded compile shapes, per-class
    # SLOs. Disabled = exact legacy behavior.
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)
    # Unified telemetry (base/telemetry.py). The gen-fleet process hosts
    # servers AND the manager, so each owns its own instance (distinct
    # worker kinds at the aggregator) instead of the process global.
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )
    # Goodput ledger (system/goodput.py): prefill/decode compute vs
    # queue-empty idle vs weight-update comm counters + analytic decode
    # FLOP/s and MFU gauges per batch. Off by default — null ledger.
    goodput: GoodputConfig = dataclasses.field(default_factory=GoodputConfig)
    # Liveness lease on the server's gen_servers/ registration
    # (docs/fault_tolerance.md): a SIGKILLed server's ghost URL expires
    # from discovery instead of being probed forever. 0 falls back to
    # the supervisor-set AREAL_WORKER_KEEPALIVE_TTL env.
    keepalive_ttl_secs: float = 0.0
    # Compile & HBM observatory (base/compile_watch.py +
    # system/memwatch.py): per-INSTANCE watches bound to this server's
    # telemetry (same reason telemetry itself is per-instance here — the
    # gen-fleet process hosts many servers plus the manager). Off by
    # default: raw genmod entry points, no device polls.
    compile_watch: CompileWatchConfig = dataclasses.field(
        default_factory=CompileWatchConfig
    )


class _Pending:
    __slots__ = ("rid", "prompt", "gconfig", "future", "max_tokens",
                 "tokens_done", "cls", "t_enqueue", "t_enqueue_wall",
                 "trace")

    def __init__(self, prompt, gconfig, max_tokens, future, rid=None,
                 tokens_done=0, cls="rollout", trace=None):
        self.rid = rid
        self.prompt = prompt
        self.gconfig = gconfig
        self.max_tokens = max_tokens
        self.tokens_done = tokens_done
        self.future = future
        self.cls = cls  # request class (serving.REQUEST_CLASSES)
        self.t_enqueue = time.monotonic()
        self.t_enqueue_wall = time.time()
        # Adopted cross-worker trace context (telemetry.TraceContext) —
        # the server's queue-wait/prefill/decode spans link back to the
        # client's generate span through it. None for untraced requests.
        self.trace = trace


# Retained decode states moved into the serving engine (KVStateStore);
# kept importable under the old name for callers/tests.
_ReqState = serving_mod.ReqState


class GenerationServer:
    """Owns (cfg, params) of the serving model; hot-swappable."""

    def __init__(self, cfg: GenerationServerConfig, model_cfg, params,
                 mesh=None, fault_injector=None):
        self.cfg = cfg
        self.model_cfg = model_cfg
        # Chaos seam (base/retry.py): an armed "decode" delay point
        # simulates a straggling server — the injected latency lands
        # inside the measured decode window, so the /health-reported
        # EWMAs (and the manager's straggler defense) see it exactly
        # like real slowness.
        self.faults = fault_injector
        import jax

        if mesh is not None:
            from areal_tpu.parallel import sharding as psh

            params = psh.shard_params(params, mesh, model_cfg)
        else:
            params = jax.tree.map(jax.numpy.asarray, params)
        self.params = params
        self.mesh = mesh
        self.version = 0
        # Atomic (params, version) publication for the decode thread: a
        # single attribute holding the pair — two separate attribute
        # loads could interleave with the update handler's swap and tag
        # old-weight tokens (and retained KV) with the new version.
        self._published = (params, 0)
        self._key = jax.random.PRNGKey(0)
        self._tokens_out = 0
        self._prefill_tokens = 0
        self._t_start = time.monotonic()
        self._runner_task = None
        self._last_update_latency = 0.0
        self._inflight = 0  # /generate requests accepted but not replied
        # Recent-latency EWMAs reported in /health for the manager's
        # autoscale signals + straggler defense (per decoded token, and
        # enqueue -> first tokens of a new generation).
        self._decode_ewma_secs: Optional[float] = None
        self._ttfc_ewma_secs: Optional[float] = None
        self._last_stream_stats: Dict[str, float] = {}
        # server_id "gen3" → worker_index 3 at the aggregator. Dynamic
        # (autoscaler-spawned) "dynN" ids live in a disjoint index range:
        # the aggregator merges snapshots by (worker_kind, worker_index),
        # so dyn1 sharing index 1 with baseline gen1 would silently
        # overwrite its counters/traces/flight dumps.
        idx = int("".join(c for c in cfg.server_id if c.isdigit()) or 0)
        if cfg.server_id.startswith("dyn"):
            idx += 1000
        self.telemetry = (
            telemetry.Telemetry(
                cfg.experiment, cfg.trial, "generation_server",
                idx, cfg=cfg.telemetry,
            ) if cfg.telemetry.enabled else telemetry.NULL
        )
        # Goodput ledger + live decode MFU (system/goodput.py): idle is
        # the base state (queue-empty waits), decode/prefill windows
        # enter compute, weight updates enter comm. Null when disabled.
        self.ledger = goodput_mod.make_ledger(cfg.goodput, self.telemetry)
        self._mfu = None
        self._n_chips = 1
        if self.ledger.enabled:
            self._n_chips = max(jax.device_count(), 1)
            self._mfu = goodput_mod.MfuEmitter(
                self.telemetry,
                goodput_mod.resolve_peak_flops(
                    cfg.goodput, str(jax.devices()[0])
                ),
                tflops_name="genserver/decode_tflops",
                mfu_name="genserver/decode_mfu",
                context=f"genserver {cfg.server_id}",
            )
        # Compile & HBM observatory: per-instance watches bound to THIS
        # server's telemetry (several servers share the gen-fleet
        # process). The jit entry points below route through the
        # wrappers; NULL when disabled, so the hot path pays one extra
        # plain call at most.
        arm_watch = cfg.compile_watch.enabled and cfg.telemetry.enabled
        self.compile_watch = (
            compile_watch_mod.CompileWatch(
                self.telemetry,
                storm_warmup_calls=cfg.compile_watch.storm_warmup_calls,
                cache_dir=compile_watch_mod.compilation_cache_dir(),
            ) if arm_watch else compile_watch_mod.NULL
        )
        self.memwatch = (
            memwatch_mod.MemWatch(
                self.telemetry,
                sample_interval_secs=(
                    cfg.compile_watch.mem_sample_interval_secs
                ),
            ) if arm_watch else memwatch_mod.NULL
        )
        self._prefill_fn = self.compile_watch.wrap(
            "genserver/prefill", genmod.prefill_state
        )
        self._decode_fn = self.compile_watch.wrap(
            "genserver/decode", genmod.decode_chunk_rows
        )
        self._extend_fn = self.compile_watch.wrap(
            "genserver/extend", genmod.extend_state
        )
        # The serving engine owns queueing, batch formation, retained-KV
        # lifecycle, and the compile-shape set; this server's handlers and
        # decode loop delegate those decisions (docs/serving.md).
        self.serving = serving_mod.ServingEngine(
            cfg.serving,
            kv_slots=cfg.kv_slots,
            kv_bytes_budget=cfg.kv_bytes_budget,
            kv_bucket=cfg.kv_bucket,
            chunk_tokens=cfg.chunk_tokens,
            max_batch_size=cfg.max_batch_size,
            prompt_bucket=cfg.prompt_bucket,
            telemetry=self.telemetry,
        )
        self._queue = self.serving.queue

    # ---------------- decode core ----------------

    def _decode_batch(self, batch: List[_Pending]) -> List[Dict[str, Any]]:
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        kv = self.serving.kv
        shapes = self.serving.shapes
        # Capture (params, version) atomically — a single load of the
        # published pair. handle_update_weights swaps both on the event
        # loop while we run in a thread; reading two separate attributes
        # could tag old-weight tokens (and retained KV, which the serving
        # engine hands out as prefix-reuse donors) with the new version.
        params, version = self._published
        # Sampling params are per-ROW dynamic arrays (ops.sampling), so a
        # batch may freely mix gconfigs; only the chunk length (static) is
        # shared. The shape policy rounds it to a configured bucket (rows
        # with a smaller budget stop early via row_budget), then clamps it
        # so the longest prefix in the batch still fits the largest KV
        # capacity bucket — admission guarantees at least one slot of room.
        chunk = shapes.round_chunk(
            min(cfg.chunk_tokens, max(p.max_tokens for p in batch))
        )
        if shapes.capacity_buckets is not None:
            # Remaining room under the largest capacity bucket, measured
            # against the BUCKETED prompt width (what prefill actually
            # pads to — prompt_bucket multiple, then the policy's width
            # bucket, exactly what admission checked). Admission
            # guarantees ≥ 1 slot; snapping the clamped chunk DOWN to a
            # bucket keeps near-ceiling batches from minting one compiled
            # shape per distinct room value — and with widths bucketed
            # too, room itself takes at most len(width_buckets) values.
            widest = max(
                shapes.round_width(
                    serving_mod.round_up(len(p.prompt), cfg.prompt_bucket)
                )
                for p in batch
            )
            room = shapes.capacity_buckets[-1] - widest
            chunk = max(1, shapes.round_chunk_down(min(chunk, room)))

        # Split: requests whose decode state survived (same version, prefix
        # length matches) continue from their KV; the rest prefill — via a
        # shared-prefix donor when the serving engine finds one. The state
        # OBJECT is captured here: /update_weights may clear the store on
        # the event loop while this thread runs, and a later re-lookup
        # would find nothing.
        cont: List[tuple] = []  # (pending, captured ReqState)
        fresh: List[_Pending] = []
        for p in batch:
            st = None
            if p.rid is not None and cfg.kv_slots > 0:
                st = kv.get(p.rid)
            if (
                st is not None and st.version == version
                and st.cur_len == len(p.prompt)
            ):
                st.last_used = time.monotonic()
                cont.append((p, st))
            else:
                fresh.append(p)

        row_states = {}
        fresh = [p for p in fresh
                 if not self._try_seed_from_prefix(
                     p, row_states, params, version, chunk)]
        if fresh:
            padded, plens = genmod.pad_prompts(
                [p.prompt for p in fresh], cfg.pad_token_id,
                bucket=cfg.prompt_bucket,
            )
            # Snap the padded prompt width to a policy width bucket
            # (pass-through when serving is off): per-prompt_bucket widths
            # are an unbounded compiled-shape family; geometric widths
            # keep the prefill shape set inside max_compiled_shapes.
            W = shapes.round_width(padded.shape[1])
            if W > padded.shape[1]:
                padded = np.concatenate([
                    padded,
                    np.full((padded.shape[0], W - padded.shape[1]),
                            cfg.pad_token_id, dtype=padded.dtype),
                ], axis=1)
            # Pad prefill rows up to a row bucket (dummy single-pad-token
            # prompts, sliced away below) so prefill compiles per bucketed
            # (rows, prompt, capacity), not per exact batch size.
            B_pad = shapes.round_rows(len(fresh))
            if B_pad > len(fresh):
                padded = np.concatenate([
                    padded,
                    np.full((B_pad - len(fresh), padded.shape[1]),
                            cfg.pad_token_id, dtype=padded.dtype),
                ])
                plens = np.concatenate([
                    plens, np.ones(B_pad - len(fresh), plens.dtype)
                ])
            S = shapes.round_capacity(padded.shape[1] + chunk)
            shapes.observe("prefill", B_pad, padded.shape[1], S)
            t_prefill_wall = time.time()
            t_prefill = time.monotonic()
            st = self._prefill_fn(
                params, self.model_cfg, jnp.asarray(padded),
                jnp.asarray(plens), S,
            )
            prefill_secs = time.monotonic() - t_prefill
            n_prefill = int(plens[:len(fresh)].sum())
            self._prefill_tokens += n_prefill
            if self._mfu is not None and prefill_secs > 0 and n_prefill:
                # Analytic prefill FLOP/s (forward-only, shared formula
                # family with the trainer's gauges — base/monitor.py).
                from areal_tpu.base import monitor

                pf = monitor.model_flops_per_token(
                    self.model_cfg, n_prefill / max(len(fresh), 1),
                    backward=False,
                ) * n_prefill
                self.telemetry.set_gauge(
                    "genserver/prefill_tflops",
                    pf / prefill_secs / self._n_chips / 1e12,
                )
            for i, p in enumerate(fresh):
                row_states[id(p)] = genmod.slice_state(st, i)
                if p.trace is not None:
                    # Shared batched-prefill window, tagged per request.
                    self.telemetry.add_span(
                        "genserver/prefill", t_prefill_wall, prefill_secs,
                        trace=p.trace, prompt_len=len(p.prompt),
                        batch_size=len(fresh),
                    )
        for p, rs in cont:
            row_states[id(p)] = genmod.grow_state(
                rs.state, shapes.round_capacity(rs.cur_len + chunk)
            )

        # Group rows by KV capacity (static shape per decode_chunk call).
        groups: Dict[int, List[_Pending]] = {}
        for p in batch:
            S = row_states[id(p)]["kv_k"].shape[2]
            groups.setdefault(S, []).append(p)

        res_by_id: Dict[int, Dict[str, Any]] = {}
        for S, group in groups.items():
            # Pad the group to a row bucket with copies of row 0 given a
            # zero budget — they finish at step 0 and their outputs are
            # discarded, so decode compiles per bucketed (rows, S, chunk).
            rows = shapes.round_rows(len(group))
            n_dummy = rows - len(group)
            states = [row_states[id(p)] for p in group]
            stacked = genmod.stack_states(states + states[:1] * n_dummy)
            done = jnp.asarray(
                [p.tokens_done for p in group] + [0] * n_dummy, jnp.int32
            )
            self._key, sub = jax.random.split(self._key)
            from areal_tpu.ops.sampling import sampling_from_gconfigs

            shapes.observe("decode", rows, S, chunk)
            new_state, out = self._decode_fn(
                params, self.model_cfg, stacked, done, sub,
                sampling_from_gconfigs(
                    [p.gconfig for p in group]
                    + [group[0].gconfig] * n_dummy
                ),
                n_tokens=chunk,
                eos_token_id=cfg.eos_token_id, pad_token_id=cfg.pad_token_id,
                # Rows with a smaller remaining budget than the batch chunk
                # stop sampling at their own allowance (dummies at 0).
                row_budget=jnp.asarray(
                    [min(p.max_tokens, chunk) for p in group]
                    + [0] * n_dummy, jnp.int32
                ),
            )
            out = jax.device_get(out)
            for i, p in enumerate(group):
                # Never hand back more than the request's remaining budget —
                # the client appends every token we return.
                n = min(int(out["output_lens"][i]), p.max_tokens)
                toks = np.asarray(out["output_ids"][i][:n])
                lps = np.asarray(out["output_logprobs"][i][:n])
                # "finished" = the MODEL ended the sequence (EOS). Budget
                # exhaustion is the client's call — it knows the total
                # budget across chunks, we only see this chunk's slice.
                emitted_eos = bool((toks == cfg.eos_token_id).any())
                res_by_id[id(p)] = {
                    "output_ids": toks.tolist(),
                    "output_logprobs": lps.tolist(),
                    "finished": emitted_eos,
                    "version": version,
                }
                self._tokens_out += n
                if p.rid is not None and cfg.kv_slots > 0:
                    allowance = min(p.max_tokens, chunk)
                    keep = (
                        # Serving: the client's next prefix is exactly
                        # prompt+n whenever the row ran its full allowance
                        # without EOS; even if the client never returns,
                        # the retained state doubles as a prefix-reuse
                        # donor and LRU + the bytes budget reclaim it.
                        (cfg.serving.enabled and n == allowance)
                        # Legacy: keep only full-chunk continuations with
                        # budget left (a consumed allowance might mean the
                        # client never comes back; budget truncation would
                        # desync cur_len) — the pre-serving behavior.
                        or (not cfg.serving.enabled
                            and n == chunk and n < p.max_tokens)
                    )
                    if emitted_eos or not keep:
                        kv.pop(p.rid)
                    else:
                        kv.put(p.rid, _ReqState(
                            genmod.slice_state(new_state, i),
                            cur_len=len(p.prompt) + n,
                            version=version,
                            # The full token sequence only feeds the
                            # prefix trie — skip the per-chunk O(seq)
                            # concatenate when reuse can't consume it.
                            tokens=np.concatenate([
                                np.asarray(p.prompt, np.int64),
                                toks.astype(np.int64),
                            ]) if kv.prefix_reuse else None,
                        ))
        kv.evict()
        return [res_by_id[id(p)] for p in batch]

    def _try_seed_from_prefix(self, p: _Pending, row_states: Dict,
                              params, version: int, chunk: int) -> bool:
        """Cross-request prefix seeding (docs/serving.md): if a retained
        state's token sequence shares a prefix with this prompt, clone
        the donor's KV at the shared length and prefill only the suffix.
        Returns True when ``row_states[id(p)]`` was seeded."""
        import jax.numpy as jnp

        cfg = self.cfg
        shapes = self.serving.shapes
        got = self.serving.kv.acquire_prefix(
            p.prompt, version, min_len=cfg.serving.min_prefix_tokens
        )
        if got is None:
            return False
        rid, shared = got
        try:
            T = None
            if shared < len(p.prompt):
                # Prefill and extend pad to the same width buckets, so a
                # clone+extend only saves compute when the bucketed suffix
                # is strictly narrower than the full-prompt prefill width.
                # Otherwise it's a net loss: same padded matmul, plus
                # clone/grow/trie overhead, plus it pulls the row out of
                # the batched prefill into a serial B=1 extend dispatch.
                try:
                    W_full = shapes.round_width(
                        serving_mod.round_up(
                            len(p.prompt), cfg.prompt_bucket
                        )
                    )
                    T = shapes.round_width(
                        serving_mod.round_up(
                            len(p.prompt) - shared, cfg.prompt_bucket
                        )
                    )
                except serving_mod.PromptTooLong:
                    return False  # near the capacity ceiling: plain prefill
                if T >= W_full:
                    self.telemetry.inc("serving/prefix_skipped_no_savings")
                    return False
            donor = self.serving.kv.get(rid)
            if donor is None:
                # /update_weights cleared the store on the event loop
                # between acquire and here — fall back to a plain prefill.
                return False
            st = genmod.clone_prefix(donor.state, shared)
            suffix = np.asarray(p.prompt[shared:], np.int32)
            if len(suffix) == 0:
                # Exact full-sequence match: the donor's last_logits are
                # the ones this prompt needs — a pure clone, zero prefill.
                need = shapes.round_capacity(len(p.prompt) + chunk)
                if need > st["kv_k"].shape[2]:
                    st = genmod.grow_state(st, need)
                # decode_chunk_rows donates its input state, and a
                # single-row group's stack_states returns these very
                # arrays (a one-array concatenate is the identity) —
                # donation would delete the donor's retained buffers in
                # place, poisoning the store. Copy every leaf still
                # shared with the donor (grow_state already freed the KV
                # leaves when it grew; last_logits is always shared).
                st = {
                    k: (jnp.copy(v) if v is donor.state.get(k) else v)
                    for k, v in st.items()
                }
                row_states[id(p)] = st
                self.telemetry.inc("serving/prefix_hits")
                self.telemetry.inc("serving/prefix_tokens_saved", shared)
                return True
            # T (the suffix width, through the same buckets as prefill)
            # was computed by the savings gate above; the extend kernel
            # is one more compiled-shape family the policy keeps finite.
            try:
                need = shapes.round_capacity(
                    max(len(p.prompt) + chunk, shared + T)
                )
            except serving_mod.PromptTooLong:
                return False  # near the capacity ceiling: plain prefill
            if need > st["kv_k"].shape[2]:
                st = genmod.grow_state(st, need)
            padded = np.full((1, T), cfg.pad_token_id, np.int32)
            padded[0, :len(suffix)] = suffix
            shapes.observe("extend", 1, T, st["kv_k"].shape[2])
            row_states[id(p)] = self._extend_fn(
                params, self.model_cfg, st, jnp.asarray(padded),
                jnp.asarray([len(suffix)], jnp.int32),
            )
            self._prefill_tokens += len(suffix)
            self.telemetry.inc("serving/prefix_hits")
            self.telemetry.inc("serving/prefix_tokens_saved", shared)
            return True
        finally:
            self.serving.kv.release(rid)

    async def _runner(self):
        cfg = self.cfg
        while True:
            # Re-anchor the ledger at idle every iteration: this loop is
            # the partition's single owner (weight updates accrue comm
            # via add(), never transitions — a concurrent restore racing
            # the decode's would wedge the partition in a stale state).
            self.ledger.enter("idle")
            first: _Pending = await self._queue.get()
            batch = [first]
            await asyncio.sleep(cfg.batch_window_ms / 1000)
            # Drain up to max_batch_size. The serving queue pops in class
            # priority order (interactive > eval > rollout; plain FIFO
            # when serving is disabled). Sampling params are per-row
            # vectors inside the decode kernel, so mixed gconfigs batch
            # together — no deferral, no starvation within a class.
            batch += self._queue.drain(cfg.max_batch_size - 1)
            t_formed = time.monotonic()
            for p in batch:
                # The serving engine owns the SLO observation AND the
                # per-request trace span for the queue stage.
                self.serving.record_queue_wait(
                    p.cls, t_formed - p.t_enqueue,
                    trace=p.trace, t_start_wall=p.t_enqueue_wall,
                )
            try:
                if self.faults is not None:
                    # Injected straggler latency: inside the measured
                    # decode window so the reported EWMAs include it.
                    await self.faults.maybe_delay(
                        "decode", server_id=self.cfg.server_id,
                    )
                with self.telemetry.span("genserver/decode_chunk",
                                         batch_size=len(batch)) as attrs, \
                        self.ledger.state("compute"):
                    results = await asyncio.to_thread(
                        self._decode_batch, batch
                    )
                    attrs["tokens"] = sum(
                        len(r["output_ids"]) for r in results
                    )
                self.telemetry.inc("genserver/decode_chunks")
                self.telemetry.inc("genserver/generated_tokens",
                                   attrs["tokens"])
                dt = time.monotonic() - t_formed
                t_decode_wall = time.time() - dt
                if self._mfu is not None and attrs["tokens"] and dt > 0:
                    # Analytic decode FLOP/s + MFU per batch: each new
                    # token runs one forward at roughly the row's current
                    # context length (base/monitor.py formula family).
                    from areal_tpu.base import monitor

                    avg_ctx = sum(
                        len(p.prompt) + p.tokens_done for p in batch
                    ) / len(batch)
                    self._mfu.emit(
                        monitor.model_flops_per_token(
                            self.model_cfg, avg_ctx, backward=False
                        ) * attrs["tokens"] / dt / self._n_chips
                    )
                chunk_tokens = max(
                    (len(r["output_ids"]) for r in results), default=0
                )
                if chunk_tokens > 0:
                    # Per-token decode latency EWMA for /health — the
                    # manager's straggler EWMAs feed off this.
                    sample = dt / chunk_tokens
                    self._decode_ewma_secs = (
                        sample if self._decode_ewma_secs is None
                        else 0.7 * self._decode_ewma_secs + 0.3 * sample
                    )
                for p, r in zip(batch, results):
                    n_tok = len(r["output_ids"])
                    if p.trace is not None:
                        # This request's share of the batched decode
                        # window (wall window is shared — per-request
                        # token counts distinguish the rows).
                        self.telemetry.add_span(
                            "genserver/decode", t_decode_wall, dt,
                            trace=p.trace, tokens=n_tok,
                            batch_size=len(batch),
                            version=r.get("version"),
                        )
                    if p.tokens_done == 0:
                        # Time-to-first-chunk: enqueue → first tokens of a
                        # NEW generation (continuations measure per-token).
                        ttfc = time.monotonic() - p.t_enqueue
                        self.serving.record_first_chunk(p.cls, ttfc)
                        self._ttfc_ewma_secs = (
                            ttfc if self._ttfc_ewma_secs is None
                            else 0.7 * self._ttfc_ewma_secs + 0.3 * ttfc
                        )
                    if n_tok:
                        self.serving.record_token_latency(p.cls, dt / n_tok)
                    # A disconnected client's handler task was cancelled,
                    # cancelling its future — set_result would raise
                    # InvalidStateError and the generic handler below
                    # would then 500 every other request in the batch.
                    if not p.future.done():
                        p.future.set_result(r)
                self.serving.export_gauges()
            except asyncio.CancelledError:
                # Server stopping mid-decode: fail the batch so its HTTP
                # handlers return immediately instead of hanging through
                # the runner's graceful-shutdown window.
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(
                            RuntimeError("generation server stopping")
                        )
                raise
            except Exception as e:  # noqa: BLE001 — propagate per-request
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(e)

    # ---------------- http ----------------

    async def handle_generate(self, request):
        from aiohttp import web

        d = await request.json()
        gconfig = GenerationHyperparameters(**d.get("gconfig", {}))
        cls = serving_mod.normalize_class(d.get("class"))
        prompt = np.asarray(d["prompt_ids"], np.int32)
        fut = asyncio.get_running_loop().create_future()
        p = _Pending(
            prompt=prompt,
            gconfig=gconfig,
            max_tokens=int(d.get("max_tokens", gconfig.max_new_tokens)),
            future=fut,
            rid=d.get("rid"),
            tokens_done=int(d.get("tokens_done", 0)),
            cls=cls,
            # Adopt the caller's trace (header absent / telemetry off
            # → None, zero extra work).
            trace=(telemetry.extract_headers(request.headers)
                   if self.telemetry.enabled else None),
        )
        try:
            # Admission + enqueue are one atomic decision on the event
            # loop: either the request is queued or the client gets
            # backpressure NOW (429 + Retry-After) instead of a spot in an
            # unbounded pending list its SLO could never survive.
            # "budget_total" is the chunked client's FULL remaining token
            # budget (partial_rollout sends it); absent — a single-shot
            # or third-party client — only this request's prompt is
            # feasibility-checked, the pre-existing behavior.
            budget = d.get("budget_total")
            self.serving.admit(
                p, cls, prompt_len=len(prompt),
                planned_len=(
                    len(prompt) + int(budget) if budget else None
                ),
            )
        except serving_mod.AdmissionReject as e:
            import math

            # Header is RFC 9110 delay-seconds (integer); the JSON body
            # keeps the precise float for clients that read it.
            return web.json_response(
                {"ok": False, "reason": "admission", "class": cls,
                 "queue_depth": e.depth, "retry_after": e.retry_after},
                status=429,
                headers={"Retry-After": str(math.ceil(e.retry_after))},
            )
        except serving_mod.PromptTooLong as e:
            return web.json_response(
                {"ok": False, "reason": "prompt_too_long",
                 "needed_slots": e.needed, "max_slots": e.cap},
                status=413,
            )
        self._inflight += 1
        try:
            return web.json_response(await fut)
        finally:
            self._inflight -= 1

    def _load_and_put_weights(self, path: str):
        """Host-side checkpoint read + device upload. Runs in a worker
        thread — the event loop (and /generate batching) never blocks on
        disk or transfer; only the final reference swap happens on-loop."""
        import jax

        from areal_tpu.models import hf as hfmod

        _, params = hfmod.load_checkpoint_auto(path)
        # Preserve the existing per-leaf device placement/sharding.
        return jax.tree.map(
            lambda old, npv: jax.device_put(
                np.asarray(npv, dtype=old.dtype), old.sharding
            ),
            self.params,
            params,
        )

    def _stream_and_put_weights(self, endpoint: str, version: int,
                                timeout_secs: Optional[float] = None):
        """Streamed transport (docs/weight_sync.md): pull the manifest +
        per-tensor chunks from the trainer's WeightStreamPublisher into a
        SHADOW pytree, device_put'ing each tensor as it lands so the h2d
        upload of tensor i−1 overlaps the wire transfer of tensor i (whose
        d2h gather the publisher is doing concurrently). The shadow tree
        only replaces ``self.params`` after the publisher's digest verifies
        the complete stream — a torn, reordered, or corrupted transfer
        raises before anything live is touched."""
        from areal_tpu.models.hf import flatten_pytree
        from areal_tpu.system.weight_stream import WeightStreamConsumer

        old_flat = flatten_pytree(self.params)
        consumer = WeightStreamConsumer(
            endpoint,
            pipeline_depth=self.cfg.weight_stream_pipeline_depth,
            **({} if timeout_secs is None
               else {"timeout_secs": timeout_secs}),
        )
        # The shadow-pytree swap is the server's HBM high-water mark: old
        # + new params coexist until the verified swap. The watermark
        # gauge is the measured number docs/weight_sync.md budgets 2x
        # params for.
        with self.memwatch.watermark("genserver/shadow_swap"):
            return self._stream_shadow(consumer, version, old_flat)

    def _stream_shadow(self, consumer, version: int, old_flat):
        import jax

        from areal_tpu.models.hf import unflatten_pytree
        from areal_tpu.system.weight_stream import WeightStreamError

        try:
            manifest = consumer.fetch_manifest(version)
            shadow = {}
            for name, arr in consumer.iter_tensors(version, manifest):
                old = old_flat.get(name)
                if old is None:
                    raise WeightStreamError(
                        f"streamed tensor {name!r} not in the live pytree"
                    )
                if tuple(arr.shape) != tuple(old.shape):
                    raise WeightStreamError(
                        f"tensor {name!r}: streamed shape {arr.shape} != "
                        f"live {old.shape}"
                    )
                # Async dispatch: device_put returns immediately, so the
                # upload runs while the next chunks arrive.
                shadow[name] = jax.device_put(
                    np.asarray(arr, dtype=old.dtype), old.sharding
                )
            if set(shadow) != set(old_flat):
                missing = sorted(set(old_flat) - set(shadow))
                raise WeightStreamError(
                    f"incomplete stream: {len(missing)} tensors missing "
                    f"(e.g. {missing[:3]})"
                )
            # The gate: no swap without a checksum-verified manifest.
            consumer.verify_digest(version)
            new = unflatten_pytree(shadow)
            jax.block_until_ready(new)
            # Per-leg stream stats for /metrics + telemetry: wire wait,
            # digest/checksum CPU, and total bytes of this consume.
            # Recorded ONLY on a verified success — a failed update must
            # leave /metrics unchanged (the except handler's contract).
            self._last_stream_stats = {
                "stream_bytes": float(consumer.bytes_received),
                "digest_verify_secs": consumer.checksum_secs,
                "wire_wait_secs": consumer.wire_wait_secs,
            }
            return new
        finally:
            consumer.close()

    def _reshard_published_weights(self, role: str, version: int,
                                   digest: str):
        """Device transport (docs/weight_sync.md §device): the trainer
        resharded its live params into this fleet's layout ON DEVICE and
        registered them (parallel/reshard.py); the fanout payload carries
        the publication digest out of band. consume_device verifies
        version + digest + tree compatibility against the live pytree
        before returning the weights resharded into this server's own
        shardings — any gate failure raises with the old weights still
        live, the same contract as a torn stream."""
        import jax

        from areal_tpu.parallel import reshard as rsh

        with self.memwatch.watermark("genserver/device_consume"):
            new = rsh.consume_device(
                self.cfg.experiment, self.cfg.trial, role,
                version, digest, self.params,
            )
            jax.block_until_ready(new)
        return new

    async def handle_update_weights(self, request):
        from aiohttp import web

        d = await request.json()
        t0 = time.monotonic()
        transport = ("device" if d.get("device")
                     else "stream" if d.get("endpoint") else "disk")
        try:
            with self.telemetry.span("genserver/weight_update",
                                     transport=transport,
                                     version=int(d.get("version", -1))):
                if d.get("device"):
                    new = await asyncio.to_thread(
                        self._reshard_published_weights,
                        d.get("role", "actor"), int(d["version"]),
                        d.get("digest", ""),
                    )
                elif d.get("endpoint"):
                    new = await asyncio.to_thread(
                        self._stream_and_put_weights, d["endpoint"],
                        int(d["version"]),
                        d.get("timeout"),
                    )
                else:
                    new = await asyncio.to_thread(
                        self._load_and_put_weights, d["path"]
                    )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — keep old weights, report
            # Old (params, version) stay live and /metrics unchanged; the
            # manager's fanout retry/eviction machinery owns what happens
            # to this server next (docs/fault_tolerance.md).
            self.telemetry.inc("genserver/weight_update_failures")
            logger.error(f"weight update failed; keeping v{self.version}: {e}")
            return web.json_response(
                {"ok": False, "version": self.version, "error": str(e)},
                status=500,
            )
        finally:
            # Weight-update comm is ACCRUED in the overlap family, not a
            # partition transition: the update overlaps in-flight decodes
            # on this event loop — a concurrent enter/restore pair would
            # wedge the partition (the runner owns idle<->compute
            # exclusively), and folding it into the partition counters
            # would make states sum past wall clock, deflating every
            # derived utilization fraction.
            self.ledger.add_overlap("comm", time.monotonic() - t0)
        # Atomic (params, version) swap: in-flight _decode_batch threads
        # captured the old pair and tag their tokens with the old version.
        self.params = new
        self.version = int(d.get("version", self.version + 1))
        self._published = (new, self.version)
        # KV computed under the old weights is stale — continuations after
        # a version change re-prefill once (reference: SGLang flushes its
        # cache on update_weights_from_disk). The prefix trie empties with
        # it: old-version states must never seed new requests.
        self.serving.kv.clear()
        dt = time.monotonic() - t0
        self._last_update_latency = dt
        self.telemetry.set_gauge("genserver/weight_version", self.version)
        self.telemetry.set_gauge("genserver/weight_update_secs", dt)
        if transport == "stream":
            # Disk updates must not republish the previous stream's stats
            # as if they described this sync.
            for k, v in self._last_stream_stats.items():
                self.telemetry.set_gauge(f"genserver/{k}", v)
        logger.info(f"weights updated to v{self.version} in {dt:.2f}s")
        return web.json_response({"ok": True, "version": self.version,
                                  "latency_s": dt})

    async def handle_health(self, request):
        # Polled by the gserver manager's fleet-health loop: ``version`` is
        # what the manager reconciles against when re-admitting this server
        # after an eviction (docs/fault_tolerance.md).
        from aiohttp import web

        # The manager's periodic probe doubles as the ledger's heartbeat:
        # a long queue-empty idle accrues onto the scrape without waiting
        # for the next decode transition.
        self.ledger.poll()
        return web.json_response({
            "ok": True,
            "version": self.version,
            "server_id": self.cfg.server_id,
            "uptime_secs": time.monotonic() - self._t_start,
            # Load/latency stats riding the probe: the manager's
            # autoscale signals (queue depth, TTFC SLO) and straggler
            # EWMAs come for free with the health sweep it already runs.
            "queue_depth": self._queue.qsize(),
            "inflight": self._inflight,
            "decode_ewma_secs": self._decode_ewma_secs,
            "ttfc_ewma_secs": self._ttfc_ewma_secs,
        })

    def _metrics_dict(self) -> Dict[str, Any]:
        self.ledger.poll()  # scrape-time freshness for the idle state
        # HBM gauges piggyback on the scrape cadence (rate-limited inside
        # the watch; NULL when the observatory is off).
        self.memwatch.sample()
        dt = max(time.monotonic() - self._t_start, 1e-6)
        d = {
            "generated_tokens": self._tokens_out,
            "prefill_tokens": self._prefill_tokens,
            "tokens_per_sec": self._tokens_out / dt,
            "kv_states": self.serving.kv.count,
            "kv_bytes": self.serving.kv.nbytes,
            # Distinct compiled (kind, dims) decode-engine shapes so far —
            # the compile-churn bound VERDICT #9 asks to watch.
            "compiled_shapes": self.serving.shapes.distinct_shapes,
            "version": self.version,
            "inflight_requests": self._inflight,
            "queue_depth": self._queue.qsize(),
            "decode_ewma_secs": self._decode_ewma_secs or 0.0,
            "ttfc_ewma_secs": self._ttfc_ewma_secs or 0.0,
            "last_weight_update_latency_s": self._last_update_latency,
            # Stats of the last SUCCESSFUL streamed consume (absent until
            # one lands; a later disk update does not describe these).
            **{f"last_stream_{k}": v
               for k, v in self._last_stream_stats.items()},
        }
        if self.cfg.serving.enabled:
            for c in serving_mod.REQUEST_CLASSES:
                d[f"serving_queue_{c}"] = self._queue.depth(c)
        return d

    async def handle_metrics(self, request):
        """Prometheus exposition text (docs/observability.md): live server
        state as ``areal_genserver_*`` gauges — including weight_version
        and inflight_requests — plus this server's telemetry registry
        (decode spans → histograms) when telemetry is enabled. The old
        JSON body moved to ``/metrics.json``."""
        from aiohttp import web

        d = self._metrics_dict()
        extra = {f"genserver_{k}": v for k, v in d.items()}
        # Canonical gauge name, present from boot (the registry's copy
        # only exists once the first /update_weights lands).
        extra["genserver_weight_version"] = d["version"]
        body = telemetry.render_prometheus(
            self.telemetry.snapshot(reset=False),
            extra_gauges=extra,
            labels={"server_id": self.cfg.server_id},
        )
        return web.Response(
            text=body, content_type="text/plain",
            charset="utf-8", headers={"X-Prometheus-Version": "0.0.4"},
        )

    async def handle_metrics_json(self, request):
        from aiohttp import web

        return web.json_response(self._metrics_dict())

    def build_app(self):
        from aiohttp import web

        app = web.Application(client_max_size=256 * 1024 * 1024)
        app.router.add_post("/generate", self.handle_generate)
        app.router.add_post("/update_weights", self.handle_update_weights)
        app.router.add_get("/health", self.handle_health)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/metrics.json", self.handle_metrics_json)
        return app

    async def start(self) -> str:
        """Start serving; registers the URL under names.gen_servers."""
        from aiohttp import web

        self._runner_task = asyncio.create_task(self._runner())
        app = self.build_app()
        runner = web.AppRunner(app)
        await runner.setup()
        port = self.cfg.port or network.find_free_port()
        site = web.TCPSite(runner, network.bind_addr(), port)
        await site.start()
        url = f"http://{network.gethostip()}:{port}"
        from areal_tpu.system.worker_base import (
            HeartbeatThread,
            env_keepalive_ttl,
        )

        ttl = self.cfg.keepalive_ttl_secs or env_keepalive_ttl() or 0.0
        key = names.gen_servers(self.cfg.experiment, self.cfg.trial,
                                self.cfg.server_id)
        name_resolve.add(key, url, replace=True, keepalive_ttl=ttl or None)
        # Heartbeat from a dedicated THREAD, not this event loop: a long
        # decode compile blocks the loop for minutes, and the lease must
        # not lapse (the manager would forget a merely-busy server). The
        # lease exists for SIGKILLed processes — those lose their
        # threads too, so the ghost key still expires.
        self._hb = None
        if ttl:
            from areal_tpu.system.worker_base import (
                default_heartbeat_interval,
            )

            self._hb = HeartbeatThread(
                self.cfg.experiment, self.cfg.trial,
                f"genserver_{self.cfg.server_id}",
                interval=default_heartbeat_interval(ttl),
                # Compile-aware liveness: publish names.compile_inflight
                # while prefill/decode/extend compile a fresh shape.
                inflight_fn=self.compile_watch.inflight,
            )
            self._hb.lease(key, url, ttl)
        logger.info(f"generation server {self.cfg.server_id} at {url}"
                    + (f" (keepalive {ttl:.0f}s)" if ttl else ""))
        self._runner_obj = runner
        return url

    async def stop(self, abort: bool = False):
        """Stop serving. ``abort=True`` is the crash-like path (chaos
        tests): queued requests are failed immediately instead of drained,
        so connected clients see errors now rather than a hung socket."""
        if self._runner_task:
            self._runner_task.cancel()
        if abort:
            while not self._queue.empty():
                p = self._queue.get_nowait()
                if not p.future.done():
                    p.future.set_exception(RuntimeError("server aborted"))
        if getattr(self, "_hb", None) is not None:
            self._hb.close()
        self.ledger.flush()
        self.memwatch.close()
        self.compile_watch.close()
        self.telemetry.close()
        await self._runner_obj.cleanup()
